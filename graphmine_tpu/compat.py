"""pyspark / graphframes compatibility shim — run the reference script verbatim.

The reference (``CommunityDetection/Graphframes.py``) drives everything
through pyspark and GraphFrames call sites. This module fakes exactly that
surface — ``pyspark``, ``pyspark.sql``, ``pyspark.sql.functions``,
``graphframes`` — over the TPU-native engine, so the *unmodified* script
executes here: parquet read (``Graphframes.py:16``), DataFrame preprocessing
(``:26-32``), the RDD vertex-dictionary idiom (``:53, :67``), per-row UDFs
(``:61, :71-72``), ``GraphFrame(v, e)`` + ``labelPropagation`` (``:78-81``),
and the driver-side census loops (``:100-120``).

Design stance: this is the **plugin boundary**, not the engine. DataFrame ops
delegate to :class:`graphmine_tpu.table.Table` (vectorized NumPy); graph
algorithms run on the jit/TPU path through
:class:`graphmine_tpu.frames.GraphFrame`. Only the RDD lambda surface runs
per-element Python — it exists to honor the reference's own driver-side
idioms, and `collect()` results are cached per DataFrame so the reference's
re-collect-per-iteration loops (``:102, :110``) don't repay row construction.

Usage::

    python -m graphmine_tpu.compat /path/to/Graphframes.py   # runs verbatim
    # or programmatically:
    from graphmine_tpu import compat
    compat.install()          # registers the fake modules in sys.modules
    import pyspark            # -> the shim

``install()`` refuses to shadow a real pyspark installation unless
``force=True``.
"""

from __future__ import annotations

import os
import runpy
import sys
import types
from typing import Sequence

import numpy as np

from graphmine_tpu import frames as _frames
from graphmine_tpu.table import Table, _isnull

__all__ = [
    "Column", "DataFrame", "GraphFrame", "RDD", "Row", "SQLContext",
    "SparkConf", "SparkContext", "SparkSession", "asc", "col", "collect_list",
    "collect_set", "column", "count", "countDistinct", "desc", "first",
    "install", "lit", "main", "mean", "monotonically_increasing_id", "udf",
    "when",
]


# ---------------------------------------------------------------------------
# Row — Spark's tuple-with-field-names (subscript by index or column name)
# ---------------------------------------------------------------------------


class Row(tuple):
    """Spark ``Row``: a tuple whose elements are also reachable by field
    name via ``row['col']`` / ``row.col`` (``Graphframes.py:103, :111``).

    Constructor matches pyspark: ``Row(id='a', n=1)`` (named fields, order
    preserved) or ``Row('a', 1)`` (positional, no field names)."""

    def __new__(cls, *args, **kwargs):
        if args and kwargs:
            raise ValueError("cannot mix positional and named Row arguments")
        r = tuple.__new__(cls, kwargs.values() if kwargs else args)
        r._fields_ = tuple(kwargs) if kwargs else None
        return r

    @classmethod
    def _make(cls, values, fields: Sequence[str]) -> "Row":
        r = tuple.__new__(cls, values)
        r._fields_ = tuple(fields)
        return r

    def __getitem__(self, key):
        if isinstance(key, str):
            if self._fields_ is None:
                raise KeyError(f"Row has no named fields: {key!r}")
            return tuple.__getitem__(self, self._fields_.index(key))
        return tuple.__getitem__(self, key)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return tuple.__getitem__(self, (self._fields_ or ()).index(name))
        except ValueError:
            raise AttributeError(name) from None

    def asDict(self) -> dict:
        if self._fields_ is None:
            raise TypeError("Row has no named fields")
        return dict(zip(self._fields_, self))

    def __repr__(self) -> str:
        if self._fields_ is None:
            return "Row(" + ", ".join(repr(v) for v in self) + ")"
        return "Row(" + ", ".join(
            f"{k}={v!r}" for k, v in zip(self._fields_, self)
        ) + ")"


# ---------------------------------------------------------------------------
# Column expressions — pyspark.sql.Column / functions surface
# ---------------------------------------------------------------------------


class Column:
    """Lazy column expression: evaluated against a :class:`Table` at use
    time (``df.filter(F.col("age") > 30)``, ``df.withColumn("y", ...)``).

    Comparisons follow SQL three-valued logic collapsed to ``False`` for
    null operands (matching ``Table.filter``'s predicate strings)."""

    def __init__(self, eval_fn, name: str = "col"):
        self._eval = eval_fn
        self._name = name

    # construction helpers --------------------------------------------------

    @staticmethod
    def _coerce(other) -> "Column":
        if isinstance(other, Column):
            return other
        return lit(other)

    def _binop(self, other, fn, name) -> "Column":
        other = Column._coerce(other)
        return Column(
            lambda t: fn(_numeric_view(self._eval(t)), _numeric_view(other._eval(t))),
            f"({self._name} {name} {other._name})",
        )

    def _cmp(self, other, op) -> "Column":
        from graphmine_tpu.table import _compare

        other = Column._coerce(other)
        return Column(
            lambda t: _compare(_as_arr(self._eval(t)), op, _as_arr(other._eval(t))),
            f"({self._name} {op} {other._name})",
        )

    # comparisons (SQL null semantics) --------------------------------------

    def __eq__(self, other):  # noqa: D105
        return self._cmp(other, "=")

    def __ne__(self, other):  # noqa: D105
        return self._cmp(other, "!=")

    def __lt__(self, other):
        return self._cmp(other, "<")

    def __le__(self, other):
        return self._cmp(other, "<=")

    def __gt__(self, other):
        return self._cmp(other, ">")

    def __ge__(self, other):
        return self._cmp(other, ">=")

    __hash__ = None  # mirrors pyspark: Column is unhashable

    # boolean algebra over masks --------------------------------------------

    def __and__(self, other):
        return self._binop(other, lambda a, b: _as_bool(a) & _as_bool(b), "AND")

    def __or__(self, other):
        return self._binop(other, lambda a, b: _as_bool(a) | _as_bool(b), "OR")

    def __invert__(self):
        return Column(lambda t: ~_as_bool(self._eval(t)), f"(NOT {self._name})")

    # arithmetic -------------------------------------------------------------

    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b, "+")

    def __radd__(self, other):
        return Column._coerce(other).__add__(self)

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b, "-")

    def __rsub__(self, other):
        return Column._coerce(other).__sub__(self)

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b, "*")

    def __rmul__(self, other):
        return Column._coerce(other).__mul__(self)

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b, "/")

    def __neg__(self):
        return Column(lambda t: -self._eval(t), f"(- {self._name})")

    # pyspark Column methods -------------------------------------------------

    def isNull(self) -> "Column":
        return Column(lambda t: _isnull(_as_arr(self._eval(t))),
                      f"({self._name} IS NULL)")

    def isNotNull(self) -> "Column":
        return Column(lambda t: ~_isnull(_as_arr(self._eval(t))),
                      f"({self._name} IS NOT NULL)")

    def isin(self, *values) -> "Column":
        vals = list(values[0]) if len(values) == 1 and isinstance(
            values[0], (list, tuple, set)) else list(values)

        def ev(t):
            a = _as_arr(self._eval(t))
            try:
                arr = np.asarray(vals, dtype=object if a.dtype == object
                                 else a.dtype)
                m = np.isin(a, arr)
            except (ValueError, TypeError):  # incomparable types: SQL false
                sv = set(vals)
                m = np.frompyfunc(lambda x: x in sv, 1, 1)(a).astype(bool)
            return m & ~_isnull(a)

        return Column(ev, f"({self._name} IN ...)")

    def like(self, pattern: str) -> "Column":
        from graphmine_tpu.table import _like

        return Column(lambda t: _like(_as_arr(self._eval(t)), pattern),
                      f"({self._name} LIKE {pattern!r})")

    def contains(self, sub: str) -> "Column":
        return self.like(f"%{sub}%")

    def startswith(self, prefix: str) -> "Column":
        return self.like(f"{prefix}%")

    def endswith(self, suffix: str) -> "Column":
        return self.like(f"%{suffix}")

    def alias(self, name: str) -> "Column":
        c = Column(self._eval, name)
        return c

    def cast(self, dtype) -> "Column":
        np_t = {"int": np.int64, "long": np.int64, "bigint": np.int64,
                "float": np.float32, "double": np.float64,
                "string": object}.get(dtype, dtype)

        def ev(t):
            a = _as_arr(self._eval(t))
            null = _isnull(a)
            if np_t is object:  # nulls stay null, never the string 'nan'/'None'
                out = np.frompyfunc(lambda v: str(v), 1, 1)(a).astype(object)
                out[null] = None
                return out
            base = np.where(null, 0, a).astype(np_t)
            if not null.any():
                return base
            if np.issubdtype(np_t, np.floating):
                out = base.copy()
                out[null] = np.nan
                return out
            out = base.astype(object)  # nullable-int convention
            out[null] = None
            return out

        return Column(ev, self._name)

    def asc(self) -> "_SortKey":
        return _SortKey(self._name, ascending=True)

    def desc(self) -> "_SortKey":
        return _SortKey(self._name, ascending=False)

    def otherwise(self, value) -> "Column":
        raise TypeError("otherwise() follows when(); use F.when(cond, v).otherwise(...)")


class _SortKey:
    def __init__(self, name: str, ascending: bool):
        self.name, self.ascending = name, ascending


class _WhenColumn(Column):
    """``F.when(cond, value)`` chain; closes with ``.otherwise(value)``."""

    def __init__(self, branches):
        self._branches = branches  # list of (cond Column, value Column)
        super().__init__(self._evaluate, "CASE WHEN")

    def when(self, cond: Column, value) -> "_WhenColumn":
        return _WhenColumn(self._branches + [(cond, Column._coerce(value))])

    def otherwise(self, value) -> Column:
        other = Column._coerce(value)

        def ev(t):
            out = _as_arr(other._eval(t))
            return self._fold(t, out)

        return Column(ev, "CASE WHEN")

    def _evaluate(self, t):
        # un-terminated when(): missing branches are null (pyspark semantics)
        first = _as_arr(self._branches[0][1]._eval(t))
        base = (np.full(len(t), np.nan)
                if first.dtype != object else np.full(len(t), None, object))
        return self._fold(t, base, first_arr=first)

    def _fold(self, t, out, first_arr=None):
        for i, (cond, val) in reversed(list(enumerate(self._branches))):
            arr = first_arr if (i == 0 and first_arr is not None) else _as_arr(
                val._eval(t))
            out = np.where(_as_bool(cond._eval(t)), arr, out)
        return out


def _as_arr(v) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype.kind in ("U", "S"):
        a = a.astype(object)
    return a


def _numeric_view(v) -> np.ndarray:
    """Arithmetic view of a column: object-promoted nullable-int columns
    (None for null) become float64 with NaN so null propagates through
    +,-,*,/ as in Spark; non-numeric object columns pass through."""
    from graphmine_tpu.table import _object_as_float

    a = _as_arr(v)
    if a.dtype == object:
        num = _object_as_float(a, _isnull(a))
        if num is not None:
            return num
    return a


def _as_bool(v) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype == object:
        return np.frompyfunc(lambda x: bool(x) if x is not None else False,
                             1, 1)(a).astype(bool)
    return a.astype(bool)


def col(name: str) -> Column:
    return Column(lambda t: t[name], name)


column = col


def lit(value) -> Column:
    return Column(
        lambda t: np.full(len(t), None, object) if value is None
        else np.full(len(t), value), repr(value)
    )


def when(cond: Column, value) -> _WhenColumn:
    return _WhenColumn([(cond, Column._coerce(value))])


def desc(name: str) -> _SortKey:
    return _SortKey(name, ascending=False)


def asc(name: str) -> _SortKey:
    return _SortKey(name, ascending=True)


class _AggColumn:
    """Marker from aggregate functions, consumed by ``GroupedData.agg``."""

    def __init__(self, fn: str, col_name: str, out: str):
        self.fn, self.col_name, self.out = fn, col_name, out

    def alias(self, name: str) -> "_AggColumn":
        return _AggColumn(self.fn, self.col_name, name)


def _agg_fn(fn: str):
    def make(col_name="*") -> _AggColumn:
        name = col_name if isinstance(col_name, str) else getattr(
            col_name, "_name", "col")
        return _AggColumn(fn, name, f"{fn}({name})")

    make.__name__ = fn
    return make


count = _agg_fn("count")
spark_sum = _agg_fn("sum")
spark_min = _agg_fn("min")
spark_max = _agg_fn("max")
avg = _agg_fn("mean")
mean = avg
first = _agg_fn("first")
countDistinct = _agg_fn("count_distinct")
collect_list = _agg_fn("collect_list")
collect_set = _agg_fn("collect_set")


class _UDFCol(Column):
    """Pending ``udf(...)(column)`` application (``Graphframes.py:71-72``)."""

    def __init__(self, fn, col):
        self.fn, self.col = fn, col
        super().__init__(self.evaluate, "udf")

    def evaluate(self, table: Table) -> np.ndarray:
        if isinstance(self.col, Column):
            vals = _as_arr(self.col._eval(table))
        elif isinstance(self.col, str):
            vals = table[self.col]
        else:
            vals = np.asarray(self.col)
        out = np.frompyfunc(
            lambda v: None if v is None else self.fn(v), 1, 1
        )(vals)
        return out.astype(object)


def udf(f, returnType=None):
    """``pyspark.sql.functions.udf`` (``Graphframes.py:61``). The wrapped
    function is applied per row host-side — the reference's semantics; the
    vectorized path is ``Table.to_edge_table`` / ``GraphFrame`` factorize."""
    return lambda col: _UDFCol(f, col)


class _MonotonicId:
    """Marker from ``monotonically_increasing_id()`` (``Graphframes.py:38``)."""


def monotonically_increasing_id() -> _MonotonicId:
    return _MonotonicId()


# ---------------------------------------------------------------------------
# RDD — the driver-side element view (Graphframes.py:53, :67)
# ---------------------------------------------------------------------------


class RDD:
    """List-backed RDD: the reference uses it only for the vertex-dictionary
    idiom (``flatMap``/``distinct``/``map``/``toDF``), all driver-side."""

    def __init__(self, elems):
        self._e = list(elems)

    def flatMap(self, f) -> "RDD":
        out = []
        for x in self._e:
            out.extend(f(x))
        return RDD(out)

    def map(self, f) -> "RDD":
        return RDD([f(x) for x in self._e])

    def filter(self, f) -> "RDD":
        return RDD([x for x in self._e if f(x)])

    def distinct(self) -> "RDD":
        return RDD(dict.fromkeys(self._e))

    def count(self) -> int:
        return len(self._e)

    def collect(self) -> list:
        return list(self._e)

    def toDF(self, names: Sequence[str]) -> "DataFrame":
        rows = [x if isinstance(x, (tuple, list)) else (x,) for x in self._e]
        return DataFrame(Table.from_records(rows, names))


# ---------------------------------------------------------------------------
# DataFrame — pyspark.sql.DataFrame facade over Table
# ---------------------------------------------------------------------------


class DataFrame:
    """Facade over :class:`Table` with Spark method spellings and Row-based
    ``collect`` (cached: the reference re-collects inside loops,
    ``Graphframes.py:102, :110``)."""

    def __init__(self, table: Table):
        self._t = table
        self._rows: list | None = None

    # table delegation ------------------------------------------------------

    @property
    def columns(self) -> list:
        return self._t.columns

    def count(self) -> int:
        return self._t.count()

    def withColumnRenamed(self, a: str, b: str) -> "DataFrame":
        return DataFrame(self._t.with_column_renamed(a, b))

    def filter(self, cond) -> "DataFrame":
        if isinstance(cond, Column):
            cond = _as_bool(cond._eval(self._t))
        return DataFrame(self._t.filter(cond))

    where = filter

    def select(self, *exprs) -> "DataFrame":
        if not any(isinstance(e, Column) for e in exprs):
            return DataFrame(self._t.select(*exprs))
        cols: dict = {}

        def put(name, values):
            if name in cols:  # a dict cannot hold Spark's duplicate columns
                raise ValueError(
                    f"duplicate output column {name!r} in select; alias() one"
                )
            cols[name] = values

        for e in exprs:
            if isinstance(e, Column):
                put(e._name, _as_arr(e._eval(self._t)))
            else:
                for name in [e] if isinstance(e, str) else e:
                    put(name, self._t[name])
        return DataFrame(Table(cols))

    def withColumn(self, name: str, value) -> "DataFrame":
        if isinstance(value, _MonotonicId):
            return DataFrame(self._t.with_row_ids(name))
        if isinstance(value, Column):
            value = _as_arr(value._eval(self._t))
        return DataFrame(self._t.with_column(name, value))

    def __getitem__(self, name: str) -> Column:
        if name not in self._t.columns:
            raise KeyError(name)
        return col(name)

    def __getattr__(self, name: str) -> Column:
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._t.columns:
            return col(name)
        raise AttributeError(name)

    def distinct(self) -> "DataFrame":
        return DataFrame(self._t.distinct())

    def dropDuplicates(self, subset=None) -> "DataFrame":
        return DataFrame(self._t.drop_duplicates(subset))

    def drop(self, *names) -> "DataFrame":
        return DataFrame(self._t.drop(*names))

    def dropna(self, how: str = "any", thresh: int | None = None,
               subset=None) -> "DataFrame":
        cols = subset or self._t.columns
        nulls = np.column_stack([_isnull(self._t[c]) for c in cols])
        if thresh is not None:  # Spark: keep rows with >= thresh non-nulls
            keep = (~nulls).sum(axis=1) >= thresh
        elif how == "all":
            keep = ~nulls.all(axis=1)
        else:
            keep = ~nulls.any(axis=1)
        return DataFrame(self._t.filter(keep))

    def fillna(self, value, subset=None) -> "DataFrame":
        return DataFrame(self._t.fillna(value, subset))

    def sort(self, *by, ascending=True) -> "DataFrame":
        """pyspark forms: names, Columns, F.desc/F.asc keys, or
        ``ascending=[bool, ...]`` (one per key)."""
        if isinstance(ascending, (list, tuple)):
            defaults = [bool(a) for a in ascending]
            if len(defaults) != len(by):
                raise ValueError(
                    f"ascending has {len(defaults)} entries for {len(by)} keys"
                )
        else:
            defaults = [bool(ascending)] * len(by)
        names, flags = [], []
        for b, d in zip(by, defaults):
            if isinstance(b, _SortKey):
                names.append(b.name)
                flags.append(b.ascending)
            elif isinstance(b, Column):
                names.append(b._name)
                flags.append(d)
            else:
                names.append(b)
                flags.append(d)
        return DataFrame(self._t.sort(*names, ascending=flags))

    orderBy = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._t.limit(n))

    def subtract(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._t.subtract(other._t))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._t.union(other._t))

    unionAll = union

    def join(self, other: "DataFrame", on, how: str = "inner") -> "DataFrame":
        return DataFrame(self._t.join(other._t, on, how))

    def groupBy(self, *names):
        grouped = self._t.group_by(*names)
        return _GroupedData(grouped)

    groupby = groupBy

    def agg(self, *specs, **named) -> "DataFrame":
        plain = []
        for s in specs:  # pyspark: df.agg(F.sum("v"), ...) markers
            if isinstance(s, _AggColumn):
                named[s.out] = (s.col_name, s.fn)
            else:
                plain.append(s)
        return DataFrame(self._t.agg(*plain, **named))

    def show(self, n: int = 20, truncate=True) -> None:
        width = 20 if truncate is True else (0 if truncate is False else int(truncate))
        self._t.show(n, truncate=width)

    def persist(self, *a) -> "DataFrame":
        return self  # eager engine: materialize-once is automatic

    cache = persist

    def unpersist(self, *a) -> "DataFrame":
        return self

    def collect(self) -> list:
        if self._rows is None:
            names = self._t.columns
            cols = [self._t[c] for c in names]
            self._rows = [Row._make(vals, names) for vals in zip(*cols)]
        return list(self._rows)  # fresh list per call, as in pyspark

    def head(self, n: int | None = None):
        """pyspark semantics: ``head()`` → first Row or None; ``head(n)`` →
        list of Rows."""
        if n is None:
            rows = self.limit(1).collect()
            return rows[0] if rows else None
        return self.limit(n).collect()

    def first(self):
        return self.head()

    def take(self, n: int) -> list:
        return self.head(n)

    def toPandas(self):
        import pandas as pd

        return pd.DataFrame(self._t.to_dict())

    @property
    def rdd(self) -> RDD:
        return RDD(self.collect())

    @property
    def write(self) -> "_DataFrameWriter":
        return _DataFrameWriter(self)

    @property
    def schema(self):
        return self._t.schema

    def __repr__(self) -> str:
        return "DataFrame[" + ", ".join(
            f"{c}: {self._t.schema[c]}" for c in self.columns
        ) + "]"


class _GroupedData:
    def __init__(self, grouped):
        self._g = grouped

    def count(self) -> DataFrame:
        return DataFrame(self._g.count())

    def agg(self, *specs, **named) -> DataFrame:
        plain = []
        for s in specs:  # F.sum("v").alias("total") markers → kwargs form
            if isinstance(s, _AggColumn):
                named[s.out] = (s.col_name, s.fn)
            else:
                plain.append(s)
        return DataFrame(self._g.agg(*plain, **named))

    def sum(self, *cols) -> DataFrame:
        return DataFrame(self._g.sum(*cols))

    def min(self, *cols) -> DataFrame:
        return DataFrame(self._g.min(*cols))

    def max(self, *cols) -> DataFrame:
        return DataFrame(self._g.max(*cols))

    def mean(self, *cols) -> DataFrame:
        return DataFrame(self._g.mean(*cols))

    avg = mean


# ---------------------------------------------------------------------------
# Session objects (Graphframes.py:12-14)
# ---------------------------------------------------------------------------


class SparkConf:
    def __init__(self):
        self._conf: dict = {}

    def set(self, k, v) -> "SparkConf":
        self._conf[k] = v
        return self

    def setAppName(self, name) -> "SparkConf":
        return self.set("spark.app.name", name)

    def setMaster(self, master) -> "SparkConf":
        return self.set("spark.master", master)

    def get(self, k, default=None):
        return self._conf.get(k, default)


class SparkContext:
    """``SparkContext("local[*]")`` (``Graphframes.py:12``). There is no JVM
    to launch: the TPU mesh is the runtime (``parallel/mesh.py``)."""

    def __init__(self, master: str | None = None, appName: str | None = None,
                 conf: SparkConf | None = None, **kw):
        self.master = master or "local[*]"
        self.appName = appName or "graphmine_tpu"

    def parallelize(self, data, numSlices=None) -> RDD:
        return RDD(data)

    def stop(self) -> None:
        pass

    def setLogLevel(self, level) -> None:
        pass


class _DataFrameReader:
    def parquet(self, *paths: str) -> DataFrame:
        tables = [Table.read_parquet(p) for p in paths]
        out = tables[0]
        for t in tables[1:]:
            out = out.union(t)
        return DataFrame(out)

    def csv(self, path: str, header: bool = False, sep: str = ",",
            inferSchema: bool = False) -> DataFrame:
        # Spark default: all-string columns unless inferSchema=True
        return DataFrame(Table.read_csv(path, header=header, sep=sep,
                                        infer_schema=inferSchema))


class _DataFrameWriter:
    """``df.write.mode("overwrite").parquet(path)`` — Spark's writer chain.

    Default mode is ``error`` (refuse to clobber an existing path), as in
    Spark; the target is a single file, not a part-file directory."""

    def __init__(self, df: DataFrame, mode: str = "error"):
        self._df = df
        self._mode = mode

    def mode(self, m: str) -> "_DataFrameWriter":
        if m not in ("error", "errorifexists", "overwrite", "ignore"):
            raise ValueError(f"unsupported write mode {m!r}")
        return _DataFrameWriter(self._df, m)

    def _check(self, path: str) -> bool:
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(f"path already exists: {path!r}")
            if self._mode == "ignore":
                return False
        return True

    def parquet(self, path: str, compression: str = "snappy") -> None:
        if self._check(path):
            self._df._t.write_parquet(path, compression=compression)

    def csv(self, path: str, header: bool = False) -> None:
        if self._check(path):
            self._df._t.write_csv(path, header=header)


class _SessionBuilder:
    def __init__(self):
        self._conf: dict = {}

    def appName(self, name) -> "_SessionBuilder":
        self._conf["spark.app.name"] = name
        return self

    def master(self, master) -> "_SessionBuilder":
        self._conf["spark.master"] = master
        return self

    def config(self, key=None, value=None, conf=None) -> "_SessionBuilder":
        if key is not None:
            self._conf[key] = value
        return self

    def enableHiveSupport(self) -> "_SessionBuilder":
        return self

    def getOrCreate(self) -> "SparkSession":
        return SparkSession()


class SparkSession:
    """``SparkSession.builder.appName(...).getOrCreate()``
    (``Graphframes.py:13``)."""

    builder = _SessionBuilder()

    def __init__(self):
        self.sparkContext = SparkContext()

    @property
    def read(self) -> _DataFrameReader:
        return _DataFrameReader()

    def createDataFrame(self, data, schema: Sequence[str]) -> DataFrame:
        return DataFrame(Table.from_records(list(data), list(schema)))

    def stop(self) -> None:
        pass


class SQLContext:
    """Legacy ``SQLContext(sc)`` (``Graphframes.py:14``)."""

    def __init__(self, sparkContext: SparkContext | None = None):
        self._session = SparkSession()

    @property
    def read(self) -> _DataFrameReader:
        return _DataFrameReader()

    def createDataFrame(self, data, schema: Sequence[str]) -> DataFrame:
        return self._session.createDataFrame(data, schema)


# ---------------------------------------------------------------------------
# graphframes.GraphFrame facade (Graphframes.py:78-81)
# ---------------------------------------------------------------------------


class GraphFrame:
    """GraphFrames' result convention over the TPU engine: algorithms return
    *DataFrames* of the vertex table plus a result column (``label``,
    ``component``, ``pagerank``, ...), exactly what the reference consumes at
    ``Graphframes.py:82-104``."""

    def __init__(self, v: DataFrame, e: DataFrame):
        v_t = v._t if isinstance(v, DataFrame) else Table(v)
        e_t = e._t if isinstance(e, DataFrame) else Table(e)
        self._gf = _frames.GraphFrame(v_t, e_t)  # string-id factorize path
        self._v = DataFrame(Table(self._gf.vertices))
        self._e = e if isinstance(e, DataFrame) else DataFrame(e_t)

    @property
    def vertices(self) -> DataFrame:
        return self._v

    @property
    def edges(self) -> DataFrame:
        return self._e

    def _with_result(self, name: str, values: np.ndarray) -> DataFrame:
        cols = _visible_vertex_cols(self._gf)
        cols[name] = np.asarray(values)
        return DataFrame(Table(cols))

    def labelPropagation(self, maxIter: int = 5) -> DataFrame:
        labels = np.asarray(self._gf.label_propagation(max_iter=maxIter))
        return self._with_result("label", labels.astype(np.int64))

    def connectedComponents(self, **kw) -> DataFrame:
        comp = np.asarray(self._gf.connected_components(**kw))
        return self._with_result("component", comp.astype(np.int64))

    def stronglyConnectedComponents(self, maxIter: int | None = None) -> DataFrame:
        comp = np.asarray(self._gf.strongly_connected_components())
        return self._with_result("component", comp.astype(np.int64))

    def pageRank(self, resetProbability: float = 0.15, maxIter: int = 100,
                 tol: float = 1e-6, sourceId=None) -> "GraphFrame":
        """GraphFrames convention: returns a *GraphFrame* whose vertices
        carry ``pagerank`` and whose edges carry ``weight`` (the uniform
        transition probability 1/outdeg(src))."""
        if sourceId is not None:
            reset = np.zeros(self._gf.num_vertices, dtype=np.float32)
            reset[self._vertex_index(sourceId)] = 1.0
            ranks = self._gf.pagerank(alpha=1.0 - resetProbability,
                                      max_iter=maxIter, tol=tol, reset=reset)
        else:
            ranks = self._gf.pagerank(alpha=1.0 - resetProbability,
                                      max_iter=maxIter, tol=tol)
        out = np.asarray(self._gf.out_degrees()).astype(np.float64)
        weight = 1.0 / np.maximum(out, 1.0)[self._gf.edges["src"]]
        return self._result_frame(
            "pagerank", np.asarray(ranks, dtype=np.float64), "weight", weight
        )

    def _result_frame(self, vname, vvalues, ename=None, evalues=None) -> "GraphFrame":
        g = object.__new__(GraphFrame)
        g._gf = self._gf
        vcols = _visible_vertex_cols(self._gf)
        vcols[vname] = vvalues
        g._v = DataFrame(Table(vcols))
        ecols = dict(self._e._t.to_dict())
        if ename is not None:
            ecols[ename] = evalues
        g._e = DataFrame(Table(ecols))
        return g

    def triangleCount(self) -> DataFrame:
        tri, _total = self._gf.triangle_count()
        return self._with_result("count", np.asarray(tri).astype(np.int64))

    @property
    def degrees(self) -> DataFrame:
        return self._with_result("degree", np.asarray(self._gf.degrees()))

    @property
    def inDegrees(self) -> DataFrame:
        return self._with_result("inDegree", np.asarray(self._gf.in_degrees()))

    @property
    def outDegrees(self) -> DataFrame:
        return self._with_result("outDegree", np.asarray(self._gf.out_degrees()))

    def shortestPaths(self, landmarks) -> DataFrame:
        idx = [self._vertex_index(l) for l in landmarks]
        dist = np.asarray(self._gf.shortest_paths(np.asarray(idx, np.int32)))
        unreachable = np.iinfo(np.int32).max
        dcol = np.empty(self._gf.num_vertices, dtype=object)
        for v in range(self._gf.num_vertices):
            dcol[v] = {
                lm: int(dist[v, j]) for j, lm in enumerate(landmarks)
                if 0 <= dist[v, j] < unreachable
            }
        return self._with_result("distances", dcol)

    def aggregateMessages(self, *aggs, sendToSrc=None, sendToDst=None) -> DataFrame:
        """GraphFrames ``aggregateMessages``: message expressions over the
        triplet namespace (``AM.src["attr"]``, ``AM.dst["attr"]``,
        ``AM.edge["attr"]``), aggregated per receiving vertex with
        ``F.<fn>(AM.msg)`` markers. Returns ``[id, <agg columns...>]`` for
        vertices that received at least one message (GraphFrames drops the
        rest)."""
        if sendToSrc is None and sendToDst is None:
            raise ValueError("provide sendToSrc and/or sendToDst")
        if not aggs:
            raise ValueError("provide at least one aggregate (e.g. F.sum(AM.msg))")
        for expr in (sendToSrc, sendToDst):
            if expr is not None and not isinstance(expr, Column):
                raise TypeError(
                    "sendToSrc/sendToDst must be Columns over the AM "
                    "namespace (AM.src['attr'], AM.dst['attr'], ...), got "
                    f"{expr!r}"
                )
        ids = self._ids()
        e_src = np.asarray(self._gf.edges["src"])
        e_dst = np.asarray(self._gf.edges["dst"])
        tcols: dict = {}
        for name, col in _visible_vertex_cols(self._gf).items():
            arr = np.asarray(col)
            tcols[f"src_{name}"] = arr[e_src]
            tcols[f"dst_{name}"] = arr[e_dst]
        for name, col in self._gf.edges.items():
            if name not in ("src", "dst"):
                tcols[f"edge_{name}"] = np.asarray(col)
        triplets = Table(tcols)

        recv_parts, msg_parts = [], []
        if sendToDst is not None:
            msg_parts.append(_as_arr(sendToDst._eval(triplets)))
            recv_parts.append(ids[e_dst])
        if sendToSrc is not None:
            msg_parts.append(_as_arr(sendToSrc._eval(triplets)))
            recv_parts.append(ids[e_src])
        msg_table = Table({
            "id": np.concatenate(recv_parts),
            "msg": np.concatenate(msg_parts),
        })
        named = {}
        for a in aggs:
            if not isinstance(a, _AggColumn):
                raise TypeError(
                    f"aggregates must be F.<fn>(AM.msg) markers, got {a!r}"
                )
            if a.col_name != "msg":
                raise TypeError(
                    "aggregateMessages aggregates operate on AM.msg, got "
                    f"a reference to {a.col_name!r}"
                )
            named[a.out] = ("msg", a.fn)
        return DataFrame(msg_table.group_by("id").agg(**named))

    # -- expression-driven surfaces (GraphFrames SQL strings) --------------

    def _ids(self) -> np.ndarray:
        ids = self._gf.vertices.get("id")
        return np.arange(self._gf.num_vertices) if ids is None else np.asarray(ids)

    def _vertex_sql_mask(self, expr) -> np.ndarray:
        return _sql_mask(expr, self._gf.vertices, self._gf.num_vertices)

    def bfs(self, fromExpr, toExpr, edgeFilter=None,
            maxPathLength: int = 10) -> DataFrame:
        """GraphFrames ``bfs``: SQL expression strings (or boolean masks)
        select the endpoint sets; returns the paths DataFrame with columns
        ``from, e0, v1, e1, ..., to`` — vertex cells hold the vertex id,
        edge cells ``(src_id, dst_id)`` pairs. ``edgeFilter``: SQL
        expression (or mask) over the edge columns (id-valued ``src``/
        ``dst``, GraphFrames semantics) restricting traversable edges;
        the vertex set is unchanged."""
        if edgeFilter is not None:
            return self.filterEdges(edgeFilter).bfs(
                fromExpr, toExpr, maxPathLength=maxPathLength
            )
        from graphmine_tpu.ops.paths import bfs as _bfs

        src_ids = np.flatnonzero(self._vertex_sql_mask(fromExpr))
        dst_ids = np.flatnonzero(self._vertex_sql_mask(toExpr))
        ids = self._ids()
        if maxPathLength <= 0:  # GraphFrames: no traversal, zero-hop only
            paths = [np.array([v], np.int32)
                     for v in np.intersect1d(src_ids, dst_ids)]
        else:
            paths = _bfs(self._gf.graph(symmetric=False), src_ids, dst_ids,
                         max_path_length=maxPathLength)
        if not paths:
            return DataFrame(Table({"from": np.empty(0, object),
                                    "to": np.empty(0, object)}))
        hops = len(paths[0]) - 1
        names = ["from"] + [
            x for i in range(1, hops) for x in (f"e{i-1}", f"v{i}")
        ] + ([f"e{hops-1}"] if hops else []) + ["to"]
        rows = []
        for p in paths:
            cells = [ids[p[0]]]
            for i in range(hops):
                cells.append((ids[p[i]], ids[p[i + 1]]))
                cells.append(ids[p[i + 1]])
            rows.append(cells if hops else [ids[p[0]], ids[p[0]]])
        cols = {}
        for j, name in enumerate(names):  # object columns: cells may be tuples
            col = np.empty(len(rows), dtype=object)
            for i, r in enumerate(rows):
                col[i] = r[j]
            cols[name] = col
        return DataFrame(Table(cols))

    def find(self, pattern: str) -> DataFrame:
        """GraphFrames motif ``find``: one row per match; named vertices
        are id columns, named edges ``(src_id, dst_id)`` pairs."""
        res = self._gf.find(pattern)
        ids = self._ids()
        cols: dict = {}
        for name, vals in res.vertices.items():
            cols[name] = ids[np.asarray(vals)]
        e_src = np.asarray(self._gf.edges["src"])
        e_dst = np.asarray(self._gf.edges["dst"])
        for name, rows_ in res.edges.items():
            idx = np.asarray(rows_, dtype=np.int64)
            pair_src, pair_dst = ids[e_src[idx]], ids[e_dst[idx]]
            cols[name] = np.fromiter(
                zip(pair_src, pair_dst), dtype=object, count=len(idx)
            )
        return DataFrame(Table(cols))

    def filterVertices(self, condition) -> "GraphFrame":
        sub = self._gf.filter_vertices(self._vertex_sql_mask(condition))
        return _wrap_engine(sub)

    def filterEdges(self, condition) -> "GraphFrame":
        # Predicates see id-valued src/dst (GraphFrames semantics), not the
        # engine's dense indices.
        ids = self._ids()
        view = dict(self._gf.edges)
        view["src"] = ids[np.asarray(view["src"])]
        view["dst"] = ids[np.asarray(view["dst"])]
        mask = _sql_mask(condition, view, self._gf.num_edges)
        return _wrap_engine(self._gf.filter_edges(mask))

    def dropIsolatedVertices(self) -> "GraphFrame":
        return _wrap_engine(self._gf.drop_isolated_vertices())

    def _vertex_index(self, vid) -> int:
        ids = self._gf.vertices.get("id")
        if ids is None:
            return int(vid)
        hits = np.flatnonzero(ids == vid)
        if len(hits) == 0:
            raise KeyError(f"vertex id {vid!r} not found")
        return int(hits[0])

    def persist(self, *a) -> "GraphFrame":
        return self

    cache = persist

    def __repr__(self) -> str:
        return repr(self._gf)


class _AMSide:
    """``AM.src`` / ``AM.dst`` / ``AM.edge``: attribute access yields a
    Column over the triplet namespace of :meth:`GraphFrame.aggregateMessages`."""

    def __init__(self, side: str):
        self._side = side

    def __getitem__(self, attr: str) -> Column:
        side = self._side
        return Column(lambda tr: tr[f"{side}_{attr}"], f"{side}[{attr!r}]")

    def __getattr__(self, attr: str) -> Column:
        if attr.startswith("_"):
            raise AttributeError(attr)
        return self[attr]


class AggregateMessages:
    """``graphframes.lib.AggregateMessages`` — the triplet column namespace."""

    src = _AMSide("src")
    dst = _AMSide("dst")
    edge = _AMSide("edge")
    msg = Column(lambda tr: tr["msg"], "msg")


def _friends_graph() -> "GraphFrame":
    """``graphframes.examples.Graphs.friends()`` — the canonical GraphFrames
    docs graph (7 people, 8 relationship edges)."""
    v = Table(
        id=np.array(list("abcdefg"), dtype=object),
        name=np.array(["Alice", "Bob", "Charlie", "David", "Esther",
                       "Fanny", "Gabby"], dtype=object),
        age=np.array([34, 36, 30, 29, 32, 36, 60]),
    )
    e = Table(
        src=np.array(list("abcfeeda"), dtype=object),
        dst=np.array(list("bcbcfdae"), dtype=object),
        relationship=np.array(["friend", "follow", "follow", "follow",
                               "follow", "friend", "friend", "friend"],
                              dtype=object),
    )
    return GraphFrame(DataFrame(v), DataFrame(e))


class _Graphs:
    def __init__(self, *a):  # GraphFrames: Graphs(spark).friends()
        pass

    @staticmethod
    def friends() -> "GraphFrame":
        return _friends_graph()


def _sql_mask(expr, columns, n: int) -> np.ndarray:
    """SQL predicate string (GraphFrames expression surface) or boolean
    mask/callable → boolean mask over ``columns``."""
    if isinstance(expr, str):
        from graphmine_tpu.table import _PredicateParser, _tokenize

        return _PredicateParser(_tokenize(expr), columns, n).parse()
    if callable(expr) and not isinstance(expr, np.ndarray):
        return np.asarray(expr(columns), dtype=bool)
    return np.asarray(expr, dtype=bool)


def _visible_vertex_cols(gf: "_frames.GraphFrame") -> dict:
    """Vertex columns a GraphFrames user should see: engine bookkeeping
    (the ``orig`` root-frame index threaded through filters) stays hidden."""
    cols = {k: v for k, v in gf.vertices.items() if k != "orig"}
    return cols or {"id": np.arange(gf.num_vertices, dtype=np.int64)}


def _wrap_engine(gf: "_frames.GraphFrame") -> "GraphFrame":
    """Wrap an engine GraphFrame (e.g. a filtered subgraph) without
    re-running id factorization. Edges are shown with id-valued src/dst
    (the GraphFrames convention), not the engine's dense indices."""
    g = object.__new__(GraphFrame)
    g._gf = gf
    vcols = _visible_vertex_cols(gf)
    g._v = DataFrame(Table(vcols))
    ids = vcols.get("id")
    ecols = dict(gf.edges)
    if ids is not None:
        ids = np.asarray(ids)
        ecols["src"] = ids[np.asarray(ecols["src"])]
        ecols["dst"] = ids[np.asarray(ecols["dst"])]
    g._e = DataFrame(Table(ecols))
    return g


# ---------------------------------------------------------------------------
# module installation + script runner
# ---------------------------------------------------------------------------


def _build_modules() -> dict:
    pyspark = types.ModuleType("pyspark")
    sql = types.ModuleType("pyspark.sql")
    functions = types.ModuleType("pyspark.sql.functions")
    graphframes = types.ModuleType("graphframes")

    pyspark.SparkContext = SparkContext
    pyspark.SparkConf = SparkConf
    pyspark.sql = sql
    pyspark.__all__ = ["SparkContext", "SparkConf", "sql"]
    pyspark.__doc__ = "graphmine_tpu compat shim (not real pyspark)"

    sql.SparkSession = SparkSession
    sql.SQLContext = SQLContext
    sql.DataFrame = DataFrame
    sql.Row = Row
    sql.Column = Column
    sql.functions = functions
    sql.__all__ = ["SparkSession", "SQLContext", "DataFrame", "Row", "Column",
                   "functions"]

    functions.udf = udf
    functions.monotonically_increasing_id = monotonically_increasing_id
    functions.col = col
    functions.column = column
    functions.lit = lit
    functions.when = when
    functions.desc = desc
    functions.asc = asc
    functions.count = count
    functions.sum = spark_sum
    functions.min = spark_min
    functions.max = spark_max
    functions.avg = avg
    functions.mean = mean
    functions.first = first
    functions.countDistinct = countDistinct
    functions.collect_list = collect_list
    functions.collect_set = collect_set
    functions.__all__ = [
        "udf", "monotonically_increasing_id", "col", "column", "lit", "when",
        "desc", "asc", "count", "sum", "min", "max", "avg", "mean", "first",
        "countDistinct", "collect_list", "collect_set",
    ]

    graphframes.GraphFrame = GraphFrame
    graphframes.__all__ = ["GraphFrame"]
    gf_lib = types.ModuleType("graphframes.lib")
    gf_lib.AggregateMessages = AggregateMessages
    gf_lib.__all__ = ["AggregateMessages"]
    graphframes.lib = gf_lib
    gf_examples = types.ModuleType("graphframes.examples")
    gf_examples.Graphs = _Graphs
    gf_examples.__all__ = ["Graphs"]
    graphframes.examples = gf_examples

    return {
        "pyspark": pyspark,
        "pyspark.sql": sql,
        "pyspark.sql.functions": functions,
        "graphframes": graphframes,
        "graphframes.lib": gf_lib,
        "graphframes.examples": gf_examples,
    }


def install(force: bool = False) -> dict:
    """Register the shim modules in ``sys.modules``; returns them.

    Refuses to shadow a real pyspark — imported *or* merely installed —
    unless ``force=True``. All existing ``pyspark*``/``graphframes*``
    entries are purged first so forced installs can't leave a mix of real
    submodules under shim parents."""
    mod = sys.modules.get("pyspark")
    ours = mod is not None and "graphmine_tpu compat shim" in (mod.__doc__ or "")
    if not force and not ours:
        if mod is not None:
            raise RuntimeError(
                "a real pyspark is already imported; pass force=True to shadow it"
            )
        import importlib.util

        try:
            spec = importlib.util.find_spec("pyspark")
        except (ImportError, ValueError):
            spec = None
        if spec is not None:
            raise RuntimeError(
                "a real pyspark is installed; pass force=True to shadow it"
            )
    for name in list(sys.modules):
        if name.split(".", 1)[0] in ("pyspark", "graphframes"):
            del sys.modules[name]
    mods = _build_modules()
    sys.modules.update(mods)
    return mods


def main(argv: Sequence[str] | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="Run an unmodified pyspark/GraphFrames script on the "
        "TPU-native engine (reference parity: CommunityDetection/Graphframes.py)"
    )
    p.add_argument("script", help="path to the pyspark script")
    p.add_argument(
        "--cwd", default=None,
        help="directory to run in (default: the script's own directory, so "
        "relative data paths like the reference's resolve)",
    )
    args = p.parse_args(argv)
    path = os.path.abspath(args.script)
    # Invoking this runner IS the request to use the shim, so shadow any
    # real pyspark for this process.
    install(force=True)
    os.chdir(args.cwd or os.path.dirname(path) or ".")
    runpy.run_path(path, run_name="__main__")


if __name__ == "__main__":
    main()
