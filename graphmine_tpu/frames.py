"""High-level ``GraphFrame`` API — the reference user's one-stop surface.

The reference drives everything through a GraphFrames ``GraphFrame`` object
(``Graphframes.py:78``: ``GraphFrame(Graph_Vertices, Graph_Edges)``, then
``.labelPropagation(maxIter=5)`` at ``:81``). This module gives a migrating
user the same shaped object over the TPU-native engine:

==============================  =======================================
GraphFrames                     graphmine_tpu.frames.GraphFrame
==============================  =======================================
``GraphFrame(v_df, e_df)``      ``GraphFrame(v_table, e_table)`` — works
                                verbatim: an ``id`` vertex column plus
                                string/int ``src``/``dst`` endpoints are
                                factorized to dense indices on the spot
                                (string endpoints also work without a
                                vertex table)
``g.vertices / g.edges``        ``g.vertices / g.edges`` (dict of columns)
``g.degrees/inDegrees/...``     ``g.degrees()/in_degrees()/out_degrees()``
``g.labelPropagation(5)``       ``g.label_propagation(max_iter=5)``
``g.connectedComponents()``     ``g.connected_components()``
``g.stronglyConnectedComponents()``  ``g.strongly_connected_components()``
``g.pageRank(0.15, 20)``        ``g.pagerank(alpha=0.85, max_iter=20)``
``g.shortestPaths(landmarks)``  ``g.shortest_paths(landmarks)``
``g.triangleCount()``           ``g.triangle_count()``
``g.bfs(from, to)``             ``g.bfs(from_, to)``
``g.find(motif)``               ``g.find(motif)``
``g.aggregateMessages(...)``    ``g.aggregate_messages(...)``
``g.filterVertices(expr)``      ``g.filter_vertices(mask_or_fn)``
``g.filterEdges(expr)``         ``g.filter_edges(mask_or_fn)``
``g.dropIsolatedVertices()``    ``g.drop_isolated_vertices()``
==============================  =======================================

camelCase aliases are provided for every row above, so GraphFrames call
sites typically need only expression→array changes. Where GraphFrames takes
SQL expression strings, this API takes boolean masks or callables over the
column dict — host-side vectorized NumPy, never per-row Python.

Beyond GraphFrames parity the same object exposes the framework extras:
``louvain``, ``modularity``, ``core_numbers``, ``clustering_coefficient``,
``lof_scores``, ``recursive_lpa_outliers``, ``census``, ``pregel``.

Vertices are dense int32 ids ``0..V-1`` (the factorize scheme replacing the
reference's sha1[:8] ``NodeHash``, ``Graphframes.py:57-58``). Filtering
re-indexes densely and threads an ``"orig"`` vertex column through, so ids
always map back to the originating frame.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

from graphmine_tpu.graph.container import Graph, build_graph
from graphmine_tpu.io.edges import EdgeTable
from graphmine_tpu.table import Table

_MaskLike = Any  # bool array [N], int index array, or fn(columns) -> mask


def _endpoint_lookup(ids: np.ndarray):
    """id value → dense vertex index, vectorized via one sort; raises on
    duplicate ids or endpoints absent from ``ids``."""
    ids = np.asarray(ids)
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]
    if len(sorted_ids) > 1 and (sorted_ids[1:] == sorted_ids[:-1]).any():
        dup = sorted_ids[:-1][sorted_ids[1:] == sorted_ids[:-1]][:5]
        raise ValueError(f"duplicate vertex ids: {list(dup)!r}")

    def lookup(col: np.ndarray) -> np.ndarray:
        col = np.asarray(col)
        pos = np.clip(np.searchsorted(sorted_ids, col), 0, max(len(sorted_ids) - 1, 0))
        ok = sorted_ids[pos] == col if len(sorted_ids) else np.zeros(len(col), bool)
        if not np.all(ok):
            missing = col[~ok][:5]
            raise ValueError(
                f"edge endpoints not found in the vertex 'id' column: {list(missing)!r}"
            )
        return order[pos].astype(np.int32)

    return lookup


def _factorize_by_id(vertex_cols: Mapping, edge_cols: Mapping):
    """GraphFrames-style (vertices_df, edges_df) → dense-index columns.

    Vertex row ``i`` becomes vertex index ``i``; src/dst are re-written by
    looking endpoints up in the ``id`` column (string or int — replaces the
    reference's sha1 ``NodeHash`` join, ``Graphframes.py:57-74``). The
    ``id`` column is kept as a vertex attribute so results map back."""
    v = {k: np.asarray(c) for k, c in vertex_cols.items()}
    e = {k: np.asarray(c) for k, c in edge_cols.items()}
    look = _endpoint_lookup(v["id"])
    e["src"] = look(e["src"])
    e["dst"] = look(e["dst"])
    return e, v


class GraphFrame:
    """A property graph bound to the TPU-native engine.

    Parameters
    ----------
    edges : ``(src, dst)`` int array pair, a mapping with ``"src"``/``"dst"``
        plus optional edge-attribute columns, or an
        :class:`~graphmine_tpu.io.edges.EdgeTable`.
    vertices : optional mapping of vertex-attribute columns, each ``[V]``.
    num_vertices : optional; inferred from endpoints/columns otherwise.
    """

    def __init__(self, edges, vertices: Mapping[str, np.ndarray] | None = None,
                 num_vertices: int | None = None):
        if isinstance(edges, Table):
            edges = edges.to_dict()
        if isinstance(vertices, Table):
            vertices = vertices.to_dict()
        # GraphFrames positional shape — ``GraphFrame(vertices_df, edges_df)``
        # with an "id" vertex column and (possibly string) src/dst endpoints:
        # the reference's literal call site (``Graphframes.py:78``).
        if (
            isinstance(edges, Mapping) and "id" in edges and "src" not in edges
            and isinstance(vertices, Mapping) and "src" in vertices and "dst" in vertices
        ):
            edges, vertices = _factorize_by_id(vertex_cols=edges, edge_cols=vertices)
        if isinstance(edges, EdgeTable):
            if vertices is None:
                vertices = {"name": edges.names}
            edges = {"src": edges.src, "dst": edges.dst}
        if isinstance(edges, Mapping):
            cols = {k: np.asarray(v) for k, v in edges.items()}
            if "src" not in cols or "dst" not in cols:
                raise ValueError("edge mapping needs 'src' and 'dst' columns")
        else:
            src, dst = edges
            cols = {"src": np.asarray(src), "dst": np.asarray(dst)}
        if cols["src"].dtype.kind in "OUS":  # string endpoints, no vertex df:
            if vertices is not None and "id" in vertices:
                edges2, vertices = _factorize_by_id(vertex_cols=vertices, edge_cols=cols)
                cols = {k: np.asarray(v) for k, v in edges2.items()}
            else:  # factorize the union of endpoints into dense ids
                uniq = np.unique(np.concatenate([cols["src"], cols["dst"]]))
                look = _endpoint_lookup(uniq)
                cols = dict(cols, src=look(cols["src"]), dst=look(cols["dst"]))
                vertices = dict(vertices or {}, id=uniq)
        cols["src"] = cols["src"].astype(np.int32)
        cols["dst"] = cols["dst"].astype(np.int32)
        if len(cols["src"]) != len(cols["dst"]):
            raise ValueError("src/dst length mismatch")
        self.edges: dict[str, np.ndarray] = cols

        if num_vertices is None:
            hi = int(max(cols["src"].max(initial=-1), cols["dst"].max(initial=-1))) + 1
            if vertices is not None and vertices:
                hi = max(hi, max(len(np.asarray(c)) for c in vertices.values()))
            num_vertices = hi
        self.num_vertices = int(num_vertices)
        self.vertices: dict[str, np.ndarray] = (
            {k: np.asarray(v) for k, v in vertices.items()} if vertices else {}
        )
        for k, c in self.vertices.items():
            if len(c) != self.num_vertices:
                raise ValueError(f"vertex column {k!r} has length {len(c)}, want {self.num_vertices}")
        self.weight_col: str | None = "weight"  # set None to opt out
        self._graphs: dict = {}  # (symmetric, weighted) -> Graph
        self._tri = None  # cached ops.triangles._triangles result

    # -- engine binding ----------------------------------------------------

    @property
    def num_edges(self) -> int:
        return len(self.edges["src"])

    def edge_weights(self) -> np.ndarray | None:
        """The numeric ``weight`` edge column (GraphFrames convention), or
        None. Non-numeric 'weight' columns stay inert metadata; set
        ``self.weight_col`` to another name or ``None`` to opt out."""
        col = self.edges.get(self.weight_col) if self.weight_col else None
        if col is None or not np.issubdtype(np.asarray(col).dtype, np.number):
            return None
        return col

    def graph(self, symmetric: bool = True, weighted: bool = False) -> Graph:
        """The device-resident :class:`Graph` (cached per mode).

        ``weighted=True`` attaches :meth:`edge_weights` to the graph —
        requested by the weight-aware wrappers (louvain, modularity, and
        label_propagation(weighted=True); LPA defaults to unweighted for
        GraphX parity), so weight-indifferent ops (CC, triangles, BFS,
        ...) keep the native build path and the fused LPA kernel."""
        w = self.edge_weights() if weighted else None
        key = (symmetric, w is not None)
        if key not in self._graphs:
            self._graphs[key] = build_graph(
                self.edges["src"], self.edges["dst"],
                num_vertices=self.num_vertices, symmetric=symmetric,
                edge_weights=w,
            )
        return self._graphs[key]

    @classmethod
    def from_edge_table(cls, table: EdgeTable) -> "GraphFrame":
        return cls(table)

    def __repr__(self) -> str:
        vcols = list(self.vertices) or "-"
        ecols = [c for c in self.edges if c not in ("src", "dst")] or "-"
        return (
            f"GraphFrame(V={self.num_vertices}, E={self.num_edges}, "
            f"vertex_cols={vcols}, edge_cols={ecols})"
        )

    # -- masks -------------------------------------------------------------

    def _vertex_mask(self, cond: _MaskLike) -> np.ndarray:
        return self._mask(cond, self.vertices, self.num_vertices)

    def _edge_mask(self, cond: _MaskLike) -> np.ndarray:
        return self._mask(cond, self.edges, self.num_edges)

    @staticmethod
    def _mask(cond, columns, n) -> np.ndarray:
        if callable(cond):
            cond = cond(columns)
        cond = np.asarray(cond)
        if cond.dtype == bool:
            if len(cond) != n:
                raise ValueError(f"mask length {len(cond)} != {n}")
            return cond
        mask = np.zeros(n, dtype=bool)
        mask[cond] = True
        return mask

    # -- degrees -----------------------------------------------------------

    def degrees(self):
        from graphmine_tpu.ops.degrees import degrees
        return degrees(self.graph())

    def in_degrees(self):
        from graphmine_tpu.ops.degrees import in_degrees
        return in_degrees(self.graph())

    def out_degrees(self):
        from graphmine_tpu.ops.degrees import out_degrees
        return out_degrees(self.graph())

    # -- algorithms (GraphFrames parity) -----------------------------------

    def label_propagation(self, max_iter: int = 5, weighted: bool = False, **kw):
        """GraphX/GraphFrames parity: unweighted by default even when a
        'weight' column exists (their labelPropagation ignores weights).
        ``weighted=True`` opts into weight-sum LPA (sort path)."""
        from graphmine_tpu.ops.lpa import label_propagation
        max_iter = kw.pop("maxIter", max_iter)  # GraphFrames kwarg spelling
        return label_propagation(
            self.graph(weighted=weighted), max_iter=max_iter, **kw
        )

    def connected_components(self, **kw):
        from graphmine_tpu.ops.cc import connected_components
        return connected_components(self.graph(), **kw)

    def strongly_connected_components(self):
        from graphmine_tpu.ops.scc import strongly_connected_components
        return strongly_connected_components(self.graph(symmetric=False))

    def pagerank(self, alpha: float = 0.85, max_iter: int = 100, tol: float = 1e-6,
                 reset=None, weights=None, **kw):
        """``weights``: optional [E] non-negative edge weights aligned with
        the edge table order (rank splits across out-edges by weight);
        defaults to the numeric ``"weight"`` edge column when present.
        Note parallelPersonalizedPageRank is unweighted.

        GraphFrames kwarg spellings accepted: ``maxIter``,
        ``resetProbability`` (damping ``alpha = 1 - resetProbability``)."""
        from graphmine_tpu.ops.pagerank import pagerank
        max_iter = kw.pop("maxIter", max_iter)
        if "resetProbability" in kw:
            alpha = 1.0 - kw.pop("resetProbability")
        if kw:
            raise TypeError(f"unknown pagerank arguments: {sorted(kw)}")
        if weights is None:
            weights = self.edge_weights()
        return pagerank(self.graph(symmetric=False), alpha=alpha, max_iter=max_iter,
                        tol=tol, reset=reset, weights=weights)

    def shortest_paths(self, landmarks, direction: str = "out"):
        from graphmine_tpu.ops.paths import shortest_paths
        g = self.graph(symmetric=direction == "both")
        return shortest_paths(g, landmarks, direction=direction)

    def _triangle_cache(self):
        from graphmine_tpu.ops.triangles import _triangles
        if self._tri is None:
            self._tri = _triangles(self.graph())
        return self._tri

    def triangle_count(self):
        tri, total, _ = self._triangle_cache()
        return tri, total

    def bfs(self, from_: _MaskLike, to: _MaskLike, direction: str = "out",
            max_path_length: int = 10):
        """Shortest paths between vertex sets (GraphFrames ``bfs``).

        ``from_``/``to`` are boolean masks, id arrays, or callables over the
        vertex columns (the expression-string replacement).
        """
        from graphmine_tpu.ops.paths import bfs
        src_ids = np.nonzero(self._vertex_mask(from_))[0]
        dst_ids = np.nonzero(self._vertex_mask(to))[0]
        g = self.graph(symmetric=direction == "both")
        return bfs(g, src_ids, dst_ids, direction=direction,
                   max_path_length=max_path_length)

    def find(self, pattern: str):
        from graphmine_tpu.ops.motifs import find
        return find(self.graph(symmetric=False), pattern)

    def aggregate_messages(self, vertex_values, edge_values=None, *, to_dst=None,
                           to_src=None, reduce: str = "sum"):
        """Messages travel along directed edges; undirected flow is expressed
        by giving both ``to_dst`` and ``to_src`` (GraphFrames semantics)."""
        from graphmine_tpu.ops.aggregate import aggregate_messages
        return aggregate_messages(self.graph(symmetric=False), vertex_values,
                                  edge_values, to_dst=to_dst, to_src=to_src,
                                  reduce=reduce)

    def pregel(self, init_state, **kw):
        from graphmine_tpu.ops.aggregate import pregel
        return pregel(self.graph(symmetric=False), init_state, **kw)

    # -- subgraphs ---------------------------------------------------------

    def filter_vertices(self, cond: _MaskLike) -> "GraphFrame":
        """Induced subgraph on the vertices where ``cond`` holds.

        Ids are re-indexed densely; the ``"orig"`` vertex column maps back
        to ids of the frame this one was filtered from (threaded through
        repeated filters, so it always refers to the *root* frame).
        """
        keep = self._vertex_mask(cond)
        new_of_old = np.cumsum(keep, dtype=np.int64) - 1
        ekeep = keep[self.edges["src"]] & keep[self.edges["dst"]]
        edges = {k: c[ekeep] for k, c in self.edges.items()}
        edges["src"] = new_of_old[edges["src"]].astype(np.int32)
        edges["dst"] = new_of_old[edges["dst"]].astype(np.int32)
        vertices = {k: c[keep] for k, c in self.vertices.items()}
        if "orig" not in vertices:
            vertices["orig"] = np.nonzero(keep)[0].astype(np.int32)
        return GraphFrame(edges, vertices, num_vertices=int(keep.sum()))

    def filter_edges(self, cond: _MaskLike) -> "GraphFrame":
        """Same vertex set, only the edges where ``cond`` holds."""
        keep = self._edge_mask(cond)
        edges = {k: c[keep] for k, c in self.edges.items()}
        return GraphFrame(edges, dict(self.vertices), num_vertices=self.num_vertices)

    def drop_isolated_vertices(self) -> "GraphFrame":
        present = np.zeros(self.num_vertices, dtype=bool)
        present[self.edges["src"]] = True
        present[self.edges["dst"]] = True
        return self.filter_vertices(present)

    # -- framework extras --------------------------------------------------

    def leiden(self, **kw):
        """Leiden-style refinement over Louvain: comparable modularity,
        guaranteed internally connected communities."""
        from graphmine_tpu.ops.louvain import leiden
        return leiden(self.graph(weighted=True), **kw)

    def louvain(self, **kw):
        from graphmine_tpu.ops.louvain import louvain
        return louvain(self.graph(weighted=True), **kw)

    def modularity(self, labels, **kw):
        from graphmine_tpu.ops.modularity import modularity
        return modularity(labels, self.graph(weighted=True), **kw)

    def core_numbers(self, **kw):
        from graphmine_tpu.ops.kcore import core_numbers
        return core_numbers(self.graph(), **kw)

    def hits(self, **kw):
        """HITS (hubs, authorities) on the directed edges — NetworkX parity."""
        from graphmine_tpu.ops.centrality import hits
        return hits(self.graph(symmetric=False), **kw)

    def closeness_centrality(self, vertices=None, **kw):
        """Undirected closeness centrality (NetworkX parity); pass a
        landmark sample as ``vertices`` on large graphs."""
        from graphmine_tpu.ops.centrality import closeness_centrality
        return closeness_centrality(self.graph(), vertices=vertices, **kw)

    def betweenness_centrality(self, sources=None, **kw):
        """Brandes betweenness (NetworkX parity); pass a source sample on
        large graphs for the standard approximation."""
        from graphmine_tpu.ops.centrality import betweenness_centrality
        return betweenness_centrality(self.graph(), sources=sources, **kw)

    def eigenvector_centrality(self, **kw):
        from graphmine_tpu.ops.centrality import eigenvector_centrality
        return eigenvector_centrality(self.graph(), **kw)

    def katz_centrality(self, alpha: float = 0.1, **kw):
        from graphmine_tpu.ops.centrality import katz_centrality
        return katz_centrality(self.graph(), alpha=alpha, **kw)

    def maximal_independent_set(self, **kw):
        from graphmine_tpu.ops.mis import maximal_independent_set
        return maximal_independent_set(self.graph(), **kw)

    def greedy_color(self, **kw):
        from graphmine_tpu.ops.mis import greedy_color
        return greedy_color(self.graph(), **kw)

    def link_prediction(self, pairs, method: str = "jaccard"):
        from graphmine_tpu.ops.linkpred import link_prediction
        return link_prediction(self.graph(), pairs, method=method)

    def k_truss(self, k: int):
        from graphmine_tpu.ops.ktruss import k_truss
        return k_truss(self.graph(), k)

    def spectral_embedding(self, dim: int = 8, **kw):
        from graphmine_tpu.ops.embedding import spectral_embedding
        return spectral_embedding(self.graph(), dim=dim, **kw)

    def clustering_coefficient(self):
        from graphmine_tpu.ops.triangles import clustering_coefficient
        return clustering_coefficient(self.graph(), _cached=self._triangle_cache())

    def census(self, labels):
        from graphmine_tpu.ops.census import census_table
        return census_table(labels, self.graph())

    def recursive_lpa_outliers(self, labels, **kw):
        from graphmine_tpu.ops.outliers import recursive_lpa_outliers
        return recursive_lpa_outliers(self.graph(), labels, **kw)

    def lof_scores(self, labels=None, k: int = 20, **kw):
        """kNN+LOF outlier score per vertex from structural features."""
        from graphmine_tpu.ops.features import standardize, vertex_features
        from graphmine_tpu.ops.lof import lof_scores
        if labels is None:
            labels = self.label_propagation()
        feats = standardize(vertex_features(
            self.graph(), labels, triangles_cache=self._triangle_cache()
        ))
        return lof_scores(feats, k=k, **kw)

    def triplets(self):
        """GraphFrames ``triplets``: one row per edge with src/dst vertex
        attributes joined in (columns ``src``, ``dst``, then ``src_<attr>``
        / ``dst_<attr>`` for every vertex column)."""
        from graphmine_tpu.table import Table

        src, dst = self.edges["src"], self.edges["dst"]
        cols = dict(self.edges)
        for name, vals in self.vertices.items():
            vals = np.asarray(vals)
            cols[f"src_{name}"] = vals[src]
            cols[f"dst_{name}"] = vals[dst]
        return Table(cols)

    def parallel_personalized_pagerank(self, sources, **kw):
        from graphmine_tpu.ops.pagerank import parallel_personalized_pagerank
        return parallel_personalized_pagerank(self.graph(symmetric=False), sources, **kw)

    def svd_plus_plus(self, ratings, **kw):
        """Train SVD++ on this graph's edges with per-edge ``ratings``."""
        from graphmine_tpu.ops.svdpp import svd_plus_plus
        return svd_plus_plus(
            self.edges["src"], self.edges["dst"], ratings,
            num_vertices=self.num_vertices, **kw,
        )

    def persist(self) -> "GraphFrame":
        """GraphFrames ``persist``/``cache`` parity: results here are eager
        and the engine caches the device CSR per direction mode, so this is
        the identity (the reference needed it at ``Graphframes.py:82``)."""
        return self

    cache = persist

    def unpersist(self) -> "GraphFrame":
        """Drop cached device graphs (frees HBM for a frame going cold)."""
        self._graphs.clear()
        self._tri = None
        return self

    # -- GraphFrames camelCase aliases -------------------------------------

    labelPropagation = label_propagation
    connectedComponents = connected_components
    stronglyConnectedComponents = strongly_connected_components
    pageRank = pagerank
    shortestPaths = shortest_paths
    triangleCount = triangle_count
    aggregateMessages = aggregate_messages
    filterVertices = filter_vertices
    filterEdges = filter_edges
    dropIsolatedVertices = drop_isolated_vertices
    inDegrees = in_degrees
    outDegrees = out_degrees
    parallelPersonalizedPageRank = parallel_personalized_pagerank
    svdPlusPlus = svd_plus_plus
