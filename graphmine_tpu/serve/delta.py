"""Incremental delta ingest: splice edge batches, repair labels warm.

The batch pipeline recomputes everything from scratch on every new edge
batch. Steady-state serving inverts that (GraphBLAST's argument: keep
graph state resident, re-run only the delta-affected frontier):

1. **validate** an insert/delete batch through the ingestion-quarantine
   rules (negative / absurdly-large ids, deletes that match nothing are
   counted and set aside, never crash the server);
2. **splice** it into the host edge arrays — inserts append (duplicates
   keep LPA multiplicity semantics, ``Graphframes.py:70-74``), deletes
   remove one matching directed occurrence each (multiset semantics);
3. **repair**: the previous snapshot's labels seed the new graph's
   LPA/CC via the ``init_labels`` warm-start seam
   (``parallel/sharded.py``) and propagate to a new fixpoint under a
   frontier-derived iteration budget;
4. **verify**: a sampled exact check — one exact superstep of the new
   graph evaluated at sampled vertices (every delta-affected vertex plus
   a random sample) must leave the repaired labels unchanged, and every
   label must be a real vertex id. Any disagreement (or a budget
   exhausted before the frontier emptied) emits a ``repair_fallback``
   record and falls back to a cold full recompute — serving must never
   publish a state the exact operator disagrees with.

Warm-start correctness notes (docs/SERVING.md "delta semantics"):

- **CC** repair is exact by construction: old component labels are valid
  min-propagation upper bounds after inserts (merges only); deletes can
  split, so every vertex of a component touched by a delete is reset to
  its own id first — untouched components keep their (already exact)
  labels, and the monotone min fixpoint from a valid upper bound is THE
  fixpoint. Repair == cold recompute, always.
- **LPA** fixpoints are not unique, so warm repair is *checked*, not
  assumed: the sampled exact check accepts only genuine fixpoints of the
  new graph, and the equivalence tests pin repair == cold recompute on
  CPU test graphs (insert-only, delete-only, mixed batches).

Repaired outlier scores ride the existing streaming reuse path:
:class:`~graphmine_tpu.ops.streaming_lof.StreamingLOF` with
``impl="ivf"`` re-fits its window against ONE trained set of k-means
centers, so each delta scores only the affected vertices' features.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from graphmine_tpu.pipeline import resilience
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.serve.snapshot import Snapshot, SnapshotStore

# Growth guard: a typo'd insert id must not allocate a billion-row label
# vector. Inserts past current V + this bound are quarantined.
MAX_NEW_VERTICES = 1 << 20


@dataclass
class EdgeDelta:
    """One edge insert/delete batch (directed endpoints, dense ids).

    ``insert_weight``: optional float32 per-insert edge weights (weighted
    snapshots — r9). ``None`` = unweighted inserts; splicing into a
    weighted snapshot then defaults them to 1.0. Deletes are always
    keyed by ``(src, dst)`` alone — a delete removes ONE occurrence of
    the directed edge, whatever its weight (multiset semantics; the
    earliest-position occurrence goes first, deterministically).
    """

    insert_src: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    insert_dst: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    delete_src: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    delete_dst: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    insert_weight: np.ndarray | None = None

    def __post_init__(self):
        for name in ("insert_src", "insert_dst", "delete_src", "delete_dst"):
            setattr(self, name, np.asarray(getattr(self, name), np.int64))
        if (
            self.insert_src.shape != self.insert_dst.shape
            or self.delete_src.shape != self.delete_dst.shape
        ):
            raise ValueError("src/dst arrays must be equal-length")
        if self.insert_weight is not None:
            w = np.asarray(self.insert_weight, np.float32)
            if w.shape != self.insert_src.shape:
                raise ValueError(
                    "insert_weight must be one float per insert row"
                )
            if len(w) and (not np.isfinite(w).all() or (w < 0).any()):
                raise ValueError(
                    "insert_weight must be non-negative and finite"
                )
            self.insert_weight = w

    @classmethod
    def from_pairs(cls, insert=(), delete=()) -> "EdgeDelta":
        """Build from ``[(src, dst), ...]`` pair lists (the JSON wire
        shape the HTTP front end accepts); insert rows may uniformly be
        ``(src, dst, weight)`` triples for weighted snapshots. Malformed
        input — null, non-iterable, non-numeric, fractional ids, or
        mixed 2/3-wide insert rows — raises ValueError (the HTTP
        layer's 400), never TypeError, and never silently truncates
        ``1.9`` to vertex ``1``. Integral floats (``40.0``, which JSON
        encoders routinely emit for integers) are accepted as ids.
        """

        from graphmine_tpu.serve.query import _as_int_ids

        def _rows(name, pairs, widths):
            try:
                lst = list(pairs)
            except TypeError as e:
                raise ValueError(
                    f"{name} must be an array of [src, dst] pairs ({e})"
                ) from e
            try:
                seen = {len(r) for r in lst}
            except TypeError as e:
                raise ValueError(
                    f"{name} rows must be [src, dst] pairs ({e})"
                ) from e
            if seen and seen not in [{w} for w in widths]:
                raise ValueError(
                    f"{name} rows must uniformly be "
                    f"{' or '.join(str(w) for w in widths)} wide "
                    f"(got widths {sorted(seen)})"
                )
            return lst, (seen.pop() if seen else widths[0])

        ins, iw = _rows("insert", insert, (2, 3))
        del_, _ = _rows("delete", delete, (2,))
        weight = None
        if iw == 3 and ins:
            try:
                weight = np.asarray([r[2] for r in ins], np.float32)
            except (TypeError, ValueError) as e:
                raise ValueError(f"insert weights must be numeric ({e})") from e
            ins = [(r[0], r[1]) for r in ins]
        ins_ids = _as_int_ids(ins, "insert").reshape(-1, 2)
        del_ids = _as_int_ids(del_, "delete").reshape(-1, 2)
        return cls(
            ins_ids[:, 0], ins_ids[:, 1], del_ids[:, 0], del_ids[:, 1],
            insert_weight=weight,
        )

    @property
    def num_inserts(self) -> int:
        return len(self.insert_src)

    @property
    def num_deletes(self) -> int:
        return len(self.delete_src)

    def take(self, insert_index, delete_index) -> "EdgeDelta":
        """Row-select a sub-delta by ORIGINAL row indices (the sharded
        write plane's splitter, r17): inserts keep their weights, and
        because the indices are positions into THIS delta's arrays, a
        scatter of the sub-deltas back through the same indices is
        bit-identical to the original — the splitter/merger parity the
        shardplane tests pin."""
        ins = np.asarray(insert_index, np.int64)
        dels = np.asarray(delete_index, np.int64)
        return EdgeDelta(
            self.insert_src[ins], self.insert_dst[ins],
            self.delete_src[dels], self.delete_dst[dels],
            insert_weight=(
                None if self.insert_weight is None
                else self.insert_weight[ins]
            ),
        )


def validate_delta(
    delta: EdgeDelta, num_vertices: int,
    max_new_vertices: int = MAX_NEW_VERTICES,
) -> tuple[EdgeDelta, dict]:
    """Quarantine-validate a delta against the current vertex space.

    Returns ``(clean_delta, quarantine)`` — the same count-and-set-aside
    contract as ingestion (``io/edges.from_arrays``): negative ids and
    inserts past the growth guard are dropped as ``out_of_range_ids``;
    deletes referencing vertices that don't exist can never match an
    edge and are dropped as ``unmatched_deletes``. Nothing raises on bad
    rows — a served endpoint crashing on one malformed batch row is the
    failure mode quarantine exists to prevent.
    """
    q = {"out_of_range_ids": 0, "unmatched_deletes": 0}
    cap = num_vertices + max_new_vertices
    ok_i = (
        (delta.insert_src >= 0) & (delta.insert_dst >= 0)
        & (delta.insert_src < cap) & (delta.insert_dst < cap)
    )
    q["out_of_range_ids"] += int((~ok_i).sum())
    ok_d = (
        (delta.delete_src >= 0) & (delta.delete_dst >= 0)
        & (delta.delete_src < num_vertices) & (delta.delete_dst < num_vertices)
    )
    q["unmatched_deletes"] += int((~ok_d).sum())
    return EdgeDelta(
        delta.insert_src[ok_i], delta.insert_dst[ok_i],
        delta.delete_src[ok_d], delta.delete_dst[ok_d],
        insert_weight=(
            None if delta.insert_weight is None
            else delta.insert_weight[ok_i]
        ),
    ), q


def splice_edges(src, dst, num_vertices: int, delta: EdgeDelta, weights=None):
    """Apply a validated delta to host edge arrays.

    Inserts append (multiplicity kept); each delete row removes ONE
    matching directed occurrence (multiset delete — deleting an edge
    that appears 3x leaves 2; the earliest array position goes first,
    which makes weighted splices deterministic too). Returns
    ``(src', dst', num_vertices', stats)`` with
    ``stats = {inserted, deleted, unmatched_deletes}``; the vertex space
    only ever grows (deletes remove edges, never vertices — stable ids
    are the serving contract).

    ``weights``: the snapshot's per-edge float weights (weighted graphs,
    r9). When given, the return is the FIVE-tuple
    ``(src', dst', weights', num_vertices', stats)`` — deleted rows drop
    their weight with them, inserted rows carry ``delta.insert_weight``
    (default 1.0 when the delta is unweighted). Passing a weighted delta
    against ``weights=None`` raises: silently discarding client weights
    would change weighted-LPA semantics without a trace.
    """
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if weights is None and delta.insert_weight is not None:
        raise ValueError(
            "delta carries insert weights but the snapshot is unweighted; "
            "republish the snapshot with a weights array or drop the "
            "weight column from the delta"
        )
    if weights is not None:
        weights = np.asarray(weights, np.float32)
        if weights.shape != src.shape:
            raise ValueError(
                f"weights has {weights.shape} entries for {src.shape} edges"
            )
    v_new = int(
        max(
            num_vertices,
            delta.insert_src.max(initial=-1) + 1,
            delta.insert_dst.max(initial=-1) + 1,
        )
    )
    keep = np.ones(len(src), bool)
    unmatched = 0
    if delta.num_deletes:
        enc = v_new + 1
        ekey = src * enc + dst
        dkey = delta.delete_src * enc + delta.delete_dst
        dk_u, dk_c = np.unique(dkey, return_counts=True)
        # Prefilter to rows whose key a delete targets — searchsorted
        # against the tiny sorted dk_u is O(E log d), so the
        # occurrence-rank sort runs over the handful of candidates, not
        # all E edges (np.isin would fall back to an O(E log E)
        # sort-based path for int64 key ranges this wide).
        pos_all = np.minimum(np.searchsorted(dk_u, ekey), len(dk_u) - 1)
        cand = np.flatnonzero(dk_u[pos_all] == ekey)
        order = np.argsort(ekey[cand], kind="stable")
        sk = ekey[cand][order]
        # occurrence rank of each edge within its (src, dst) group
        rank = np.arange(len(sk)) - np.searchsorted(sk, sk, side="left")
        want = dk_c[np.searchsorted(dk_u, sk)]  # every sk is in dk_u
        drop_sorted = rank < want
        keep[cand[order[drop_sorted]]] = False
        unmatched = int(delta.num_deletes - drop_sorted.sum())
    src2 = np.concatenate([src[keep], delta.insert_src])
    dst2 = np.concatenate([dst[keep], delta.insert_dst])
    stats = {
        "inserted": delta.num_inserts,
        "deleted": int((~keep).sum()),
        "unmatched_deletes": unmatched,
    }
    if weights is not None:
        ins_w = (
            delta.insert_weight if delta.insert_weight is not None
            else np.ones(delta.num_inserts, np.float32)
        )
        w2 = np.concatenate([weights[keep], ins_w]).astype(np.float32)
        return src2.astype(np.int32), dst2.astype(np.int32), w2, v_new, stats
    return src2.astype(np.int32), dst2.astype(np.int32), v_new, stats


def affected_vertices(delta: EdgeDelta) -> np.ndarray:
    """Distinct vertex ids a delta touches directly — the repair frontier
    seed (their labels may change first; propagation widens from here)."""
    return np.unique(
        np.concatenate(
            [delta.insert_src, delta.insert_dst,
             delta.delete_src, delta.delete_dst]
        )
    ).astype(np.int64)


def frontier_budget(num_vertices: int, affected: int) -> int:
    """Frontier-derived superstep budget for a warm repair.

    Label effects propagate one hop per superstep, so a delta touching
    ``affected`` seeds needs depth proportional to how far its influence
    can reach before dying out: ``log2``-ish in the graph size (pointer
    jumping / small-world propagation depth) plus a term in the seed
    count. Deliberately generous — exhausting it without convergence
    triggers the full-recompute fallback, so a tight budget only costs a
    wasted warm attempt, never a wrong answer.
    """
    v_term = math.ceil(math.log2(num_vertices + 2))
    a_term = math.ceil(math.log2(affected + 2))
    return int(min(128, 2 * v_term + a_term + 8))


# ---- warm fixpoint runners -------------------------------------------------


def _warm_lpa(graph, init_labels: np.ndarray, budget: int):
    """Warm-start synchronous LPA to fixpoint, bounded by ``budget``.

    One jitted superstep per iteration (the serving graphs this runs on
    are the delta-affected working set, not the 100M-vertex batch case;
    the sharded twin is
    :func:`graphmine_tpu.parallel.sharded.sharded_lpa_fixpoint`).
    Returns ``(labels, iterations, converged)``.

    Period-2 cycles — synchronous LPA's known livelock on e.g. bipartite
    hub structures — are detected (state t+1 == state t-1) and exit
    early as ``converged=False``: burning the rest of the budget on a
    cycle that can never fixpoint would only delay the caller's
    full-recompute fallback.
    """
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.lpa import lpa_superstep

    step = jax.jit(lpa_superstep)
    labels = jnp.asarray(init_labels, jnp.int32)
    prev = None
    for it in range(budget):
        new = step(labels, graph)
        if not bool(jnp.any(new != labels)):
            return np.asarray(new), it + 1, True
        if prev is not None and not bool(jnp.any(new != prev)):
            return np.asarray(new), it + 1, False  # period-2 livelock
        prev = labels
        labels = new
    return np.asarray(labels), budget, False


def _warm_lpa_sharded(shards, init_labels: np.ndarray, budget: int):
    """Sharded twin of :func:`_warm_lpa` with the SAME stop conditions
    (fixpoint, period-2 livelock, budget): drives the sharded entry one
    superstep at a time so cycle detection — which the jitted while-loop
    carry lacks — happens host-side. Synchronous LPA is deterministic,
    so the stepped trajectory is identical to the fused one; only the
    exit point differs on livelock graphs."""
    import jax.numpy as jnp

    from graphmine_tpu.parallel.sharded import sharded_lpa_fixpoint

    sg, mesh = shards
    labels = np.asarray(init_labels, np.int32)
    prev = None
    for it in range(budget):
        new, _, _ = sharded_lpa_fixpoint(
            sg, mesh, max_iter=1, init_labels=jnp.asarray(labels)
        )
        new = np.asarray(new)
        if np.array_equal(new, labels):
            return new, it + 1, True
        if prev is not None and np.array_equal(new, prev):
            return new, it + 1, False  # period-2 livelock
        prev = labels
        labels = new
    return labels, budget, False


def _warm_cc(graph, init_labels: np.ndarray, budget: int):
    """Warm-start min-propagation CC to fixpoint (monotone, so any valid
    upper-bound init converges to THE fixpoint). Returns
    ``(labels, iterations, converged)``."""
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.cc import cc_superstep

    step = jax.jit(cc_superstep)
    labels = jnp.asarray(init_labels, jnp.int32)
    for it in range(budget):
        new = step(labels, graph)
        if not bool(jnp.any(new != labels)):
            return np.asarray(new), it + 1, True
        labels = new
    return np.asarray(labels), budget, False


def cc_repair_init(
    prev_cc: np.ndarray, num_vertices: int, delta: EdgeDelta
) -> np.ndarray:
    """Valid min-propagation upper bounds seeded from the previous CC
    labels: every vertex of a component touched by a DELETE resets to its
    own id (the split case — its old min may have landed in the other
    part), new vertices get their own id, everything else keeps its
    (exact) label. See the module docstring for why this makes CC repair
    == cold recompute by construction."""
    init = np.arange(num_vertices, dtype=np.int32)
    init[: len(prev_cc)] = prev_cc
    if delta.num_deletes:
        touched = np.unique(
            prev_cc[
                np.concatenate([delta.delete_src, delta.delete_dst]).astype(
                    np.int64
                )
            ]
        )
        reset = np.isin(prev_cc, touched)
        init[: len(prev_cc)][reset] = np.arange(len(prev_cc), dtype=np.int32)[
            reset
        ]
    return init


def _clear_sharded_jit_caches():
    """Evict the sharded entries' module-global jit caches. They are
    keyed by array shapes and never evicted, so on a long-lived serving
    ingestor every delta that changes the padded shard shapes would
    otherwise accrete one more compiled XLA executable forever
    (unbounded host/device memory). The caller clears only when the
    shapes actually changed — steady same-shape deltas keep their warm
    cache.

    The caches are process-global, so this also evicts any OTHER
    in-process user of the sharded entries (e.g. a driver publish in
    the same process). That is functionally safe — worst case is one
    recompile on their next call — and a serving ingestor is normally
    the only sharded user in its process; jax exposes no per-entry
    eviction, and scoping compiled caches per ingestor would require
    the sharded kernel entries to take a caller-owned jit handle, a
    kernel-API change out of proportion to this fallback-path cache."""
    from graphmine_tpu.parallel import sharded as _sharded

    for fn in (
        _sharded._sharded_lpa_fixpoint_jit,
        _sharded._sharded_cc_jit,
    ):
        clear = getattr(fn, "clear_cache", None)
        if clear is not None:
            clear()


def _sharded_exact_step(shards, labels: np.ndarray, kind: str) -> np.ndarray:
    """One exact superstep through the sharded entries: ``max_iter=1``
    with the current labels as init leaves them unchanged iff they are a
    superstep fixpoint — the same acceptance predicate as the
    single-device twin, without materializing an unsharded whole-graph
    superstep on one device."""
    import jax.numpy as jnp

    from graphmine_tpu.parallel.sharded import (
        sharded_connected_components,
        sharded_lpa_fixpoint,
    )

    sg, mesh = shards
    init = jnp.asarray(labels, jnp.int32)
    if kind == "lpa":
        nxt, _, _ = sharded_lpa_fixpoint(sg, mesh, max_iter=1, init_labels=init)
    else:
        nxt = sharded_connected_components(
            sg, mesh, max_iter=1, init_labels=init
        )
    return np.asarray(nxt)


def sampled_exact_check(
    graph, labels: np.ndarray, samples: np.ndarray, kind: str = "lpa",
    shards=None,
) -> tuple[bool, int]:
    """The repair tripwire: one EXACT superstep of the new graph must
    leave the repaired labels unchanged at every sampled vertex, and
    every sampled label must be a real vertex id. A genuine fixpoint
    passes by construction; corrupted state, a non-fixpoint (budget ran
    out), or a wrong-graph mixup does not. Returns
    ``(ok, mismatching_samples)``.

    ``shards``: optional ``(sharded_graph, mesh)`` pair — the exact
    superstep then runs through the sharded entries, so working sets
    past one device (the reason ``num_shards > 1`` exists) are never
    funneled back into a single-device whole-graph superstep here.
    """
    v = graph.num_vertices
    lbl = np.asarray(labels)
    oob = int(((lbl < 0) | (lbl >= v)).sum())
    if oob:
        return False, oob
    if shards is not None:
        nxt = _sharded_exact_step(shards, lbl, kind)
    else:
        import jax
        import jax.numpy as jnp

        from graphmine_tpu.ops.cc import cc_superstep
        from graphmine_tpu.ops.lpa import lpa_superstep

        step = lpa_superstep if kind == "lpa" else cc_superstep
        nxt = np.asarray(jax.jit(step)(jnp.asarray(lbl, jnp.int32), graph))
    samples = np.asarray(samples, np.int64)
    samples = samples[(samples >= 0) & (samples < v)]
    bad = int((nxt[samples] != lbl[samples]).sum())
    return bad == 0, bad


@dataclass(frozen=True)
class RepairResult:
    """Outcome of one delta repair."""

    labels: np.ndarray            # community labels [V'] (LPA fixpoint)
    cc_labels: np.ndarray         # CC labels [V']
    method: str                   # "warm" | "full_recompute"
    iterations: int               # supersteps the winning path ran (LPA + CC)
    fallback_reason: str | None = None
    checked_samples: int = 0
    budget: int = 0               # frontier budget the warm attempt was granted


class RepairDebt:
    """Host-side ledger of how far behind serving-state repair is.

    The write-heavy-serving rungs the ROADMAP names next (delta
    coalescing, admission control, load shedding) all need ONE signal:
    how much un-repaired work has accumulated, and how fast repairs are
    keeping up. This ledger is that signal, fed from the two ends of the
    delta path:

    - :meth:`submitted` when a delta batch *arrives* (the HTTP handler,
      before it queues on the publish lock) — pending rows and the
      arrival time of the oldest unapplied batch (**ingest lag**: how
      stale the served snapshot is against accepted writes);
    - :meth:`applied` when the ingestor *publishes* — drains the oldest
      pending entry and accrues the repair economics: warm vs
      full-recompute counts (the warm ratio is the number the serve
      bench tier exists to improve), supersteps spent vs the frontier
      budget granted (a budget fraction pinned near 1.0 means deltas
      are one graph-growth away from the fallback cliff).

    Pure host bookkeeping under one lock — nothing here touches a
    device, so the repair hot path's compiled programs are untouched.
    When a ``registry`` is given, the ledger mirrors itself into
    scrapeable gauges/counters on every event.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._pending: deque = deque()   # (t_submitted, rows) FIFO
        self._pending_rows = 0
        self.applies_warm = 0
        self.applies_cold = 0
        self.supersteps_total = 0
        self.budget_granted_total = 0
        self.last_budget_frac = 0.0
        self.rows_applied_total = 0
        self.sheds_total = 0
        self.rows_shed_total = 0
        self._registry = registry

    def submitted(self, rows: int, t: float | None = None) -> None:
        """One delta batch accepted (``rows`` = insert + delete rows)."""
        with self._lock:
            self._pending.append((time.time() if t is None else t, int(rows)))
            self._pending_rows += int(rows)
        self._export()

    def applied(
        self, method: str, iterations: int, budget: int, batches: int = 1
    ) -> None:
        """One delta apply published; drains the ``batches`` oldest
        pending entries — a coalesced apply settles every batch it
        merged, not just one (no-op on the pending side when the
        ingestor is driven directly, without a front end calling
        :meth:`submitted`)."""
        with self._lock:
            for _ in range(max(1, int(batches))):
                if not self._pending:
                    break
                _, rows = self._pending.popleft()
                self._pending_rows -= rows
                self.rows_applied_total += rows
            if method == "warm":
                self.applies_warm += 1
            else:
                self.applies_cold += 1
            self.supersteps_total += int(iterations)
            self.budget_granted_total += int(budget)
            self.last_budget_frac = (
                round(int(iterations) / int(budget), 4) if budget else 0.0
            )
        reg = self._registry
        if reg is not None:
            reg.counter(
                "graphmine_serve_repairs_warm_total",
                "delta applies repaired warm",
            ).inc(1 if method == "warm" else 0)
            reg.counter(
                "graphmine_serve_repairs_cold_total",
                "delta applies that fell back to full recompute",
            ).inc(0 if method == "warm" else 1)
            reg.counter(
                "graphmine_serve_repair_supersteps_total",
                "repair supersteps spent across all delta applies",
            ).inc(int(iterations))
        self._export()

    @property
    def applies_total(self) -> int:
        """Settled applies (warm + cold) — the caller's marker for "did
        my apply get as far as settling its debt before it raised"."""
        with self._lock:
            return self.applies_warm + self.applies_cold

    def abandoned(self) -> None:
        """A submitted batch will never publish (validation raised, the
        ingestor refused the snapshot, admission shed it off the queue):
        drop the oldest pending entry so the ledger doesn't report a
        phantom backlog forever. FIFO is an approximation under
        concurrent submitters — the ledger is advisory telemetry, and
        totals rebalance as the queue drains."""
        with self._lock:
            if self._pending:
                _, rows = self._pending.popleft()
                self._pending_rows -= rows
        self._export()

    def shed(self, rows: int) -> None:
        """Admission control refused ``rows`` delta rows (a 503 the
        client must retry) — the lost-write accounting the serve bench
        tier's shed rate reads. Pure accounting: sheds at the front door
        were never :meth:`submitted`, so nothing drains here (a
        queued-then-shed batch pairs this with :meth:`abandoned`)."""
        with self._lock:
            self.sheds_total += 1
            self.rows_shed_total += int(rows)
        self._export()

    def ingest_lag_s(self, now: float | None = None) -> float:
        """Age of the oldest accepted-but-unapplied delta (0.0 when the
        queue is drained) — the staleness bound a load balancer reads."""
        with self._lock:
            if not self._pending:
                return 0.0
            return max(0.0, (time.time() if now is None else now)
                       - self._pending[0][0])

    def snapshot(self) -> dict:
        """One JSON-ready read of the whole ledger."""
        lag = self.ingest_lag_s()
        with self._lock:
            applies = self.applies_warm + self.applies_cold
            return {
                "pending_deltas": len(self._pending),
                "pending_rows": self._pending_rows,
                "ingest_lag_s": round(lag, 4),
                "applies_warm": self.applies_warm,
                "applies_cold": self.applies_cold,
                "warm_ratio": (
                    round(self.applies_warm / applies, 4) if applies else 1.0
                ),
                "supersteps_total": self.supersteps_total,
                "budget_granted_total": self.budget_granted_total,
                "last_budget_frac": self.last_budget_frac,
                "rows_applied_total": self.rows_applied_total,
                "sheds_total": self.sheds_total,
                "rows_shed_total": self.rows_shed_total,
            }

    def _export(self) -> None:
        reg = self._registry
        if reg is None:
            return
        snap = self.snapshot()
        reg.gauge(
            "graphmine_serve_repair_debt_rows",
            "delta rows accepted but not yet repaired/published",
        ).set(snap["pending_rows"])
        reg.gauge(
            "graphmine_serve_ingest_lag_seconds",
            "age of the oldest accepted-but-unapplied delta batch",
        ).set(snap["ingest_lag_s"])
        reg.gauge(
            "graphmine_serve_repair_budget_frac",
            "supersteps used / frontier budget granted, last apply",
        ).set(snap["last_budget_frac"])


def cold_recompute(graph, budget: int = 0, shards=None):
    """Cold full recompute — the fallback AND the equivalence oracle the
    repair tests compare against: LPA from identity init run to fixpoint
    (bounded, period-2 cycles exit early), CC from identity. Returns
    ``(labels, cc_labels, iters)``. On graphs whose synchronous LPA
    livelocks (never fixpoints), the result is the cycle-stopped bounded
    recompute — the same semantics class as the batch pipeline's bounded
    ``max_iter`` — and every delta on such a graph routes here via the
    repair fallback (the sampled check refuses non-fixpoints).

    ``shards``: optional ``(sharded_graph, mesh)`` pair — the recompute
    then runs through ``sharded_lpa_fixpoint`` (identity init) /
    ``sharded_connected_components`` (label parity with the
    single-device ops is pinned by the sharded suite), so the sharded
    repair path's fallback never OOMs on exactly the working sets that
    needed sharding in the first place. Livelock graphs take the fused
    fixpoint run first (fast path), then replay with host-side period-2
    detection so the published labels match the single-device oracle's
    cycle-stopped state, not a budget-parity-dependent cycle phase."""
    import numpy as _np

    v = graph.num_vertices
    budget = budget or frontier_budget(v, v)
    if shards is not None:
        from graphmine_tpu.parallel.sharded import (
            sharded_connected_components,
            sharded_lpa_fixpoint,
        )

        import jax.numpy as jnp

        sg, mesh = shards
        labels, it_l, conv = sharded_lpa_fixpoint(sg, mesh, max_iter=budget)
        if not conv:
            # The jitted while-loop carry has no cycle detection, so a
            # period-2 livelock burns the whole budget and lands on
            # whichever phase budget parity picks. Probe two more
            # supersteps: back-to-start means the end state sits IN a
            # 2-cycle — replay one superstep at a time (identical
            # deterministic trajectory) with the same host-side
            # new==prev exit as _warm_lpa to land on its cycle-stopped
            # state. Genuine budget exhaustion (still converging) skips
            # the replay: it would retrace the whole budget only to
            # reproduce the same truncated labels.
            probe, _, _ = sharded_lpa_fixpoint(
                sg, mesh, max_iter=2, init_labels=jnp.asarray(labels)
            )
            if _np.array_equal(_np.asarray(probe), _np.asarray(labels)):
                labels, it_l, _ = _warm_lpa_sharded(
                    shards, _np.arange(v, dtype=_np.int32), budget
                )
        cc = sharded_connected_components(sg, mesh)
        return _np.asarray(labels), _np.asarray(cc), int(it_l)
    labels, it_l, _ = _warm_lpa(
        graph, _np.arange(v, dtype=_np.int32), budget
    )
    from graphmine_tpu.ops.cc import connected_components

    cc = _np.asarray(connected_components(graph))
    return labels, cc, it_l


def _verify_or_fallback(
    graph, labels, cc, conv_l, conv_c, delta: EdgeDelta, budget: int,
    iterations: int, check_samples: int, sink, num_shards: int = 1,
    seed: int = 0, shards=None, tenant: str = "",
) -> RepairResult:
    """The shared tail of BOTH repair paths (single-device and sharded):
    fault seam → sampled exact check → accept or fall back. One owner so
    the two paths can never diverge on what gets published. ``shards``
    (the sharded caller's ``(sharded_graph, mesh)``) keeps the check and
    the fallback recompute on the sharded entries too — no single-device
    full-graph funnel.

    The fault seam is where tests corrupt the repaired state
    (poison_labels-style mutator) to prove the sampled check catches
    silent damage and the fallback republishes exact labels.
    """
    state = {"labels": labels, "cc_labels": cc}
    # tenant rides the ctx (ISSUE 16): a tenant-targeted injector
    # (noisy_neighbor_burst's staller) fires only on the abusive
    # tenant's applies, leaving its co-tenants' repairs untouched.
    resilience.fault_point(
        "delta_repair", state=state, num_shards=num_shards, tenant=tenant,
    )
    labels, cc = state["labels"], state["cc_labels"]

    v = graph.num_vertices
    rng = np.random.default_rng(seed)
    extra = rng.integers(0, v, size=min(check_samples, v))
    samples = np.unique(np.concatenate([affected_vertices(delta), extra]))
    ok_l, bad_l = sampled_exact_check(
        graph, labels, samples, kind="lpa", shards=shards
    )
    ok_c, bad_c = sampled_exact_check(
        graph, cc, samples, kind="cc", shards=shards
    )

    reason = None
    if not (conv_l and conv_c):
        reason = (
            f"budget exhausted before fixpoint (lpa converged={conv_l}, "
            f"cc converged={conv_c}, budget={budget})"
        )
    elif not (ok_l and ok_c):
        reason = (
            f"sampled exact check failed ({bad_l} lpa / {bad_c} cc "
            f"disagreements over {len(samples)} samples)"
        )
    if reason is None:
        return RepairResult(
            labels=labels, cc_labels=cc, method="warm",
            iterations=iterations, checked_samples=len(samples),
            budget=budget,
        )
    if sink is not None:
        sink.emit("repair_fallback", stage="delta_repair", reason=reason)
    labels, cc, it = cold_recompute(graph, shards=shards)
    return RepairResult(
        labels=labels, cc_labels=cc, method="full_recompute",
        iterations=it, fallback_reason=reason,
        checked_samples=len(samples), budget=budget,
    )


def repair_labels(
    graph,
    prev_labels: np.ndarray,
    prev_cc: np.ndarray,
    delta: EdgeDelta,
    budget: int | None = None,
    check_samples: int = 64,
    sink=None,
    seed: int = 0,
    tenant: str = "",
) -> RepairResult:
    """Warm-start repair of community + CC labels on the spliced graph.

    The previous snapshot's labels seed both propagations (see module
    docstring for the exact init rules); the sampled exact check accepts
    or rejects the result, and rejection — or a budget exhausted before
    the frontier emptied — falls back to :func:`cold_recompute` with a
    ``repair_fallback`` record through ``sink``. The returned labels are
    therefore ALWAYS a verified fixpoint of the new graph.
    """
    v = graph.num_vertices
    if budget is None:
        budget = frontier_budget(v, len(affected_vertices(delta)))

    init_lpa = np.arange(v, dtype=np.int32)
    init_lpa[: len(prev_labels)] = prev_labels
    labels, it_l, conv_l = _warm_lpa(graph, init_lpa, budget)
    cc, it_c, conv_c = _warm_cc(
        graph, cc_repair_init(np.asarray(prev_cc), v, delta), budget
    )
    return _verify_or_fallback(
        graph, labels, cc, conv_l, conv_c, delta, budget, it_l + it_c,
        check_samples, sink, seed=seed, tenant=tenant,
    )


class DeltaIngestor:
    """Applies edge deltas to a snapshot store: validate → splice →
    warm repair → streaming LOF refresh → publish.

    Holds the host-side working state (edge arrays + labels) between
    deltas so consecutive batches never re-load the store, and one
    :class:`~graphmine_tpu.ops.streaming_lof.StreamingLOF` whose trained
    IVF centers are reused across deltas (``impl="ivf"`` — Lloyd runs
    once per ingestor, not once per batch).

    ``num_shards > 1`` runs the repair propagations through the sharded
    entries (:func:`~graphmine_tpu.parallel.sharded.sharded_lpa_fixpoint`
    / ``sharded_connected_components(init_labels=...)``) on a
    ``num_shards``-device mesh — identical labels (parity-tested), for
    working sets past one device.
    """

    def __init__(
        self,
        store: SnapshotStore,
        sink=None,
        lof_k: int = 16,
        lof_capacity: int = 4096,
        check_samples: int = 64,
        num_shards: int = 1,
        snapshot: Snapshot | None = None,
        debt: RepairDebt | None = None,
        epoch: int | None = None,
        quality: bool | None = None,
    ):
        self.store = store
        self.sink = sink
        self.check_samples = check_samples
        self.num_shards = num_shards
        # Writer epoch every publish carries (replicated writers, r11):
        # None = inherit the store's epoch (single-writer callers). A
        # stale epoch makes the publish refuse with PublishFencedError —
        # the deposed-writer fence lives at the store, this just says
        # which epoch this ingestor believes it is.
        self.epoch = epoch
        # Repair-debt ledger (docs/OBSERVABILITY.md "serving SLO"): the
        # front end owns one and shares it here so the pending side
        # survives ingestor rebasing on /reload; a bare ingestor gets a
        # private ledger so the delta_apply record always carries debt.
        self.debt = debt if debt is not None else RepairDebt(
            registry=sink.registry if sink is not None else None
        )
        snap = snapshot if snapshot is not None else store.load(sink=sink)
        if snap is None:
            raise ValueError(
                f"snapshot store at {store.root!r} is empty; publish a "
                "pipeline snapshot (--snapshot-out) before ingesting deltas"
            )
        self.snapshot = snap
        self.src = np.asarray(snap["src"], np.int32)
        self.dst = np.asarray(snap["dst"], np.int32)
        # Weighted snapshots ingest deltas end-to-end (r9): the graph is
        # rebuilt with edge_weights, so warm LPA/sampled-check/cold
        # fallback all run the WEIGHTED supersteps (weight-sum mode,
        # ops/lpa.py) — CC is weight-oblivious min-propagation. The loud
        # refusal below remains only for a genuinely unsupported shape:
        # a weights column that doesn't align with the edge arrays.
        w = snap.get("weights")
        self.weights = None if w is None else np.asarray(w, np.float32)
        if self.weights is not None and self.weights.shape != self.src.shape:
            raise ValueError(
                f"snapshot weights array has {self.weights.shape} entries "
                f"for {self.src.shape} edges; this store is damaged or was "
                "published by an incompatible writer — republish it"
            )
        self.labels = np.asarray(snap["labels"], np.int32)
        self.cc_labels = np.asarray(
            snap.get("cc_labels", snap["labels"]), np.int32
        )
        lof = snap.get("lof")
        self.lof = (
            np.zeros(len(self.labels), np.float32) if lof is None
            else np.asarray(lof, np.float32).copy()
        )
        self.lof_k = lof_k
        self.lof_capacity = max(lof_capacity, lof_k + 2)
        self._stream = None
        # IVF centers from a prior process's publishes (if any): the
        # StreamingLOF(centers=...) reuse path — Lloyd never re-trains
        # what an earlier ingestor already paid for.
        self._centers = snap.get("lof_centers")
        # padded shard shapes of the last sharded apply (jit-cache
        # eviction key; see _clear_sharded_jit_caches)
        self._shard_jit_key = None
        # superstep family of the last sharded repair ("sharded_2d" past
        # the r16 crossover, else "sort"; None before any sharded apply
        # / on single-shard ingestors)
        self.last_shard_family = None
        # LOF-staleness backlog (admission rung 2, serve/admission.py):
        # vertices whose scores a deferred apply skipped. The next
        # lof_mode="refresh" apply re-scores the union. A snapshot loaded
        # already-stale has no backlog list — the first refresh then
        # re-scores everything (rare, and the honest recovery).
        self._stale_aff = np.empty(0, np.int64)
        self._stale_all = bool(snap.meta.get("lof_stale", False))
        # Result-quality plane (ISSUE 13, docs/OBSERVABILITY.md "Result
        # quality"): every publish runs a bounded host-side quality pass
        # — census/LOF drift vs the parent (whose labels this ingestor
        # already holds), sketch states, and the canary probe re-score.
        # GRAPHMINE_QUALITY=0 (or quality=False) disables the whole
        # pass; the canary probe persists in the snapshot (the
        # lof_centers pattern) so every writer in the store's lifetime
        # scores the SAME frozen probe — a fresh store generates one,
        # seeded by GRAPHMINE_CANARY_SEED.
        if quality is None:
            quality = os.environ.get("GRAPHMINE_QUALITY", "1") != "0"
        self.quality_enabled = bool(quality)
        self.last_quality = None       # QualityReport of the last apply
        self._quality_state = None     # parent state reused next apply
        self._canary = None
        if self.quality_enabled:
            from graphmine_tpu.obs.quality import CanaryProbe

            self._canary = CanaryProbe.from_snapshot(snap)
            if self._canary is None:
                self._canary = CanaryProbe.generate(
                    seed=int(os.environ.get("GRAPHMINE_CANARY_SEED", "0"))
                )

    @property
    def num_vertices(self) -> int:
        return len(self.labels)

    def _repair(self, graph, delta: EdgeDelta) -> RepairResult:
        # Rotate the sampled-check seed per apply (the snapshot version
        # increments every publish): a fixed seed would pick the same
        # "random" vertices on every delta, gutting the tripwire's
        # long-run coverage of silent corruption outside the frontier.
        seed = self.snapshot.version
        tenant = getattr(self.store, "tenant", "")
        if self.num_shards <= 1:
            return repair_labels(
                graph, self.labels, self.cc_labels, delta,
                check_samples=self.check_samples, sink=self.sink,
                seed=seed, tenant=tenant,
            )
        return self._repair_sharded(graph, delta, seed)

    def _resolve_shard_family(self, graph) -> str:
        """Plan-time superstep-family resolution for the sharded repair
        path (r16): the planner's single crossover owner picks between
        the 2D neighbor-exchange partition and the one-all_gather sort
        bodies, then the memory plane pre-degrades a 2D pick whose
        per-peer boundary tables (modeled at worst case — the pre-build
        view cannot know the real boundary) would not fit the HBM
        budget, with the oversized inventory in the degrade record (the
        r15 contract). Returns ``"sharded_2d"`` or ``"sort"`` — any
        degraded rung routes to the plain partition these repairs always
        ran. NOTE the degrade is a return to the pre-r16 status quo, not
        a claim of a leaner footprint: the replicated-label sort path
        can model MORE per-chip bytes than the 2D family it declined
        (2D label terms are sharded) — what the pre-degrade protects is
        the NEW, worst-case-modeled per-peer boundary tables, whose
        real width is unknown until the partition is built."""
        from graphmine_tpu.obs.memmodel import predegrade_superstep
        from graphmine_tpu.pipeline import planner

        plan_family = planner.plan_superstep(
            graph.num_vertices, graph.num_messages,
            weighted=self.weights is not None,
            num_devices=self.num_shards,
        ).family
        if plan_family != "sharded_2d":
            return "sort"
        budget = int(
            planner.hbm_bytes_per_device() * planner._HBM_HEADROOM
        )
        fam, _fit, steps = predegrade_superstep(
            "sharded_2d", graph.num_vertices, graph.num_messages,
            graph.num_edges, self.weights is not None, budget,
            num_devices=self.num_shards,
        )
        if not steps:
            return "sharded_2d"
        if self.sink is not None:
            frm, _to, oversized = steps[0]
            self.sink.emit(
                "degrade", stage="delta_repair_plan", to="sort", depth=1,
                kind="mem_plan",
                error=(
                    f"plan-time memory pre-degrade: modeled {frm!r} "
                    f"footprint {oversized.total_bytes:,} B (per-peer "
                    f"exchange tables included) exceeds the {budget:,} B "
                    "budget — repairing via the one-all_gather partition"
                ),
                mem=oversized.record(),
            )
        return "sort"

    def _repair_sharded(
        self, graph, delta: EdgeDelta, seed: int = 0
    ) -> RepairResult:
        """Mesh twin of :func:`repair_labels`: same inits, propagation
        through the sharded entries, same shared verify/fallback tail
        (:func:`_verify_or_fallback`). The partition family comes from
        :meth:`_resolve_shard_family` — past the 2D crossover the
        repair supersteps run the neighbor-only boundary exchange
        (``partition_graph(build_plan2d=True)``), so a near-empty
        repair frontier stops paying an O(V) label all_gather per
        fixpoint superstep; labels are bit-identical either way (the
        r16 parity pins)."""
        from graphmine_tpu.obs.costmodel import emit_shard_exchange
        from graphmine_tpu.parallel.mesh import make_mesh
        from graphmine_tpu.parallel.sharded import (
            partition_graph,
            shard_graph_arrays,
            sharded_connected_components,
            sharded_lpa_fixpoint,
        )

        v = graph.num_vertices
        budget = frontier_budget(v, len(affected_vertices(delta)))
        mesh = make_mesh(self.num_shards)
        family = self._resolve_shard_family(graph)
        self.last_shard_family = family
        sg = shard_graph_arrays(
            partition_graph(
                graph, mesh=mesh, build_plan2d=family == "sharded_2d"
            ),
            mesh,
        )
        emit_shard_exchange(
            self.sink, "delta_repair", sg, version=self.snapshot.version
        )
        import jax
        import jax.numpy as jnp

        # One compiled-executable generation at a time: when this
        # delta's padded shard shapes differ from the previous apply's,
        # drop the stale jit entries before compiling the new ones.
        key = tuple(
            tuple(x.shape) for x in jax.tree_util.tree_leaves(sg)
            if hasattr(x, "shape")
        )
        if self._shard_jit_key is not None and key != self._shard_jit_key:
            _clear_sharded_jit_caches()
        self._shard_jit_key = key

        init_lpa = np.arange(v, dtype=np.int32)
        init_lpa[: len(self.labels)] = self.labels
        labels, it_l, conv_l = sharded_lpa_fixpoint(
            sg, mesh, max_iter=budget, init_labels=jnp.asarray(init_lpa)
        )
        # telemetry rides the while-loop carry and gives the convergence
        # verdict the bare call lacks: exhausted-at-budget iff the final
        # superstep still changed labels. The 2D family's CC replaces
        # the full-vector pointer jump with a CHUNK-LOCAL one (the
        # global jump needs exactly the O(V) random access the family
        # removes), so min-propagation converges in O(D + log Vc)-ish
        # supersteps on range-clustered repairs but up to O(diameter)
        # when a repaired chain alternates shards — grant the CC run a
        # D-scaled budget (each 2D superstep is exactly the cheap
        # exchange this family buys) so those repairs still land warm;
        # a genuinely pathological diameter exhausts it and takes the
        # cold-recompute fallback, same as always.
        budget_cc = (
            min(budget * self.num_shards, 512)
            if family == "sharded_2d" else budget
        )
        cc, tele = sharded_connected_components(
            sg, mesh, max_iter=budget_cc,
            init_labels=jnp.asarray(cc_repair_init(self.cc_labels, v, delta)),
            telemetry=True,
        )
        conv_c = tele.iterations < budget_cc or (
            len(tele.labels_changed) > 0 and int(tele.labels_changed[-1]) == 0
        )
        return _verify_or_fallback(
            graph, np.asarray(labels), np.asarray(cc), conv_l, conv_c,
            delta, budget, int(it_l) + int(tele.iterations),
            self.check_samples, self.sink, num_shards=self.num_shards,
            seed=seed, shards=(sg, mesh),
            tenant=getattr(self.store, "tenant", ""),
        )

    def _refresh_lof(self, graph, labels: np.ndarray, aff: np.ndarray):
        """Score delta-affected vertices through the streaming IVF-reuse
        path and splice them into the LOF column. The first delta
        bootstraps the window from the full feature matrix (and refreshes
        every score); later deltas STREAM-SCORE only the affected rows —
        but the feature matrix itself is still the whole-graph vectorized
        pass (vertex_features has no per-vertex entry point; features
        depend on neighbor degrees and community sizes, which a delta can
        shift beyond its own endpoints). That O(V+E) host term is the
        delta hot path's known cost floor — incremental features are the
        ROADMAP's serving scale-out item, not a claim this code makes."""
        from graphmine_tpu.ops.features import standardize, vertex_features
        from graphmine_tpu.ops.streaming_lof import StreamingLOF

        feats = np.asarray(
            standardize(
                vertex_features(graph, labels, include_clustering="sampled")
            ),
            np.float32,
        )
        grew = len(self.lof) < len(feats)
        if grew:
            # vertex growth: new vertices start at score 0 (fresh array —
            # concatenate never resizes in place)
            self.lof = np.concatenate([
                self.lof,
                np.zeros(len(feats) - len(self.lof), np.float32),
            ])
        k = min(self.lof_k, len(feats) - 2)
        if self._stream is None:
            if k < 1:
                # Too few vertices to LOF-score (k needs >= 1 real
                # neighbors): keep the existing scores and publish —
                # never crash the apply over an unscorable batch. The
                # bootstrap retries once the graph grows past the
                # threshold.
                return
            self._stream = StreamingLOF(
                k=k,
                capacity=min(self.lof_capacity, max(len(feats), self.lof_k + 2)),
                impl="ivf",
                sink=self.sink,
                centers=self._centers,
            )
            # np.array (copy), not asarray: device buffers view read-only
            self.lof = np.array(self._stream.update(feats), np.float32)
            self._centers = self._stream._centers
            return
        if len(aff):
            # Copy-on-write: the last published Snapshot (and any
            # QueryEngine serving it) aliases self.lof, so an in-place
            # splice would mutate the live engine mid-apply — torn reads
            # under the double-buffer's no-torn-read guarantee. A growth
            # delta already rebuilt the column fresh above; nothing
            # published aliases that one, so skip the second O(V) copy.
            lof = self.lof if grew else self.lof.copy()
            lof[aff] = self._stream.update(feats[aff])
            self.lof = lof
        self._centers = self._stream._centers

    def apply(
        self, delta: EdgeDelta, lof_mode: str = "refresh", batches: int = 1,
        extra_meta: dict | None = None,
    ) -> Snapshot:
        """Validate, splice, repair, rescore and publish one delta batch.

        Returns the newly published snapshot (its ``parent`` is the
        snapshot this ingestor last published/loaded). Emits one
        ``delta_apply`` record carrying the quarantine counts, the repair
        method (warm vs fallback) and the per-stage outcome.

        ``lof_mode="defer"`` (admission rung 2, serve/admission.py):
        skip the per-delta LOF refresh — the dominant non-repair cost —
        and publish with the outlier column marked stale
        (``lof_stale`` manifest flag). Labels are NEVER deferred: repair
        plus the sampled exact check run unconditionally, so served
        labels stay verified. The deferred vertices accumulate and the
        next ``refresh`` apply re-scores the whole backlog.

        ``batches``: how many submitted delta batches this apply settles
        in the debt ledger (a coalesced apply settles its whole group).

        ``extra_meta``: extra manifest keys for the publish (the apply
        worker stamps ``wal_applied_seq`` — the WAL cursor this snapshot
        absorbs — so startup/promotion can reconcile the watermark
        against the store instead of trusting a commit that may have
        been lost to a crash between publish and commit).
        """
        if lof_mode not in ("refresh", "defer"):
            raise ValueError(
                f"lof_mode must be 'refresh' or 'defer', got {lof_mode!r}"
            )
        t0 = time.perf_counter()
        span = (
            self.sink.span("delta_apply") if self.sink is not None
            else _null_ctx()
        )
        with span:
            # Parent snapshot's result columns, captured BEFORE the
            # repair overwrites them: the quality pass's drift baseline.
            # References, not copies — the LOF splice is copy-on-write
            # and labels are reassigned wholesale, so these stay the
            # parent's arrays.
            prev_labels, prev_lof = self.labels, self.lof
            prev_version = self.snapshot.version
            clean, quarantine = validate_delta(delta, self.num_vertices)
            if self.weights is not None:
                src2, dst2, w2, v2, stats = splice_edges(
                    self.src, self.dst, self.num_vertices, clean,
                    weights=self.weights,
                )
            else:
                src2, dst2, v2, stats = splice_edges(
                    self.src, self.dst, self.num_vertices, clean
                )
                w2 = None
            quarantine["unmatched_deletes"] += stats.pop("unmatched_deletes")
            from graphmine_tpu.graph.container import build_graph

            graph = build_graph(
                src2, dst2, num_vertices=v2, edge_weights=w2
            )
            t_r = time.perf_counter()
            result = self._repair(graph, clean)
            repair_seconds = time.perf_counter() - t_r
            self.src, self.dst, self.weights = src2, dst2, w2
            self.labels, self.cc_labels = result.labels, result.cc_labels
            aff = affected_vertices(clean)
            t_l = time.perf_counter()
            lof_stale = self._lof_pass(graph, result.labels, aff, lof_mode)
            lof_seconds = time.perf_counter() - t_l

            from graphmine_tpu.ops.census import census_table

            present, sizes, edge_counts = census_table(result.labels, graph)
            arrays = {
                "src": self.src,
                "dst": self.dst,
                "labels": self.labels,
                "cc_labels": self.cc_labels,
                "lof": self.lof,
                "census_present": np.asarray(present),
                "census_sizes": np.asarray(sizes),
                "census_edges": np.asarray(edge_counts),
            }
            if self.weights is not None:
                arrays["weights"] = self.weights
            if self._centers is not None:
                arrays["lof_centers"] = np.asarray(self._centers, np.float32)
            if self._canary is not None:
                # probe identity rides the store (the lof_centers
                # pattern): a restarted or promoted writer re-scores the
                # SAME frozen probe, so canary recall is comparable
                # across the whole version chain
                arrays.update(self._canary.arrays())
            snap = self.store.publish(
                arrays,
                fingerprint=graph_fingerprint(
                    self.src, self.dst, self.weights
                ),
                run_id=self.snapshot.meta.get("run_id", ""),
                mesh_shape=[self.num_shards],
                extra_meta={
                    **(extra_meta or {}),
                    **({"lof_stale": True} if lof_stale else {}),
                    **(
                        {"canary": self._canary.meta()}
                        if self._canary is not None else {}
                    ),
                } or None,
                sink=self.sink,
                epoch=self.epoch,
            )
            self.snapshot = snap
            if self.quality_enabled:
                # The result-quality pass (ISSUE 13): still inside the
                # delta_apply span, so quality_snapshot/quality_drift/
                # canary_score land span-joined to the publishing trace.
                # Bounded O(V) host work + the tiny frozen canary probe;
                # its seconds ride the quality_snapshot record (the
                # bench `quality_pass` sub-record measures the same
                # pass at three graph sizes).
                from graphmine_tpu.obs.quality import run_quality_pass

                # The cached state is reusable only when it describes
                # the ACTUAL parent (a skipped/failed pass leaves it at
                # an older version — drift vs stale sketches would lie).
                parent_state = self._quality_state
                if (
                    parent_state is not None
                    and parent_state.version != prev_version
                ):
                    parent_state = None
                try:
                    report = run_quality_pass(
                        self.labels, self.lof, snap.version,
                        parent_labels=prev_labels, parent_lof=prev_lof,
                        parent_version=prev_version,
                        parent_state=parent_state,
                        canary=self._canary,
                        sink=self.sink,
                        registry=(
                            self.sink.registry if self.sink is not None
                            else None
                        ),
                    )
                    self.last_quality = report
                    self._quality_state = report.state
                except Exception as e:  # noqa: BLE001 — telemetry only:
                    # a quality-pass crash must never fail (or appear to
                    # fail) a publish that already landed
                    if self.sink is not None:
                        self.sink.emit(
                            "warning",
                            message=f"quality pass failed: {e!r}",
                        )
            # Settle the debt ledger BEFORE emitting, so the record's
            # repair_debt snapshot reflects this apply as drained.
            self.debt.applied(
                method=result.method, iterations=result.iterations,
                budget=result.budget, batches=batches,
            )
            if self.sink is not None:
                self.sink.emit(
                    "delta_apply",
                    inserts=stats["inserted"],
                    deletes=stats["deleted"],
                    method=result.method,
                    iterations=result.iterations,
                    budget=result.budget,
                    quarantine=quarantine,
                    affected=len(aff),
                    version=snap.version,
                    num_vertices=v2,
                    num_edges=len(self.src),
                    batches=int(batches),
                    lof_mode=lof_mode,
                    lof_stale=bool(lof_stale),
                    seconds=round(time.perf_counter() - t0, 4),
                    # stage split: the repair-vs-recompute comparison the
                    # bench serve tier reports is the repair term; LOF
                    # refresh amortizes (full bootstrap only on the first
                    # apply of an ingestor's lifetime)
                    repair_seconds=round(repair_seconds, 4),
                    lof_seconds=round(lof_seconds, 4),
                    # the repair-debt ledger as of this publish — the
                    # obs_report SLO section's debt-timeline raw material
                    repair_debt=self.debt.snapshot(),
                )
        return snap

    def _lof_pass(
        self, graph, labels: np.ndarray, aff: np.ndarray, lof_mode: str
    ) -> bool:
        """Refresh — or defer — the LOF column for this apply. Returns
        whether the published column is stale. Deferred applies still
        pad the column for vertex growth (new vertices score 0, same as
        a refresh would seed them) so every published array stays
        [V]-aligned."""
        v = graph.num_vertices
        if lof_mode == "defer":
            if len(self.lof) < v:
                self.lof = np.concatenate(
                    [self.lof, np.zeros(v - len(self.lof), np.float32)]
                )
            self._stale_aff = np.union1d(self._stale_aff, aff.astype(np.int64))
            return True
        if self._stale_all:
            # loaded from an already-stale snapshot with no backlog
            # list: the only honest repair is re-scoring everything
            aff = np.arange(v, dtype=np.int64)
            self._stale_all = False
        elif len(self._stale_aff):
            aff = np.union1d(self._stale_aff, aff.astype(np.int64))
        self._stale_aff = np.empty(0, np.int64)
        self._refresh_lof(graph, labels, aff)
        return False


def _null_ctx():
    import contextlib

    return contextlib.nullcontext()
