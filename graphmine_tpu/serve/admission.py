"""Write-path admission control: one policy owner for overload verdicts.

PR 6 built the *signal* — the :class:`~graphmine_tpu.serve.delta.RepairDebt`
ledger (pending rows, ingest lag, warm ratio, budget fraction) — but
nothing consumed it: every POST /delta ran synchronously under the
publish lock, so a write burst or one slow repair convoyed every
subsequent delta unboundedly. This module closes the signal→policy loop
the same way the batch pipeline's planner ladders do (r3/r4): ONE owner
(:class:`AdmissionController`) reads the live debt state against
configured bounds and resolves every incoming delta to exactly one of
four verdicts, forming an overload degradation ladder:

``accept``
    The apply queue is idle: the delta applies immediately.
``queue``
    An apply is in flight but nothing else waits: the delta parks on the
    bounded apply queue and publishes next.
``coalesce``
    Deltas are already queued: this one will be MERGED with them into a
    single :class:`~graphmine_tpu.serve.delta.EdgeDelta`
    (:func:`coalesce_deltas` — order-exact multiset union), so a burst
    of N batches pays ONE splice + ONE warm repair instead of N.
``shed``
    A bound saturated (queue depth, pending repair-debt rows, or ingest
    lag): the delta is refused with a structured verdict the HTTP layer
    turns into **503 + Retry-After**. Shedding keeps the debt ledger —
    and therefore the staleness bound ``/healthz`` advertises — inside
    the configured envelope instead of letting the backlog grow without
    limit.

Orthogonal to the verdict, sustained pressure past ``defer_frac`` of the
bounds flips ``lof_mode`` to ``defer``: the apply skips the per-delta
LOF refresh (the dominant non-repair cost — a whole-graph feature pass)
and publishes the snapshot with its outlier column marked **stale**
(``lof_stale`` manifest flag, served alongside results); the next
uncongested apply re-scores the accumulated backlog. Labels are never
deferred — they still ride the sampled-exact-check gate, so served
labels are never a state the exact operator disputes.

Every resolution emits one ``admission`` record (verdict, reason, queue
depth, rows, debt snapshot) — the provenance trail
``tools/obs_report.py`` renders as the admission timeline next to the
repair-debt timeline.

All bounds are env-overridable following the ``GRAPHMINE_*`` convention
(``GRAPHMINE_ADMIT_MAX_PENDING_ROWS``, ``GRAPHMINE_ADMIT_MAX_LAG_S``,
``GRAPHMINE_ADMIT_MAX_QUEUE_DEPTH``, ``GRAPHMINE_ADMIT_DEFER_FRAC``,
``GRAPHMINE_ADMIT_DEADLINE_S``, ``GRAPHMINE_ADMIT_RETRY_AFTER_S``).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass

import numpy as np

from graphmine_tpu.serve.delta import EdgeDelta
from graphmine_tpu.serve.tenancy import DEFAULT_TENANT

# Defaults sized for the CPU-fallback container this repo develops in; a
# real deployment tunes via env. pending-rows bounds the repair backlog
# (the staleness a balancer reads), queue depth bounds memory held by
# parked request bodies, lag bounds how old an acked-but-unpublished
# write may get before new writes are refused instead.
DEFAULT_MAX_PENDING_ROWS = 100_000
DEFAULT_MAX_INGEST_LAG_S = 60.0
DEFAULT_MAX_QUEUE_DEPTH = 16
DEFAULT_DEFER_FRAC = 0.5
DEFAULT_DEADLINE_S = 30.0
DEFAULT_RETRY_AFTER_S = 2.0

_ENV = {
    "max_pending_rows": ("GRAPHMINE_ADMIT_MAX_PENDING_ROWS", int),
    "max_ingest_lag_s": ("GRAPHMINE_ADMIT_MAX_LAG_S", float),
    "max_queue_depth": ("GRAPHMINE_ADMIT_MAX_QUEUE_DEPTH", int),
    "defer_frac": ("GRAPHMINE_ADMIT_DEFER_FRAC", float),
    "deadline_s": ("GRAPHMINE_ADMIT_DEADLINE_S", float),
    "retry_after_s": ("GRAPHMINE_ADMIT_RETRY_AFTER_S", float),
}

VERDICTS = ("accept", "queue", "coalesce", "shed")


@dataclass(frozen=True)
class AdmissionBounds:
    """The admission envelope. Immutable — policy changes are a new
    controller, not a mutated one (same contract as PipelineConfig)."""

    max_pending_rows: int = DEFAULT_MAX_PENDING_ROWS
    max_ingest_lag_s: float = DEFAULT_MAX_INGEST_LAG_S
    max_queue_depth: int = DEFAULT_MAX_QUEUE_DEPTH
    # fraction of max_pending_rows / max_ingest_lag_s past which the
    # LOF-defer rung arms (0 = always defer, >=1 = never)
    defer_frac: float = DEFAULT_DEFER_FRAC
    # default per-request deadline: a batch still QUEUED when its
    # deadline passes is shed (the client stopped waiting; applying its
    # rows anyway would spend repair budget on an answer nobody reads)
    deadline_s: float = DEFAULT_DEADLINE_S
    # Retry-After hint on sheds
    retry_after_s: float = DEFAULT_RETRY_AFTER_S

    def __post_init__(self):
        if self.max_pending_rows < 1 or self.max_queue_depth < 1:
            raise ValueError(
                "max_pending_rows and max_queue_depth must be >= 1"
            )
        if self.max_ingest_lag_s <= 0 or self.deadline_s <= 0:
            raise ValueError("max_ingest_lag_s and deadline_s must be > 0")
        if self.defer_frac < 0:
            raise ValueError("defer_frac must be >= 0")
        if self.retry_after_s < 0:
            raise ValueError("retry_after_s must be >= 0")

    @classmethod
    def from_env(cls, **overrides) -> "AdmissionBounds":
        """Bounds from ``GRAPHMINE_ADMIT_*`` env vars; explicit keyword
        overrides win over env, env over defaults. A malformed env value
        raises loudly (a typo'd bound silently falling back to the
        default is exactly how an operator 'raises' a bound to no
        effect)."""
        kv = {}
        for field, (var, parse) in _ENV.items():
            raw = os.environ.get(var)
            if raw is None or field in overrides:
                continue
            try:
                kv[field] = parse(raw)
            except ValueError as e:
                raise ValueError(
                    f"{var}={raw!r} is not a valid {parse.__name__}"
                ) from e
        kv.update(overrides)
        return cls(**kv)

    def snapshot(self) -> dict:
        return {
            "max_pending_rows": self.max_pending_rows,
            "max_ingest_lag_s": self.max_ingest_lag_s,
            "max_queue_depth": self.max_queue_depth,
            "defer_frac": self.defer_frac,
            "deadline_s": self.deadline_s,
            "retry_after_s": self.retry_after_s,
        }


@dataclass(frozen=True)
class AdmissionDecision:
    """One resolution: the verdict plus everything the caller needs to
    act on it without re-reading policy state."""

    verdict: str              # accept | queue | coalesce | shed
    reason: str               # the bound/branch that decided, with numbers
    lof_mode: str             # refresh | defer (the rung-2 degradation)
    retry_after_s: float      # the 503 hint (shed verdicts only)
    rows: int
    queue_depth: int


class AdmissionController:
    """THE policy owner for the serve write path (no scattered threshold
    checks — acceptance criterion of ISSUE 8). Host-only bookkeeping
    under one lock; nothing here touches a device.

    ``sink`` gets one ``admission`` record per :meth:`resolve` and one
    ``delta_shed`` record per :meth:`record_shed`; ``registry`` mirrors
    verdict totals into scrapeable counters and the live queue-depth /
    overloaded gauges.

    ``tenant`` (ISSUE 16): a multi-tenant server runs ONE controller per
    tenant — each with its own bounds ladder (per-tenant overrides via
    the :class:`~graphmine_tpu.serve.tenancy.TenantRegistry`) and its
    own verdict counters, so tenant A saturating its debt bound sheds
    only A. Records carry the tenant id (absent = default tenant); the
    shared registry gauges are exported by the DEFAULT tenant's
    controller only — per-tenant controllers writing one unlabelled
    gauge would race each other into a meaningless last-writer value,
    so per-tenant admission state lives on ``/statusz`` instead.
    """

    def __init__(
        self,
        bounds: AdmissionBounds | None = None,
        sink=None,
        registry=None,
        tenant: str = DEFAULT_TENANT,
    ):
        self.bounds = bounds if bounds is not None else AdmissionBounds.from_env()
        self.sink = sink
        self.registry = registry
        self.tenant = tenant or DEFAULT_TENANT
        self._lock = threading.Lock()
        self._verdicts = {v: 0 for v in VERDICTS}
        self._deferred_lof = 0

    def _tenant_kv(self) -> dict:
        """The record tag: present only for non-default tenants (the
        schema contract — an absent key reads as the default tenant, so
        every pre-tenancy record stays valid)."""
        if self.tenant != DEFAULT_TENANT:
            return {"tenant": self.tenant}
        return {}

    # -- the ladder --------------------------------------------------------
    def _shed_reason(self, rows: int, queue_depth: int, debt: dict) -> str | None:
        """The saturation test, shared by :meth:`resolve` and
        :meth:`overloaded` so the balancer-drain signal and the actual
        shed verdict can never disagree on where the envelope is."""
        b = self.bounds
        if queue_depth >= b.max_queue_depth:
            return (
                f"queue_depth {queue_depth} >= max_queue_depth "
                f"{b.max_queue_depth}"
            )
        pending = int(debt.get("pending_rows", 0))
        if pending + rows > b.max_pending_rows:
            return (
                f"pending_rows {pending} + {rows} > max_pending_rows "
                f"{b.max_pending_rows}"
            )
        lag = float(debt.get("ingest_lag_s", 0.0))
        if lag > b.max_ingest_lag_s:
            return (
                f"ingest_lag {lag:.1f}s > max_ingest_lag_s "
                f"{b.max_ingest_lag_s:.1f}s"
            )
        return None

    def resolve(
        self, rows: int, queue_depth: int, debt: dict,
        applying: bool = False, emit: bool = True, replay: bool = False,
    ) -> AdmissionDecision:
        """Resolve one incoming delta batch against the live debt state.

        ``debt`` is a :meth:`RepairDebt.snapshot` dict; ``queue_depth``
        counts batches already parked on the apply queue; ``applying``
        says whether an apply is in flight right now. Emits the
        ``admission`` provenance record and updates the counters on
        every call. ``emit=False`` defers just the record to a later
        :meth:`emit_admission` call — the server resolves under its
        queue lock, and a sink's disk write must not serialize every
        handler, the worker and /healthz behind one fsync (counters and
        gauges are memory-only and stay here either way).

        ``replay=True`` (WAL startup replay / promotion, serve/wal.py):
        the batch was already accepted and durably acknowledged in a
        previous life — shedding it now would un-accept acknowledged
        work, so the shed rung is skipped and the verdict records why.
        The LOF-defer rung still applies (replay pressure is pressure).
        """
        rows = int(rows)
        shed = None if replay else self._shed_reason(rows, queue_depth, debt)
        if shed is not None:
            verdict, reason, lof_mode = "shed", shed, "refresh"
        else:
            lof_mode, defer_why = self._lof_mode_reason(rows, debt)
            if queue_depth >= 1:
                verdict = "coalesce"
                reason = (
                    f"{queue_depth} batch(es) already queued: merging into "
                    "one splice + one repair"
                )
            elif applying:
                verdict = "queue"
                reason = "apply in flight; parking on the apply queue"
            else:
                verdict = "accept"
                reason = "within bounds, queue idle"
            if replay:
                reason = (
                    "WAL replay of an already-acknowledged batch "
                    f"(shed rung skipped); {reason}"
                )
            if defer_why:
                reason += f"; {defer_why}"
        decision = AdmissionDecision(
            verdict=verdict, reason=reason, lof_mode=lof_mode,
            retry_after_s=self.bounds.retry_after_s if verdict == "shed" else 0.0,
            rows=rows, queue_depth=queue_depth,
        )
        with self._lock:
            self._verdicts[verdict] += 1
            if lof_mode == "defer" and verdict != "shed":
                self._deferred_lof += 1
        self._export(queue_depth, debt)
        if emit:
            self.emit_admission(decision, debt)
        return decision

    def emit_admission(self, decision: AdmissionDecision, debt: dict) -> None:
        """The ``admission`` provenance record for one resolution —
        split out so a caller that resolved under a lock can write the
        record after releasing it."""
        if self.sink is not None:
            self.sink.emit(
                "admission",
                verdict=decision.verdict,
                reason=decision.reason,
                queue_depth=decision.queue_depth,
                rows=decision.rows,
                lof_mode=decision.lof_mode,
                repair_debt=dict(debt),
                **self._tenant_kv(),
            )

    def _lof_mode_reason(self, rows: int, debt: dict) -> tuple[str, str]:
        """Rung 2 of the ladder: defer the LOF refresh under sustained
        pressure (past ``defer_frac`` of either bound). Never defers
        label repair — only the outlier column, which the snapshot then
        marks stale."""
        b = self.bounds
        pending = int(debt.get("pending_rows", 0)) + int(rows)
        lag = float(debt.get("ingest_lag_s", 0.0))
        row_thresh = b.defer_frac * b.max_pending_rows
        lag_thresh = b.defer_frac * b.max_ingest_lag_s
        if pending > row_thresh:
            return "defer", (
                f"lof deferred: pending_rows {pending} > "
                f"{b.defer_frac:g}*max ({row_thresh:g})"
            )
        if lag > lag_thresh:
            return "defer", (
                f"lof deferred: ingest_lag {lag:.1f}s > "
                f"{b.defer_frac:g}*max ({lag_thresh:g}s)"
            )
        return "refresh", ""

    def lof_mode(self, debt: dict, rows: int = 0) -> str:
        """Re-resolve just the LOF rung at apply time (pressure may have
        changed while the batch sat on the queue)."""
        return self._lof_mode_reason(rows, debt)[0]

    def overloaded(self, queue_depth: int, debt: dict) -> tuple[bool, str]:
        """Would a minimal (1-row) delta shed right now? The
        ``/healthz`` drain signal — driven by the SAME saturation test
        as the shed verdict, so balancer drain logic needs no duplicated
        thresholds."""
        reason = self._shed_reason(1, queue_depth, debt)
        return reason is not None, reason or ""

    # -- accounting --------------------------------------------------------
    def record_shed(
        self, reason: str, rows: int, queue_depth: int, debt: dict,
        stage: str = "admission",
    ) -> None:
        """One structured ``delta_shed`` record + counter. ``stage``:
        ``admission`` (refused at the front door) or ``deadline`` /
        ``shutdown`` (accepted, then shed off the queue before apply)."""
        if self.registry is not None:
            self.registry.counter(
                "graphmine_serve_deltas_shed_total",
                "delta batches refused or dropped by admission control",
            ).inc()
        if self.sink is not None:
            self.sink.emit(
                "delta_shed",
                stage=stage,
                reason=reason,
                rows=int(rows),
                queue_depth=int(queue_depth),
                retry_after_s=self.bounds.retry_after_s,
                repair_debt=dict(debt),
                **self._tenant_kv(),
            )

    def record_coalesce(self, info: dict, debt: dict) -> None:
        """One ``delta_coalesce`` record + counter per merged group."""
        if self.registry is not None:
            self.registry.counter(
                "graphmine_serve_deltas_coalesced_total",
                "delta batches merged into a coalesced apply",
            ).inc(int(info.get("batches", 0)))
        if self.sink is not None:
            self.sink.emit(
                "delta_coalesce", repair_debt=dict(debt),
                **self._tenant_kv(), **info,
            )

    def _export(self, queue_depth: int, debt: dict) -> None:
        reg = self.registry
        if reg is None:
            return
        if self.tenant != DEFAULT_TENANT:
            # Per-tenant controllers would race each other into one
            # unlabelled gauge (last writer wins = noise); the default
            # tenant's controller keeps the fleet-facing gauges and
            # per-tenant state is served on /statusz.
            return
        with self._lock:
            counts = dict(self._verdicts)
        for verdict, n in counts.items():
            # set-on-gauge, not counter.inc: resolve() under the queue
            # lock must stay cheap, and totals are authoritative in
            # self._verdicts (one owner) — the gauge mirrors it.
            reg.gauge(
                f"graphmine_serve_admission_{verdict}_total",
                f"delta batches resolved to the {verdict} verdict",
            ).set(n)
        reg.gauge(
            "graphmine_serve_delta_queue_depth",
            "delta batches parked on the apply queue",
        ).set(queue_depth)
        over, _ = self.overloaded(queue_depth, debt)
        reg.gauge(
            "graphmine_serve_overloaded",
            "1 when a new delta would shed (the /healthz drain signal)",
        ).set(1 if over else 0)

    def snapshot(self) -> dict:
        """Admission state for ``/statusz`` — verdict totals, the bounds
        in force, and the LOF-defer count."""
        with self._lock:
            counts = dict(self._verdicts)
            deferred = self._deferred_lof
        return {
            "tenant": self.tenant,
            "verdicts": counts,
            "lof_deferred": deferred,
            "bounds": self.bounds.snapshot(),
        }


# ---- coalescing ------------------------------------------------------------


def coalesce_deltas(
    deltas, base_src, base_dst
) -> tuple[EdgeDelta, dict]:
    """Merge validated delta batches into ONE order-exact ``EdgeDelta``.

    Splicing the merged delta produces BYTE-IDENTICAL edge arrays to
    splicing the batches sequentially (pinned by
    ``tests/test_admission.py::test_coalesce_equals_sequential``), which
    is what lets a burst pay one splice + one warm repair instead of N.
    The subtlety is insert/delete interaction ACROSS batches: a delete in
    batch *i* consumes, in order of preference,

    1. a remaining *base* occurrence of its ``(src, dst)`` key — splice
       removes earliest-position matches first, and base edges precede
       every in-window insert;
    2. the OLDEST surviving insert of that key from batches ``< i``
       (sequential appends keep batch order, so the oldest insert is the
       earliest position) — the pair cancels and never reaches splice;
    3. nothing — the delete is unmatched and dropped (counted, same
       quarantine semantics as a sequential apply).

    Within one batch, deletes resolve BEFORE that batch's inserts (splice
    processes deletes against the pre-batch arrays), so a batch can never
    delete its own inserts — exactly as sequential applies behave.

    ``base_src``/``base_dst`` are the ingestor's current edge arrays
    (occurrence counts only — O(E log d) via the same searchsorted
    prefilter as splice, never a full sort of E). Weighted deltas
    coalesce too: surviving inserts keep their weights (absent weights
    default to 1.0 when any batch in the group carries them).

    Returns ``(merged, info)`` with ``info = {batches, inserts, deletes,
    cancelled_pairs, unmatched_deletes, rows_in, rows_out}``.
    """
    deltas = list(deltas)
    if not deltas:
        raise ValueError("coalesce_deltas needs at least one delta")
    weighted = any(d.insert_weight is not None for d in deltas)
    if not any(d.num_deletes for d in deltas):
        # Insert-only fast path — the typical append-heavy burst, and
        # exactly when groups are largest: with no deletes there is
        # nothing to cancel, so the merge is a pure concatenation in
        # batch order (identical to sequential appends) and the per-row
        # cancellation walk below never runs on the overload hot path.
        rows_in = sum(d.num_inserts for d in deltas)
        merged = EdgeDelta(
            insert_src=np.concatenate([d.insert_src for d in deltas]),
            insert_dst=np.concatenate([d.insert_dst for d in deltas]),
            insert_weight=(
                np.concatenate([
                    d.insert_weight if d.insert_weight is not None
                    else np.ones(d.num_inserts, np.float32)
                    for d in deltas
                ]) if weighted else None
            ),
        )
        return merged, {
            "batches": len(deltas),
            "inserts": merged.num_inserts,
            "deletes": 0,
            "cancelled_pairs": 0,
            "unmatched_deletes": 0,
            "rows_in": rows_in,
            "rows_out": merged.num_inserts,
        }
    base_src = np.asarray(base_src, np.int64)
    base_dst = np.asarray(base_dst, np.int64)

    all_ids = [base_src, base_dst]
    for d in deltas:
        all_ids.extend(
            [d.insert_src, d.insert_dst, d.delete_src, d.delete_dst]
        )
    enc = int(max((int(a.max()) for a in all_ids if len(a)), default=0)) + 2

    # base occurrence counts, restricted to keys any delete targets
    del_keys = np.unique(
        np.concatenate(
            [d.delete_src * enc + d.delete_dst for d in deltas]
            or [np.empty(0, np.int64)]
        )
    )
    base_remaining: dict = {}
    if len(del_keys) and len(base_src):
        ekey = base_src * enc + base_dst
        pos = np.minimum(np.searchsorted(del_keys, ekey), len(del_keys) - 1)
        hit = del_keys[pos] == ekey
        counts = np.bincount(pos[hit], minlength=len(del_keys))
        base_remaining = {
            int(k): int(c) for k, c in zip(del_keys, counts) if c
        }

    pending: list = []            # [src, dst, weight, alive]
    by_key: dict = {}             # key -> deque of pending indices (oldest first)
    out_del: list = []            # surviving base-delete keys
    cancelled = unmatched = rows_in = 0

    for d in deltas:
        rows_in += d.num_inserts + d.num_deletes
        for s, t in zip(d.delete_src.tolist(), d.delete_dst.tolist()):
            k = s * enc + t
            left = base_remaining.get(k, 0)
            if left:
                base_remaining[k] = left - 1
                out_del.append((s, t))
            else:
                dq = by_key.get(k)
                if dq:
                    pending[dq.popleft()][3] = False
                    cancelled += 1
                else:
                    unmatched += 1
        w = d.insert_weight
        for i, (s, t) in enumerate(
            zip(d.insert_src.tolist(), d.insert_dst.tolist())
        ):
            idx = len(pending)
            pending.append([s, t, 1.0 if w is None else float(w[i]), True])
            by_key.setdefault(s * enc + t, deque()).append(idx)

    ins = [(p[0], p[1], p[2]) for p in pending if p[3]]
    merged = EdgeDelta(
        insert_src=np.asarray([r[0] for r in ins], np.int64),
        insert_dst=np.asarray([r[1] for r in ins], np.int64),
        delete_src=np.asarray([r[0] for r in out_del], np.int64),
        delete_dst=np.asarray([r[1] for r in out_del], np.int64),
        insert_weight=(
            np.asarray([r[2] for r in ins], np.float32) if weighted else None
        ),
    )
    info = {
        "batches": len(deltas),
        "inserts": merged.num_inserts,
        "deletes": merged.num_deletes,
        "cancelled_pairs": cancelled,
        "unmatched_deletes": unmatched,
        "rows_in": rows_in,
        "rows_out": merged.num_inserts + merged.num_deletes,
    }
    return merged, info
