"""Stdlib HTTP front end: JSON queries over double-buffered snapshots.

One :class:`SnapshotServer` owns a snapshot store, serves lookups from an
immutable :class:`~graphmine_tpu.serve.query.QueryEngine`, and accepts
delta batches. Publishes are **double-buffered**: a delta builds the next
engine off to the side and swaps it in with one reference assignment —
in-flight requests keep the engine they grabbed at entry, so a publish
never drops or torn-reads a live query (pinned by
``tests/test_serve.py::test_server_swap_under_live_queries``).

Endpoints (JSON unless noted):

====================  =====================================================
``GET  /healthz``      liveness (``ok``) + **readiness** (``ready``:
                       false while draining or stale-beyond-bound) +
                       snapshot version, snapshot age and repair debt —
                       the one documented probe contract
                       (docs/SERVING.md "healthz schema") the fleet
                       prober and external balancers key off
``GET  /statusz``      the SLO page: uptime, in-flight count, per-endpoint
                       latency quantiles (p50/p95/p99), error rates,
                       repair-debt ledger, batched-query stage split
``GET  /metrics``      live Prometheus text exposition (counters, gauges,
                       request-latency histogram buckets)
``GET  /alertz``       result-quality alerts + the quality section
                       (sketches, anomaly rate, drift, canary) —
                       evaluated at read time (docs/OBSERVABILITY.md
                       "Result quality")
``GET  /snapshot``     current snapshot manifest metadata
``GET  /vertex?v=``    one vertex: label, component, LOF, size, decile
``GET  /explain?vertex=`` per-vertex outlier explanation (LOF score +
                       rank/percentile, community id/size/decile,
                       neighbors + their score context) — the triage
                       companion to a firing canary/drift alert
``GET  /neighbors?v=`` neighbor ids of one vertex
``GET  /topk?community=&k=``  top-k LOF outliers of one community
``POST /query``        ``{"vertices": [...]}`` — the batched gather path
``POST /delta``        ``{"insert": [[s,d],...], "delete": [[s,d],...]}``
                       (``X-Deadline-Ms`` narrows the queued deadline;
                       ``X-Delta-Id`` is the idempotency key the WAL
                       dedupes retries on; ``X-Delta-Ack: wal`` answers
                       **202** once the batch is WAL-durable instead of
                       blocking to the publish)
``GET  /wal``          ``?from=SEQ&limit=N`` — WAL entries for log
                       shipping (the standby's tail; serve/wal.py)
``POST /promote``      standby → writer: fence the store epoch, adopt
                       the newest snapshot, replay the WAL tail, resume
                       writes (the fleet failover ladder's last rung)
``POST /reload``       reload the store's newest snapshot and swap
``POST /drain``        flip readiness off (``ready: false``) — take the
                       replica out of rotation without killing it
``POST /undrain``      restore readiness
``POST /profilez``     guarded on-demand XLA profiler capture
                       (``{"duration_ms": N}``): 403 unless the server
                       was started with a capture dir, 501 when
                       jax/profiler is unavailable; the trace dir is
                       tagged with the requesting trace_id
====================  =====================================================

**Fleet integration** (r10, serve/fleet.py): read endpoints honor an
``X-Serve-Version`` pin (409 on mismatch — the router's mixed-version
guard closes at the replica, where the swap happens), and the apply
worker REBASES on an unseen external publish before building on the
served engine (the /reload-vs-inflight-delta contract under the fleet
prober's reload cadence — see ``_apply_group``).

**Request observability** (docs/OBSERVABILITY.md "serving SLO"): every
request runs through one timing middleware — wall time observed into a
per-endpoint bucket histogram (``graphmine_serve_request_seconds``), an
``access_log`` record emitted per request (schema-registered; requests
slower than ``slow_request_s`` also carry the request body's sha256
digest, so a pathological batch is identifiable without logging its
payload), and an ``X-Request-Id`` stamped on every response — propagated
from the client when provided, generated otherwise, and carried by the
record alongside the sink's span identity so one slow request joins the
span timeline and the offline JSONL alike.

**Write-path overload protection** (r9, docs/SERVING.md "admission
control"): POST /delta no longer convoys on one publish lock. Every
batch resolves through ONE
:class:`~graphmine_tpu.serve.admission.AdmissionController` —
accept/queue/coalesce/shed — and accepted batches park on a bounded
apply queue drained by one background worker that MERGES everything
waiting into a single splice + repair
(:func:`~graphmine_tpu.serve.admission.coalesce_deltas`). Batches still
queued when their deadline passes are shed (the client stopped
listening); shed verdicts answer **503 + Retry-After** with a structured
body, and ``/healthz`` carries an ``overloaded`` field driven by the
same bounds so a balancer drains a saturated replica without duplicating
thresholds.

**Write durability + replicated writers** (r11, docs/SERVING.md
"Replicated writers"): with a :class:`~graphmine_tpu.serve.wal
.WriteAheadLog` attached, every admission-accepted batch is
append-fsync'd *before* it is acknowledged or queued — a writer kill
loses nothing acknowledged: startup replays the accepted-but-unapplied
tail through the admission path (deduped by ``X-Delta-Id``), and a
clean :meth:`stop` resolves WAL-durable queued batches as **202
accepted** (they replay on restart) instead of shedding acknowledged
work as 503s. Publishes carry this server's ``writer_epoch``; a
deposed writer's comeback publish is refused at the store
(``publish_fenced``). A server started with ``standby_of=<primary
url>`` refuses client writes and tails the primary's WAL instead
(bounded, observable replication lag on ``/healthz``); ``/promote``
turns it into the writer: fence the epoch, adopt the newest snapshot,
replay the WAL tail, resume writes.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import math
import os
import re
import secrets
import threading
import time
import warnings
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from graphmine_tpu.obs.alerts import AlertManager
from graphmine_tpu.obs.memmodel import (
    export_memory_gauges,
    host_memory,
    serve_mem_budget_bytes,
)
from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.spans import (
    TRACE_HEADER,
    TraceContext,
    sink_trace_header,
)
from graphmine_tpu.serve.admission import (
    AdmissionController,
    coalesce_deltas,
)
from graphmine_tpu.serve.delta import (
    DeltaIngestor,
    EdgeDelta,
    RepairDebt,
    validate_delta,
)
from graphmine_tpu.serve.query import QueryEngine
from graphmine_tpu.serve.shardplane import (
    ShardPlan,
    ShardRangeUnavailableError,
    ShardedWritePlane,
    writer_shards_from_env,
)
from graphmine_tpu.serve.snapshot import PublishFencedError, SnapshotStore
from graphmine_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    UnknownTenantError,
)
from graphmine_tpu.serve.wal import LogShipper, WriteAheadLog

# Client-supplied request ids are echoed into headers, records and logs:
# constrain them so a hostile header can't smuggle newlines/quotes.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")

# One table per method, mapping path -> _Handler method name. The SAME
# table resolves the histogram/access_log endpoint label (the path minus
# its slash) and dispatches the request, so a route can never exist in
# one place and not the other; unlisted paths 404 and share one
# "unknown" metric bucket (client typos must not mint unbounded label
# cardinality).
_GET_ROUTES = {
    "/healthz": "_ep_healthz",
    "/statusz": "_ep_statusz",
    "/metrics": "_ep_metrics",
    "/alertz": "_ep_alertz",
    "/snapshot": "_ep_snapshot",
    "/vertex": "_ep_vertex",
    "/explain": "_ep_explain",
    "/neighbors": "_ep_neighbors",
    "/topk": "_ep_topk",
    "/wal": "_ep_wal",
}
_POST_ROUTES = {
    "/query": "_ep_query",
    "/delta": "_ep_delta",
    "/reload": "_ep_reload",
    "/drain": "_ep_drain",
    "/undrain": "_ep_undrain",
    "/promote": "_ep_promote",
    "/profilez": "_ep_profilez",
}


def _jsonable(obj):
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


class _PendingDelta:
    """One accepted batch parked on the apply queue. State transitions
    (always under the queue condition's lock): ``queued`` →
    ``applying`` → ``done``/``error``, or ``queued`` → ``shed``
    (deadline passed / shutdown). ``event`` fires exactly once, at the
    terminal transition."""

    __slots__ = ("delta", "rows", "deadline", "deadline_s", "status",
                 "result", "error", "event", "shed_reason", "seq",
                 "delta_id", "async_ack", "trace", "t_accept",
                 "t_durable", "tenant", "shard_seqs")

    def __init__(
        self, delta: EdgeDelta, rows: int, deadline: float,
        deadline_s: float,
    ):
        self.delta = delta
        self.rows = rows
        self.deadline = deadline
        self.deadline_s = deadline_s  # the budget, for shed messages
        self.status = "queued"
        self.result: dict | None = None
        self.error: BaseException | None = None
        self.event = threading.Event()
        self.shed_reason = ""
        # Trace identity + causal-chain stamps (ISSUE 11 time-to-visible
        # SLO): `trace` is the accepting request's propagated traceparent
        # header (WAL-durable, so it survives kill/replay and log
        # shipping); t_accept/t_durable are monotonic marks of the
        # admission verdict and the WAL fsync — the apply worker turns
        # them into the per-stage breakdown (`delta_stages` record +
        # graphmine_serve_delta_stage_seconds histograms).
        self.trace = ""
        self.t_accept = time.monotonic()
        self.t_durable: float | None = None
        # WAL identity (serve/wal.py): seq is the batch's durable log
        # position (None = no WAL on this server), delta_id the client's
        # idempotency key. async_ack batches were answered 202 at append
        # time — nobody waits on the event, and the deadline is inf (a
        # durable acknowledgement is never deadline-shed: the client
        # already stopped waiting, by design).
        self.seq: int | None = None
        self.delta_id = ""
        self.async_ack = False
        # Tenant ownership (ISSUE 16): which tenant's sub-queue this
        # batch parks on — its debt, sheds and apply all charge HERE,
        # never to another tenant's ledger.
        self.tenant = DEFAULT_TENANT
        # Sharded-write-plane identity (r17, serve/shardplane.py): the
        # {shard: seq} map of every per-range WAL frame this batch is
        # durable in — the (delta_id, shard) exactly-once pairs. None on
        # the single-WAL (or WAL-less) path.
        self.shard_seqs: dict | None = None


class _TenantSink:
    """Sink proxy for one non-default tenant's ingest/alert plane: every
    record emitted through it carries ``tenant=<id>`` (the obs-schema
    contract — an ABSENT key reads as the default tenant, so the default
    tenant's path never pays the proxy and every pre-tenancy record
    stays valid). Spans, the registry and tracer identity pass through
    to the real sink untouched."""

    __slots__ = ("_sink", "_tenant")

    def __init__(self, sink, tenant: str):
        self._sink = sink
        self._tenant = tenant

    def emit(self, phase: str, **kv):
        kv.setdefault("tenant", self._tenant)
        return self._sink.emit(phase, **kv)

    def __getattr__(self, name):
        return getattr(self._sink, name)


class _TenantState:
    """Everything ONE tenant owns on this server (ISSUE 16): its
    namespaced store and double-buffered engine, its own admission
    ladder + repair-debt ledger (so a tenant saturating its bounds
    sheds only itself), its apply sub-queue — the unit the
    weighted-fair worker dequeues, with its deficit-round-robin
    balance — and its quality report + alert plane. The default
    tenant's state IS the legacy single-tenant server state, aliased
    through :class:`SnapshotServer` properties so every pre-tenancy
    call site (and test) reads and writes the same objects."""

    __slots__ = ("tenant", "store", "engine", "ingestor", "admission",
                 "debt", "alerts", "queue", "reserved", "deficit",
                 "quality_report", "plane")

    def __init__(self, tenant: str, store: SnapshotStore):
        self.tenant = tenant
        self.store = store
        self.engine: QueryEngine | None = None
        self.ingestor: DeltaIngestor | None = None
        self.admission: AdmissionController | None = None
        self.debt: RepairDebt | None = None
        self.alerts: AlertManager | None = None
        self.queue: deque = deque()
        self.reserved = 0        # queue slots promised mid-WAL-append
        self.deficit = 0.0       # DRR balance, in rows
        self.quality_report = None
        # Sharded write plane (r17, serve/shardplane.py): this tenant's
        # vertex-range writer shards + epoch coordinator. None below
        # writer_shards=2 — the single-WAL path stays bit-identical.
        self.plane: ShardedWritePlane | None = None


class SnapshotServer:
    """Query server + delta ingest endpoint over one snapshot store."""

    def __init__(
        self,
        store: SnapshotStore,
        host: str = "127.0.0.1",
        port: int = 0,
        sink=None,
        prom_out: str | None = None,
        num_shards: int = 1,
        slow_request_s: float = 1.0,
        admission: AdmissionController | None = None,
        ready_max_age_s: float | None = None,
        wal=None,
        writer_epoch: int | None = None,
        standby_of: str | None = None,
        primary_wal: str | None = None,
        ship_interval_s: float = 0.2,
        profilez_dir: str | None = None,
        writer_shards: int | None = None,
    ):
        self.store = store
        self.sink = sink
        self.prom_out = prom_out
        self.num_shards = num_shards
        self.slow_request_s = float(slow_request_s)
        # Readiness bound (liveness vs readiness split, docs/SERVING.md
        # "healthz schema"): past this snapshot age the replica reports
        # ready: false so a balancer/fleet prober stops routing to it.
        # None (default, or unset env GRAPHMINE_READY_MAX_AGE_S) = age
        # never gates readiness.
        if ready_max_age_s is None:
            raw = os.environ.get("GRAPHMINE_READY_MAX_AGE_S")
            if raw is not None:
                try:
                    ready_max_age_s = float(raw)
                except ValueError as e:
                    raise ValueError(
                        f"GRAPHMINE_READY_MAX_AGE_S={raw!r} is not a float"
                    ) from e
        self.ready_max_age_s = ready_max_age_s
        self._draining = False
        # Chaos seams (testing/faults.py replica_slow / replica_stale):
        # per-instance, so one replica of an in-process fleet can be
        # slowed or version-pinned without touching its peers (the
        # global fault_point hook is process-wide). Production value is
        # the zero/False no-op.
        self.chaos_delay_s = 0.0
        self.chaos_hold_version = False
        # The metric surface exists with or without a record sink: a
        # sinkless server still serves /metrics and /statusz.
        self.registry: Registry = (
            sink.registry if sink is not None else Registry()
        )
        # Multi-tenant state (ISSUE 16, serve/tenancy.py): one
        # _TenantState per tenant. The default tenant's is created here
        # and the legacy single-tenant attributes (engine, admission,
        # debt, alerts, queue) are property-aliased into it, so every
        # assignment below this point lands on the default state. _rr is
        # the weighted-fair dequeue's rotation of tenants with queued
        # work; the quantum is the per-visit row grant of the deficit
        # round-robin.
        self.tenancy = TenantRegistry()
        self._tenants: dict[str, _TenantState] = {
            DEFAULT_TENANT: _TenantState(DEFAULT_TENANT, store),
        }
        self._tenants_lock = threading.Lock()
        self._rr: deque = deque()
        raw_q = os.environ.get("GRAPHMINE_FAIR_QUANTUM_ROWS", "4096")
        try:
            self._fair_quantum_rows = max(1, int(raw_q))
        except ValueError as e:
            raise ValueError(
                f"GRAPHMINE_FAIR_QUANTUM_ROWS={raw_q!r} is not an int"
            ) from e
        self.debt = RepairDebt(registry=self.registry)
        # Result-quality alerting (ISSUE 13, obs/alerts.py): evaluated
        # on the EXISTING cadences — every /healthz (the fleet prober's
        # probe loop drives it fleet-wide), every /alertz or /statusz
        # read, and after each publish swap. No new threads.
        # GRAPHMINE_QUALITY=0 is the same kill switch the ingestor
        # honors: it must also stop the READ-time engine-state pass, or
        # the first /healthz after every swap would still pay the O(V)
        # census/sketch build the operator switched off.
        self.quality_enabled = os.environ.get("GRAPHMINE_QUALITY", "1") != "0"
        self.alerts = AlertManager(sink=sink, registry=self.registry)
        # The writer's last full quality pass (drift + canary, from the
        # ingestor); replicas fall back to the engine's lazily-built
        # QualityState — both served on /statusz + /alertz.
        self._quality_report = None
        # The single write-path policy owner (serve/admission.py). A
        # caller-supplied controller keeps its own bounds; the default
        # reads GRAPHMINE_ADMIT_* env.
        self.admission = admission if admission is not None else (
            AdmissionController(sink=sink, registry=self.registry)
        )
        if self.admission.sink is None:
            self.admission.sink = sink
        if self.admission.registry is None:
            self.admission.registry = self.registry
        # The durable write-ahead log (serve/wal.py). ``wal`` may be a
        # WriteAheadLog, a directory path, or True (= <store>/wal). None
        # keeps the pre-r11 in-memory-only write path.
        if wal is True:
            wal = os.path.join(store.root, "wal")
        if isinstance(wal, str):
            wal = WriteAheadLog(wal, sink=sink, registry=self.registry)
        self.wal: WriteAheadLog | None = wal
        if self.wal is not None:
            if self.wal.sink is None:
                self.wal.sink = sink
            if self.wal.registry is None:
                self.wal.registry = self.registry
        # Vertex-range writer sharding (r17, serve/shardplane.py).
        # writer_shards=1 (the default, env GRAPHMINE_WRITER_SHARDS) is
        # the EXACT pre-shard write path — no plane object exists, every
        # branch below keys off `ts.plane is None`. Above 1, each
        # tenant's namespace gets its own ShardedWritePlane (per-range
        # WAL + admission + debt) and epoch coordinator; the whole-graph
        # `wal=` and `standby_of=` knobs are mutually exclusive with it
        # (durability and standby machinery move INTO the plane, one
        # per range — double-logging every batch would make neither log
        # authoritative).
        if writer_shards is None:
            writer_shards = writer_shards_from_env(1)
        self.writer_shards = int(writer_shards)
        if self.writer_shards > 1:
            if self.wal is not None:
                raise ValueError(
                    "writer_shards > 1 owns per-range WALs under "
                    f"{store.root}/shards; drop wal= (the plane logs "
                    "every sub-batch itself)"
                )
            if standby_of is not None:
                raise ValueError(
                    "writer_shards > 1 replicates per range "
                    "(plane.attach_standby), not per process; drop "
                    "standby_of="
                )
        # The epoch this writer stamps on publishes: adopt the store's
        # unless told otherwise (a promotion bumps it via promote()).
        self.writer_epoch = (
            store.current_epoch() if writer_epoch is None
            else int(writer_epoch)
        )
        self.standby_of = standby_of.rstrip("/") if standby_of else None
        self.primary_wal = primary_wal
        self._shipper: LogShipper | None = None
        if self.standby_of is not None:
            if self.wal is None:
                raise ValueError(
                    "a standby needs its own WAL directory to ship the "
                    "primary's log into (pass wal=...)"
                )
            self._shipper = LogShipper(
                self.wal, self.standby_of,
                poll_interval_s=ship_interval_s, sink=sink,
                registry=self.registry,
            )
            # Compaction guard: the shipped watermark describes the
            # PRIMARY's store — this standby's own store (a bootstrap
            # copy, possibly old) pins what its WAL may prune, or a
            # separate-store promotion would rewind into pruned
            # entries (acked loss past the shipped lag).
            self.wal.protect_version = None  # set after the store loads
        snap = store.load(sink=sink)
        if snap is None:
            raise ValueError(
                f"snapshot store at {store.root!r} is empty; publish one "
                "first (pipeline --snapshot-out or serve_cli publish)"
            )
        # The double buffer: _engine is replaced atomically (one reference
        # assignment); handlers bind it to a local once per request.
        self._engine = QueryEngine(snap)
        if self._shipper is not None:
            self.wal.protect_version = snap.version
        if self.writer_shards > 1:
            self._attach_plane(self._tenants[DEFAULT_TENANT], snap)
        self._ingestor: DeltaIngestor | None = None
        # One publisher at a time — the store's generation rotation (and
        # the ingestor's host state) assume it. Held by the apply worker
        # around each apply+swap, and by /reload.
        self._delta_lock = threading.Lock()
        # The bounded apply queues (one sub-queue per tenant, each gated
        # by that tenant's admission bounds) + the one background worker
        # that drains them weighted-fair. Each tenant's `reserved`
        # counts slots promised to batches that are mid-WAL-append
        # (between the admission verdict and the enqueue) so concurrent
        # submitters can't overshoot max_queue_depth through that
        # window. ONE condition guards every sub-queue: the worker waits
        # on work from any tenant.
        self._queue_cv = threading.Condition()
        self._applying = False
        self._worker: threading.Thread | None = None
        self._worker_stop = False
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # Serializes promote(): a router retry racing a slow promotion
        # (or two operators) must not fence twice and re-enqueue the
        # same pending entries (deltas are not idempotent). _promoted
        # marks a COMPLETED promotion so the retry short-circuits.
        self._promote_lock = threading.Lock()
        self._promoted = False
        # Set when a publish came back fenced (the store's epoch moved
        # past ours — a standby was promoted while we were partitioned):
        # this process is a DEPOSED writer. It must stop answering 202
        # "accepted, durable" for new deltas — its publishes refuse
        # forever, so the acknowledgements would be black holes (the
        # promoted writer does not tail a zombie's WAL). Reads keep
        # serving; writes refuse 503 until a later /promote re-legitimizes
        # this process.
        self._fenced: str | None = None
        self._host, self._port = host, port
        self._t0_wall = time.time()
        self._t0_mono = time.perf_counter()
        self._inflight = 0
        self._req_lock = threading.Lock()
        self._endpoint_errors: dict = {}
        # On-demand device profiling (POST /profilez): disabled unless a
        # capture directory is configured — an open profiler endpoint on
        # a serving replica would let any client burn device time and
        # disk. One capture at a time (the profiler is process-global).
        self.profilez_dir = profilez_dir or os.environ.get(
            "GRAPHMINE_PROFILEZ_DIR"
        )
        self._profilez_lock = threading.Lock()
        # Serve-process memory budget (ISSUE 14): resolved ONCE at
        # construction so a malformed env override fails loudly here,
        # not silently per scrape (env GRAPHMINE_SERVE_MEM_BUDGET_BYTES
        # → host MemTotal → None = headroom unknown, rule never fires).
        self._mem_budget = serve_mem_budget_bytes()
        self._export_metrics()
        # Startup replay: accepted-but-unapplied WAL entries re-enqueue
        # through the admission path (replay never sheds — the work was
        # already acknowledged) so a killed writer's restart publishes
        # everything it ever 202'd. Standbys skip it: the primary owns
        # applies until /promote.
        if self.wal is not None and self.standby_of is None:
            # A fresh primary WAL records its store's current version as
            # the (0, version) baseline pair — the voucher that lets a
            # standby bootstrapped from a copy of THIS version replay
            # from seq 0 exactly at promotion. Standbys never write it:
            # their store is a copy, and copies are vouched for by the
            # primary's shipped history, not local guesses.
            self.wal.note_baseline(snap.version)
            # Reconcile before replaying: a crash between publish and
            # wal.commit leaves the watermark behind the store (replay
            # would double-apply the absorbed entries); a store rollback
            # to .prev leaves it ahead (replay would skip acknowledged
            # work the rollback evicted).
            self._reconcile_wal_cursor(snap, "startup")
            self._replay_wal(source="startup")
        # Sharded-plane startup (r17): converge the epoch store first —
        # a coordinator crash between stage and commit left either a
        # finishable generation (re-commit) or a torn one (sweep); only
        # then replay each range's accepted-but-unapplied WAL tail, so
        # replayed applies build on the recovered committed epoch.
        if self.writer_shards > 1:
            self._replay_plane(
                self._tenants[DEFAULT_TENANT], source="startup"
            )

    # -- lifecycle --------------------------------------------------------
    def start(self) -> tuple[str, int]:
        """Bind and serve on a daemon thread; returns (host, port)."""
        server = self

        class Handler(_Handler):
            srv = server

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="graphmine-serve",
            daemon=True,
        )
        self._thread.start()
        if self._shipper is not None:
            self._shipper.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        if self._shipper is not None:
            self._shipper.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # Drain the apply worker. WAL-durable queued batches are NOT
        # shed: their acceptance is on disk and they replay on restart,
        # so a clean stop resolves them as **accepted** (202) — a 503
        # here would tell the client to resubmit work the server still
        # owns (the r11 shutdown contract, tests/test_wal.py). Only
        # never-durable entries (no WAL) shed with the shutdown verdict.
        with self._queue_cv:
            self._worker_stop = True
            leftovers = []
            for ts in list(self._tenants.values()):
                leftovers.extend(ts.queue)
                ts.queue.clear()
            self._rr.clear()
            for p in leftovers:
                if p.seq is not None or p.shard_seqs:
                    p.status = "accepted"
                    p.result = self._accepted_payload(
                        p, note="server stopping; replays on restart",
                    )
                else:
                    p.status = "shed"
                    p.shed_reason = "server shutting down"
            self._queue_cv.notify_all()
        for p in leftovers:
            ts = self._tenants[p.tenant]
            ts.debt.abandoned()
            if p.status == "shed":
                ts.debt.shed(p.rows)
                ts.admission.record_shed(
                    p.shed_reason, p.rows, 0, ts.debt.snapshot(),
                    stage="shutdown",
                )
            p.event.set()
        if self._worker is not None:
            self._worker.join(timeout=30)
            self._worker = None
        self._worker_stop = False
        if self.wal is not None:
            self.wal.close()
        for ts in list(self._tenants.values()):
            if ts.plane is not None:
                ts.plane.close()

    def _ensure_worker(self) -> None:
        """Start the apply worker lazily (first delta) so in-process
        users (serve_cli one-shots, the bench tier) get the full
        admission path without calling :meth:`start`."""
        with self._queue_cv:
            if self._worker_stop:
                # stop() is mid-shutdown: it already shed everything
                # queued (including this caller's batch). Spawning a
                # fresh worker here would clear the stop flag under
                # stop()'s feet and leave it joining a thread that
                # never exits.
                return
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._apply_worker, name="graphmine-delta-apply",
                daemon=True,
            )
            self._worker.start()

    # -- default-tenant aliases -------------------------------------------
    # The pre-tenancy single-tenant attributes now live on the default
    # tenant's _TenantState; these properties keep every existing call
    # site (and test) reading and writing the same objects, so a
    # single-tenant deployment never sees the tenancy layer.
    @property
    def _default(self) -> _TenantState:
        return self._tenants[DEFAULT_TENANT]

    @property
    def _engine(self) -> QueryEngine:
        return self._tenants[DEFAULT_TENANT].engine

    @_engine.setter
    def _engine(self, value: QueryEngine) -> None:
        self._tenants[DEFAULT_TENANT].engine = value

    @property
    def _ingestor(self):
        return self._tenants[DEFAULT_TENANT].ingestor

    @_ingestor.setter
    def _ingestor(self, value) -> None:
        self._tenants[DEFAULT_TENANT].ingestor = value

    @property
    def admission(self) -> AdmissionController:
        return self._tenants[DEFAULT_TENANT].admission

    @admission.setter
    def admission(self, value: AdmissionController) -> None:
        self._tenants[DEFAULT_TENANT].admission = value

    @property
    def debt(self) -> RepairDebt:
        return self._tenants[DEFAULT_TENANT].debt

    @debt.setter
    def debt(self, value: RepairDebt) -> None:
        self._tenants[DEFAULT_TENANT].debt = value

    @property
    def alerts(self) -> AlertManager:
        return self._tenants[DEFAULT_TENANT].alerts

    @alerts.setter
    def alerts(self, value: AlertManager) -> None:
        self._tenants[DEFAULT_TENANT].alerts = value

    @property
    def _quality_report(self):
        return self._tenants[DEFAULT_TENANT].quality_report

    @_quality_report.setter
    def _quality_report(self, value) -> None:
        self._tenants[DEFAULT_TENANT].quality_report = value

    @property
    def _queue(self) -> deque:
        return self._tenants[DEFAULT_TENANT].queue

    @property
    def _reserved(self) -> int:
        return self._tenants[DEFAULT_TENANT].reserved

    @_reserved.setter
    def _reserved(self, value: int) -> None:
        self._tenants[DEFAULT_TENANT].reserved = value

    # -- tenant plumbing ---------------------------------------------------
    def _tenant_state(self, tenant: str, create: bool = True) -> _TenantState:
        """The tenant's state, admitting it lazily on first touch when
        its store namespace already holds a published snapshot. A
        malformed id raises ``ValueError`` (HTTP 400, before any path is
        built); a valid id with no namespace behind it raises
        :class:`UnknownTenantError` (HTTP 404)."""
        ts = self._tenants.get(tenant)
        if ts is not None:
            return ts
        # validates the id (ValueError -> 400) before touching the disk
        store = self.store.for_tenant(tenant)
        if not create:
            raise UnknownTenantError(tenant)
        snap = store.load(sink=self.sink)
        if snap is None:
            raise UnknownTenantError(tenant)
        ts = self._make_tenant_state(tenant, store, snap)
        with self._tenants_lock:
            registered = self._tenants.setdefault(tenant, ts)
        self.tenancy.note(tenant)
        self.tenancy.note_bytes(tenant, registered.engine.snapshot.nbytes)
        if registered is ts and ts.plane is not None:
            # Replay only AFTER the state is registered: replayed
            # batches park on ts.queue and the worker resolves the
            # tenant through self._tenants — parking work under an
            # unregistered name would KeyError in the pop. (A lost
            # setdefault race closes the plane we built for nothing.)
            self._replay_plane(ts, source="tenant_admit")
        elif registered is not ts and ts.plane is not None:
            ts.plane.close()
        return registered

    def _make_tenant_state(
        self, tenant: str, store: SnapshotStore, snap,
    ) -> _TenantState:
        ts = _TenantState(tenant, store)
        sink = self._tenant_sink(tenant)
        # registry=None on the ledger and the alert manager: per-tenant
        # instances writing the one unlabelled gauge each would race
        # last-writer-wins; the default tenant keeps the fleet-facing
        # gauges, per-tenant state is served on /statusz and /alertz.
        ts.debt = RepairDebt()
        ts.admission = AdmissionController(
            bounds=self.tenancy.bounds_for(tenant), sink=self.sink,
            registry=self.registry, tenant=tenant,
        )
        ts.alerts = AlertManager(sink=sink, tenant=tenant)
        ts.engine = QueryEngine(snap)
        if self.standby_of is None:
            # A writer's lazily-admitted namespace inherits the process
            # fence: without this, a deposed writer could keep
            # publishing into tenant stores the promotion never touched.
            try:
                store.fence_epoch(self.writer_epoch)
            except (OSError, ValueError):
                pass  # equal/lower epochs are already fenced
        if self.writer_shards > 1:
            # Tenancy × shardplane composition (r17): tenancy splits by
            # namespace, the plane splits each namespace's range space —
            # a lazily-admitted tenant gets its own full set of range
            # writers and its own epoch chain.
            self._attach_plane(ts, snap)
        return ts

    def _attach_plane(self, ts: _TenantState, snap) -> None:
        """Build one tenant's sharded write plane over its namespace
        store and converge its epoch directory (finish or sweep a torn
        publish) before anything can read or append. Non-default
        tenants pass registry=None — same rule as their alert manager:
        the per-shard gauge children are keyed by shard alone, and two
        tenants' shard-0 series racing one child would be the
        last-writer-wins bug tenancy exists to prevent."""
        plan = ShardPlan.build(
            self.writer_shards, int(len(snap["labels"]))
        )
        ts.plane = ShardedWritePlane(
            ts.store, plan, sink=self._tenant_sink(ts.tenant),
            registry=(
                self.registry if ts.tenant == DEFAULT_TENANT else None
            ),
            tenant=ts.tenant,
            # per-shard ladders inherit the server's envelope — a batch
            # the front ladder admitted must not be re-shed by a shard
            # ladder running tighter DEFAULTS than the operator set
            admission_bounds=self.admission.bounds,
        )
        ts.plane.coordinator.recover()
        ts.plane.note_versions(ts.plane.coordinator.version_vector())

    def _tenant_sink(self, tenant: str):
        """The sink a tenant's ingest/alert plane emits through: the
        real sink for the default tenant, the tagging proxy otherwise."""
        if self.sink is None or tenant == DEFAULT_TENANT:
            return self.sink
        return _TenantSink(self.sink, tenant)

    def engine_for(self, tenant: str) -> QueryEngine:
        """The tenant's double-buffered engine — the read path's router.
        Every handler binds it ONCE per request, so a concurrent swap
        (of any tenant) never mixes two versions inside one response."""
        if not tenant or tenant == DEFAULT_TENANT:
            return self._engine
        return self._tenant_state(tenant).engine

    # -- snapshot swap ----------------------------------------------------
    @property
    def engine(self) -> QueryEngine:
        return self._engine

    def _swap(self, engine: QueryEngine, tenant: str = DEFAULT_TENANT) -> None:
        self._tenants[tenant].engine = engine  # atomic ref: the flip
        self.tenancy.note_bytes(tenant, engine.snapshot.nbytes)
        if tenant != DEFAULT_TENANT:
            # the fleet-facing gauges and the standby compaction guard
            # track the default tenant's chain; per-tenant versions and
            # bytes are served on /healthz + /statusz
            return
        if self.standby_of is not None and self.wal is not None:
            # a standby that reload-followed to a newer store version
            # may release its WAL retention up to that version's floor
            self.wal.protect_version = engine.version
        self._export_metrics()

    def _current_trace_header(self) -> str:
        """The emitting thread's current span as a propagatable header
        ("" without a tracer). Inside the request middleware this is the
        ADOPTED span of an inherited traceparent, so a delta's WAL entry
        and worker-side records stay in the originating request's
        trace."""
        return sink_trace_header(self.sink)

    def _run_labels(self) -> dict | None:
        """The run_id label BOTH exposition paths attach — the textfile
        and the live scrape must emit the same series, or a deployment
        scraping both double-counts every sample."""
        tracer = getattr(self.sink, "tracer", None)
        return {"run_id": tracer.run_id} if tracer is not None else None

    def _export_metrics(self) -> None:
        self.registry.gauge(
            "graphmine_serve_snapshot_version",
            "snapshot version currently serving queries",
        ).set(self._engine.version)
        if self.prom_out:
            try:
                self.registry.write_textfile(
                    self.prom_out, labels=self._run_labels()
                )
            except OSError:
                pass  # metrics export must never take queries down

    def reload(self, tenant: str = DEFAULT_TENANT) -> dict:
        """Load the tenant's newest store snapshot; swap if it is newer
        than the one serving (another process may have published).
        Serialized with delta ingest, and a swap drops the ingestor: its
        host edge/label state derives from the snapshot it last
        published, and applying a delta on top of the STALE state would
        silently discard the externally published snapshot's edges (its
        next publish would still chain version numbers from the store's
        manifest)."""
        ts = self._tenant_state(tenant)
        if self.chaos_hold_version:
            # replica_stale injector: this replica never advances
            return {
                "version": ts.engine.version, "swapped": False,
                "held": True,
            }
        with self._delta_lock:
            snap = ts.store.load(sink=self.sink)
            swapped = snap is not None and snap.version != ts.engine.version
            if swapped:
                self._swap(QueryEngine(snap), tenant=ts.tenant)
                ts.ingestor = None
            return {"version": ts.engine.version, "swapped": swapped}

    def apply_delta(
        self, payload: dict, deadline_s: float | None = None,
        delta_id: str | None = None, ack: str | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> dict:
        """Ingest one delta batch (the POST /delta body) through
        admission control. Returns the publish result — or, on a shed,
        a structured refusal dict (``verdict: "shed"``) the HTTP layer
        turns into 503 + Retry-After.

        The caller blocks until its batch publishes (possibly coalesced
        with others — ``coalesced`` in the result says how many batches
        the publish carried) or until its deadline passes while still
        queued, in which case it is shed: an apply the client has
        stopped waiting for would spend repair budget on an answer
        nobody reads. ``deadline_s`` (the ``X-Deadline-Ms`` header,
        propagated end-to-end by the fleet router and serve_cli) narrows
        the queued-batch deadline below the admission default — a
        client's budget can tighten the envelope, never widen it.

        **Durability** (r11, serve/wal.py): with a WAL attached, an
        accepted batch is append-fsync'd BEFORE it can queue or be
        acknowledged. ``delta_id`` (the ``X-Delta-Id`` header) is the
        idempotency key — a retry of a logged id returns ``verdict:
        "duplicate"`` instead of a second apply. ``ack="wal"`` (the
        ``X-Delta-Ack: wal`` header) returns ``verdict: "accepted"``
        (HTTP **202**) right after the fsync: the batch applies in the
        background, and survives a writer kill via startup replay —
        durable acknowledgements are never deadline-shed.

        **Tenancy** (ISSUE 16): the batch charges ``tenant``'s ledger
        end to end — ITS admission bounds decide the verdict against ITS
        queue depth and debt, the batch parks on ITS sub-queue, and the
        WAL frame carries the tenant id durably so replay and the
        idempotency dedupe stay tenant-scoped. One tenant saturating its
        bounds sheds only itself.
        """
        # Resolve the tenant FIRST: an unknown tenant must 404 before
        # any admission/WAL side effect, and a malformed id must 400.
        ts = self._tenant_state(tenant)
        tenant = ts.tenant
        if self.standby_of is not None:
            # A standby is not a writer: it tails the primary's WAL and
            # waits for /promote. Accepting a delta here would be the
            # split-brain the epoch fence exists to prevent.
            return self._shed_payload(
                f"standby of {self.standby_of}: writes go to the primary "
                "(or POST /promote to make this replica the writer)",
                ts.admission.bounds.retry_after_s,
            )
        if self._fenced is not None:
            # Deposed writer: a publish already refused with
            # publish_fenced, so every future apply here would too.
            # Accepting (and WAL-fsyncing) more deltas would acknowledge
            # work that can never publish on this store and is never
            # shipped to the promoted writer — the acknowledgement would
            # lie. Refuse until a /promote re-fences in our favor.
            return self._shed_payload(
                f"writer fenced ({self._fenced}): a newer writer owns "
                "the store; send writes to the promoted writer or POST "
                "/promote here to take ownership back",
                ts.admission.bounds.retry_after_s,
            )
        if ack not in (None, "wal"):
            raise ValueError(f"unknown ack mode {ack!r} (use 'wal')")
        if ack == "wal" and self.wal is None and ts.plane is None:
            raise ValueError(
                "X-Delta-Ack: wal needs a server running with a "
                "write-ahead log (serve --wal or --writer-shards)"
            )
        bound = ts.admission.bounds.deadline_s
        deadline_s = bound if deadline_s is None else max(
            0.001, min(float(deadline_s), bound)
        )
        # Fast-path dedupe: a retry of an id this WAL already holds maps
        # onto the original accept — applied or still pending, never a
        # second apply (the duplicate-submit parity pin). Tenant-scoped:
        # two tenants reusing the same id are distinct batches.
        if delta_id and self.wal is not None:
            seq = self.wal.lookup(delta_id, tenant=tenant)
            if seq is not None:
                return self._duplicate_payload(delta_id, seq, tenant=tenant)
        delta = EdgeDelta.from_pairs(
            insert=payload.get("insert", ()), delete=payload.get("delete", ())
        )
        if (
            delta.insert_weight is not None
            and ts.engine.snapshot.get("weights") is None
        ):
            # Refuse HERE, before the batch can queue: merged into a
            # coalesced group, this splice-time error would fail every
            # innocent batch in the group with it (sequential applies
            # would only fail this one).
            raise ValueError(
                "delta carries insert weights but the served snapshot is "
                "unweighted; drop the weight column or republish a "
                "weighted snapshot"
            )
        rows = delta.num_inserts + delta.num_deletes
        # Only memory-cheap work happens under the queue lock (the
        # worker, /healthz and every other handler contend on it); the
        # sink's record writes — potentially a disk fsync each — happen
        # after release. _reserved holds this batch's queue slot across
        # the out-of-lock WAL fsync below, so concurrent submitters
        # can't resolve their way past max_queue_depth through that
        # window.
        with self._queue_cv:
            if self._worker_stop:
                # stop() already drained the queue; parking here would
                # wait on a worker that is exiting
                return self._shed_payload(
                    "server shutting down",
                    ts.admission.bounds.retry_after_s,
                )
            debt_at_resolve = ts.debt.snapshot()
            decision = ts.admission.resolve(
                rows=rows, queue_depth=len(ts.queue) + ts.reserved,
                debt=debt_at_resolve, applying=self._applying, emit=False,
            )
            if decision.verdict != "shed":
                ts.reserved += 1
        if decision.verdict == "shed":
            ts.admission.emit_admission(decision, debt_at_resolve)
            ts.debt.shed(rows)
            ts.admission.record_shed(
                decision.reason, rows, decision.queue_depth,
                ts.debt.snapshot(),
            )
            return self._shed_payload(decision.reason, decision.retry_after_s)
        # Durability point: the fsync'd append happens BEFORE the batch
        # can queue — from here on, a kill replays it on restart, so the
        # acknowledgement below never lies.
        pending = _PendingDelta(delta, rows, 0.0, deadline_s)
        pending.delta_id = delta_id or ""
        pending.async_ack = ack == "wal"
        pending.trace = self._current_trace_header()
        pending.tenant = tenant
        try:
            if ts.plane is not None:
                # Sharded plane (r17): the plane splits the batch by
                # dst-range ownership, runs each owner shard's admission
                # ladder, dedupes (delta_id, shard) per shard, and
                # fsyncs one sub-batch per touched range. The batch
                # queues with the ORIGINAL unsplit delta — the apply
                # splices exactly what a single-WAL server would, so
                # published bytes are identical by construction.
                try:
                    sub = ts.plane.submit(
                        delta, delta_id=delta_id or "",
                        deadline_s=deadline_s,
                        queue_depth=decision.queue_depth,
                        applying=self._applying, trace=pending.trace,
                    )
                except ShardRangeUnavailableError as exc:
                    ts.admission.emit_admission(decision, debt_at_resolve)
                    ts.debt.shed(rows)
                    ts.admission.record_shed(
                        str(exc), rows, decision.queue_depth,
                        ts.debt.snapshot(),
                    )
                    return self._shed_payload(
                        str(exc), ts.admission.bounds.retry_after_s
                    )
                if sub["verdict"] == "duplicate":
                    ts.admission.emit_admission(decision, debt_at_resolve)
                    return self._duplicate_plane_payload(
                        ts, delta_id or "", sub
                    )
                if sub["verdict"] == "shed":
                    ts.admission.emit_admission(decision, debt_at_resolve)
                    ts.debt.shed(rows)
                    return self._shed_payload(
                        sub["reason"], sub["retry_after_s"]
                    )
                pending.shard_seqs = sub["shard_seqs"]
                pending.t_durable = time.monotonic()
            elif self.wal is not None:
                seq, dup = self.wal.append(
                    payload, delta_id=delta_id or "", deadline_s=deadline_s,
                    trace=pending.trace, tenant=tenant,
                )
                if dup:
                    # the resolve still happened — one admission record
                    # per resolve, duplicate outcome or not (the finally
                    # below releases this batch's reserved queue slot)
                    ts.admission.emit_admission(decision, debt_at_resolve)
                    return self._duplicate_payload(
                        delta_id or "", seq, tenant=tenant,
                    )
                pending.seq = seq
                pending.t_durable = time.monotonic()
        finally:
            enqueued = False
            with self._queue_cv:
                ts.reserved = max(0, ts.reserved - 1)
                # In plane mode, only a plane-accepted batch (shard_seqs
                # set) may queue: a plane shed/duplicate/refusal
                # returning through this finally must not enqueue work
                # the client was just told is NOT pending.
                durable_ok = (
                    pending.shard_seqs is not None
                    if ts.plane is not None
                    else (pending.seq is not None or self.wal is None)
                )
                if not self._worker_stop and durable_ok:
                    if pending.status == "queued":
                        # durable acknowledgements never deadline-shed;
                        # sync callers keep the client's budget
                        pending.deadline = (
                            math.inf if pending.async_ack
                            else time.monotonic() + deadline_s
                        )
                        # Debt accrues at ACCEPTANCE: batches parked on
                        # the apply queue are pending work the ledger
                        # (and /healthz) must already see — it is
                        # exactly what the shed bound reads.
                        ts.debt.submitted(rows)
                        ts.queue.append(pending)
                        if tenant not in self._rr:
                            self._rr.append(tenant)
                        self._queue_cv.notify_all()
                        enqueued = True
                elif self._worker_stop and (
                    pending.seq is not None or pending.shard_seqs
                ):
                    # stop() won the race after the append: the batch is
                    # durable and replays on restart — acknowledged, not
                    # shed
                    pending.status = "accepted"
                    pending.result = self._accepted_payload(
                        pending,
                        note="server stopping; replays on restart",
                    )
        ts.admission.emit_admission(decision, debt_at_resolve)
        if not enqueued:
            if pending.status == "accepted":
                return pending.result
            return self._shed_payload(
                "server shutting down", ts.admission.bounds.retry_after_s
            )
        self._ensure_worker()
        if pending.async_ack:
            # the 202 path: WAL-durable IS the acknowledgement
            return self._accepted_payload(pending)

        # Wait for a terminal state. First leg: bounded by the deadline —
        # a batch STILL QUEUED past it is shed here (deadline-aware
        # shedding; the worker's pop applies the same rule, whichever
        # side gets there first).
        pending.event.wait(
            max(0.0, pending.deadline - time.monotonic()) + 0.05
        )
        shed_now = False
        with self._queue_cv:
            if pending.status == "queued" and pending.deadline <= time.monotonic():
                try:
                    ts.queue.remove(pending)
                except ValueError:
                    pass  # the worker popped it between wait and lock
                else:
                    pending.status = "shed"
                    pending.shed_reason = (
                        f"deadline {pending.deadline_s:g}s passed while "
                        "queued"
                    )
                    shed_now = True
        if shed_now:
            self._skip_walled(pending)
            ts.debt.abandoned()
            ts.debt.shed(pending.rows)
            ts.admission.record_shed(
                pending.shed_reason, pending.rows, len(ts.queue),
                ts.debt.snapshot(), stage="deadline",
            )
            pending.event.set()
        # Second leg: unbounded-by-deadline — once APPLYING, the apply
        # finishes (its runtime is bounded by the repair budget) and the
        # client gets the real outcome, never a 503 for published work.
        pending.event.wait()
        if pending.status in ("done", "accepted"):
            return pending.result
        if pending.status == "shed":
            return self._shed_payload(
                pending.shed_reason, ts.admission.bounds.retry_after_s
            )
        raise pending.error

    def _shed_payload(self, reason: str, retry_after_s: float) -> dict:
        return {
            "verdict": "shed",
            "error": "overloaded: delta shed by admission control",
            "reason": reason,
            "retry_after_s": float(retry_after_s),
        }

    def _accepted_payload(self, pending: _PendingDelta, note: str = "") -> dict:
        """The 202 body: WAL-durable, not yet in a published snapshot."""
        out = {
            "verdict": "accepted",
            "applied": False,
            "durable": (
                pending.seq is not None or bool(pending.shard_seqs)
            ),
            "seq": pending.seq,
            "delta_id": pending.delta_id,
        }
        if pending.shard_seqs:
            out["shard_seqs"] = {
                str(k): int(v) for k, v in pending.shard_seqs.items()
            }
        if note:
            out["note"] = note
        return out

    def _duplicate_plane_payload(
        self, ts: _TenantState, delta_id: str, sub: dict,
    ) -> dict:
        """A retried key EVERY touched shard already holds maps onto the
        original accept (the per-shard twin of _duplicate_payload)."""
        applied = bool(sub.get("applied"))
        out = {
            "verdict": "duplicate",
            "delta_id": delta_id,
            "shard_seqs": {
                str(k): int(v) for k, v in sub["shard_seqs"].items()
            },
            "applied": applied,
        }
        if applied:
            out["version"] = ts.engine.version
        return out

    def _duplicate_payload(
        self, delta_id: str, seq: int, tenant: str = DEFAULT_TENANT,
    ) -> dict:
        """A retried idempotency key maps onto its original accept."""
        applied = self.wal.seq_applied(seq)
        out = {
            "verdict": "duplicate",
            "delta_id": delta_id,
            "seq": int(seq),
            "applied": applied,
        }
        if applied:
            out["version"] = self.engine_for(tenant).version
            out["applied_version"] = self.wal.applied_version
        return out

    def _skip_walled(self, pending: _PendingDelta) -> None:
        """Tombstone a WAL-durable batch that was shed off the queue so
        a later replay can't resurrect work the client was told is NOT
        applied (its retry still dedupes-by-id into a fresh accept)."""
        if pending.shard_seqs:
            ts = self._tenants.get(pending.tenant)
            if ts is not None and ts.plane is not None:
                ts.plane.skip(pending.shard_seqs)
            return
        if pending.seq is None or self.wal is None:
            return
        try:
            self.wal.skip(pending.seq)
        except OSError:
            pass  # tombstone is best-effort; dedupe bounds the damage

    # -- WAL replay / log shipping / promotion ----------------------------
    def _replay_wal(self, source: str = "startup") -> int:
        """Re-enqueue every accepted-but-unapplied WAL entry through the
        admission path (``replay=True`` — acknowledged work is never
        shed), as async batches nobody waits on. Returns the count."""
        entries = self.wal.pending()
        if not entries:
            return 0
        n = 0
        for e in entries:
            payload = e.get("payload") or {}
            try:
                delta = EdgeDelta.from_pairs(
                    insert=payload.get("insert", ()),
                    delete=payload.get("delete", ()),
                )
            except ValueError:
                continue  # the accept path parsed it once; be defensive
            # Route the entry back to the tenant whose frame it is — the
            # durable tenant id is what keeps replay from applying one
            # tenant's acknowledged rows into another's graph. A frame
            # naming a tenant whose namespace vanished (operator rm) is
            # skipped loudly rather than misapplied.
            entry_tenant = e.get("tenant") or DEFAULT_TENANT
            try:
                ts = self._tenant_state(entry_tenant)
            except (UnknownTenantError, ValueError):
                self._warn(
                    f"wal replay ({source}): seq {e.get('seq')} names "
                    f"tenant {entry_tenant!r} with no store namespace — "
                    "skipping (the tenant's snapshot chain is gone)"
                )
                continue
            rows = delta.num_inserts + delta.num_deletes
            with self._queue_cv:
                if self._worker_stop:
                    break
                debt_at = ts.debt.snapshot()
                decision = ts.admission.resolve(
                    rows=rows,
                    queue_depth=len(ts.queue) + ts.reserved,
                    debt=debt_at, applying=self._applying, emit=False,
                    replay=True,
                )
                ts.debt.submitted(rows)
                p = _PendingDelta(delta, rows, math.inf, float(
                    e.get("deadline_s") or ts.admission.bounds.deadline_s
                ))
                p.seq = int(e["seq"])
                p.delta_id = e.get("id", "")
                p.async_ack = True
                p.tenant = ts.tenant
                # replayed entries keep their originating request's
                # trace: the durable header re-adopts across the kill
                # (or across a promotion, via the shipped copy)
                p.trace = e.get("trace", "")
                p.t_durable = p.t_accept
                ts.queue.append(p)
                if ts.tenant not in self._rr:
                    self._rr.append(ts.tenant)
                self._queue_cv.notify_all()
            ts.admission.emit_admission(decision, debt_at)
            n += 1
        if self.sink is not None:
            self.sink.emit(
                "wal_replay", entries=n, from_seq=int(entries[0]["seq"]),
                to_seq=int(entries[-1]["seq"]), source=source,
            )
        if n:
            self._ensure_worker()
        return n

    def _replay_plane(self, ts: _TenantState, source: str = "startup") -> int:
        """Per-range WAL replay (r17): each shard's accepted-but-
        unapplied sub-batches re-enqueue as independent async batches.
        Applying the sub-batches separately is semantically equal to the
        original whole-batch apply — disjoint dst ranges mean disjoint
        delete keys, so the per-shard applies commute (the splitter-
        parity property tests/test_shardplane.py pins). Each replayed
        batch carries exactly its own ``{shard: seq}`` pair, so the
        commit after its publish advances only that range's log."""
        n, lo_seq, hi_seq = 0, None, 0
        for ws in ts.plane.shards:
            if ws.read_only:
                continue
            for e in ws.wal.pending():
                payload = e.get("payload") or {}
                try:
                    delta = EdgeDelta.from_pairs(
                        insert=payload.get("insert", ()),
                        delete=payload.get("delete", ()),
                    )
                except ValueError:
                    continue  # the accept path parsed it once
                rows = delta.num_inserts + delta.num_deletes
                with self._queue_cv:
                    if self._worker_stop:
                        break
                    debt_at = ws.debt.snapshot()
                    decision = ws.admission.resolve(
                        rows=rows,
                        queue_depth=len(ts.queue) + ts.reserved,
                        debt=debt_at, applying=self._applying,
                        emit=False, replay=True,
                    )
                    ws.debt.submitted(rows)
                    ts.debt.submitted(rows)
                    p = _PendingDelta(delta, rows, math.inf, float(
                        e.get("deadline_s")
                        or ts.admission.bounds.deadline_s
                    ))
                    p.shard_seqs = {ws.shard: int(e["seq"])}
                    p.delta_id = e.get("id", "")
                    p.async_ack = True
                    p.tenant = ts.tenant
                    p.trace = e.get("trace", "")
                    p.t_durable = p.t_accept
                    ts.queue.append(p)
                    if ts.tenant not in self._rr:
                        self._rr.append(ts.tenant)
                    self._queue_cv.notify_all()
                ws.admission.emit_admission(decision, debt_at)
                seq = int(e["seq"])
                lo_seq = seq if lo_seq is None else min(lo_seq, seq)
                hi_seq = max(hi_seq, seq)
                n += 1
        if n and self.sink is not None:
            self.sink.emit(
                "wal_replay", entries=n, from_seq=int(lo_seq),
                to_seq=int(hi_seq), source=source, tenant=ts.tenant,
                shards=ts.plane.plan.num_shards,
            )
        if n:
            self._ensure_worker()
        return n

    def wal_entries(self, from_seq: int, limit: int = 512) -> dict:
        """The ``GET /wal`` body — the log-shipping feed the standby's
        :class:`~graphmine_tpu.serve.wal.LogShipper` tails."""
        if self.wal is None:
            raise ValueError(
                "this server runs without a write-ahead log (serve --wal)"
            )
        return {
            "entries": self.wal.entries(max(0, int(from_seq)),
                                        limit=max(1, int(limit))),
            "last_seq": self.wal.last_seq,
            "applied_seq": self.wal.applied_seq,
            "applied_version": self.wal.applied_version,
            "history": self.wal.commit_history(),
            "epoch": self.writer_epoch,
        }

    def wait_applied(self, timeout: float = 60.0) -> bool:
        """Block until the apply queue is drained and nothing is
        applying — the promotion path's (and tests') 'is every durable
        acknowledgement published' barrier."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._queue_cv:
                idle = not self._any_queued_locked() and not self._applying
            if idle:
                return True
            time.sleep(0.02)
        return False

    def _any_queued_locked(self) -> bool:
        """Under the queue condition's lock: is ANY tenant's sub-queue
        non-empty? (The worker's wake predicate.)"""
        return any(ts.queue for ts in list(self._tenants.values()))

    def _warn(self, message: str) -> None:
        """Loud in both channels: a ``warnings.warn`` (the ann.py /
        checkpoint.py idiom) AND a schema-registered ``warning`` record
        when a sink is attached — promotion anomalies must not depend on
        the operator having wired telemetry."""
        warnings.warn(message)
        if self.sink is not None:
            self.sink.emit("warning", message=message)

    def _rewind_wal(self, floor: int, snap, context: str) -> None:
        oldest = self.wal.oldest_retained_seq()
        if oldest is not None and floor + 1 < oldest:
            self._warn(
                f"{context}: rewind to seq {floor} reaches below the "
                f"compaction horizon (oldest retained seq {oldest}): "
                f"entries {floor + 1}..{oldest - 1} were pruned here "
                "and cannot replay — acknowledged-delta loss past the "
                "shipped lag"
            )
        self._warn(
            f"{context}: adopted snapshot v{snap.version} is behind the "
            f"WAL watermark (seq {self.wal.applied_seq}, "
            f"v{self.wal.applied_version}): rewinding the replay cursor "
            f"to seq {floor} so durable-but-unapplied entries replay"
        )
        self.wal.rewind(floor, snap.version)

    def _reconcile_wal_cursor(self, snap, context: str) -> None:
        """Place the WAL replay cursor to match the store state actually
        adopted — the watermark is a claim about THIS store, and three
        windows can break it: a crash between publish and commit (store
        ahead), a store rollback to ``.prev`` (store behind), and a
        separate-store standby whose mirrored watermark describes the
        primary's store. Voucher priority: the manifest's own
        ``wal_applied_seq`` (stamped at publish — exact) > the watermark
        history pair recorded AT the adopted version > a loud refusal to
        guess (deltas are not idempotent; an off-by-one replays one
        twice or drops an acknowledged one). An entry-less WAL skips:
        there is nothing to replay, and adopting a foreign lineage's
        cursor would park fresh appends below the watermark."""
        if self.wal.last_seq == 0:
            return
        # Publish-time vouchers from EVERY tenant namespace (ISSUE 16):
        # the watermark is ONE cursor over an interleaved multi-tenant
        # log, advanced by whichever tenant published last — so the
        # default manifest's voucher alone can LAG a later non-default
        # publish, and trusting it would rewind into (and double-apply)
        # entries that tenant's snapshot already absorbed. The max
        # voucher wins; every manifest's absorbed-above list is excluded
        # from replay. Single-tenant stores gather exactly one voucher —
        # the pre-tenancy behavior, byte for byte.
        vouchers = []  # (seq, version-at-that-publish, absorbed-above)
        voucher = snap.meta.get("wal_applied_seq")
        if voucher is not None:
            vouchers.append((
                int(voucher), snap.version,
                tuple(snap.meta.get("wal_applied_above") or ()),
            ))
        for tid in self.store.list_tenants():
            if tid == self.store.tenant:
                continue  # the adopted snap already vouched above
            man = self.store.for_tenant(tid)._peek_manifest()
            if not man:
                continue
            mv = man.get("wal_applied_seq")
            if mv is None:
                continue
            try:
                ver = int(man.get("version", 0))
            except (TypeError, ValueError):
                ver = 0
            vouchers.append((
                int(mv), ver, tuple(man.get("wal_applied_above") or ()),
            ))
        if vouchers:
            best_seq, best_ver, _ = max(vouchers)
            if best_seq > self.wal.applied_seq:
                # publish landed, its wal.commit was lost to the crash:
                # move the cursor forward so replay can't double-apply
                self.wal.commit(best_seq, best_ver)
            elif best_seq < self.wal.applied_seq:
                self._rewind_wal(best_seq, snap, context)
            for _, ver, above in vouchers:
                if above:
                    # entries a snapshot absorbed above the contiguous
                    # floor (published over a then-unresolved gap):
                    # exclude them from replay the same crash-safe way
                    self.wal.commit_applied(above, ver)
            return
        if self.wal.applied_version > snap.version:
            floor = self.wal.replay_floor(snap.version)
            if floor is not None:
                self._rewind_wal(floor, snap, context)
            else:
                self._warn(
                    f"{context}: adopted snapshot v{snap.version} is "
                    "behind the WAL watermark "
                    f"(v{self.wal.applied_version}) and no retained "
                    "watermark pair vouches for it — the replay cursor "
                    "cannot be placed exactly; continuing from the "
                    "watermark. Loss bound exceeds the shipped lag: "
                    "re-bootstrap this standby from a fresher copy (or "
                    "run the shared-store deployment)"
                )

    def promote(self) -> dict:
        """Standby → writer, the failover ladder's last rung: (1) final
        ship pass — catch up from the primary's ``/wal`` if it still
        answers, then copy the un-shipped tail straight from its WAL
        directory when reachable (the shared-store deployment: a
        same-filesystem writer kill loses nothing; without shared
        storage the loss bound is the shipped lag, which is why the lag
        is a first-class observable); (2) **fence the epoch** durably at
        the store — from this instant the deposed writer's publishes
        refuse with ``publish_fenced``; (3) adopt the newest published
        snapshot; (4) replay the WAL tail through admission; (5) resume
        writes. Emits one ``writer_promote`` record.

        Serialized and idempotent: concurrent calls queue on the lock,
        and a call landing after the promotion completed (a router that
        timed out mid-replay and retried next prober pass) answers
        ``promoted: false, already_writer: true`` with the live epoch
        instead of fencing again and re-enqueuing the same pending
        entries."""
        if self.wal is None:
            raise ValueError(
                "promote needs a write-ahead log (serve --wal)"
            )
        with self._promote_lock:
            return self._promote_locked()

    def _promote_locked(self) -> dict:
        if self._promoted:
            # THIS process already completed a promotion: the caller is
            # a retry of it (router timed out mid-replay). A plain
            # writer that never promoted does NOT short-circuit — an
            # explicit /promote on it is a fence request (epoch bump
            # cuts off a suspected zombie co-writer) and proceeds.
            return {
                "promoted": False,
                "already_writer": True,
                "epoch": self.writer_epoch,
                "version": self._engine.version,
                "replayed": 0,
                "copied_tail": 0,
            }
        t0 = time.perf_counter()
        if self._shipper is not None:
            try:
                self._shipper.poll_once()  # final catch-up, best effort
            except Exception:  # noqa: BLE001 — primary usually dead here
                pass
            self._shipper.stop()
        copied = 0
        if self.primary_wal and os.path.isdir(self.primary_wal):
            try:
                # read_only: the primary may be a partitioned-but-alive
                # zombie sharing this storage — a writable open's scan
                # would "repair" (truncate) its in-flight append as a
                # torn tail, destroying a frame it is about to fsync
                # and acknowledge.
                foreign = WriteAheadLog(self.primary_wal, read_only=True)
                copied = self.wal.copy_from(
                    foreign.entries(self.wal.last_seq + 1)
                )
                self.wal.merge_history(foreign.commit_history())
                foreign.close()
            except Exception as e:  # noqa: BLE001 — promote must proceed
                self._warn(
                    "promotion could not read the deposed "
                    f"primary's WAL at {self.primary_wal!r}: {e!r}"
                    " — continuing from the shipped copy (loss "
                    "bound = replication lag)"
                )
        # Mint-and-fence atomically: composing current_epoch() + 1 with
        # fence_epoch would let two concurrent promotions (prober
        # auto-promote racing an operator's /promote on another server)
        # fence the SAME epoch and both pass the store's fence.
        new_epoch = self.store.advance_epoch(
            sink=None,
            reason=f"standby promotion (was standby of {self.standby_of})",
        )
        was = self.standby_of or ""
        self.standby_of = None
        self.writer_epoch = new_epoch
        # The fence is now in OUR favor: a previously-deposed writer
        # taking ownership back resumes accepting writes.
        self._fenced = None
        # Every tenant namespace inherits the new fence and adopts its
        # newest published snapshot — the deposed writer must lose ALL
        # tenants at once, not just the default (a half-fenced server
        # would split-brain per tenant). Namespaces this process never
        # served are fenced lazily on first touch (_make_tenant_state).
        with self._delta_lock:
            for ts in list(self._tenants.values()):
                if ts.tenant != DEFAULT_TENANT:
                    try:
                        ts.store.fence_epoch(new_epoch)
                    except (OSError, ValueError):
                        pass  # already at/above: fence holds
                fresh_t = ts.store.load(sink=self.sink)
                if fresh_t is not None and fresh_t.version != ts.engine.version:
                    self._swap(QueryEngine(fresh_t), tenant=ts.tenant)
                ts.ingestor = None
            fresh = self._engine.snapshot
        self._reconcile_wal_cursor(fresh, "promotion")
        replayed = self._replay_wal(source="promotion")
        # Now the primary: local commits describe THIS store, so the
        # standby-era compaction guard lifts.
        self.wal.protect_version = None
        self._promoted = True
        seconds = round(time.perf_counter() - t0, 3)
        if self.sink is not None:
            self.sink.emit(
                "writer_promote", epoch=new_epoch, replayed=replayed,
                copied_tail=copied, version=self._engine.version,
                was_standby_of=was, seconds=seconds,
            )
        return {
            "promoted": True,
            "epoch": new_epoch,
            "replayed": replayed,
            "copied_tail": copied,
            "version": self._engine.version,
            "was_standby_of": was,
            "seconds": seconds,
        }

    # -- the apply worker --------------------------------------------------
    def _pop_group(self) -> tuple[str, list, list]:
        """Under the queue lock: pick the next tenant by deficit
        round-robin and pop ITS waiting batches (coalescing never
        crosses a tenant — one publish builds on exactly one tenant's
        store and ingestor), splitting expired-deadline batches out for
        shedding (all tenants — a deadline is a deadline regardless of
        whose turn it is). Returns ``(tenant, group, expired)``.

        **Weighted fairness (ISSUE 16):** each tenant in the rotation
        earns ``_fair_quantum_rows`` of deficit per visit and spends it
        on its queued rows; leftover deficit carries to its next turn,
        so a tenant of many small batches and a tenant of few huge ones
        converge on the same row share. A group always carries at least
        one batch (a batch larger than the quantum must still make
        progress). With at most ONE tenant holding queued work the
        quantum is infinite — the pre-tenancy pop-everything behavior,
        coalescing counts and all."""
        group, expired = [], []
        now = time.monotonic()
        # list(): a lazy tenant admit can grow the dict mid-iteration
        for ts in list(self._tenants.values()):
            n = len(ts.queue)
            for _ in range(n):
                p = ts.queue.popleft()
                if p.status != "queued":
                    continue  # a handler-side deadline shed won the race
                if p.deadline <= now:
                    p.status = "shed"
                    p.shed_reason = (
                        f"deadline {p.deadline_s:g}s passed while queued"
                    )
                    expired.append(p)
                else:
                    ts.queue.append(p)
        active = sum(1 for ts in self._tenants.values() if ts.queue)
        quantum = (
            math.inf if active <= 1 else float(self._fair_quantum_rows)
        )
        tenant = DEFAULT_TENANT
        for _ in range(len(self._rr)):
            tid = self._rr[0]
            ts = self._tenants.get(tid)
            if ts is None or not ts.queue:
                # drained (or shed empty) since it joined the rotation:
                # a fresh enqueue re-adds it with a clean balance
                self._rr.popleft()
                if ts is not None:
                    ts.deficit = 0.0
                continue
            tenant = tid
            ts.deficit += quantum
            rows = 0
            while ts.queue and (
                not group or rows + ts.queue[0].rows <= ts.deficit
            ):
                p = ts.queue.popleft()
                p.status = "applying"
                group.append(p)
                rows += p.rows
            self._rr.popleft()
            if ts.queue:
                # unfinished backlog: spend the popped rows, keep the
                # remainder, go to the back of the rotation
                ts.deficit = max(0.0, ts.deficit - rows)
                self._rr.append(tid)
            else:
                ts.deficit = 0.0
            break
        return tenant, group, expired

    def _apply_worker(self) -> None:
        """Drain the apply queue: one iteration = one coalesced publish.

        Every popped batch is ALWAYS resolved (done/shed/error) — the
        ``finally`` discipline below is what lets handlers block on
        ``pending.event`` without a liveness caveat."""
        while True:
            with self._queue_cv:
                while not self._any_queued_locked() and not self._worker_stop:
                    self._queue_cv.wait(timeout=0.5)
                if self._worker_stop and not self._any_queued_locked():
                    return
                tenant, group, expired = self._pop_group()
                self._applying = bool(group)
            for p in expired:
                try:
                    # Telemetry must never take the worker down: a full
                    # disk killing the sink's JSONL write would strand
                    # every already-popped 'applying' batch on an event
                    # that nobody will ever set.
                    pts = self._tenants[p.tenant]
                    self._skip_walled(p)
                    pts.debt.abandoned()
                    pts.debt.shed(p.rows)
                    pts.admission.record_shed(
                        p.shed_reason, p.rows, len(pts.queue),
                        pts.debt.snapshot(), stage="deadline",
                    )
                except Exception:  # noqa: BLE001 — bookkeeping only
                    pass
                finally:
                    p.event.set()
            if not group:
                continue
            try:
                result = self._apply_group(tenant, group)
                for p in group:
                    p.status, p.result = "done", result
            except BaseException as e:  # resolve, then keep serving
                if isinstance(e, PublishFencedError) and self._fenced is None:
                    # Deposed: flip the write path closed (reads keep
                    # serving). Latched until a /promote re-fences the
                    # epoch in this process's favor.
                    self._fenced = str(e)
                    self._warn(
                        "publish fenced by a newer writer epoch — this "
                        "process is deposed and now refuses new deltas "
                        f"(503): {e}"
                    )
                for p in group:
                    p.status, p.error = "error", e
            finally:
                with self._queue_cv:
                    self._applying = False
                for p in group:
                    p.event.set()

    def _apply_group(self, tenant: str, group: list) -> dict:
        """Apply one popped group — all batches of ONE tenant — as a
        single publish: validate each batch, coalesce when more than one
        waited, re-resolve the LOF rung at apply time (pressure may have
        moved while they sat queued), swap the tenant's fresh engine in.

        REBASE GUARD (the /reload-vs-inflight-delta contract, pinned
        under the fleet prober's reload cadence in tests/test_fleet.py):
        before building on the served engine, peek the store's newest
        version. An external publish the server hasn't reloaded yet —
        a /reload racing this apply, or a prober cadence that hasn't
        fired — means applying on the served snapshot would chain a new
        version number from the store's manifest while silently
        DISCARDING the external snapshot's edges. Reload-in-place first
        (swap + drop the stale ingestor), then apply on top: the delta
        rebases instead of clobbering.

        TRACE ADOPTION (ISSUE 11): the worker thread has no request
        span, so without help the `delta_apply`/`snapshot_publish`
        records it emits would land in the server's run trace instead of
        the delta's. The whole apply runs under a span adopted from the
        group LEADER's propagated context (the first batch with one),
        and each batch additionally gets its own `delta_stages` record
        in its OWN trace — so a coalesced group's non-leader batches
        still stitch end-to-end."""
        t_apply_start = time.monotonic()
        ts = self._tenants[tenant]
        leader_ctx = None
        if self.sink is not None:
            for p in group:
                leader_ctx = TraceContext.from_header(p.trace)
                if leader_ctx is not None:
                    break
        span = (
            self.sink.span(
                "delta_publish", emit=False, annotate=False,
                remote=leader_ctx,
            )
            if self.sink is not None and leader_ctx is not None
            else contextlib.nullcontext()
        )
        with span, self._delta_lock:
            newest = ts.store.peek_version()
            if newest is not None and newest != ts.engine.version:
                fresh = ts.store.load(sink=self.sink)
                if fresh is not None and fresh.version != ts.engine.version:
                    self._swap(QueryEngine(fresh), tenant=tenant)
                    ts.ingestor = None
            # Applies settle the ledger inside apply(); the worker is the
            # only applier, so an unchanged applies_total at a raise
            # means THIS group never settled — drop its pending entries.
            # The guard covers the whole group path (ingestor build,
            # validation, coalesce, apply): any of them failing means
            # these batches will never publish. (An apply that raised
            # after settling — or a failing engine build on the
            # already-published snapshot — must NOT drain entries
            # belonging to batches queued behind us.)
            settled_before = ts.debt.applies_total
            try:
                if ts.ingestor is None:
                    ts.ingestor = DeltaIngestor(
                        ts.store, sink=self._tenant_sink(tenant),
                        num_shards=self.num_shards,
                        snapshot=ts.engine.snapshot, debt=ts.debt,
                        epoch=self.writer_epoch,
                    )
                ing = ts.ingestor
                if len(group) > 1:
                    cleans, quarantined = [], 0
                    # Validate each batch against the vertex space AS
                    # GROWN by the batches before it — exactly what
                    # sequential applies would see. Against the fixed
                    # base count, a delete referencing a vertex an
                    # earlier batch in the group created would be
                    # quarantined here and the coalesced apply would
                    # serve an edge the sequential applies delete.
                    v_cur = ing.num_vertices
                    for p in group:
                        clean, q = validate_delta(p.delta, v_cur)
                        cleans.append(clean)
                        quarantined += sum(q.values())
                        if clean.num_inserts:
                            v_cur = max(
                                v_cur,
                                int(clean.insert_src.max()) + 1,
                                int(clean.insert_dst.max()) + 1,
                            )
                    merged, info = coalesce_deltas(cleans, ing.src, ing.dst)
                    info["quarantined_rows"] = quarantined
                    ts.admission.record_coalesce(info, ts.debt.snapshot())
                else:
                    merged = group[0].delta
                lof_mode = ts.admission.lof_mode(ts.debt.snapshot())
                # The manifest voucher must survive a crash between
                # this publish and the wal.commit below (restart replay
                # of absorbed entries = double apply). It CANNOT be the
                # group's max seq: appends fsync outside the queue
                # lock, so an acked lower seq can still be racing
                # toward the queue while this group publishes — a
                # max-seq watermark would jump that gap and a kill in
                # the window silently drops the acked entry on restart.
                # Stamp the CONTIGUOUS floor the WAL would reach plus
                # the resolved seqs parked above it (wal_applied_above);
                # replay excludes exactly those.
                seqs = [p.seq for p in group if p.seq is not None]
                if seqs and self.wal is not None:
                    floor, above = self.wal.preview_commit(seqs)
                    extra = {
                        "wal_applied_seq": floor,
                        "wal_applied_above": above,
                    }
                else:
                    extra = None
                snap = ing.apply(
                    merged, lof_mode=lof_mode, batches=len(group),
                    extra_meta=extra,
                )
            except BaseException:
                if ts.debt.applies_total == settled_before:
                    for _ in group:
                        ts.debt.abandoned()
                raise
            self._swap(QueryEngine(snap), tenant=tenant)
            # Adopt the ingestor's quality pass (drift + canary) for
            # /statusz, /alertz and the alert rules — the served engine
            # and the report now describe the same version.
            ts.quality_report = ing.last_quality
            if self.wal is not None and seqs:
                # Compaction keyed to the published snapshot version:
                # the durable watermark says "everything up to this seq
                # is in snapshot v" — replay keys off it, pruning
                # follows it. commit_applied advances only over the
                # contiguous resolved run (never past an acked entry
                # still in flight toward the queue).
                self.wal.commit_applied(seqs, snap.version)
            if ts.plane is not None:
                # Sharded plane (r17): advance each touched range's WAL
                # watermark, then two-phase-publish the epoch — stage
                # every range's arrays, durably commit the epoch →
                # version-vector record. Readers key off the committed
                # epoch, so a multi-range group becomes visible
                # atomically (or, on a torn commit, not at all: the
                # previous epoch stays served and startup recovery
                # finishes or sweeps the stage).
                merged_seqs: dict[int, list] = {}
                for p in group:
                    for s, q in (p.shard_seqs or {}).items():
                        merged_seqs.setdefault(int(s), []).append(int(q))
                if merged_seqs:
                    ts.plane.commit_applied(merged_seqs, snap.version)
                self._publish_epoch(ts, snap)
        self._emit_delta_stages(group, snap, t_apply_start)
        # Publish-time alert evaluation (outside the delta lock — a
        # record fsync must not serialize handlers): a quality or canary
        # regression this publish introduced fires NOW, not at the next
        # prober pass.
        self.evaluate_alerts()
        self.registry.counter(
            "graphmine_serve_deltas_total", "delta batches ingested"
        ).inc(len(group))
        return {
            "version": snap.version,
            "snapshot_id": snap.snapshot_id,
            "num_vertices": int(len(snap["labels"])),
            "num_edges": int(len(snap["src"])),
            "coalesced": len(group),
            "lof_stale": bool(snap.meta.get("lof_stale", False)),
        }

    def _publish_epoch(self, ts: _TenantState, snap) -> int:
        """Stage + commit the next publish epoch (r17, two-phase): each
        range's slice of the per-vertex result arrays lands in its own
        shard directory (the r2 sharded-checkpoint manifest format — no
        gather through one writer), then the coordinator durably commits
        epoch → version vector under the store's fence lock. Growth rows
        (vertices born past the plan) ride with the LAST range, same
        rule as the splitter's ownership."""
        plane = ts.plane
        labels = np.asarray(snap["labels"])
        lof = snap.get("lof")
        n = len(labels)
        shard_arrays: dict[int, dict] = {}
        versions: dict[int, int] = {}
        last = plane.plan.num_shards - 1
        for ws in plane.shards:
            lo = min(ws.lo, n)
            hi = n if ws.shard == last else min(ws.hi, n)
            arrs = {"labels": labels[lo:hi]}
            if lof is not None:
                arrs["lof"] = np.asarray(lof)[lo:hi]
            shard_arrays[ws.shard] = arrs
            versions[ws.shard] = int(ws.version)
        epoch = plane.coordinator.committed_epoch() + 1
        plane.coordinator.stage(epoch, shard_arrays, versions=versions)
        plane.coordinator.commit(epoch, plane.version_vector())
        return epoch

    # -- per-delta time-to-visible stages ---------------------------------
    def _emit_delta_stages(self, group: list, snap, t_apply_start: float):
        """The writer-side causal chain of every batch this publish
        absorbed: admission accept → WAL fsync → queued → apply →
        published, observed into per-stage histograms
        (``graphmine_serve_delta_stage_seconds{stage=...}``, the
        ``/statusz`` breakdown) and emitted as one ``delta_stages``
        record per batch IN THAT BATCH's trace — telemetry only, so a
        failure here must never fail a publish that already landed."""
        t_done = time.monotonic()
        try:
            for p in group:
                stages = {}
                if p.t_durable is not None:
                    stages["wal_fsync_s"] = round(
                        max(0.0, p.t_durable - p.t_accept), 6
                    )
                stages["queued_s"] = round(
                    max(0.0, t_apply_start - (p.t_durable or p.t_accept)), 6
                )
                stages["apply_s"] = round(
                    max(0.0, t_done - t_apply_start), 6
                )
                stages["total_s"] = round(
                    max(0.0, t_done - p.t_accept), 6
                )
                for stage, seconds in stages.items():
                    self.registry.histogram(
                        "graphmine_serve_delta_stage_seconds",
                        "per-stage delta latency: accept to queryable "
                        "on this writer",
                        stage=stage[:-2],  # wal_fsync_s -> wal_fsync
                    ).observe(seconds)
                if self.sink is None:
                    continue
                ctx = TraceContext.from_header(p.trace) if p.trace else None
                span = (
                    self.sink.span(
                        "delta_stages", emit=False, annotate=False,
                        remote=ctx,
                    )
                    if ctx is not None else contextlib.nullcontext()
                )
                with span:
                    self.sink.emit(
                        "delta_stages",
                        version=snap.version,
                        seq=p.seq,
                        delta_id=p.delta_id,
                        rows=p.rows,
                        coalesced=len(group),
                        stages=stages,
                    )
        except Exception:  # noqa: BLE001 — bookkeeping only
            pass

    def delta_stage_latency(self) -> dict:
        """Per-stage p50/p99 of the delta causal chain — the
        ``/statusz`` time-to-visible breakdown (the router adds the
        read-side tail: each replica's reload-to-queryable)."""
        fam = self.registry.histogram_family(
            "graphmine_serve_delta_stage_seconds"
        )
        out: dict = {}
        if fam is None:
            return out
        for child in fam.children():
            s = child.snapshot()
            if not s.count:
                continue
            out[child.labels.get("stage", "?")] = s.summary()
        return out

    # -- on-demand device profiling (POST /profilez) ----------------------
    def profilez(
        self, duration_ms: int = 1000, kind: str = "trace",
    ) -> tuple[int, dict]:
        """Capture an XLA profiler trace — or, with ``kind="memory"``
        (ISSUE 14 satellite), an on-demand
        ``jax.profiler.device_memory_profile`` allocator snapshot —
        from this live replica, tagged with the requesting trace.
        Returns ``(http_status, body)``: 403 when no capture directory
        is configured (the guard — an open profiler endpoint burns
        device time and disk for anyone who can reach the port), 501
        when jax / the profiler is unavailable (CPU-only or jax-less
        deployments degrade, never crash), 409 when a capture is
        already running (the profiler is process-global; BOTH kinds
        share the one single-flight lock), 200 with the capture path
        otherwise."""
        if not self.profilez_dir:
            return 403, {
                "error": "profilez disabled: start the server with "
                "profilez_dir= (serve_cli --profilez-dir) to allow "
                "on-demand captures",
            }
        duration_ms = max(1, min(int(duration_ms), 30_000))
        trace_header = self._current_trace_header()
        ctx = TraceContext.from_header(trace_header)
        tag = ctx.trace_id if ctx is not None else secrets.token_hex(4)
        if kind == "memory":
            return self._profilez_memory(tag, ctx)
        out_dir = os.path.join(
            self.profilez_dir, f"profile-{int(time.time())}-{tag}"
        )
        if not self._profilez_lock.acquire(blocking=False):
            return 409, {"error": "a profile capture is already running"}
        try:
            try:
                import jax

                jax.profiler.start_trace(out_dir)
            except Exception as e:  # noqa: BLE001 — no jax / no profiler
                if self.sink is not None:
                    self.sink.emit(
                        "profile_capture", dir=out_dir, ok=False,
                        error=repr(e),
                    )
                return 501, {
                    "error": "jax profiler unavailable on this replica",
                    "detail": repr(e),
                }
            try:
                time.sleep(duration_ms / 1000.0)
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001 — trace incomplete
                    if self.sink is not None:
                        self.sink.emit(
                            "profile_capture", dir=out_dir, ok=False,
                            error=repr(e),
                        )
                    return 500, {
                        "error": "profiler stop_trace failed; the trace "
                        "directory may be incomplete",
                        "dir": out_dir,
                        "detail": repr(e),
                    }
        finally:
            self._profilez_lock.release()
        if self.sink is not None:
            self.sink.emit(
                "profile_capture", dir=out_dir, ok=True,
                duration_ms=duration_ms,
            )
        return 200, {
            "ok": True,
            "dir": out_dir,
            "duration_ms": duration_ms,
            "trace_id": ctx.trace_id if ctx is not None else "",
        }

    def _profilez_memory(self, tag: str, ctx) -> tuple[int, dict]:
        """``kind="memory"``: one ``device_memory_profile`` snapshot (a
        pprof proto of live device allocations) written next to the
        trace captures, under the same single-flight lock — the on-OOM
        triage step after the watermark said WHICH phase blew the model
        (docs/RUNBOOKS.md §14). 501 when the profiler (or jax) is
        unavailable on this replica."""
        os.makedirs(self.profilez_dir, exist_ok=True)
        path = os.path.join(
            self.profilez_dir, f"memprof-{int(time.time())}-{tag}.pb"
        )
        if not self._profilez_lock.acquire(blocking=False):
            return 409, {"error": "a profile capture is already running"}
        try:
            try:
                import jax

                blob = jax.profiler.device_memory_profile()
            except Exception as e:  # noqa: BLE001 — no jax / no profiler
                if self.sink is not None:
                    self.sink.emit(
                        "profile_capture", dir=path, ok=False,
                        kind="memory", error=repr(e),
                    )
                return 501, {
                    "error": "jax device_memory_profile unavailable on "
                    "this replica",
                    "detail": repr(e),
                }
            with open(path, "wb") as f:
                f.write(blob)
        finally:
            self._profilez_lock.release()
        if self.sink is not None:
            self.sink.emit(
                "profile_capture", dir=path, ok=True, kind="memory",
                bytes=len(blob),
            )
        return 200, {
            "ok": True,
            "path": path,
            "kind": "memory",
            "bytes": len(blob),
            "trace_id": ctx.trace_id if ctx is not None else "",
        }

    # -- liveness vs readiness --------------------------------------------
    def drain(self) -> dict:
        """Flip readiness off (``ready: false``) while keeping the
        process fully alive — the balancer/fleet-prober contract for
        taking a replica out of rotation without killing in-flight
        work. Idempotent; :meth:`undrain` restores."""
        self._draining = True
        return self.healthz()

    def undrain(self) -> dict:
        self._draining = False
        return self.healthz()

    def _ready(self, eng) -> tuple[bool, str]:
        """The readiness verdict (``/healthz`` ``ready``): false while
        draining or while the served snapshot is stale beyond the
        configured age bound. Liveness (``ok``) is separate — a
        draining or stale replica is alive, just not routable."""
        if self._draining:
            return False, "draining"
        age = self._snapshot_age_s(eng)
        if self.ready_max_age_s is not None and age > self.ready_max_age_s:
            return False, (
                f"snapshot_age {age:.1f}s > ready_max_age_s "
                f"{self.ready_max_age_s:g}s"
            )
        return True, ""

    # -- SLO surfaces -----------------------------------------------------
    def healthz(self) -> dict:
        """Liveness + readiness + staleness: version, snapshot age,
        repair debt, the ``overloaded`` drain signal, and ``ready`` —
        the one documented contract (docs/SERVING.md "healthz schema")
        the fleet prober and external balancers key off. ``ok`` is
        liveness (the process answers); ``ready`` is routability (false
        while draining or stale-beyond-bound); ``overloaded`` is the
        write-path drain signal, driven by the same admission bounds
        that decide the shed verdict."""
        eng = self._engine
        debt = self.debt.snapshot()
        tenants = list(self._tenants.values())
        with self._queue_cv:
            depths = {ts.tenant: len(ts.queue) for ts in tenants}
        depth = sum(depths.values())
        overloaded, why = self.admission.overloaded(
            depths.get(DEFAULT_TENANT, 0), debt
        )
        if not overloaded:
            # any tenant saturating ITS OWN bounds flips the fleet-level
            # drain signal (the replica is a shared process), with the
            # culprit named — the per-tenant sections say who
            for ts in tenants:
                if ts.tenant == DEFAULT_TENANT:
                    continue
                over_t, why_t = ts.admission.overloaded(
                    depths.get(ts.tenant, 0), ts.debt.snapshot()
                )
                if over_t:
                    overloaded, why = True, f"tenant {ts.tenant}: {why_t}"
                    break
        ready, not_ready_why = self._ready(eng)
        # The prober cadence IS the alert-evaluation cadence (ISSUE 13):
        # the fleet prober polls /healthz, so firing→resolved transitions
        # happen fleet-wide without a new timer thread.
        self.evaluate_alerts()
        out = {
            "ok": True,
            "alerts_firing": sum(len(ts.alerts.firing()) for ts in tenants),
            "ready": ready,
            "draining": self._draining,
            "version": eng.version,
            "snapshot_id": eng.snapshot.snapshot_id,
            "num_vertices": eng.num_vertices,
            "snapshot_age_s": self._snapshot_age_s(eng),
            "repair_debt_rows": debt["pending_rows"],
            "ingest_lag_s": debt["ingest_lag_s"],
            "overloaded": overloaded,
            "delta_queue_depth": depth,
            "lof_stale": eng.lof_stale,
            "writer_epoch": self.writer_epoch,
            # Tenancy (ISSUE 16): count + per-tenant snapshot age and
            # version maps. The fleet router's rolling reload reads
            # tenant_versions to call a replica caught up only when it
            # is caught up on EVERY tenant, and serve_cli --tenant
            # health checks read tenant_snapshot_age_s.
            "tenants": len(tenants),
            "tenant_snapshot_age_s": {
                ts.tenant: self._snapshot_age_s(ts.engine) for ts in tenants
            },
            "tenant_versions": {
                ts.tenant: ts.engine.version for ts in tenants
            },
        }
        if self._fenced is not None:
            # deposed writer: reads serve, writes refuse 503 — the
            # balancer/operator signal that this process lost ownership
            out["fenced"] = self._fenced
        if self.standby_of is not None:
            out["standby"] = True
            out["standby_of"] = self.standby_of
            if self._shipper is not None:
                ship = self._shipper.snapshot()
                # the replication-lag gauge pair (docs/SERVING.md
                # "Replicated writers"): entries behind + seconds behind
                out["replication_lag_entries"] = ship["lag_entries"]
                out["replication_lag_s"] = ship["lag_s"]
        if self.wal is not None:
            out["wal"] = self.wal.snapshot()
        dts = self._tenants[DEFAULT_TENANT]
        if dts.plane is not None:
            # Sharded-plane probe surface (r17): the committed epoch and
            # the per-range version vector — the router's /healthz
            # aggregates these fleet-wide, and the fleet prober's
            # mixed-epoch guard keys off them.
            out["writer_shards"] = self.writer_shards
            out["epoch"] = dts.plane.coordinator.committed_epoch()
            out["shard_versions"] = {
                str(k): int(v)
                for k, v in dts.plane.version_vector().items()
            }
            degraded = [
                ws.shard for ws in dts.plane.shards if ws.read_only
            ]
            if degraded:
                out["degraded_shards"] = degraded
        if not ready:
            out["not_ready_reason"] = not_ready_why
        if overloaded:
            out["overload_reason"] = why
        return out

    def _snapshot_age_s(self, eng: QueryEngine) -> float:
        created = eng.snapshot.meta.get("created")
        base = float(created) if created else self._t0_wall
        return round(max(0.0, time.time() - base), 3)

    # -- memory plane ------------------------------------------------------
    def memory_payload(self) -> dict:
        """The ``/statusz`` "memory" section + ``graphmine_memory_*``
        gauges (ISSUE 14, docs/OBSERVABILITY.md "Memory plane"): host
        RSS and headroom against the process budget, the served
        snapshot's array bytes vs the derived query index, and the
        retained WAL segment bytes — byte accounting for everything this
        process deliberately holds, so "RSS grew" decomposes into WHICH
        plane grew. Updated on the cadences that already read it
        (/statusz, and /healthz through the alert values — the prober
        cadence); no new threads."""
        out = host_memory(self._mem_budget)
        eng = self._engine
        out.update(eng.memory_bytes())
        if self.wal is not None:
            out["wal_segment_bytes"] = int(
                self.wal.snapshot().get("segment_bytes", 0)
            )
        export_memory_gauges(self.registry, out)
        return out

    # -- result quality & alerts ------------------------------------------
    def quality_payload(self, tenant: str = DEFAULT_TENANT) -> dict:
        """The "quality" section /statusz and /alertz serve: the
        writer's last full pass (state + drift + canary) when it is
        still the served version, else the engine's own lazily-built
        state — a replica that only reloads still exposes its sketches
        for the router's fleet merge. Tenant-scoped: each tenant's
        sketches and canary describe ITS graph only."""
        ts = self._tenant_state(tenant)
        eng = ts.engine
        rep = ts.quality_report
        if rep is not None and rep.state.version == eng.version:
            return rep.payload()
        if not self.quality_enabled:
            return {"disabled": True}
        from graphmine_tpu.obs.quality import export_gauges

        state = eng.quality_state()
        if tenant == DEFAULT_TENANT:
            # unlabelled quality gauges track the default tenant only
            # (the per-tenant race rule — see _make_tenant_state)
            export_gauges(self.registry, state)
        return {"state": state.payload()}

    def _alert_values(self, tenant: str = DEFAULT_TENANT) -> dict:
        """The flat metric dict the alert rules evaluate over: quality
        numbers from the freshest source plus the serving-side gauges
        the default ingest-lag rule reads. Per tenant — a canary
        regression in tenant A's graph must page naming A and never
        trip B's rules."""
        ts = self._tenant_state(tenant)
        debt = ts.debt.snapshot()
        eng = ts.engine
        values = {
            "ingest_lag_s": debt["ingest_lag_s"],
            "repair_debt_rows": debt["pending_rows"],
            "snapshot_age_s": self._snapshot_age_s(eng),
        }
        if tenant == DEFAULT_TENANT:
            # Memory headroom rides the same evaluation (ISSUE 14): the
            # prober's /healthz cadence drives the low-headroom rule
            # fleet-wide, and the read refreshes the graphmine_memory_*
            # gauges as a side effect. Metric absent when no budget is
            # resolvable — the rule then simply never fires. The budget
            # (and RSS) is the PROCESS's, so only the default tenant's
            # rule set carries it — one page per replica, not one per
            # tenant.
            headroom = self.memory_payload().get("headroom_frac")
            if headroom is not None:
                values["memory_headroom_frac"] = headroom
        rep = ts.quality_report
        if rep is not None and rep.state.version == eng.version:
            values.update(rep.values())
        elif self.quality_enabled:
            # cached-only (build=False): /healthz drives this path at
            # probe cadence, and a liveness probe must not pay the O(V)
            # state build after every swap — the quality rules simply
            # don't evaluate until an /alertz or /statusz read (or the
            # router's fan-out) builds the state explicitly.
            state = eng.quality_state(build=False)
            if state is not None:
                values["quality_anomaly_rate"] = state.anomaly_rate
                values["quality_num_communities"] = state.num_communities
        return values

    def evaluate_alerts(self) -> list:
        """One alert-rule evaluation pass over EVERY tenant's rule set;
        returns the transitions. Never raises into a caller — /healthz
        answering 500 because a quality pass hiccuped would fail the
        prober over telemetry."""
        out = []
        for ts in list(self._tenants.values()):
            try:
                out.extend(ts.alerts.evaluate(self._alert_values(ts.tenant)))
            except Exception:  # noqa: BLE001 — alerting must not break serving
                pass
        return out

    def alertz(self, tenant: str = DEFAULT_TENANT) -> dict:
        """The ``/alertz`` body: alert level state + the quality section
        (evaluated at read time, so a drained-and-idle server still
        transitions rules whose conditions cleared). ``?tenant=`` or
        ``X-Tenant-Id`` scopes the page to that tenant's rule set."""
        self.evaluate_alerts()
        ts = self._tenant_state(tenant)
        out = {
            "version": ts.engine.version,
            **ts.alerts.snapshot(),
            "quality": self.quality_payload(tenant),
        }
        if tenant != DEFAULT_TENANT:
            out["tenant"] = tenant
        return out

    def endpoint_latency(self) -> dict:
        """Per-endpoint latency/error summary from the request histogram
        family: count, errors, error_rate, p50/p95/p99 (bucket-estimated
        — within one bucket of the exact offline quantiles from the
        ``access_log`` JSONL, the ``tests/test_slo.py`` acceptance)."""
        fam = self.registry.histogram_family("graphmine_serve_request_seconds")
        out: dict = {}
        if fam is None:
            return out
        with self._req_lock:
            errors = dict(self._endpoint_errors)
        for child in fam.children():
            ep = child.labels.get("endpoint", "?")
            snap = child.snapshot()
            if not snap.count:
                continue
            err = errors.get(ep, 0)
            out[ep] = {
                **snap.summary(),
                "errors": err,
                "error_rate": round(err / snap.count, 4),
                "mean_s": round(snap.sum / snap.count, 6),
                "p95_s": round(snap.quantile(0.95), 6),
            }
        return out

    def statusz(self) -> dict:
        """The SLO page — and, when a sink is attached, one
        ``slo_rollup`` record per read, so the offline JSONL carries
        periodic rollup checkpoints a scrape-less run can still plot."""
        eng = self._engine
        tenants = list(self._tenants.values())
        with self._req_lock:
            inflight = self._inflight
        with self._queue_cv:
            depths = {ts.tenant: len(ts.queue) for ts in tenants}
            depth, applying = sum(depths.values()), self._applying
        payload = {
            "version": eng.version,
            "snapshot_id": eng.snapshot.snapshot_id,
            "snapshot_age_s": self._snapshot_age_s(eng),
            "uptime_s": round(time.perf_counter() - self._t0_mono, 3),
            "inflight": inflight,
            "endpoints": self.endpoint_latency(),
            "repair_debt": self.debt.snapshot(),
            "query_stages": eng.stage_snapshot(),
            "admission": {
                **self.admission.snapshot(),
                "queue_depth": depth,
                "applying": applying,
                "lof_stale": eng.lof_stale,
            },
            "writer_epoch": self.writer_epoch,
            "delta_stages": self.delta_stage_latency(),
            # result-quality section (ISSUE 13): the served snapshot's
            # sketches/anomaly rate (+ drift/canary on the writer) and
            # the alert level view — the same payloads /alertz serves
            "quality": self.quality_payload(),
            "alerts": self.alerts.snapshot(),
            # memory plane (ISSUE 14): RSS + headroom, snapshot vs index
            # vs WAL byte accounting — the serve-side mirror of the
            # driver's memory_watermark records
            "memory": self.memory_payload(),
            # tenancy (ISSUE 16): registry view (known tenants +
            # overrides), the packing-oracle memory map (per-tenant
            # snapshot bytes vs the ONE fleet-wide budget), and each
            # tenant's own admission/queue/debt section — the page that
            # names the noisy neighbor
            "tenancy": {
                **self.tenancy.snapshot(),
                "memory": self.tenancy.memory_payload(self._mem_budget),
                "fair_quantum_rows": self._fair_quantum_rows,
                "per_tenant": {
                    ts.tenant: {
                        **ts.admission.snapshot(),
                        "queue_depth": depths.get(ts.tenant, 0),
                        "repair_debt": ts.debt.snapshot(),
                        "version": ts.engine.version,
                    }
                    for ts in tenants
                },
            },
        }
        if self.wal is not None:
            payload["wal"] = self.wal.snapshot()
        dts = self._tenants[DEFAULT_TENANT]
        if dts.plane is not None:
            # Per-shard WAL/admission/debt children (r17): the single
            # "wal" section becomes a per-range table — one entry per
            # shard, mirroring the per-shard-labeled gauge children on
            # /metrics.
            payload["shardplane"] = dts.plane.snapshot()
        if self._shipper is not None:
            payload["replication"] = self._shipper.snapshot()
        if self.sink is not None:
            self.sink.emit(
                "slo_rollup",
                uptime_s=payload["uptime_s"],
                endpoints=payload["endpoints"],
                repair_debt=payload["repair_debt"],
                version=payload["version"],
                inflight=inflight,
            )
        return payload

    def metrics_text(self) -> str:
        """Live Prometheus exposition — the same deterministic rendering
        (and the same run_id labels) as the textfile path, served hot.
        Refreshes the graphmine_memory_* gauges on the scrape itself: a
        deployment that only reads /metrics (no prober, nobody on
        /statusz) must not see absent or stale memory accounting."""
        self.memory_payload()
        return self.registry.render_textfile(labels=self._run_labels())

    # -- request middleware hooks -----------------------------------------
    def _inflight_gauge(self):
        return self.registry.gauge(
            "graphmine_serve_inflight_requests",
            "requests currently being handled",
        )

    def request_started(self) -> None:
        # The gauge set stays under _req_lock: two racing updates setting
        # out of order would park the gauge on a stale value forever.
        gauge = self._inflight_gauge()
        with self._req_lock:
            self._inflight += 1
            gauge.set(self._inflight)

    def request_finished(
        self, method: str, endpoint: str, status: int, seconds: float,
        request_id: str, body: bytes = b"", tenant: str = "",
    ) -> None:
        """The middleware tail: histogram observe + counters +
        ``access_log`` record. Runs on every request, including errored
        ones — an SLO page that only counts successes is lying about the
        tail."""
        gauge = self._inflight_gauge()
        with self._req_lock:
            self._inflight -= 1
            gauge.set(self._inflight)
            if status >= 400:
                self._endpoint_errors[endpoint] = (
                    self._endpoint_errors.get(endpoint, 0) + 1
                )
        reg = self.registry
        reg.histogram(
            "graphmine_serve_request_seconds",
            "HTTP request wall time by endpoint",
            endpoint=endpoint,
        ).observe(seconds)
        reg.counter(
            "graphmine_serve_http_requests_total", "HTTP requests handled"
        ).inc()
        if status >= 400:
            reg.counter(
                "graphmine_serve_http_errors_total",
                "HTTP requests answered with a 4xx/5xx status",
            ).inc()
        if self.sink is None:
            return
        kv = {
            "method": method,
            "endpoint": endpoint,
            "status": int(status),
            "seconds": round(seconds, 6),
            "request_id": request_id,
        }
        if tenant and tenant != DEFAULT_TENANT:
            # explicit non-default routing only: pre-tenancy access_log
            # consumers keep seeing exactly the records they always did
            kv["tenant"] = tenant
        if seconds >= self.slow_request_s:
            # Identify the offending payload without logging it: the
            # digest joins a client-side replay to this exact request.
            kv["slow"] = True
            if body:
                kv["body_sha256"] = hashlib.sha256(body).hexdigest()
                kv["body_bytes"] = len(body)
        self.sink.emit("access_log", **kv)

    # -- query plumbing (shared with serve_cli's in-process mode) ---------
    def vertex_row(self, engine: QueryEngine, v: int) -> dict:
        row = {
            "vertex": int(v),
            "label": engine.membership(v),
            "component": engine.component(v),
            "lof": engine.score(v),
            "community_size": engine.community_size(v),
            "community_decile": engine.community_decile(v),
        }
        if engine.lof_stale:
            # deferred-refresh staleness flag (admission rung 2): the
            # label/component columns are verified-fresh, the LOF score
            # may predate the last few deltas
            row["lof_stale"] = True
        return row

    def record_batch(self, endpoint: str, n: int, seconds: float) -> None:
        if self.sink is not None:
            self.sink.emit(
                "query_batch", endpoint=endpoint, n=int(n),
                seconds=round(seconds, 6),
            )
        self.registry.counter(
            "graphmine_serve_queries_total", "vertex lookups served"
        ).inc(n)


class _Handler(BaseHTTPRequestHandler):
    srv: SnapshotServer  # bound by SnapshotServer.start

    # stdlib default logs every request to stderr; the metrics stream is
    # the intended record of serving traffic (access_log records).
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _reply(self, code: int, payload: dict, headers: dict | None = None) -> None:
        body = json.dumps(_jsonable(payload)).encode()
        self._send(code, body, "application/json", headers=headers)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        self._send(code, text.encode(), content_type)

    def _send(
        self, code: int, body: bytes, content_type: str,
        headers: dict | None = None,
    ) -> None:
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header("X-Request-Id", rid)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._reply(code, {"error": message})

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        self._raw_body = self.rfile.read(length)
        data = json.loads(self._raw_body.decode())
        if not isinstance(data, dict):
            raise ValueError("request body must be a JSON object")
        return data

    # -- the timing middleware --------------------------------------------
    def _serve(self, method: str, routes: dict) -> None:
        """One wrapper around every request: resolve the handler AND the
        endpoint label from the same route table, stamp/propagate the
        trace id, time the full handle, and ALWAYS run the middleware
        tail — histogram + counters + access_log — even when the handler
        errored (a narrow catch turns bad input into a 400; anything
        else still records as the in-flight 500 before propagating)."""
        url = urlparse(self.path)
        handler = routes.get(url.path)
        endpoint = url.path.lstrip("/") if handler else "unknown"
        rid = self.headers.get("X-Request-Id", "")
        # fullmatch, not match: `$` would accept a trailing newline,
        # and the id is echoed into a response header verbatim.
        if not _REQUEST_ID_RE.fullmatch(rid or ""):
            rid = secrets.token_hex(8)
        self._request_id = rid
        self._status = 500
        self._raw_body = b""
        self._tenant = ""
        self._tenant_explicit = False
        self.srv.request_started()
        chaos = self.srv.chaos_delay_s
        if chaos > 0:
            time.sleep(chaos)  # replica_slow injector (testing/faults.py)
        # Inherited trace identity (docs/OBSERVABILITY.md "Fleet
        # tracing"): a propagated traceparent header makes this whole
        # request — access_log, admission, wal_append, query_batch,
        # everything emitted on this thread — land in the SENDER's
        # trace (the fleet router's per-request root span). No header,
        # or a malformed one: records stay in this server's run trace,
        # exactly as before.
        ctx = TraceContext.from_header(self.headers.get(TRACE_HEADER, ""))
        span = (
            self.srv.sink.span(
                f"http:{endpoint}", emit=False, annotate=False, remote=ctx,
            )
            if ctx is not None and self.srv.sink is not None
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with span:
            try:
                if handler is None:
                    self._error(404, f"unknown path {url.path!r}")
                else:
                    getattr(self, handler)(url)
            except UnknownTenantError:
                # valid-but-unknown tenant id: 404, with the SAME body a
                # wrong-tenant vertex miss gets below — the existence of
                # other tenants' data must not be probeable from status
                # or message differences (malformed ids stay 400 via
                # the ValueError arm).
                try:
                    self._error(404, "not found")
                except OSError:
                    self._status = 499
            except (KeyError, ValueError, IndexError) as e:
                code = 400
                if self._tenant_explicit and isinstance(
                    e, (KeyError, IndexError)
                ):
                    # Explicitly tenant-routed lookup miss (a vertex id
                    # that exists in another tenant's graph, or in
                    # none): 404 "not found", indistinguishable from an
                    # unknown tenant. Bad input (ValueError) keeps 400.
                    code = 404
                try:
                    # KeyError.__str__ repr-quotes its message; unwrap it
                    msg = (
                        "not found" if code == 404
                        else (str(e.args[0]) if e.args else str(e))
                    )
                    self._error(code, msg)
                except OSError:
                    self._status = 499  # socket died while sending the 400
            except OSError:
                # The connection died under us (client disconnect
                # mid-write): nothing more can be sent, but the SLO
                # surface must not count an unreceived reply as a served
                # 2xx — record 499 (client closed request), the signal a
                # tail of impatient clients actually leaves.
                self._status = 499
            finally:
                self.srv.request_finished(
                    method, endpoint, self._status,
                    time.perf_counter() - t0, rid, body=self._raw_body,
                    tenant=self._tenant,
                )

    def do_GET(self) -> None:  # noqa: N802
        self._serve("GET", _GET_ROUTES)

    def do_POST(self) -> None:  # noqa: N802
        self._serve("POST", _POST_ROUTES)

    # -- GET routes --------------------------------------------------------
    # Handlers that read result state bind `eng = ...` ONCE: a
    # concurrent snapshot swap must not mix two versions inside one
    # response.

    def _tenant_of(self, url) -> str:
        """The request's tenant routing: ``X-Tenant-Id`` header first
        (what the fleet router forwards), ``?tenant=`` as the curl-able
        fallback. Absent = the default tenant — the pre-tenancy
        contract. The raw value is NOT validated here: the server's
        tenant resolution 400s malformed ids and 404s unknown ones."""
        raw = self.headers.get("X-Tenant-Id", "").strip()
        if not raw:
            vals = parse_qs(url.query).get("tenant")
            raw = vals[0].strip() if vals else ""
        if raw:
            self._tenant_explicit = True
            self._tenant = raw
            return raw
        return DEFAULT_TENANT

    def _pin_ok(self, eng) -> bool:
        """The fleet router's consistency pin: an ``X-Serve-Version``
        header demands the response come from exactly that snapshot
        version. A replica that swapped between the router's pick and
        this handler answers 409 and the router retries elsewhere —
        the mixed-version window closes at the replica, where the swap
        actually happens (the engine is already bound, so the check and
        the response read one version)."""
        want = self.headers.get("X-Serve-Version", "")
        if not want:
            return True
        try:
            want_v = int(want)
        except ValueError:
            return True
        if want_v == eng.version:
            return True
        self._reply(409, {
            "error": "version mismatch",
            "version": eng.version,
            "requested": want_v,
        })
        return False

    def _ep_healthz(self, url) -> None:
        self._reply(200, self.srv.healthz())

    def _ep_statusz(self, url) -> None:
        self._reply(200, self.srv.statusz())

    def _ep_metrics(self, url) -> None:
        self._reply_text(
            200, self.srv.metrics_text(),
            "text/plain; version=0.0.4; charset=utf-8",
        )

    def _ep_snapshot(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        self._reply(200, eng.snapshot.meta)

    def _ep_vertex(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        t0 = time.perf_counter()
        v = int(parse_qs(url.query)["v"][0])
        row = self.srv.vertex_row(eng, v)
        self.srv.record_batch("vertex", 1, time.perf_counter() - t0)
        self._reply(200, row)

    def _ep_explain(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        t0 = time.perf_counter()
        qs = parse_qs(url.query)
        vals = qs.get("vertex") or qs.get("v")
        if not vals:
            raise ValueError("explain needs ?vertex=<id>")
        row = eng.explain(int(vals[0]))
        self.srv.record_batch("explain", 1, time.perf_counter() - t0)
        self._reply(200, row)

    def _ep_alertz(self, url) -> None:
        self._reply(200, self.srv.alertz(self._tenant_of(url)))

    def _ep_neighbors(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        t0 = time.perf_counter()
        v = int(parse_qs(url.query)["v"][0])
        nbrs = eng.neighbors(v)
        self.srv.record_batch("neighbors", 1, time.perf_counter() - t0)
        self._reply(200, {"vertex": v, "neighbors": nbrs})

    def _ep_topk(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        t0 = time.perf_counter()
        qs = parse_qs(url.query)
        community = int(qs["community"][0])
        k = int(qs.get("k", ["10"])[0])
        top = eng.top_outliers(community, k)
        self.srv.record_batch("topk", len(top), time.perf_counter() - t0)
        self._reply(200, {
            "community": community,
            "top": [{"vertex": v, "lof": s} for v, s in top],
        })

    # -- POST routes -------------------------------------------------------
    def _ep_query(self, url) -> None:
        eng = self.srv.engine_for(self._tenant_of(url))
        if not self._pin_ok(eng):
            return
        t0 = time.perf_counter()
        body = self._body()
        out = eng.query_batch(body.get("vertices", []))
        self.srv.record_batch(
            "query", len(out["vertex"]), time.perf_counter() - t0
        )
        payload = {**out, "version": eng.version}
        if eng.lof_stale:
            payload["lof_stale"] = True
        self._reply(200, payload)

    def _ep_delta(self, url) -> None:
        # X-Deadline-Ms (r9 deadline semantics, end-to-end): the
        # client's remaining budget narrows the queued-batch deadline.
        deadline_s = None
        raw_ms = self.headers.get("X-Deadline-Ms", "")
        if raw_ms:
            try:
                deadline_s = max(1, int(raw_ms)) / 1000.0
            except ValueError:
                deadline_s = None
        # X-Delta-Id (r11, serve/wal.py): the client's idempotency key —
        # same constrained alphabet as request ids (it lands in records
        # and response bodies verbatim).
        delta_id = self.headers.get("X-Delta-Id", "")
        if delta_id and not _REQUEST_ID_RE.fullmatch(delta_id):
            self._error(
                400, "X-Delta-Id must match [A-Za-z0-9._:-]{1,64}"
            )
            return
        raw_ack = self.headers.get("X-Delta-Ack", "").strip().lower()
        if raw_ack and raw_ack != "wal":
            # an unknown mode must not silently downgrade to the
            # blocking path — the client believes it asked for a fast
            # durable 202 and would block to the full deadline instead
            self._error(
                400, f"unknown X-Delta-Ack mode {raw_ack!r} (use 'wal')"
            )
            return
        ack = raw_ack or None
        tenant = self._tenant_of(url)
        try:
            out = self.srv.apply_delta(
                self._body(), deadline_s=deadline_s,
                delta_id=delta_id or None, ack=ack, tenant=tenant,
            )
        except PublishFencedError as e:
            # The FIRST fenced sync publish surfaces here (the worker
            # latches the write path closed as it raises — every later
            # write gets the front-door shed). Answer the same
            # structured 503 instead of dying with a dropped socket.
            out = self.srv._shed_payload(
                f"writer fenced ({e}): a newer writer owns the store",
                self.srv.admission.bounds.retry_after_s,
            )
        verdict = out.get("verdict")
        if verdict == "shed":
            # the structured refusal: 503 + a Retry-After the client's
            # backoff can obey without parsing the body
            self._reply(503, out, headers={
                "Retry-After": str(
                    max(1, math.ceil(out.get("retry_after_s", 1.0)))
                ),
            })
        elif verdict == "accepted":
            # WAL-durable, not yet published: the honest 202
            self._reply(202, out)
        elif verdict == "duplicate":
            self._reply(200 if out.get("applied") else 202, out)
        else:
            self._reply(200, out)

    def _ep_wal(self, url) -> None:
        qs = parse_qs(url.query)
        from_seq = int(qs.get("from", ["1"])[0])
        limit = min(4096, int(qs.get("limit", ["512"])[0]))
        self._reply(200, self.srv.wal_entries(from_seq, limit=limit))

    def _ep_promote(self, url) -> None:
        self._reply(200, self.srv.promote())

    def _ep_profilez(self, url) -> None:
        body = self._body()
        try:
            duration_ms = int(body.get("duration_ms", 1000))
        except TypeError as e:  # JSON null/list/object: bad input, not 500
            raise ValueError(f"duration_ms must be an integer: {e}") from e
        kind = body.get("kind", "trace")
        if kind not in ("trace", "memory"):
            raise ValueError(f"unknown profilez kind {kind!r} "
                             "(use 'trace' or 'memory')")
        status, payload = self.srv.profilez(
            duration_ms=duration_ms, kind=kind,
        )
        self._reply(status, payload)

    def _ep_reload(self, url) -> None:
        self._reply(200, self.srv.reload(self._tenant_of(url)))

    def _ep_drain(self, url) -> None:
        self._reply(200, self.srv.drain())

    def _ep_undrain(self, url) -> None:
        self._reply(200, self.srv.undrain())
