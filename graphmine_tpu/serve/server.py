"""Stdlib HTTP front end: JSON queries over double-buffered snapshots.

One :class:`SnapshotServer` owns a snapshot store, serves lookups from an
immutable :class:`~graphmine_tpu.serve.query.QueryEngine`, and accepts
delta batches. Publishes are **double-buffered**: a delta builds the next
engine off to the side and swaps it in with one reference assignment —
in-flight requests keep the engine they grabbed at entry, so a publish
never drops or torn-reads a live query (pinned by
``tests/test_serve.py::test_server_swap_under_live_queries``).

Endpoints (all JSON):

====================  =====================================================
``GET  /healthz``      liveness + current snapshot version
``GET  /snapshot``     current snapshot manifest metadata
``GET  /vertex?v=``    one vertex: label, component, LOF, size, decile
``GET  /neighbors?v=`` neighbor ids of one vertex
``GET  /topk?community=&k=``  top-k LOF outliers of one community
``POST /query``        ``{"vertices": [...]}`` — the batched gather path
``POST /delta``        ``{"insert": [[s,d],...], "delete": [[s,d],...]}``
``POST /reload``       reload the store's newest snapshot and swap
====================  =====================================================

Observability: every batch resolve emits a ``query_batch`` record, every
delta a ``delta_apply`` (from the ingestor) and the store a
``snapshot_publish`` — all span-stamped through the sink's tracer and
rendered by ``tools/obs_report.py``; the counter/gauge registry exports
through the existing Prometheus textfile path (``prom_out``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from graphmine_tpu.serve.delta import DeltaIngestor, EdgeDelta
from graphmine_tpu.serve.query import QueryEngine
from graphmine_tpu.serve.snapshot import SnapshotStore


def _jsonable(obj):
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


class SnapshotServer:
    """Query server + delta ingest endpoint over one snapshot store."""

    def __init__(
        self,
        store: SnapshotStore,
        host: str = "127.0.0.1",
        port: int = 0,
        sink=None,
        prom_out: str | None = None,
        num_shards: int = 1,
    ):
        self.store = store
        self.sink = sink
        self.prom_out = prom_out
        self.num_shards = num_shards
        snap = store.load(sink=sink)
        if snap is None:
            raise ValueError(
                f"snapshot store at {store.root!r} is empty; publish one "
                "first (pipeline --snapshot-out or serve_cli publish)"
            )
        # The double buffer: _engine is replaced atomically (one reference
        # assignment); handlers bind it to a local once per request.
        self._engine = QueryEngine(snap)
        self._ingestor: DeltaIngestor | None = None
        # One publisher at a time — the store's generation rotation (and
        # the ingestor's host state) assume it.
        self._delta_lock = threading.Lock()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._host, self._port = host, port
        self._export_metrics()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> tuple[str, int]:
        """Bind and serve on a daemon thread; returns (host, port)."""
        server = self

        class Handler(_Handler):
            srv = server

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="graphmine-serve",
            daemon=True,
        )
        self._thread.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- snapshot swap ----------------------------------------------------
    @property
    def engine(self) -> QueryEngine:
        return self._engine

    def _swap(self, engine: QueryEngine) -> None:
        self._engine = engine  # atomic ref swap: the double-buffer flip
        self._export_metrics()

    def _export_metrics(self) -> None:
        if self.sink is None:
            return
        self.sink.registry.gauge(
            "graphmine_serve_snapshot_version",
            "snapshot version currently serving queries",
        ).set(self._engine.version)
        if self.prom_out:
            try:
                self.sink.registry.write_textfile(self.prom_out)
            except OSError:
                pass  # metrics export must never take queries down

    def reload(self) -> dict:
        """Load the store's newest snapshot; swap if it is newer than the
        one serving (another process may have published). Serialized with
        delta ingest, and a swap drops the ingestor: its host edge/label
        state derives from the snapshot it last published, and applying a
        delta on top of the STALE state would silently discard the
        externally published snapshot's edges (its next publish would
        still chain version numbers from the store's manifest)."""
        with self._delta_lock:
            snap = self.store.load(sink=self.sink)
            swapped = snap is not None and snap.version != self._engine.version
            if swapped:
                self._swap(QueryEngine(snap))
                self._ingestor = None
            return {"version": self._engine.version, "swapped": swapped}

    def apply_delta(self, payload: dict) -> dict:
        """Ingest one delta batch (the POST /delta body) and swap the
        fresh snapshot in under live queries."""
        delta = EdgeDelta.from_pairs(
            insert=payload.get("insert", ()), delete=payload.get("delete", ())
        )
        with self._delta_lock:
            if self._ingestor is None:
                self._ingestor = DeltaIngestor(
                    self.store, sink=self.sink, num_shards=self.num_shards,
                    snapshot=self._engine.snapshot,
                )
            snap = self._ingestor.apply(delta)
            self._swap(QueryEngine(snap))
        if self.sink is not None:
            self.sink.registry.counter(
                "graphmine_serve_deltas_total", "delta batches ingested"
            ).inc()
        return {
            "version": snap.version,
            "snapshot_id": snap.snapshot_id,
            "num_vertices": int(len(snap["labels"])),
            "num_edges": int(len(snap["src"])),
        }

    # -- query plumbing (shared with serve_cli's in-process mode) ---------
    def vertex_row(self, engine: QueryEngine, v: int) -> dict:
        return {
            "vertex": int(v),
            "label": engine.membership(v),
            "component": engine.component(v),
            "lof": engine.score(v),
            "community_size": engine.community_size(v),
            "community_decile": engine.community_decile(v),
        }

    def record_batch(self, endpoint: str, n: int, seconds: float) -> None:
        if self.sink is None:
            return
        self.sink.emit(
            "query_batch", endpoint=endpoint, n=int(n),
            seconds=round(seconds, 6),
        )
        self.sink.registry.counter(
            "graphmine_serve_queries_total", "vertex lookups served"
        ).inc(n)


class _Handler(BaseHTTPRequestHandler):
    srv: SnapshotServer  # bound by SnapshotServer.start

    # stdlib default logs every request to stderr; the metrics stream is
    # the intended record of serving traffic.
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(_jsonable(payload)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._reply(code, {"error": message})

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        data = json.loads(self.rfile.read(length).decode())
        if not isinstance(data, dict):
            raise ValueError("request body must be a JSON object")
        return data

    def do_GET(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        qs = parse_qs(url.query)
        # One engine ref for the whole request: a concurrent snapshot
        # swap must not mix two versions inside one response.
        eng = self.srv.engine
        t0 = time.perf_counter()
        try:
            if url.path == "/healthz":
                self._reply(200, {
                    "ok": True,
                    "version": eng.version,
                    "snapshot_id": eng.snapshot.snapshot_id,
                    "num_vertices": eng.num_vertices,
                })
            elif url.path == "/snapshot":
                self._reply(200, eng.snapshot.meta)
            elif url.path == "/vertex":
                v = int(qs["v"][0])
                row = self.srv.vertex_row(eng, v)
                self.srv.record_batch("vertex", 1, time.perf_counter() - t0)
                self._reply(200, row)
            elif url.path == "/neighbors":
                v = int(qs["v"][0])
                nbrs = eng.neighbors(v)
                self.srv.record_batch("neighbors", 1, time.perf_counter() - t0)
                self._reply(200, {"vertex": v, "neighbors": nbrs})
            elif url.path == "/topk":
                community = int(qs["community"][0])
                k = int(qs.get("k", ["10"])[0])
                top = eng.top_outliers(community, k)
                self.srv.record_batch("topk", len(top), time.perf_counter() - t0)
                self._reply(200, {
                    "community": community,
                    "top": [{"vertex": v, "lof": s} for v, s in top],
                })
            else:
                self._error(404, f"unknown path {url.path!r}")
        except (KeyError, ValueError, IndexError) as e:
            # KeyError.__str__ repr-quotes its message; unwrap it
            self._error(400, str(e.args[0]) if e.args else str(e))

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        eng = self.srv.engine
        t0 = time.perf_counter()
        try:
            if url.path == "/query":
                body = self._body()
                out = eng.query_batch(body.get("vertices", []))
                self.srv.record_batch(
                    "query", len(out["vertex"]), time.perf_counter() - t0
                )
                self._reply(200, {**out, "version": eng.version})
            elif url.path == "/delta":
                self._reply(200, self.srv.apply_delta(self._body()))
            elif url.path == "/reload":
                self._reply(200, self.srv.reload())
            else:
                self._error(404, f"unknown path {url.path!r}")
        except (KeyError, ValueError, IndexError) as e:
            # KeyError.__str__ repr-quotes its message; unwrap it
            self._error(400, str(e.args[0]) if e.args else str(e))
