"""Serving layer: versioned snapshots, incremental delta repair, queries.

The batch pipeline (``pipeline/driver.py``) computes communities and LOF
scores and exits; nothing served those results, and every new edge batch
forced a cold full recompute. This package is the steady-state side
(docs/SERVING.md):

- :mod:`~graphmine_tpu.serve.snapshot` — versioned, atomically-published
  result snapshots (the checkpoint manifest pattern applied to pipeline
  *outputs*);
- :mod:`~graphmine_tpu.serve.delta` — edge insert/delete batches spliced
  into the graph with **warm-start repair**: the previous snapshot's
  labels seed LPA/CC via ``init_labels`` and only the delta-affected
  frontier re-runs (GraphBLAST's steady-state argument), tripwire-guarded
  by a sampled exact check with full-recompute fallback;
- :mod:`~graphmine_tpu.serve.query` — O(1)/O(log n) lookups over a loaded
  snapshot, with a batched one-device-gather path;
- :mod:`~graphmine_tpu.serve.server` — a stdlib HTTP front end that
  double-buffers snapshots so a delta publish swaps atomically under
  live queries;
- :mod:`~graphmine_tpu.serve.admission` — write-path overload
  protection: ONE policy owner resolving every incoming delta to
  accept/queue/coalesce/shed against the live repair-debt state, with
  order-exact delta coalescing and an LOF-defer degradation rung
  (docs/SERVING.md "admission control");
- :mod:`~graphmine_tpu.serve.fleet` — the replicated tier: a front
  router with consistent-version routing over N replicas, per-replica
  circuit breakers, single-writer forwarding (writer loss = read-only,
  never split-brain) and zero-downtime rolling reload
  (docs/SERVING.md "Fleet");
- :mod:`~graphmine_tpu.serve.wal` — the durable write-ahead delta log
  + log shipping: accepted batches fsync before acknowledgement,
  startup replay, idempotent retries (``X-Delta-Id``), a log-shipped
  standby writer with bounded observable replication lag, and
  writer-epoch fencing at the snapshot store so a deposed writer can
  never clobber the promoted standby (docs/SERVING.md "Replicated
  writers").
"""

from graphmine_tpu.serve.admission import (
    AdmissionBounds,
    AdmissionController,
    AdmissionDecision,
    coalesce_deltas,
)
from graphmine_tpu.serve.delta import (
    DeltaIngestor,
    EdgeDelta,
    RepairDebt,
    RepairResult,
)
from graphmine_tpu.serve.fleet import (
    CircuitBreaker,
    FleetConfig,
    FleetRouter,
    ReplicaSet,
    ReplicaSpec,
)
from graphmine_tpu.serve.query import QueryEngine
from graphmine_tpu.serve.snapshot import (
    PublishFencedError,
    Snapshot,
    SnapshotStore,
)
from graphmine_tpu.serve.wal import LogShipper, WriteAheadLog

__all__ = [
    "AdmissionBounds",
    "AdmissionController",
    "AdmissionDecision",
    "CircuitBreaker",
    "DeltaIngestor",
    "EdgeDelta",
    "FleetConfig",
    "FleetRouter",
    "LogShipper",
    "PublishFencedError",
    "QueryEngine",
    "ReplicaSet",
    "ReplicaSpec",
    "RepairDebt",
    "RepairResult",
    "Snapshot",
    "SnapshotStore",
    "WriteAheadLog",
    "coalesce_deltas",
]
