"""Durable write-ahead delta log + log shipping — writes survive the writer.

The serve write path acknowledged work out of memory: an
admission-accepted POST /delta lived only on the in-process apply queue
until its publish, so a writer SIGKILL silently lost acknowledged
batches — violating the serve layer's own "never lie to the client"
contract. This module is the durability spine that closes that hole
(docs/SERVING.md "Replicated writers"):

- :class:`WriteAheadLog` — an append-only segmented log of
  admission-accepted delta batches. Every entry is a **checksummed
  framed record** (length header + sha256 + payload), append-fsync'd
  *before* the acceptance is answered, so an acknowledged delta is on
  disk before the client hears "accepted". Readers are **torn-tail
  tolerant** (the r3 checkpoint-reader discipline): a kill mid-append
  leaves a tail the next open detects, truncates, and keeps appending
  past — every record before the tear is intact by construction.
  Segments rotate at a size bound; **compaction is keyed to the
  published snapshot version**: the apply worker commits a durable
  ``(applied_seq, snapshot_version)`` watermark after each publish, and
  segments wholly below the watermark are pruned (a bounded retention
  tail is kept so duplicate-submit detection survives the prune).
- **Idempotency**: entries carry a client-suppliable delta id
  (``X-Delta-Id``); :meth:`WriteAheadLog.append` dedupes on it under
  the log's own lock, so a client retry after a lost acknowledgement
  can never double-apply (tests/test_wal.py duplicate-submit parity).
- :class:`LogShipper` — the standby side of log shipping: tails the
  primary's ``GET /wal?from=seq`` endpoint, appends fetched entries
  **verbatim (same seq, same id)** into the standby's own WAL copy,
  and merges the primary's watermark HISTORY (every ``(applied_seq,
  snapshot_version)`` pair, not just the latest) — keeping the
  standby's durable state within a bounded, *observable* replication
  lag (``ship_lag`` records + the ``/healthz`` replication gauges). On
  promotion the standby replays its WAL tail (plus, when the deposed
  primary's WAL directory is still reachable — the shared-store
  deployment this repo runs — the un-shipped tail straight from it, so
  a same-filesystem writer kill loses nothing). A standby running its
  OWN bootstrap copy of the store places the replay cursor from the
  shipped history at the version it adopts (:meth:`WriteAheadLog.
  replay_floor` + :meth:`WriteAheadLog.rewind`) — the primary's
  watermark describes the primary's store, so trusting it verbatim
  would mask shipped-but-locally-unapplied acked entries as applied.
  With the cursor placed exactly, the loss bound IS the shipped lag in
  both deployments — which is exactly why the lag is a first-class
  observable; a bootstrap too old for the retained history refuses to
  guess and says so loudly instead.

Epoch fencing lives in :mod:`~graphmine_tpu.serve.snapshot`
(``writer_epoch`` in the manifest chain + the durable ``EPOCH`` fence
file): a deposed writer's comeback publish is refused AT THE STORE with
:class:`~graphmine_tpu.serve.snapshot.PublishFencedError` and a loud
``publish_fenced`` record — split-brain goes from refusal-by-convention
(the r10 read-only degradation) to impossibility.

All host-side stdlib + numpy-free code; nothing here touches a device.
"""

from __future__ import annotations

import bisect
import glob
import hashlib
import json
import os
import struct
import threading
import time
from urllib import request as urlrequest

from graphmine_tpu.pipeline.checkpoint import _fsync_dir, _fsync_file
from graphmine_tpu.serve.tenancy import DEFAULT_TENANT

# Segment framing. Each segment starts with the magic; each record is
#   <8-byte seq little-endian> <4-byte payload length> <32-byte sha256> <payload>
# A record whose bytes run out, or whose digest disagrees, is a torn
# tail: everything before it is intact (appends are sequential and each
# append fsyncs), everything from it on is discarded.
_MAGIC = b"GMWAL1\x00\n"
_HDR = struct.Struct("<QI")
_DIGEST_LEN = 32

DEFAULT_SEGMENT_BYTES = 4 << 20
# Fully-applied segments kept after compaction: the duplicate-submit
# dedupe horizon (a retry older than the retained tail re-applies; the
# retention bound is the documented contract, not a silent cap).
DEFAULT_RETAIN_SEGMENTS = 2

_ENV_SEGMENT = "GRAPHMINE_WAL_SEGMENT_BYTES"
_ENV_RETAIN = "GRAPHMINE_WAL_RETAIN_SEGMENTS"

COMMIT_NAME = "COMMIT"

# Watermark-history bound: one (applied_seq, snapshot_version) pair per
# publish, kept in the COMMIT file. The history is what maps a snapshot
# VERSION back to a replay cursor — a promotion that adopts a store
# older than the mirrored watermark (separate-store standby) rewinds to
# the pair vouching for the adopted version instead of trusting the
# primary's watermark about a store it never published to. Bounded so
# the COMMIT file stays small; a bootstrap older than the bound falls
# back to the loud no-voucher path, never a silent wrong cursor.
HISTORY_MAX = 4096


class WalCorruptionError(RuntimeError):
    """Damaged bytes in a *non-tail* position: history this log already
    acknowledged is unreadable. Refused loudly (the checkpoint-reader
    contract) — silently dropping acknowledged entries is the exact
    failure mode the WAL exists to prevent."""


def _env_int(var: str, default: int) -> int:
    raw = os.environ.get(var)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(f"{var}={raw!r} is not an int") from e


def _parse_frames(blob: bytes) -> tuple[list, int, str | None]:
    """Walk the record frames in ``blob`` (offsets relative to its
    start). Returns ``(frames, valid_end, tear)``: ``frames`` is
    ``[(seq, entry, offset)]`` for every intact record, ``valid_end``
    the byte length of the intact prefix, ``tear`` the first damage
    found (``None`` = clean to the end). The ONE owner of the frame
    format — open-time recovery classifies the tear, shipping reads
    just stop at it."""
    frames, pos = [], 0
    while pos < len(blob):
        if pos + _HDR.size + _DIGEST_LEN > len(blob):
            return frames, pos, "truncated frame header"
        seq, length = _HDR.unpack_from(blob, pos)
        start = pos + _HDR.size + _DIGEST_LEN
        if start + length > len(blob):
            return frames, pos, f"payload of seq {seq} truncated"
        payload = blob[start: start + length]
        if hashlib.sha256(payload).digest() != blob[pos + _HDR.size: start]:
            return frames, pos, f"checksum mismatch at seq {seq}"
        try:
            entry = json.loads(payload.decode())
        except ValueError:
            return frames, pos, f"unparseable payload at seq {seq}"
        frames.append((int(seq), entry, pos))
        pos = start + length
    return frames, pos, None


class _Segment:
    """Bookkeeping for one on-disk segment file. ``index`` maps each
    intact record to its byte offset (``(seq, offset)`` pairs, seq
    ascending — appends are monotone) so tail reads seek instead of
    re-checksumming the whole segment on every shipping poll."""

    __slots__ = ("path", "first_seq", "last_seq", "size", "index")

    def __init__(self, path: str, first_seq: int):
        self.path = path
        self.first_seq = first_seq
        self.last_seq = 0        # 0 = no intact records yet
        self.size = len(_MAGIC)
        self.index: list[tuple[int, int]] = []


class WriteAheadLog:
    """Segmented, fsync'd, checksummed write-ahead log of delta batches.

    One writer per directory is the concurrency contract (the snapshot
    store's rule); any number of readers may scan (:meth:`entries` is
    what the primary's ``GET /wal`` serves and the standby's shipper
    consumes). All mutation happens under one lock; ``append`` returns
    only after the record's bytes AND the segment file are fsync'd.

    Entry shape (the JSON payload inside each frame)::

        {"seq": int, "op": "delta" | "skip", "id": str,
         "payload": {...the POST /delta body...},
         "deadline_s": float | None, "t": epoch-seconds}

    Entries for a non-default tenant additionally carry ``"tenant"``
    (ISSUE 16): the tenant id rides the durable frame so standby replay
    routes each entry back to ITS tenant's apply queue, and idempotency
    dedupe is scoped ``(tenant, delta_id)`` — two tenants may reuse the
    same client-side id without colliding, and a retry can never be
    answered with another tenant's seq. An absent key is the default
    tenant (every pre-tenancy frame), so existing logs replay unchanged.

    ``skip`` entries are tombstones: a WAL-durable batch that was shed
    off the queue (deadline expiry) before applying — replay excludes
    the skipped seq, and the shed entry's id leaves the dedupe map so
    the retry the 503 asked for re-accepts as a fresh entry (dedupe
    against a tombstoned seq would report the work applied when replay
    explicitly excludes it).
    """

    def __init__(
        self,
        root: str,
        segment_max_bytes: int | None = None,
        retain_segments: int | None = None,
        sink=None,
        registry=None,
        read_only: bool = False,
        shard: int | None = None,
    ):
        self.root = root
        # Writer-shard identity (r17 shardplane): when set, the seq
        # gauges export as per-shard-labeled children
        # (``...{shard="2"}``) — one unlabeled gauge would silently
        # average a dead shard's backlog into healthy ranges.
        self.shard = None if shard is None else int(shard)
        # read_only opens a FOREIGN log (a promotion reading the deposed
        # primary's directory): scan must not repair — truncating a
        # "torn" tail that is really the still-alive zombie's in-flight
        # append would destroy a frame it is about to fsync and
        # acknowledge (silent acked loss on shared storage). Mutators
        # refuse; the intact prefix is readable as usual.
        self.read_only = read_only
        # Set when an append failure left the active segment's tail in
        # an unknown state (the rollback itself failed) — every later
        # append refuses until a restart re-scans the segments.
        self._failed: str | None = None
        self.sink = sink
        self.registry = registry
        self.segment_max_bytes = (
            segment_max_bytes if segment_max_bytes is not None
            else _env_int(_ENV_SEGMENT, DEFAULT_SEGMENT_BYTES)
        )
        self.retain_segments = max(1, (
            retain_segments if retain_segments is not None
            else _env_int(_ENV_RETAIN, DEFAULT_RETAIN_SEGMENTS)
        ))
        self._lock = threading.Lock()
        self._segments: list[_Segment] = []
        self._active = None            # open file handle of the last segment
        self._last_seq = 0
        self._applied_seq = 0
        self._applied_version = 0
        # (applied_seq, snapshot_version) pairs, ascending by seq — the
        # version→cursor map replay_floor answers from.
        self._history: list[tuple[int, int]] = []
        # (tenant, delta_id) -> seq (process lifetime): the idempotency
        # map is tenant-scoped so ids never collide across tenants
        self._ids: dict[tuple[str, str], int] = {}
        self._skipped: set[int] = set()
        # The watermark is a CONTIGUOUS floor: every seq at or below it
        # is resolved (published, or a tombstone). Concurrent accepts
        # fsync outside the queue lock, so a group can publish seq N+1
        # while acked seq N is still racing toward the queue — the floor
        # must never jump that gap (a crash in the window would make
        # restart replay skip the acked entry: silent loss).
        # _applied_above holds published seqs stuck above an unresolved
        # gap (persisted in COMMIT, vouched per-snapshot by the
        # manifest's wal_applied_above); _meta_above holds non-work seqs
        # (tombstone records and their targets) the floor may pass.
        self._applied_above: set[int] = set()
        self._meta_above: set[int] = set()
        # Standby compaction guard: when set, never prune entries the
        # store version named here has not absorbed (its replay floor) —
        # the primary's mirrored watermark describes the PRIMARY's
        # store, and pruning against it would eat entries a
        # separate-store promotion still needs to replay.
        self.protect_version: int | None = None
        # The lock-free stats cache snapshot()/healthz read (see the seq
        # properties below for why it must not take the lock).
        self._snap: dict = {}
        if not self.read_only:
            os.makedirs(self.root, exist_ok=True)
        self._load_commit()
        self._scan()
        self._refresh_snap_locked()
        self._export()

    # -- open / recovery ---------------------------------------------------
    def _seg_path(self, first_seq: int) -> str:
        return os.path.join(self.root, f"wal-{first_seq:012d}.seg")

    def _load_commit(self) -> None:
        try:
            with open(os.path.join(self.root, COMMIT_NAME)) as f:
                body = json.load(f)
            self._applied_seq = int(body.get("applied_seq", 0))
            self._applied_version = int(body.get("snapshot_version", 0))
            self._history = [
                (int(s), int(v)) for s, v in body.get("history", ())
            ]
            self._applied_above = {
                int(s) for s in body.get("applied_above", ())
                if int(s) > self._applied_seq
            }
            if not self._history and self._applied_seq > 0:
                # pre-history COMMIT format: the latest pair is all we
                # can vouch for
                self._history = [(self._applied_seq, self._applied_version)]
        except (OSError, ValueError):
            pass  # empty/absent watermark: nothing applied yet

    def _scan(self) -> None:
        """Open-time recovery: read every retained segment, verify each
        frame, tolerate (and truncate) a torn tail in the LAST segment,
        refuse damage anywhere else."""
        paths = sorted(glob.glob(os.path.join(self.root, "wal-*.seg")))
        for i, path in enumerate(paths):
            last = i == len(paths) - 1
            seg = _Segment(path, 0)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                raise WalCorruptionError(f"cannot read {path}: {e}") from e
            if not blob.startswith(_MAGIC):
                if last and len(blob) < len(_MAGIC):
                    # killed between create and the magic fsync: an empty
                    # husk, not history — drop it (read-only leaves the
                    # foreign file alone: it may be the live owner's
                    # create-in-progress)
                    if not self.read_only:
                        os.remove(path)
                    continue
                raise WalCorruptionError(
                    f"{path} lacks the WAL segment magic; this directory "
                    "holds something that is not a graphmine WAL"
                )
            frames, valid_rel, torn = _parse_frames(blob[len(_MAGIC):])
            valid = len(_MAGIC) + valid_rel
            for seq, entry, off in frames:
                self._index(entry)
                seg.index.append((seq, off + len(_MAGIC)))
                if seg.first_seq == 0:
                    seg.first_seq = seq
                seg.last_seq = seq
            if torn is not None:
                if not last:
                    raise WalCorruptionError(
                        f"{path}: {torn} in a non-tail segment — "
                        "acknowledged history is damaged; restore the "
                        "directory from the standby's copy"
                    )
                if not self.read_only:
                    # torn tail: keep the intact prefix, drop the tear so
                    # the next append continues from a clean boundary. A
                    # read-only open of a FOREIGN log must not: the
                    # "tear" may be the live owner's in-flight append,
                    # and truncating it under the owner destroys a frame
                    # it is about to fsync and acknowledge.
                    with open(path, "r+b") as f:
                        f.truncate(valid)
                    _fsync_file(path)
                    if self.sink is not None:
                        self.sink.emit(
                            "wal_replay", entries=0,
                            from_seq=self._last_seq + 1,
                            torn_tail=torn, truncated_to=valid, path=path,
                        )
            seg.size = valid
            if seg.first_seq == 0:
                seg.first_seq = self._last_seq + 1  # intact but empty
            self._segments.append(seg)

    def _index(self, entry: dict) -> None:
        seq = int(entry["seq"])
        self._last_seq = max(self._last_seq, seq)
        if entry.get("op") == "skip":
            skipped = int(entry.get("skip_seq", 0))
            self._skipped.add(skipped)
            # neither the tombstone record nor its target is unapplied
            # work: the contiguous floor may advance past both
            self._meta_above.add(seq)
            self._meta_above.add(skipped)
            # the shed entry's id leaves the dedupe map: the client was
            # TOLD the work was shed (503 + Retry-After), so its retry
            # must re-accept as a fresh entry — answering "duplicate"
            # against a tombstoned seq would swallow the very retry the
            # server asked for (silent acknowledged loss)
            for key, s in list(self._ids.items()):
                if s == skipped:
                    del self._ids[key]
        elif entry.get("id"):
            tenant = entry.get("tenant") or DEFAULT_TENANT
            self._ids.setdefault((tenant, entry["id"]), seq)

    # -- append ------------------------------------------------------------
    def _open_active(self) -> None:
        if self._active is not None:
            return
        if self._segments:
            seg = self._segments[-1]
            self._active = open(seg.path, "ab")
            return
        self._new_segment(self._last_seq + 1)

    def _new_segment(self, first_seq: int) -> None:
        if self._active is not None:
            self._active.close()
            self._active = None
        seg = _Segment(self._seg_path(first_seq), first_seq)
        self._active = open(seg.path, "ab")
        self._active.write(_MAGIC)
        self._active.flush()
        os.fsync(self._active.fileno())
        _fsync_dir(self.root)
        seg.last_seq = 0
        self._segments.append(seg)

    def append(
        self,
        payload: dict,
        delta_id: str = "",
        deadline_s: float | None = None,
        seq: int | None = None,
        t: float | None = None,
        trace: str = "",
        tenant: str = DEFAULT_TENANT,
    ) -> tuple[int, bool]:
        """Durably append one accepted delta batch; returns
        ``(seq, duplicate)``.

        ``duplicate=True`` means the id (or, for a shipped copy, the
        explicit ``seq``) is already in the log — nothing was written,
        and the returned seq is the original's (the idempotency
        contract: a client retry after a lost acknowledgement maps onto
        the first accept instead of minting a second apply).

        ``seq``: explicit sequence number for the log-shipping copy
        path — the standby appends the primary's entries verbatim so
        both logs speak one sequence space. Client appends leave it
        None and take the next local seq. Returns only after the
        record's bytes and the segment file are fsync'd.

        ``trace``: the accepting request's propagated trace header
        (``obs/spans.py`` :class:`TraceContext` wire form) — carried in
        the durable entry so the trace survives fsync → ship → standby
        replay, and a promoted writer's apply of a shipped entry still
        lands in the ORIGINATING request's trace.

        ``tenant``: the owning tenant (ISSUE 16) — durable in the frame
        for non-default tenants, and the dedupe scope for ``delta_id``.
        """
        t0 = time.perf_counter()
        with self._lock:
            if seq is not None and int(seq) <= self._last_seq:
                return int(seq), True   # shipped retry: already copied
            dedupe_key = (tenant or DEFAULT_TENANT, delta_id)
            if seq is None and delta_id and dedupe_key in self._ids:
                return self._ids[dedupe_key], True
            use_seq = int(seq) if seq is not None else self._last_seq + 1
            entry = {
                "seq": use_seq,
                "op": "delta",
                "id": delta_id or "",
                "payload": payload,
                "deadline_s": deadline_s,
                "t": time.time() if t is None else float(t),
            }
            if trace:
                entry["trace"] = trace
            if tenant and tenant != DEFAULT_TENANT:
                entry["tenant"] = tenant
            written = self._write_locked(entry)
            self._index(entry)
            self._refresh_snap_locked()
        seconds = time.perf_counter() - t0
        self._export()
        if self.sink is not None:
            rows = 0
            if isinstance(payload, dict):
                rows = len(payload.get("insert", ()) or ()) + len(
                    payload.get("delete", ()) or ()
                )
            self.sink.emit(
                "wal_append", seq=use_seq, rows=rows, bytes=written,
                seconds=round(seconds, 6), delta_id=delta_id or "",
            )
        return use_seq, False

    def skip(self, skip_seq: int) -> int:
        """Tombstone a durable-but-shed entry so replay excludes it."""
        with self._lock:
            entry = {
                "seq": self._last_seq + 1,
                "op": "skip",
                "skip_seq": int(skip_seq),
                "t": time.time(),
            }
            self._write_locked(entry)
            self._index(entry)
            self._refresh_snap_locked()
            return entry["seq"]

    def _write_locked(self, entry: dict) -> int:
        self._assert_writable_locked()
        self._open_active()
        seg = self._segments[-1]
        if seg.size > self.segment_max_bytes and seg.last_seq:
            self._new_segment(int(entry["seq"]))
            seg = self._segments[-1]
        payload = json.dumps(entry, separators=(",", ":")).encode()
        frame = (
            _HDR.pack(int(entry["seq"]), len(payload))
            + hashlib.sha256(payload).digest()
            + payload
        )
        try:
            self._active.write(frame)
            self._active.flush()
            os.fsync(self._active.fileno())
        except OSError:
            # The frame may be partially on disk while bookkeeping has
            # not advanced: left alone, the caller's retry of this seq
            # would land AFTER the orphan bytes — two frames under one
            # seq, every later index offset shifted by the orphan, so
            # shipping seeks land mid-frame and restart replay can apply
            # both payloads. Roll the file back to the last frame
            # boundary so disk and bookkeeping agree again; if even that
            # fails, the segment's tail state is unknown — poison the
            # log so every later append refuses loudly instead of
            # acknowledging into a file we can no longer reason about.
            try:
                self._active.truncate(seg.size)
                self._active.flush()
                os.fsync(self._active.fileno())
            except OSError as e2:
                self._failed = (
                    f"append of seq {entry['seq']} failed and the "
                    f"segment could not be rolled back: {e2!r}"
                )
            raise
        seg.index.append((int(entry["seq"]), seg.size))
        seg.size += len(frame)
        if seg.first_seq == 0 or seg.last_seq == 0:
            seg.first_seq = min(seg.first_seq or entry["seq"], entry["seq"])
        seg.last_seq = max(seg.last_seq, int(entry["seq"]))
        return len(frame)

    def _assert_writable_locked(self) -> None:
        if self.read_only:
            raise ValueError(
                f"{self.root}: write-ahead log opened read_only (a "
                "foreign directory — promotions read the deposed "
                "primary's log, they never write it)"
            )
        if self._failed is not None:
            raise WalCorruptionError(
                f"{self.root}: log poisoned by an earlier append "
                f"failure — {self._failed}; restart to re-scan the "
                "segments before accepting new writes"
            )

    # -- the applied watermark / compaction --------------------------------
    def _advance_floor_locked(self) -> bool:
        """Move the contiguous floor up through resolved seqs: published
        entries parked in ``_applied_above`` and non-work seqs
        (tombstones + targets) in ``_meta_above``. Stops at the first
        seq that is neither — an acked entry still racing toward the
        apply queue, whose loss the floor exists to prevent."""
        moved = False
        while True:
            nxt = self._applied_seq + 1
            if nxt in self._applied_above:
                self._applied_above.discard(nxt)
            elif nxt in self._meta_above:
                self._meta_above.discard(nxt)
            else:
                break
            self._applied_seq = nxt
            moved = True
        return moved

    def commit(self, applied_seq: int, snapshot_version: int) -> None:
        """Durably record that every entry up to ``applied_seq`` is
        reflected in published snapshot ``snapshot_version``, then prune
        fully-applied segments past the retention tail. The watermark is
        what replay keys off — compaction is therefore keyed to the
        published snapshot version, never to wall clock.

        This is the ABSOLUTE form (ship mirror, reconcile forward-jump:
        the caller holds an external voucher that everything at or below
        ``applied_seq`` is in the snapshot). The apply worker commits
        through :meth:`commit_applied`, which only advances the floor
        over a contiguous resolved run."""
        with self._lock:
            if int(applied_seq) <= self._applied_seq:
                return
            self._applied_seq = int(applied_seq)
            self._applied_version = int(snapshot_version)
            self._applied_above = {
                s for s in self._applied_above if s > self._applied_seq
            }
            self._meta_above = {
                s for s in self._meta_above if s > self._applied_seq
            }
            self._advance_floor_locked()
            self._history.append((self._applied_seq, self._applied_version))
            del self._history[:-HISTORY_MAX]
            self._write_commit_locked()
            self._compact_locked()
            self._refresh_snap_locked()
        self._export()

    def commit_applied(self, seqs, snapshot_version: int) -> None:
        """Mark published entry seqs resolved and advance the watermark
        over the contiguous resolved prefix — the apply worker's (and
        the reconcile voucher's) commit path. Seqs above an unresolved
        gap persist in the COMMIT file's ``applied_above`` so a crash
        can't replay (double-apply) them, while the floor itself never
        jumps an acked-but-unapplied entry (silent loss on restart —
        the exact hole the WAL closes). The ``(floor, version)``
        history pair is appended only when the floor moves; the
        snapshot at ``snapshot_version`` contains every resolved entry
        by construction (publishes are cumulative)."""
        with self._lock:
            new = {
                int(s) for s in seqs
                if int(s) > self._applied_seq
                and int(s) not in self._applied_above
            }
            if not new:
                return
            self._applied_above |= new
            if self._advance_floor_locked():
                self._applied_version = int(snapshot_version)
                self._history.append(
                    (self._applied_seq, self._applied_version)
                )
                del self._history[:-HISTORY_MAX]
            self._write_commit_locked()
            self._compact_locked()
            self._refresh_snap_locked()
        self._export()

    def preview_commit(self, seqs) -> tuple[int, list[int]]:
        """What :meth:`commit_applied` *would* leave as ``(floor,
        applied_above)`` — computed without mutating, so the apply
        worker can stamp the manifest voucher BEFORE the publish whose
        success the real commit waits on."""
        with self._lock:
            above = set(self._applied_above) | {
                int(s) for s in seqs if int(s) > self._applied_seq
            }
            meta = set(self._meta_above)
            floor = self._applied_seq
            while True:
                nxt = floor + 1
                if nxt in above:
                    above.discard(nxt)
                elif nxt in meta:
                    meta.discard(nxt)
                else:
                    break
                floor = nxt
            return floor, sorted(above)

    def seq_applied(self, seq: int) -> bool:
        """Is this entry's effect in a published snapshot? (At or below
        the contiguous floor, or resolved above a gap.)"""
        with self._lock:
            return int(seq) <= self._applied_seq or (
                int(seq) in self._applied_above
            )

    def _write_commit_locked(self) -> None:
        if self.read_only:
            raise ValueError(
                f"{self.root}: write-ahead log opened read_only (a "
                "foreign directory) — refusing to move its COMMIT "
                "watermark"
            )
        tmp = os.path.join(self.root, COMMIT_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump({
                "applied_seq": self._applied_seq,
                "snapshot_version": self._applied_version,
                "history": self._history,
                "applied_above": sorted(self._applied_above),
                "t": time.time(),
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, COMMIT_NAME))
        _fsync_dir(self.root)

    def note_baseline(self, snapshot_version: int) -> None:
        """Record pair ``(0, version)`` — "this store at ``version``
        contains no WAL entries" — once, when a fresh WAL starts next to
        an already-published store. Only a PRIMARY may write it (a
        standby's store is a bootstrap *copy*; the primary's shipped
        history is what vouches for copies). It is the pair that lets a
        later separate-store promotion replay from seq 0 exactly."""
        with self._lock:
            if self._history or self._applied_seq or self._last_seq:
                return  # not fresh: the baseline claim would be a guess
            self._history = [(0, int(snapshot_version))]
            self._applied_version = int(snapshot_version)
            self._write_commit_locked()
            self._refresh_snap_locked()

    def commit_history(self) -> list[tuple[int, int]]:
        """The retained ``(applied_seq, snapshot_version)`` pairs — the
        ship feed carries them so a standby can map any bootstrap copy's
        version back to a replay cursor."""
        with self._lock:
            return list(self._history)

    def merge_history(self, pairs) -> None:
        """Merge a primary's shipped history pairs (ship path). New seqs
        fill in; an existing seq keeps the local pair. The watermark
        advances to the merged maximum — the same mirror the shipper
        used to do with the latest pair only, now with the full map."""
        with self._lock:
            have = {s for s, _ in self._history}
            added = False
            for s, v in pairs:
                s, v = int(s), int(v)
                if s in have:
                    continue
                self._history.append((s, v))
                have.add(s)
                added = True
            if not added:
                return
            self._history.sort()
            del self._history[:-HISTORY_MAX]
            top_seq, top_version = self._history[-1]
            if top_seq > self._applied_seq:
                self._applied_seq = top_seq
                self._applied_version = top_version
            self._write_commit_locked()
            self._compact_locked()
            self._refresh_snap_locked()
        self._export()

    def _replay_floor_locked(self, snapshot_version: int) -> int | None:
        for s, v in reversed(self._history):
            if v == int(snapshot_version):
                return s
        return None

    def replay_floor(self, snapshot_version: int) -> int | None:
        """The replay cursor vouched for ``snapshot_version``: the
        ``applied_seq`` of the pair recorded AT that exact version
        (entries ≤ it are in the snapshot; entries past it are not).
        ``None`` when no retained pair matches — the caller must treat
        the version as unvouched and say so loudly, never guess a
        cursor (an off-by-one replays a non-idempotent delta twice or
        drops an acknowledged one)."""
        with self._lock:
            return self._replay_floor_locked(snapshot_version)

    def rewind(self, applied_seq: int, snapshot_version: int) -> None:
        """Durably move the watermark BACK to ``(applied_seq,
        snapshot_version)`` — the promotion path after adopting a store
        older than the mirrored watermark. Pairs above the new cursor
        describe the deposed primary's lineage, not this store's: they
        drop, and the local apply worker re-records true local pairs as
        the replayed entries publish."""
        with self._lock:
            if int(applied_seq) >= self._applied_seq:
                return
            self._applied_seq = int(applied_seq)
            self._applied_version = int(snapshot_version)
            self._history = [
                (s, v) for s, v in self._history if s < self._applied_seq
            ]
            self._history.append((self._applied_seq, self._applied_version))
            # applied_above pairs above the new cursor describe the
            # deposed lineage's store too — they must replay here.
            # Tombstones (_meta_above) stay: they are log facts, shipped
            # verbatim, true in every copy.
            self._applied_above = {
                s for s in self._applied_above if s <= self._applied_seq
            }
            self._write_commit_locked()
            self._refresh_snap_locked()
        self._export()

    def oldest_retained_seq(self) -> int | None:
        """The smallest seq still readable (compaction prunes below the
        watermark) — ``None`` for an entry-less log. A promotion rewind
        below this has a durability hole it must announce."""
        with self._lock:
            firsts = [s.first_seq for s in self._segments if s.last_seq]
            return min(firsts) if firsts else None

    def _compact_locked(self) -> None:
        floor = self._applied_seq
        if self.protect_version is not None:
            # Standby: the mirrored watermark vouches for the PRIMARY's
            # store. Never prune past what OUR store version has
            # absorbed — a separate-store promotion rewinds there and
            # replays everything above it. No vouching pair retained =
            # protect everything (an unvouched prune is silent acked
            # loss; unbounded growth is the honest price until the
            # bootstrap is refreshed).
            pf = self._replay_floor_locked(self.protect_version)
            floor = 0 if pf is None else min(floor, pf)
        applied = [
            s for s in self._segments
            if s.last_seq and s.last_seq <= floor
        ]
        # never prune the active (last) segment, and keep the newest
        # retain_segments fully-applied ones as the dedupe horizon
        prunable = [s for s in applied if s is not self._segments[-1]]
        for seg in prunable[: max(0, len(prunable) - self.retain_segments)]:
            try:
                os.remove(seg.path)
            except OSError:
                pass  # already gone; the bookkeeping below still drops
                # it — keeping a fileless segment would make
                # oldest_retained_seq() vouch for entries that cannot
                # be read back, silencing the promotion-rewind loss
                # warning that horizon exists to trigger
            self._segments.remove(seg)

    # -- reads -------------------------------------------------------------
    def entries(self, from_seq: int = 0, limit: int = 0) -> list[dict]:
        """Intact entries with ``seq >= from_seq`` in order (both ops —
        the ship path copies tombstones too). ``limit`` bounds one
        response (0 = all retained). The per-segment offset index turns
        a tail read (every shipping poll) into a seek — without it each
        poll re-checksums the whole active segment from byte zero."""
        out: list[dict] = []
        with self._lock:
            plan = []
            for seg in self._segments:
                if seg.last_seq and seg.last_seq < from_seq:
                    continue
                start = len(_MAGIC)
                if seg.index:
                    i = bisect.bisect_left(seg.index, (int(from_seq), -1))
                    if i >= len(seg.index):
                        continue  # every indexed record is below from_seq
                    start = seg.index[i][1]
                plan.append((seg.path, start))
        for path, start in plan:
            for entry in self._read_segment(path, start):
                if int(entry["seq"]) < from_seq:
                    continue
                out.append(entry)
                if limit and len(out) >= limit:
                    return out
        return out

    def _read_segment(self, path: str, start: int | None = None):
        """Yield intact frames from ``start`` (a frame boundary from the
        offset index; ``None`` = first record). Seeks — a tail read must
        not re-read the whole segment from disk on every shipping
        poll."""
        offset = len(_MAGIC) if start is None else int(start)
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                blob = f.read()
        except OSError:
            return
        # a tear here is a racing append (or the torn tail open-time
        # recovery will classify): stop at it, never past it
        frames, _, _ = _parse_frames(blob)
        for _, entry, _ in frames:
            yield entry

    def pending(self) -> list[dict]:
        """Accepted-but-unapplied delta entries (seq above the applied
        watermark, minus tombstoned seqs) — the startup-replay work
        list."""
        with self._lock:
            applied = self._applied_seq
            resolved = set(self._skipped) | set(self._applied_above)
        return [
            e for e in self.entries(applied + 1)
            if e.get("op") == "delta" and int(e["seq"]) not in resolved
        ]

    def copy_from(self, entries) -> int:
        """Append foreign entries VERBATIM (same seq, same id) — the
        log-shipping copy path shared by the standby's shipper and the
        promotion's final tail catch-up. Already-held seqs are skipped
        (idempotent retries); returns how many were newly written."""
        copied = 0
        for entry in entries:
            if entry.get("op") == "skip":
                with self._lock:
                    if int(entry["seq"]) > self._last_seq:
                        self._write_locked(entry)
                        self._index(entry)
                        self._refresh_snap_locked()
                        copied += 1
                continue
            _, dup = self.append(
                entry.get("payload", {}),
                delta_id=entry.get("id", ""),
                deadline_s=entry.get("deadline_s"),
                seq=int(entry["seq"]),
                t=entry.get("t"),
                trace=entry.get("trace", ""),
                tenant=entry.get("tenant") or DEFAULT_TENANT,
            )
            if not dup:
                copied += 1
        return copied

    def lookup(
        self, delta_id: str, tenant: str = DEFAULT_TENANT,
    ) -> int | None:
        with self._lock:
            return self._ids.get((tenant or DEFAULT_TENANT, delta_id))

    # The seq properties and snapshot() are deliberately LOCK-FREE:
    # append() holds the log's lock across its fsyncs, and /healthz (the
    # fleet prober's verdict) reads these — taking the lock here would
    # couple probe latency to write-path disk stalls, and a >timeout
    # fsync stall would mark a live, merely-slow writer DOWN and fire a
    # promotion against a healthy primary. Ints are rebound atomically
    # under the GIL; the stats dict is rebuilt under the lock by every
    # mutator and swapped in with one reference assignment.
    @property
    def last_seq(self) -> int:
        return self._last_seq

    @property
    def applied_seq(self) -> int:
        return self._applied_seq

    @property
    def applied_version(self) -> int:
        return self._applied_version

    def _refresh_snap_locked(self) -> None:
        """Rebuild the cached stats dict (callers hold the lock).
        ``pending_entries`` counts acked-but-unpublished work: the
        above-floor span minus seqs resolved ABOVE the floor (published
        over a gap, tombstones + their targets). The all-time
        ``_skipped`` set must not be subtracted — seqs the floor already
        passed would be double-counted and the gauge would read 0 while
        a durable acknowledged delta still awaits apply."""
        floor = self._applied_seq
        resolved_above = sum(1 for s in self._applied_above if s > floor)
        resolved_above += sum(1 for s in self._meta_above if s > floor)
        self._snap = {
            "last_seq": self._last_seq,
            "applied_seq": floor,
            "applied_version": self._applied_version,
            "pending_entries": max(
                0, self._last_seq - floor - resolved_above
            ),
            "segments": len(self._segments),
            "segment_bytes": sum(s.size for s in self._segments),
        }

    def snapshot(self) -> dict:
        return dict(self._snap)

    def _export(self) -> None:
        reg = self.registry
        if reg is None:
            return
        snap = self.snapshot()
        # A shard-owned WAL exports labeled children; the single-writer
        # log keeps the exact pre-shard unlabeled series.
        lab = {} if self.shard is None else {"shard": str(self.shard)}
        reg.gauge(
            "graphmine_serve_wal_last_seq",
            "highest sequence number appended to the write-ahead log",
            **lab,
        ).set(snap["last_seq"])
        reg.gauge(
            "graphmine_serve_wal_applied_seq",
            "WAL watermark: entries at or below this seq are published",
            **lab,
        ).set(snap["applied_seq"])
        reg.gauge(
            "graphmine_serve_wal_pending_entries",
            "WAL entries accepted but not yet in a published snapshot",
            **lab,
        ).set(snap["pending_entries"])
        # memory plane (ISSUE 14): retained-segment bytes on the same
        # scrape as the seq gauges — the WAL's share of the serve
        # process's /statusz memory section (name/help owned by
        # obs/memmodel.MEMORY_GAUGE_HELP like every memory gauge)
        from graphmine_tpu.obs.memmodel import export_memory_gauges

        export_memory_gauges(
            reg, {"wal_segment_bytes": snap["segment_bytes"]}
        )

    def close(self) -> None:
        with self._lock:
            if self._active is not None:
                self._active.close()
                self._active = None


class LogShipper:
    """Standby-side WAL tailer: keeps a verbatim durable copy of the
    primary's log within a bounded, observable replication lag.

    Polls ``GET {primary_url}/wal?from=<local last_seq + 1>`` on a
    cadence, appends fetched entries into the standby's own
    :class:`WriteAheadLog` (same seq, same id — one sequence space
    across the pair), and merges the primary's watermark history so the
    shared-store promotion never replays work the primary already
    published — while a separate-store promotion can still map its own
    adopted version to the exact replay cursor. Lag is
    exported two ways: entries behind (``primary last_seq - local
    last_seq``) and seconds behind (age of the oldest entry not yet
    shipped), as ``ship_lag`` records (rate-limited) and registry
    gauges; ``/healthz`` on a standby server surfaces both.

    ``chaos_delay_s`` is the :func:`~graphmine_tpu.testing.faults.ship_lag`
    injector's seam — an extra sleep before each poll, the deterministic
    stand-in for a slow replication link. Production value is 0.0.
    """

    def __init__(
        self,
        wal: WriteAheadLog,
        primary_url: str,
        poll_interval_s: float = 0.2,
        timeout_s: float = 5.0,
        batch_limit: int = 512,
        sink=None,
        registry=None,
        shard: int | None = None,
    ):
        self.wal = wal
        # Per-range shipping lane (r17): labels the lag gauges so one
        # range's replication stall never hides inside a plane average.
        self.shard = None if shard is None else int(shard)
        self.primary_url = primary_url.rstrip("/")
        self.poll_interval_s = float(poll_interval_s)
        self.timeout_s = float(timeout_s)
        self.batch_limit = int(batch_limit)
        self.sink = sink
        self.registry = registry
        self.chaos_delay_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._primary_last_seq = 0
        self._primary_epoch = 0
        self._behind_since: float | None = None
        self._polls = 0
        self._errors = 0
        self._last_error = ""
        self._last_emit = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="graphmine-wal-shipper", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            delay = self.chaos_delay_s
            if delay > 0:
                self._stop.wait(delay)  # ship_lag injector
                if self._stop.is_set():
                    return
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the shipper must not die
                with self._lock:
                    self._errors += 1
                    self._last_error = repr(e)
            self._stop.wait(self.poll_interval_s)

    # -- one poll ----------------------------------------------------------
    def poll_once(self) -> dict:
        """One catch-up pass (public so tests and the promotion path can
        drive it deterministically): fetch from the primary, append the
        batch, mirror the watermark, refresh the lag verdict. Returns
        the shipped summary; raises on transport failure (the loop
        counts it; promotion treats an unreachable primary as 'ship what
        we have')."""
        from_seq = self.wal.last_seq + 1
        url = f"{self.primary_url}/wal?from={from_seq}&limit={self.batch_limit}"
        with urlrequest.urlopen(url, timeout=self.timeout_s) as resp:
            body = json.loads(resp.read().decode())
        # tombstones ship verbatim too, so the standby's replay
        # exclusion matches the primary's
        shipped = self.wal.copy_from(body.get("entries", ()))
        hist = body.get("history")
        if hist:
            # the full (seq, version) map, so a separate-store promotion
            # can place its adopted bootstrap version on the log exactly
            self.wal.merge_history(hist)
        else:  # pre-history primary: mirror the latest pair as before
            applied = int(body.get("applied_seq", 0))
            if applied > self.wal.applied_seq:
                self.wal.commit(applied, int(body.get("applied_version", 0)))
        now = time.monotonic()
        with self._lock:
            self._polls += 1
            self._primary_last_seq = int(
                body.get("last_seq", self._primary_last_seq)
            )
            self._primary_epoch = int(body.get("epoch", self._primary_epoch))
            behind = self._primary_last_seq - self.wal.last_seq
            if behind > 0:
                if self._behind_since is None:
                    self._behind_since = now
            else:
                self._behind_since = None
        snap = self.snapshot()
        self._export(snap)
        if snap["lag_entries"] > 0 and self.sink is not None:
            if now - self._last_emit >= 1.0:  # rate-limit the record spam
                self._last_emit = now
                self.sink.emit(
                    "ship_lag",
                    lag_entries=snap["lag_entries"],
                    lag_s=snap["lag_s"],
                    primary_last_seq=snap["primary_last_seq"],
                    shipped_seq=snap["shipped_seq"],
                )
        return {"shipped": shipped, **snap}

    def snapshot(self) -> dict:
        local = self.wal.last_seq
        with self._lock:
            behind = max(0, self._primary_last_seq - local)
            lag_s = (
                round(time.monotonic() - self._behind_since, 3)
                if self._behind_since is not None else 0.0
            )
            return {
                "primary_last_seq": self._primary_last_seq,
                "primary_epoch": self._primary_epoch,
                "shipped_seq": local,
                "lag_entries": behind,
                "lag_s": lag_s,
                "polls": self._polls,
                "errors": self._errors,
                "last_error": self._last_error,
            }

    def _export(self, snap: dict) -> None:
        reg = self.registry
        if reg is None:
            return
        lab = {} if self.shard is None else {"shard": str(self.shard)}
        reg.gauge(
            "graphmine_serve_replication_lag_entries",
            "WAL entries the standby has not yet shipped from the primary",
            **lab,
        ).set(snap["lag_entries"])
        reg.gauge(
            "graphmine_serve_replication_lag_seconds",
            "how long the standby has been behind the primary's WAL",
            **lab,
        ).set(snap["lag_s"])
