"""Versioned result snapshots — the serving layer's durable artifact.

A *snapshot* is the published output of one pipeline run or one delta
repair: community labels, CC labels, LOF scores, the community census,
and the edge arrays the query engine needs for neighbor lookups, plus
provenance (run_id, parent snapshot, graph fingerprint, mesh shape).

The on-disk format is the checkpoint manifest pattern
(``pipeline/checkpoint.py``) applied to pipeline outputs: per-array
``.npy`` files + a JSON manifest with per-file sha256 and a whole-manifest
checksum, written into a tmp generation directory (every file fsync'd,
manifest last) and published by ONE directory rename after rotating the
previous generation to ``*.prev`` — a kill at any point leaves the old or
the new snapshot fully intact, never a torn mix. Loads verify every hash,
roll back to ``.prev`` on corruption (condemned generation preserved at
``*.corrupt``), and refuse a wrong graph fingerprint WITHOUT rollback
(every generation of that store indexes the same wrong graph). The
rollback state machine is literally shared with the checkpoint formats
(:func:`~graphmine_tpu.pipeline.checkpoint._load_with_rollback`).

Versioning: each publish increments a monotonic ``version`` counter and
records its parent's ``snapshot_id`` — the provenance chain a delta
repair extends (docs/SERVING.md "snapshot format").

**Writer-epoch fencing** (docs/SERVING.md "Replicated writers"): every
manifest carries a monotonic ``writer_epoch``, and a publish whose
epoch is *below* the store's current epoch (max of the newest manifest
and the durable ``EPOCH`` fence file a promotion writes) is refused
loudly with :class:`PublishFencedError` plus a ``publish_fenced``
record — a deposed writer returning from a partition can never clobber
the promoted standby's publishes, because the refusal happens AT the
store, not by router convention. Epoch-less publishes (``epoch=None``,
every pre-r11 caller) inherit the current epoch unchanged, so
single-writer deployments never trip the fence.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import time
from dataclasses import dataclass

try:
    import fcntl
except ImportError:  # non-POSIX: single-process stores only
    fcntl = None

import numpy as np

from graphmine_tpu.pipeline import resilience
from graphmine_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    TENANT_RE,
    validate_tenant_id,
)
from graphmine_tpu.pipeline.checkpoint import (
    CheckpointCorruptionError,
    FingerprintMismatch,
    _CORRUPTION_ERRORS,
    _file_sha256,
    _fsync_dir,
    _fsync_file,
    _load_with_rollback,
    _manifest_checksum,
    _tree_bytes,
)

MANIFEST_NAME = "manifest.json"
EPOCH_NAME = "EPOCH"
TENANTS_DIRNAME = "tenants"
# Sharded-write-plane publish epochs (r17, serve/shardplane.py): staged
# per-range generations and their durable commit records live under
# <root>/epochs — beside the snapshot chain, namespaced per tenant like
# everything else under the root.
EPOCHS_DIRNAME = "epochs"
_FORMAT_VERSION = 1


class PublishFencedError(RuntimeError):
    """A publish carried a writer epoch below the store's current epoch:
    the publisher was deposed (a standby was promoted past it) and its
    work must not reach readers. Not a retryable condition — the honest
    recovery is rejoining as a replica/standby of the new writer."""
# Array names become file names; keep them boring so a hostile/typo'd
# name can never escape the generation directory.
_NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.-]*$")

# The standard array set the driver publishes and the query engine reads.
# publish() accepts any dict (the format is name-agnostic); these names
# are the serving contract documented in docs/SERVING.md.
STANDARD_ARRAYS = (
    "src", "dst", "labels", "cc_labels", "lof",
    "census_present", "census_sizes", "census_edges",
)


@dataclass(frozen=True)
class Snapshot:
    """One loaded snapshot generation: arrays + manifest metadata."""

    arrays: dict                # name -> np.ndarray
    meta: dict                  # manifest body minus per-file hashes
    path: str = ""              # generation dir it was loaded from

    @property
    def version(self) -> int:
        return int(self.meta["version"])

    @property
    def snapshot_id(self) -> str:
        return self.meta["snapshot_id"]

    @property
    def nbytes(self) -> int:
        """Total array payload bytes — the serve memory plane's snapshot
        accounting (ISSUE 14, ``graphmine_memory_snapshot_bytes``)."""
        return int(sum(int(a.nbytes) for a in self.arrays.values()))

    @property
    def parent(self) -> str:
        return self.meta.get("parent", "")

    @property
    def fingerprint(self) -> str:
        return self.meta.get("fingerprint", "")

    @property
    def num_vertices(self) -> int:
        return int(self.meta.get("num_vertices", 0))

    @property
    def num_edges(self) -> int:
        return int(self.meta.get("num_edges", 0))

    @property
    def writer_epoch(self) -> int:
        return int(self.meta.get("writer_epoch", 0))

    def __getitem__(self, name: str) -> np.ndarray:
        return self.arrays[name]

    def get(self, name: str, default=None):
        return self.arrays.get(name, default)


class SnapshotStore:
    """Two-generation versioned snapshot store rooted at one directory.

    ``publish`` is safe against kills at any point (see module docstring);
    ``load`` returns the newest intact generation. One publisher per root
    is the concurrency contract (same as the checkpoint generation
    rotation); any number of concurrent readers may load.

    **Tenant namespace** (ISSUE 16): a store optionally belongs to one
    tenant. The default tenant lives at the bare ``root`` — byte-for-byte
    the pre-tenancy layout, so every existing deployment IS a default-
    tenant store — while tenant ``t`` lives at ``<root>/tenants/<t>/``
    with its own version chain, ``.prev`` rotation, ``EPOCH`` fence,
    fence lock, canary arrays and ``lof_centers``: complete blast-radius
    isolation at the filesystem layer (one tenant's corrupt generation
    rolls back alone; one tenant's fence fences only its own writer).
    Tenant ids are validated before any path is built.
    """

    def __init__(self, root: str, tenant: str = DEFAULT_TENANT):
        self.base_root = root
        self.tenant = validate_tenant_id(tenant)
        if self.tenant == DEFAULT_TENANT:
            self.root = root
        else:
            self.root = os.path.join(root, TENANTS_DIRNAME, self.tenant)

    # -- tenancy -----------------------------------------------------------
    def for_tenant(self, tenant: str) -> SnapshotStore:
        """The sibling store for ``tenant`` under the same base root
        (``self`` when already that tenant's store). Hostile ids raise
        ``ValueError`` here, before any filesystem path exists."""
        tenant = validate_tenant_id(tenant)
        if tenant == self.tenant:
            return self
        return SnapshotStore(self.base_root, tenant=tenant)

    def list_tenants(self) -> list[str]:
        """Every tenant with a store directory under this base root:
        the default tenant whenever the bare root has published (or is
        an empty-but-created store), plus each valid id under
        ``tenants/``. Non-conforming directory names are ignored rather
        than surfaced — they cannot have been created through this API."""
        out = []
        base = SnapshotStore(self.base_root)
        if base._peek_manifest() is not None:
            out.append(DEFAULT_TENANT)
        tdir = os.path.join(self.base_root, TENANTS_DIRNAME)
        try:
            names = sorted(os.listdir(tdir))
        except OSError:
            names = []
        for name in names:
            if TENANT_RE.fullmatch(name) and os.path.isdir(
                os.path.join(tdir, name)
            ):
                out.append(name)
        return out

    # -- paths ------------------------------------------------------------
    def _gen(self) -> str:
        return os.path.join(self.root, "snapshot")

    def _prev(self) -> str:
        return self._gen() + ".prev"

    # -- writer epoch ------------------------------------------------------
    @contextlib.contextmanager
    def _fence_lock(self):
        """Inter-process exclusive lock serializing the fence write
        against the publish commit boundary. Without it the re-check at
        the commit rename is a TOCTOU: a promotion (fence bump + first
        publish) can land between a deposed writer's epoch read and its
        generation rotation, and the deposed writer then evicts the
        promoted writer's snapshot — the exact clobber the fence
        declares impossible. ``flock`` releases on process death, so a
        killed holder can never wedge the store."""
        os.makedirs(self.root, exist_ok=True)
        if fcntl is None:
            yield
            return
        fd = os.open(
            os.path.join(self.root, ".fence.lock"),
            os.O_CREAT | os.O_RDWR, 0o644,
        )
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def fence_lock(self):
        """The store's inter-process fence lock as a public context
        manager — the serialization point the sharded write plane's
        epoch coordinator commits under (r17): epoch minting, per-range
        promotion fencing and the two-phase publish commit all take THIS
        lock, so a deposed coordinator and a promotion can never
        interleave their commit records."""
        return self._fence_lock()

    def _fence_file_epoch(self) -> int:
        try:
            with open(os.path.join(self.root, EPOCH_NAME)) as f:
                return int(json.load(f).get("epoch", 0))
        except (OSError, ValueError):
            return 0

    def current_epoch(self) -> int:
        """The store's writer epoch: max of the newest manifest's
        ``writer_epoch`` and the durable fence file (a promotion bumps
        the fence first, so the deposed writer is fenced before the new
        writer's first publish exists)."""
        peek = self._peek_manifest()
        manifest_epoch = int(peek.get("writer_epoch", 0)) if peek else 0
        return max(manifest_epoch, self._fence_file_epoch())

    def fence_epoch(self, epoch: int, sink=None, reason: str = "") -> int:
        """Durably raise the store's writer epoch (atomic write + fsync
        of the ``EPOCH`` fence file). From the moment this returns, any
        publish carrying a lower epoch refuses with
        :class:`PublishFencedError` — the promotion's first act, before
        the standby replays a single WAL entry. Lowering is refused
        (an epoch that can move backwards fences nothing)."""
        epoch = int(epoch)
        with self._fence_lock():
            cur = self.current_epoch()
            if epoch < cur:
                raise ValueError(
                    f"fence_epoch({epoch}) below the store's current epoch "
                    f"{cur}: epochs are monotonic"
                )
            self._write_fence_locked(epoch, reason)
        if sink is not None:
            sink.emit(
                "writer_promote", epoch=epoch, store=self.root,
                reason=reason or "epoch fence raised",
            )
        return epoch

    def _write_fence_locked(self, epoch: int, reason: str) -> None:
        tmp = os.path.join(self.root, EPOCH_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(
                {"epoch": epoch, "t": time.time(), "reason": reason}, f
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, EPOCH_NAME))
        _fsync_dir(self.root)

    def advance_epoch(self, sink=None, reason: str = "") -> int:
        """Atomically mint-and-fence the NEXT writer epoch: read the
        current epoch and durably raise it by one under the fence lock,
        returning the new epoch this caller now exclusively owns.
        ``fence_epoch(current_epoch() + 1)`` composed by the caller is
        NOT equivalent — two concurrent promotions would read the same
        current epoch and both fence the same value (fence_epoch
        accepts an equal epoch as an idempotent re-assert), leaving two
        writers that both pass the fence: the split-brain the epoch
        exists to make impossible. Every promotion allocates here."""
        with self._fence_lock():
            epoch = self.current_epoch() + 1
            self._write_fence_locked(epoch, reason)
        if sink is not None:
            sink.emit(
                "writer_promote", epoch=epoch, store=self.root,
                reason=reason or "epoch fence advanced",
            )
        return epoch

    def _check_fence(self, epoch: int | None, sink) -> int:
        """Resolve the publish epoch against the fence; raises
        :class:`PublishFencedError` (with its loud ``publish_fenced``
        record) for a deposed writer. ``None`` inherits — legacy
        single-writer callers never trip this."""
        cur = self.current_epoch()
        if epoch is None:
            return cur
        epoch = int(epoch)
        if epoch < cur:
            if sink is not None:
                sink.emit(
                    "publish_fenced", attempted_epoch=epoch,
                    store_epoch=cur, store=self.root,
                    reason=(
                        f"publish at writer epoch {epoch} refused: the "
                        f"store was fenced at epoch {cur} (a standby was "
                        "promoted past this writer)"
                    ),
                )
            raise PublishFencedError(
                f"publish refused: writer epoch {epoch} is behind the "
                f"store's epoch {cur} at {self.root!r} — this writer was "
                "deposed; rejoin as a replica of the promoted writer "
                "instead of republishing"
            )
        return epoch

    # -- publish ----------------------------------------------------------
    def publish(
        self,
        arrays: dict,
        fingerprint: str = "",
        run_id: str = "",
        mesh_shape=None,
        extra_meta: dict | None = None,
        sink=None,
        epoch: int | None = None,
    ) -> Snapshot:
        """Durably publish one snapshot generation; returns it as loaded.

        ``epoch``: the publisher's writer epoch (replicated-writer
        deployments). ``None`` (every single-writer caller) inherits the
        store's current epoch; an epoch below the store's refuses with
        :class:`PublishFencedError` + a ``publish_fenced`` record — the
        fence is checked on entry (cheap refusal before any bytes are
        written) and again at the commit rename (a promotion racing a
        slow publish still fences it).

        ``fingerprint`` ties the snapshot to the exact edge arrays /
        id assignment (``checkpoint.graph_fingerprint``); loads under a
        different graph refuse. Version/parent chain continues from the
        current generation (version 1 when the store is empty). ``sink``:
        emits a ``snapshot_publish`` record (span-stamped, rendered by
        ``tools/obs_report.py``).

        The returned :class:`Snapshot` ALIASES the caller's arrays (no
        defensive copy of potentially-GB columns): snapshots are
        immutable by contract, so a publisher that keeps mutable working
        state must copy-on-write before changing it (the delta
        ingestor's LOF splice does) — a live ``QueryEngine`` built on
        the returned snapshot reads these same buffers.
        """
        t0 = time.perf_counter()
        for name, arr in arrays.items():
            if not _NAME_RE.match(name):
                raise ValueError(f"unsafe snapshot array name {name!r}")
            if not isinstance(arr, np.ndarray):
                raise TypeError(
                    f"snapshot arrays must be host numpy (got "
                    f"{type(arr).__name__} for {name!r}); np.asarray() first"
                )
        epoch = self._check_fence(epoch, sink)
        parent_version, parent_id = 0, ""
        peek = self._peek_manifest()
        if peek is not None:
            parent_version = int(peek.get("version", 0))
            parent_id = peek.get("snapshot_id", "")
        version = parent_version + 1
        snapshot_id = f"{version:06d}-{os.urandom(4).hex()}"

        os.makedirs(self.root, exist_ok=True)
        gen = self._gen()
        tmp = f"{gen}.tmp.{os.getpid()}"
        # Sweep EVERY stale tmp generation (same rationale as
        # checkpoint.save_sharded): each kill mid-publish leaves one
        # behind, and restarted publishers never reuse the old pid.
        import glob as _glob
        import shutil

        for stale in _glob.glob(gen + ".tmp.*"):
            shutil.rmtree(stale, ignore_errors=True)
        os.makedirs(tmp)

        entries = {}
        for name, arr in arrays.items():
            fname = f"{name}.npy"
            path = os.path.join(tmp, fname)
            np.save(path, arr)
            _fsync_file(path)
            entries[name] = {
                "file": fname,
                "sha256": _file_sha256(path),
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }

        body = {
            "format_version": _FORMAT_VERSION,
            "version": version,
            "snapshot_id": snapshot_id,
            "parent": parent_id,
            "run_id": run_id or "",
            "fingerprint": fingerprint or "",
            "writer_epoch": int(epoch),
            "mesh_shape": list(mesh_shape) if mesh_shape else [1],
            "created": time.time(),
            "arrays": entries,
        }
        if extra_meta:
            overlap = set(extra_meta) & set(body)
            if overlap:
                raise ValueError(
                    f"extra_meta may not shadow manifest keys {sorted(overlap)}"
                )
            body.update(extra_meta)
        body["checksum"] = _manifest_checksum(body)
        man_tmp = os.path.join(tmp, MANIFEST_NAME + ".tmp")
        with open(man_tmp, "w") as f:
            json.dump(body, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(man_tmp, os.path.join(tmp, MANIFEST_NAME))
        _fsync_dir(tmp)

        # Torn-publish seam: a fault/preemption injected HERE (every file
        # written, nothing published) must leave the previous generation
        # the loadable one — pinned by tests/test_serve.py.
        resilience.fault_point(
            "snapshot_publish_commit", version=version, tmp=tmp
        )

        # Re-check the fence at the commit boundary: a promotion that
        # landed while this publish was writing its (possibly large)
        # arrays must still fence it — the deposed writer's work dies in
        # the tmp directory, never in the published slot. The check and
        # the rotation+rename hold the fence lock together: a
        # fence_epoch cannot slip between them, so a fenced writer can
        # never evict the promoted writer's generation (atomic with the
        # fence, not merely checked near it).
        with self._fence_lock():
            cur = self.current_epoch()
            if int(epoch) < cur:
                shutil.rmtree(tmp, ignore_errors=True)
                if sink is not None:
                    sink.emit(
                        "publish_fenced", attempted_epoch=int(epoch),
                        store_epoch=cur, store=self.root,
                        reason=(
                            f"publish at writer epoch {epoch} fenced at the "
                            f"commit rename: the store moved to epoch {cur} "
                            "mid-publish (standby promoted during the write)"
                        ),
                    )
                raise PublishFencedError(
                    f"publish refused at commit: writer epoch {epoch} is "
                    f"behind the store's epoch {cur} at {self.root!r} — a "
                    "standby was promoted while this publish was in flight"
                )

            prev = self._prev()
            if os.path.exists(gen):
                if self._peek_dir(gen) is None:
                    # The current generation's manifest is unreadable:
                    # rotating it into .prev would EVICT the only intact
                    # snapshot and install garbage as the rollback target
                    # (a kill before the final rename would then lose every
                    # loadable generation). Condemn it aside instead — the
                    # same *.corrupt convention as the loader's rollback.
                    condemned = gen + ".corrupt"
                    n = 0
                    while os.path.exists(condemned):
                        n += 1
                        condemned = f"{gen}.corrupt.{n}"
                    os.replace(gen, condemned)
                else:
                    if os.path.exists(prev):
                        shutil.rmtree(prev)
                    os.replace(gen, prev)
            os.replace(tmp, gen)
            _fsync_dir(self.root)
        if sink is not None:
            sink.emit(
                "snapshot_publish",
                version=version,
                snapshot_id=snapshot_id,
                parent=parent_id,
                path=gen,
                bytes=_tree_bytes(gen),
                arrays=sorted(arrays),
                seconds=round(time.perf_counter() - t0, 4),
            )
        meta = {k: v for k, v in body.items() if k not in ("arrays", "checksum")}
        return Snapshot(arrays=dict(arrays), meta=meta, path=gen)

    # -- load -------------------------------------------------------------
    @staticmethod
    def _peek_dir(gen_dir: str) -> dict | None:
        """Cheap one-directory manifest read (JSON + manifest checksum,
        no array hashing); None = absent/unparseable/checksum-damaged.
        Applies the loader's manifest-level corruption verdict so the
        publish rotation never treats a bit-damaged-but-parseable
        manifest as an intact generation, and stats every listed array
        file (existence + non-empty, no hashing — damage overwhelmingly
        lands in the GB-scale arrays, not the KB manifest) so a
        generation missing its arrays is never rotated over an intact
        ``.prev``."""
        try:
            with open(os.path.join(gen_dir, MANIFEST_NAME)) as f:
                body = json.load(f)
        except Exception:
            return None
        if body.get("checksum", "") != _manifest_checksum(body):
            return None
        for ent in body.get("arrays", {}).values():
            try:
                if os.path.getsize(os.path.join(gen_dir, ent["file"])) <= 0:
                    return None
            except (OSError, KeyError, TypeError):
                return None
        return body

    def _peek_manifest(self) -> dict | None:
        """Cheap manifest read for the version/parent chain: the current
        generation, falling back to ``.prev`` when the current one is
        missing/unreadable — a kill in the window between the two
        publish renames leaves only ``.prev`` intact, and the chain must
        continue from it, never reset to version 1. None = neither
        generation readable."""
        for gen in (self._gen(), self._prev()):
            peek = self._peek_dir(gen)
            if peek is not None:
                return peek
        return None

    def peek_version(self) -> int | None:
        peek = self._peek_manifest()
        if peek is None:
            return None
        try:
            return int(peek["version"])
        except (KeyError, TypeError, ValueError):
            return None

    def peek_arrays(self, names) -> tuple[dict, dict] | None:
        """Load ONLY the named arrays (plus the manifest meta) from the
        newest intact generation, without per-array hash verification —
        the cheap parent read the publish-time quality pass
        (``obs/quality.py``) uses for snapshot-over-parent drift when the
        parent is not already in memory. Advisory-telemetry contract:
        full verification stays with :meth:`load`; any read failure here
        returns None (drift is then simply skipped) instead of raising
        into a publish. Returns ``({name: array}, meta)`` with absent
        names simply missing from the dict."""
        for gen in (self._gen(), self._prev()):
            body = self._peek_dir(gen)
            if body is None:
                continue
            out = {}
            try:
                for name in names:
                    ent = body.get("arrays", {}).get(name)
                    if ent is None:
                        continue
                    out[name] = np.load(os.path.join(gen, ent["file"]))
            except Exception:  # noqa: BLE001 — advisory read, never raise
                continue
            meta = {
                k: v for k, v in body.items()
                if k not in ("arrays", "checksum")
            }
            return out, meta
        return None

    def _read_verified(self, gen_dir: str, fingerprint: str | None):
        """Load one generation, verifying manifest checksum, every
        array's sha256/dtype/shape, then the graph fingerprint. Raises a
        :data:`_CORRUPTION_ERRORS` member on damaged bytes,
        :class:`FingerprintMismatch` on a wrong-graph snapshot."""
        man_path = os.path.join(gen_dir, MANIFEST_NAME)
        try:
            with open(man_path) as f:
                body = json.load(f)
        except json.JSONDecodeError as e:
            raise CheckpointCorruptionError(
                f"snapshot manifest at {man_path} is not valid JSON ({e})"
            ) from e
        want = body.get("checksum", "")
        got = _manifest_checksum(body)
        if want != got:
            raise CheckpointCorruptionError(
                f"snapshot manifest at {man_path} failed its checksum "
                f"({got[:12]}... != recorded {want[:12]}...)"
            )
        saved_fp = body.get("fingerprint", "")
        if fingerprint and saved_fp and fingerprint != saved_fp:
            raise FingerprintMismatch(
                f"snapshot at {gen_dir} was published for a different graph "
                f"or vertex-id assignment (fingerprint {saved_fp[:12]}... != "
                f"{fingerprint[:12]}...); republish from the current graph "
                "or query the snapshot it was built from"
            )
        arrays = {}
        for name, ent in body.get("arrays", {}).items():
            path = os.path.join(gen_dir, ent["file"])
            sha = _file_sha256(path)
            if sha != ent["sha256"]:
                raise CheckpointCorruptionError(
                    f"snapshot array {name!r} at {path} failed its sha256 "
                    f"({sha[:12]}... != manifest {ent['sha256'][:12]}...)"
                )
            arr = np.load(path)
            if list(arr.shape) != ent["shape"] or str(arr.dtype) != ent["dtype"]:
                raise CheckpointCorruptionError(
                    f"snapshot array {name!r} at {path} is "
                    f"{arr.dtype}{list(arr.shape)}, manifest says "
                    f"{ent['dtype']}{ent['shape']}"
                )
            arrays[name] = arr
        meta = {k: v for k, v in body.items() if k not in ("arrays", "checksum")}
        snap = Snapshot(arrays=arrays, meta=meta, path=gen_dir)
        # (snapshot, version) so the shared rollback state machine — whose
        # contract is (payload, generation-counter) tuples — applies as-is.
        return snap, snap.version

    def _read_confirmed(self, gen_dir: str, fingerprint: str | None):
        """One confirming re-read before a corruption verdict — the same
        transient-I/O-weather rationale as the checkpoint readers."""
        try:
            return self._read_verified(gen_dir, fingerprint)
        except FingerprintMismatch:
            raise
        except _CORRUPTION_ERRORS as first:
            try:
                return self._read_verified(gen_dir, fingerprint)
            except FingerprintMismatch:
                raise
            except _CORRUPTION_ERRORS:
                raise first

    def load(self, fingerprint: str | None = None, sink=None) -> Snapshot | None:
        """Newest intact snapshot, or None when the store is empty.

        A corrupt current generation rolls back to ``.prev`` (promoted to
        the current slot, the condemned directory preserved at
        ``*.corrupt`` — ``checkpoint_rollback`` records through ``sink``);
        a wrong ``fingerprint`` raises :class:`FingerprintMismatch`
        without rollback. ``sink`` also gets a ``snapshot_load`` record.
        """
        t0 = time.perf_counter()
        out = _load_with_rollback(
            self._gen(), self._prev(),
            lambda p: self._read_confirmed(p, fingerprint),
            sink, "snapshot",
            f"delete {self._gen()!r} (and its .prev) and republish",
        )
        if out is None:
            return None
        snap, version = out
        if sink is not None:
            sink.emit(
                "snapshot_load", version=int(version), path=snap.path,
                snapshot_id=snap.snapshot_id,
                seconds=round(time.perf_counter() - t0, 4),
            )
        return snap
