"""Versioned result snapshots — the serving layer's durable artifact.

A *snapshot* is the published output of one pipeline run or one delta
repair: community labels, CC labels, LOF scores, the community census,
and the edge arrays the query engine needs for neighbor lookups, plus
provenance (run_id, parent snapshot, graph fingerprint, mesh shape).

The on-disk format is the checkpoint manifest pattern
(``pipeline/checkpoint.py``) applied to pipeline outputs: per-array
``.npy`` files + a JSON manifest with per-file sha256 and a whole-manifest
checksum, written into a tmp generation directory (every file fsync'd,
manifest last) and published by ONE directory rename after rotating the
previous generation to ``*.prev`` — a kill at any point leaves the old or
the new snapshot fully intact, never a torn mix. Loads verify every hash,
roll back to ``.prev`` on corruption (condemned generation preserved at
``*.corrupt``), and refuse a wrong graph fingerprint WITHOUT rollback
(every generation of that store indexes the same wrong graph). The
rollback state machine is literally shared with the checkpoint formats
(:func:`~graphmine_tpu.pipeline.checkpoint._load_with_rollback`).

Versioning: each publish increments a monotonic ``version`` counter and
records its parent's ``snapshot_id`` — the provenance chain a delta
repair extends (docs/SERVING.md "snapshot format").
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass

import numpy as np

from graphmine_tpu.pipeline import resilience
from graphmine_tpu.pipeline.checkpoint import (
    CheckpointCorruptionError,
    FingerprintMismatch,
    _CORRUPTION_ERRORS,
    _file_sha256,
    _fsync_dir,
    _fsync_file,
    _load_with_rollback,
    _manifest_checksum,
    _tree_bytes,
)

MANIFEST_NAME = "manifest.json"
_FORMAT_VERSION = 1
# Array names become file names; keep them boring so a hostile/typo'd
# name can never escape the generation directory.
_NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.-]*$")

# The standard array set the driver publishes and the query engine reads.
# publish() accepts any dict (the format is name-agnostic); these names
# are the serving contract documented in docs/SERVING.md.
STANDARD_ARRAYS = (
    "src", "dst", "labels", "cc_labels", "lof",
    "census_present", "census_sizes", "census_edges",
)


@dataclass(frozen=True)
class Snapshot:
    """One loaded snapshot generation: arrays + manifest metadata."""

    arrays: dict                # name -> np.ndarray
    meta: dict                  # manifest body minus per-file hashes
    path: str = ""              # generation dir it was loaded from

    @property
    def version(self) -> int:
        return int(self.meta["version"])

    @property
    def snapshot_id(self) -> str:
        return self.meta["snapshot_id"]

    @property
    def parent(self) -> str:
        return self.meta.get("parent", "")

    @property
    def fingerprint(self) -> str:
        return self.meta.get("fingerprint", "")

    @property
    def num_vertices(self) -> int:
        return int(self.meta.get("num_vertices", 0))

    @property
    def num_edges(self) -> int:
        return int(self.meta.get("num_edges", 0))

    def __getitem__(self, name: str) -> np.ndarray:
        return self.arrays[name]

    def get(self, name: str, default=None):
        return self.arrays.get(name, default)


class SnapshotStore:
    """Two-generation versioned snapshot store rooted at one directory.

    ``publish`` is safe against kills at any point (see module docstring);
    ``load`` returns the newest intact generation. One publisher per root
    is the concurrency contract (same as the checkpoint generation
    rotation); any number of concurrent readers may load.
    """

    def __init__(self, root: str):
        self.root = root

    # -- paths ------------------------------------------------------------
    def _gen(self) -> str:
        return os.path.join(self.root, "snapshot")

    def _prev(self) -> str:
        return self._gen() + ".prev"

    # -- publish ----------------------------------------------------------
    def publish(
        self,
        arrays: dict,
        fingerprint: str = "",
        run_id: str = "",
        mesh_shape=None,
        extra_meta: dict | None = None,
        sink=None,
    ) -> Snapshot:
        """Durably publish one snapshot generation; returns it as loaded.

        ``fingerprint`` ties the snapshot to the exact edge arrays /
        id assignment (``checkpoint.graph_fingerprint``); loads under a
        different graph refuse. Version/parent chain continues from the
        current generation (version 1 when the store is empty). ``sink``:
        emits a ``snapshot_publish`` record (span-stamped, rendered by
        ``tools/obs_report.py``).

        The returned :class:`Snapshot` ALIASES the caller's arrays (no
        defensive copy of potentially-GB columns): snapshots are
        immutable by contract, so a publisher that keeps mutable working
        state must copy-on-write before changing it (the delta
        ingestor's LOF splice does) — a live ``QueryEngine`` built on
        the returned snapshot reads these same buffers.
        """
        t0 = time.perf_counter()
        for name, arr in arrays.items():
            if not _NAME_RE.match(name):
                raise ValueError(f"unsafe snapshot array name {name!r}")
            if not isinstance(arr, np.ndarray):
                raise TypeError(
                    f"snapshot arrays must be host numpy (got "
                    f"{type(arr).__name__} for {name!r}); np.asarray() first"
                )
        parent_version, parent_id = 0, ""
        peek = self._peek_manifest()
        if peek is not None:
            parent_version = int(peek.get("version", 0))
            parent_id = peek.get("snapshot_id", "")
        version = parent_version + 1
        snapshot_id = f"{version:06d}-{os.urandom(4).hex()}"

        os.makedirs(self.root, exist_ok=True)
        gen = self._gen()
        tmp = f"{gen}.tmp.{os.getpid()}"
        # Sweep EVERY stale tmp generation (same rationale as
        # checkpoint.save_sharded): each kill mid-publish leaves one
        # behind, and restarted publishers never reuse the old pid.
        import glob as _glob
        import shutil

        for stale in _glob.glob(gen + ".tmp.*"):
            shutil.rmtree(stale, ignore_errors=True)
        os.makedirs(tmp)

        entries = {}
        for name, arr in arrays.items():
            fname = f"{name}.npy"
            path = os.path.join(tmp, fname)
            np.save(path, arr)
            _fsync_file(path)
            entries[name] = {
                "file": fname,
                "sha256": _file_sha256(path),
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }

        body = {
            "format_version": _FORMAT_VERSION,
            "version": version,
            "snapshot_id": snapshot_id,
            "parent": parent_id,
            "run_id": run_id or "",
            "fingerprint": fingerprint or "",
            "mesh_shape": list(mesh_shape) if mesh_shape else [1],
            "created": time.time(),
            "arrays": entries,
        }
        if extra_meta:
            overlap = set(extra_meta) & set(body)
            if overlap:
                raise ValueError(
                    f"extra_meta may not shadow manifest keys {sorted(overlap)}"
                )
            body.update(extra_meta)
        body["checksum"] = _manifest_checksum(body)
        man_tmp = os.path.join(tmp, MANIFEST_NAME + ".tmp")
        with open(man_tmp, "w") as f:
            json.dump(body, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(man_tmp, os.path.join(tmp, MANIFEST_NAME))
        _fsync_dir(tmp)

        # Torn-publish seam: a fault/preemption injected HERE (every file
        # written, nothing published) must leave the previous generation
        # the loadable one — pinned by tests/test_serve.py.
        resilience.fault_point(
            "snapshot_publish_commit", version=version, tmp=tmp
        )

        prev = self._prev()
        if os.path.exists(gen):
            if self._peek_dir(gen) is None:
                # The current generation's manifest is unreadable:
                # rotating it into .prev would EVICT the only intact
                # snapshot and install garbage as the rollback target
                # (a kill before the final rename would then lose every
                # loadable generation). Condemn it aside instead — the
                # same *.corrupt convention as the loader's rollback.
                condemned = gen + ".corrupt"
                n = 0
                while os.path.exists(condemned):
                    n += 1
                    condemned = f"{gen}.corrupt.{n}"
                os.replace(gen, condemned)
            else:
                if os.path.exists(prev):
                    shutil.rmtree(prev)
                os.replace(gen, prev)
        os.replace(tmp, gen)
        _fsync_dir(self.root)
        if sink is not None:
            sink.emit(
                "snapshot_publish",
                version=version,
                snapshot_id=snapshot_id,
                parent=parent_id,
                path=gen,
                bytes=_tree_bytes(gen),
                arrays=sorted(arrays),
                seconds=round(time.perf_counter() - t0, 4),
            )
        meta = {k: v for k, v in body.items() if k not in ("arrays", "checksum")}
        return Snapshot(arrays=dict(arrays), meta=meta, path=gen)

    # -- load -------------------------------------------------------------
    @staticmethod
    def _peek_dir(gen_dir: str) -> dict | None:
        """Cheap one-directory manifest read (JSON + manifest checksum,
        no array hashing); None = absent/unparseable/checksum-damaged.
        Applies the loader's manifest-level corruption verdict so the
        publish rotation never treats a bit-damaged-but-parseable
        manifest as an intact generation, and stats every listed array
        file (existence + non-empty, no hashing — damage overwhelmingly
        lands in the GB-scale arrays, not the KB manifest) so a
        generation missing its arrays is never rotated over an intact
        ``.prev``."""
        try:
            with open(os.path.join(gen_dir, MANIFEST_NAME)) as f:
                body = json.load(f)
        except Exception:
            return None
        if body.get("checksum", "") != _manifest_checksum(body):
            return None
        for ent in body.get("arrays", {}).values():
            try:
                if os.path.getsize(os.path.join(gen_dir, ent["file"])) <= 0:
                    return None
            except (OSError, KeyError, TypeError):
                return None
        return body

    def _peek_manifest(self) -> dict | None:
        """Cheap manifest read for the version/parent chain: the current
        generation, falling back to ``.prev`` when the current one is
        missing/unreadable — a kill in the window between the two
        publish renames leaves only ``.prev`` intact, and the chain must
        continue from it, never reset to version 1. None = neither
        generation readable."""
        for gen in (self._gen(), self._prev()):
            peek = self._peek_dir(gen)
            if peek is not None:
                return peek
        return None

    def peek_version(self) -> int | None:
        peek = self._peek_manifest()
        if peek is None:
            return None
        try:
            return int(peek["version"])
        except (KeyError, TypeError, ValueError):
            return None

    def _read_verified(self, gen_dir: str, fingerprint: str | None):
        """Load one generation, verifying manifest checksum, every
        array's sha256/dtype/shape, then the graph fingerprint. Raises a
        :data:`_CORRUPTION_ERRORS` member on damaged bytes,
        :class:`FingerprintMismatch` on a wrong-graph snapshot."""
        man_path = os.path.join(gen_dir, MANIFEST_NAME)
        try:
            with open(man_path) as f:
                body = json.load(f)
        except json.JSONDecodeError as e:
            raise CheckpointCorruptionError(
                f"snapshot manifest at {man_path} is not valid JSON ({e})"
            ) from e
        want = body.get("checksum", "")
        got = _manifest_checksum(body)
        if want != got:
            raise CheckpointCorruptionError(
                f"snapshot manifest at {man_path} failed its checksum "
                f"({got[:12]}... != recorded {want[:12]}...)"
            )
        saved_fp = body.get("fingerprint", "")
        if fingerprint and saved_fp and fingerprint != saved_fp:
            raise FingerprintMismatch(
                f"snapshot at {gen_dir} was published for a different graph "
                f"or vertex-id assignment (fingerprint {saved_fp[:12]}... != "
                f"{fingerprint[:12]}...); republish from the current graph "
                "or query the snapshot it was built from"
            )
        arrays = {}
        for name, ent in body.get("arrays", {}).items():
            path = os.path.join(gen_dir, ent["file"])
            sha = _file_sha256(path)
            if sha != ent["sha256"]:
                raise CheckpointCorruptionError(
                    f"snapshot array {name!r} at {path} failed its sha256 "
                    f"({sha[:12]}... != manifest {ent['sha256'][:12]}...)"
                )
            arr = np.load(path)
            if list(arr.shape) != ent["shape"] or str(arr.dtype) != ent["dtype"]:
                raise CheckpointCorruptionError(
                    f"snapshot array {name!r} at {path} is "
                    f"{arr.dtype}{list(arr.shape)}, manifest says "
                    f"{ent['dtype']}{ent['shape']}"
                )
            arrays[name] = arr
        meta = {k: v for k, v in body.items() if k not in ("arrays", "checksum")}
        snap = Snapshot(arrays=arrays, meta=meta, path=gen_dir)
        # (snapshot, version) so the shared rollback state machine — whose
        # contract is (payload, generation-counter) tuples — applies as-is.
        return snap, snap.version

    def _read_confirmed(self, gen_dir: str, fingerprint: str | None):
        """One confirming re-read before a corruption verdict — the same
        transient-I/O-weather rationale as the checkpoint readers."""
        try:
            return self._read_verified(gen_dir, fingerprint)
        except FingerprintMismatch:
            raise
        except _CORRUPTION_ERRORS as first:
            try:
                return self._read_verified(gen_dir, fingerprint)
            except FingerprintMismatch:
                raise
            except _CORRUPTION_ERRORS:
                raise first

    def load(self, fingerprint: str | None = None, sink=None) -> Snapshot | None:
        """Newest intact snapshot, or None when the store is empty.

        A corrupt current generation rolls back to ``.prev`` (promoted to
        the current slot, the condemned directory preserved at
        ``*.corrupt`` — ``checkpoint_rollback`` records through ``sink``);
        a wrong ``fingerprint`` raises :class:`FingerprintMismatch`
        without rollback. ``sink`` also gets a ``snapshot_load`` record.
        """
        t0 = time.perf_counter()
        out = _load_with_rollback(
            self._gen(), self._prev(),
            lambda p: self._read_confirmed(p, fingerprint),
            sink, "snapshot",
            f"delete {self._gen()!r} (and its .prev) and republish",
        )
        if out is None:
            return None
        snap, version = out
        if sink is not None:
            sink.emit(
                "snapshot_load", version=int(version), path=snap.path,
                snapshot_id=snap.snapshot_id,
                seconds=round(time.perf_counter() - t0, 4),
            )
        return snap
