"""Batched query engine over a loaded result snapshot.

Serving reads are the inverse shape of the batch pipeline: millions of
tiny lookups instead of one huge propagation. Every query here is O(1) /
O(log n) against indexes built ONCE at snapshot load ("Making Caches
Work for Graph Analytics" locality argument — pay the sort/CSR
construction once, then every lookup is a contiguous slice):

- ``membership`` / ``score`` / ``community_size`` / ``community_decile``:
  one array index;
- ``neighbors``: one CSR row slice (the message CSR rebuilt host-side
  from the snapshot's edge arrays);
- ``top_outliers(community, k)``: one binary search + a k-slice of the
  (label asc, LOF desc)-sorted vertex order;
- ``query_batch``: the vectorized path — a whole vector of vertex ids
  resolves in ONE device gather over a stacked ``[3, V]`` int table (+
  one for the float LOF column), jitted per engine with batches padded
  to power-of-two buckets (bounded retraces; traces die with the engine
  at snapshot swap).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from graphmine_tpu.serve.snapshot import Snapshot


def _as_int_ids(values, what: str) -> np.ndarray:
    """Coerce wire input to an int64 id array. Integral floats are
    accepted (JSON encoders routinely emit ``40.0`` for 40); fractional,
    non-finite or non-numeric ids raise ValueError (the HTTP layer's
    400) — never a TypeError crash, never a silent truncation of ``1.9``
    to id ``1``. Shared by the query and delta wire paths so the two can
    never drift on what counts as a valid id."""
    try:
        arr = np.asarray(values)
    except TypeError as e:
        raise ValueError(f"{what} must be an array of integers ({e})") from e
    if arr.size == 0 or np.issubdtype(arr.dtype, np.integer):
        return arr.astype(np.int64)
    if (
        np.issubdtype(arr.dtype, np.floating)
        and np.isfinite(arr).all()
        and (arr == np.floor(arr)).all()
    ):
        return arr.astype(np.int64)
    raise ValueError(f"{what} ids must be integers (got dtype {arr.dtype})")


class QueryEngine:
    """Immutable per-snapshot read index. Thread-safe by construction
    (nothing mutates after ``__init__`` — the one exception is the
    lock-guarded stage-timing accumulator, which is advisory telemetry,
    never read by a query), which is what lets the server double-buffer:
    in-flight requests keep serving the engine they grabbed while a
    delta publish swaps the reference under them."""

    def __init__(self, snapshot: Snapshot, device: bool = True):
        self.snapshot = snapshot
        # Stage-split accounting for the batched path (docs/OBSERVABILITY
        # "serving SLO"): host wall-clock around stages that already
        # exist — pad (validate + power-of-two pad), gather (device
        # gather + the np.asarray transfer that was always the sync
        # point), host (response assembly). Zero added device syncs.
        self._stage_lock = threading.Lock()
        self._stages = {
            "batches": 0, "ids": 0,
            "pad_seconds": 0.0, "gather_seconds": 0.0, "host_seconds": 0.0,
        }
        # Deferred-LOF staleness (admission rung 2, serve/admission.py):
        # when the publish skipped the outlier refresh under write
        # pressure, results carry this flag so readers can tell a fresh
        # score from one that predates the latest deltas.
        self.lof_stale = bool(snapshot.meta.get("lof_stale", False))
        # Result-quality plane (docs/OBSERVABILITY.md "Result quality"):
        # the anomaly threshold /explain verdicts use, and a lazily-
        # built-once QualityState (sketches + census scalars) served on
        # /statusz and /alertz and merged fleet-wide by the router.
        from graphmine_tpu.obs.quality import lof_threshold

        self._lof_threshold = lof_threshold()
        self._quality_state = None
        self._explain_idx = None   # lazy /explain side index
        self._quality_lock = threading.Lock()
        self.labels = np.asarray(snapshot["labels"], np.int32)
        v = len(self.labels)
        self.num_vertices = v
        self.cc_labels = np.asarray(
            snapshot.get("cc_labels", self.labels), np.int32
        )
        lof = snapshot.get("lof")
        self.lof = (
            np.zeros(v, np.float32) if lof is None
            else np.asarray(lof, np.float32)
        )

        # neighbors: the message CSR over the snapshot's edge arrays
        # (both directions, multiplicity kept — the same adjacency LPA
        # propagated over). Host-side; one O(E) build per load.
        from graphmine_tpu.graph.container import build_graph

        g = build_graph(
            np.asarray(snapshot["src"], np.int32),
            np.asarray(snapshot["dst"], np.int32),
            num_vertices=v, to_device=False,
        )
        self._nbr_ptr = np.asarray(g.msg_ptr)
        self._nbr = np.asarray(g.msg_send)

        # community census: sizes per present community + size deciles
        if "census_sizes" in snapshot.arrays:
            self._present = np.asarray(snapshot["census_present"], np.int64)
            self._sizes = np.asarray(snapshot["census_sizes"], np.int64)
        else:
            counts = np.bincount(self.labels, minlength=v)
            self._present = np.flatnonzero(counts).astype(np.int64)
            self._sizes = counts[self._present].astype(np.int64)
        size_of = np.zeros(v, np.int64)
        size_of[self._present] = self._sizes
        self._size_by_vertex = size_of[self.labels].astype(np.int32)
        self._sizes_sorted = np.sort(self._sizes)

        # top-k outliers per community: vertices sorted (label asc, LOF
        # desc) once; each community is then one contiguous block whose
        # start binary-searches in O(log C).
        order = np.lexsort((-self.lof, self.labels))
        self._by_comm = order.astype(np.int64)
        sorted_labels = self.labels[order].astype(np.int64)
        self._block_labels, self._block_starts = np.unique(
            sorted_labels, return_index=True
        )

        self._dev = None
        self._table = None
        if not device:
            # host twin of the device table, built ONCE (a per-call
            # np.stack would memcpy 3x[V] ints on every batch)
            self._table = np.stack(
                [self.labels, self.cc_labels, self._size_by_vertex]
            )
        else:
            import jax
            import jax.numpy as jnp

            self._dev = (
                jnp.stack([
                    jnp.asarray(self.labels),
                    jnp.asarray(self.cc_labels),
                    jnp.asarray(self._size_by_vertex),
                ]),
                jnp.asarray(self.lof),
            )
            # Per-ENGINE jit (not module-global): traces die with the
            # engine at snapshot swap instead of accreting one stale
            # entry per (batch shape, V) forever on a long-lived server.
            self._gather = jax.jit(lambda t, s, i: (t[:, i], s[i]))

    @property
    def version(self) -> int:
        return self.snapshot.version

    def memory_bytes(self) -> dict:
        """Host-side byte accounting of this engine (ISSUE 14): the
        snapshot's array payload vs the DERIVED query index (adjacency
        CSR, census columns, per-vertex size map, the stacked gather
        table) — the two components a serve process deliberately holds,
        so a growing RSS decomposes into "the graph grew" vs "the index
        grew" from /statusz alone. Engines are immutable, so the counts
        are stable for this served version (the lazy /explain side
        index is counted when built)."""
        # np.asarray on an already-right-dtype snapshot array returns the
        # SAME object (and cc_labels falls back to labels when absent):
        # count each underlying buffer once, and never re-count a buffer
        # the snapshot accounting already covers — otherwise a label-heavy
        # snapshot reads 2-3x its real RSS contribution across the split.
        seen = set()
        for a in self.snapshot.arrays.values():
            seen.add(id(a))
            if getattr(a, "base", None) is not None:
                seen.add(id(a.base))
        idx = 0
        arrays = []
        for name in (
            "labels", "cc_labels", "lof", "_nbr_ptr", "_nbr", "_present",
            "_sizes", "_size_by_vertex", "_sizes_sorted", "_by_comm",
            "_block_labels", "_block_starts", "_table", "_explain_idx",
        ):
            a = getattr(self, name, None)
            if isinstance(a, tuple):  # the lazy /explain index is a pair
                arrays.extend(a)
            elif a is not None:
                arrays.append(a)
        for a in arrays:
            if not hasattr(a, "nbytes"):
                continue
            base = a.base if getattr(a, "base", None) is not None else a
            if id(a) in seen or id(base) in seen:
                continue
            seen.add(id(a))
            seen.add(id(base))
            idx += int(a.nbytes)
        return {
            "snapshot_bytes": self.snapshot.nbytes,
            "index_bytes": idx,
        }

    def quality_state(self, build: bool = True):
        """This snapshot's :class:`~graphmine_tpu.obs.quality
        .QualityState`, built ONCE on first read (engines are immutable
        and die at snapshot swap, so the state can never go stale) —
        the /statusz "quality" section and the router's fleet-merge
        source. Lazy: a replica nobody asks never pays the O(V) pass.
        ``build=False`` returns only an already-built state (else None)
        — the /healthz alert pass reads through here, and a liveness
        probe must never be the thing that pays the O(V) build (the
        probe would time out on exactly the replicas swapping snapshots
        fastest)."""
        with self._quality_lock:
            if self._quality_state is None and build:
                from graphmine_tpu.obs.quality import QualityState

                self._quality_state = QualityState.from_arrays(
                    self.labels, self.lof, version=self.version,
                    threshold=self._lof_threshold,
                )
            return self._quality_state

    def stage_snapshot(self) -> dict:
        """Accumulated batched-path stage split since this engine was
        built (engines die at snapshot swap, so the window is one served
        version): batches/ids resolved and pad/gather/host seconds —
        ``/statusz`` serves it so a p99 spike triages to the stage that
        actually moved (RUNBOOKS §7) instead of "the device is slow"."""
        with self._stage_lock:
            out = dict(self._stages)
        for k in ("pad_seconds", "gather_seconds", "host_seconds"):
            out[k] = round(out[k], 6)
        return out

    # -- single lookups ----------------------------------------------------
    def _check(self, vertex: int) -> int:
        vertex = int(vertex)
        if not 0 <= vertex < self.num_vertices:
            raise KeyError(
                f"vertex {vertex} not in [0, {self.num_vertices})"
            )
        return vertex

    def membership(self, vertex: int) -> int:
        """Community label of one vertex."""
        return int(self.labels[self._check(vertex)])

    def component(self, vertex: int) -> int:
        """Weakly-connected-component label of one vertex."""
        return int(self.cc_labels[self._check(vertex)])

    def score(self, vertex: int) -> float:
        """LOF outlier score of one vertex (higher = more outlying)."""
        return float(self.lof[self._check(vertex)])

    def community_size(self, vertex: int) -> int:
        """Vertex count of the community ``vertex`` belongs to."""
        return int(self._size_by_vertex[self._check(vertex)])

    def community_decile(self, vertex: int) -> int:
        """Size decile (0-9) of the vertex's community among all present
        communities — 0 = smallest tenth (the recursive-LPA outlier
        criterion's bottom decile), 9 = largest."""
        size = self._size_by_vertex[self._check(vertex)]
        n = len(self._sizes_sorted)
        if not n:
            return 0
        rank = int(np.searchsorted(self._sizes_sorted, size, side="right"))
        return min(9, 10 * (rank - 1) // n)

    def neighbors(self, vertex: int) -> np.ndarray:
        """Message-neighbor ids of one vertex (both edge directions,
        multiplicity kept) — one CSR row slice."""
        vertex = self._check(vertex)
        return self._nbr[self._nbr_ptr[vertex]: self._nbr_ptr[vertex + 1]]

    def _explain_index(self):
        """Lazily-built-once /explain side index (the quality_state
        lifecycle: engines are immutable and die at swap): the inverse
        permutation of the (label asc, LOF desc) vertex order — making
        rank-in-community one subtraction — and the sorted LOF column
        for O(log V) percentile lookups. Without it every /explain
        would scan the full LOF column; a dashboard walking a firing
        alert's top-k would pay O(kV)."""
        with self._quality_lock:
            if self._explain_idx is None:
                pos = np.empty(self.num_vertices, np.int64)
                pos[self._by_comm] = np.arange(self.num_vertices)
                self._explain_idx = (pos, np.sort(self.lof))
            return self._explain_idx

    def explain(self, vertex: int, max_neighbors: int = 32) -> dict:
        """Per-vertex outlier explanation — the triage companion to a
        firing canary/drift alert (RUNBOOKS §13): everything the engine's
        existing indexes say about WHY this vertex scores the way it
        does, in one read — O(log V + deg) against indexes built at
        load plus a lazily-built-once side index (:meth:`_explain_index`):
        LOF/label columns, the census tables, the neighbor CSR and the
        (label asc, LOF desc) community blocks.

        Fields: the vertex row (label/component/LOF/size/decile), its
        LOF rank within its community and global score percentile, and
        degree + up to ``max_neighbors`` neighbor ids with their scores'
        mean/max (an outlier whose neighbors also score high is a
        shifted REGION — drift — not a point anomaly). Per-vertex
        k-distances are NOT served: the streaming scorer's window does
        not cover all V, so no snapshot column holds them.
        """
        vertex = self._check(vertex)
        nbrs = self.neighbors(vertex)
        label = int(self.labels[vertex])
        score = float(self.lof[vertex])
        pos_in_order, lof_sorted = self._explain_index()
        # rank of this vertex inside its community's LOF-desc block:
        # its position in the global (label asc, LOF desc) order minus
        # the block start — one array read, no block scan
        i = int(np.searchsorted(self._block_labels, label))
        start = int(self._block_starts[i])
        rank_in_comm = int(pos_in_order[vertex]) - start
        out = {
            "vertex": int(vertex),
            "label": label,
            "component": int(self.cc_labels[vertex]),
            "lof": score,
            "lof_stale": self.lof_stale,
            "community_size": int(self._size_by_vertex[vertex]),
            "community_decile": self.community_decile(vertex),
            "lof_rank_in_community": rank_in_comm,
            "community_top_lof": float(self.lof[self._by_comm[start]]),
            # global percentile of this score (1.0 = the most outlying)
            "lof_percentile": round(
                float(np.searchsorted(lof_sorted, score, side="right"))
                / max(1, len(lof_sorted)), 4
            ),
            "anomaly": bool(score > self._lof_threshold),
            "lof_threshold": self._lof_threshold,
            "degree": int(len(nbrs)),
            "neighbors": nbrs[:max_neighbors],
            "neighbors_truncated": bool(len(nbrs) > max_neighbors),
        }
        if len(nbrs):
            nscores = self.lof[nbrs]
            out["neighbor_lof_mean"] = round(float(nscores.mean()), 4)
            out["neighbor_lof_max"] = round(float(nscores.max()), 4)
        return out

    def top_outliers(self, community: int, k: int = 10):
        """Top-``k`` LOF outliers of one community:
        ``[(vertex, score), ...]`` descending. O(log C) block lookup +
        an O(k) slice."""
        i = np.searchsorted(self._block_labels, int(community))
        if i >= len(self._block_labels) or self._block_labels[i] != community:
            raise KeyError(f"community {community} has no members")
        start = self._block_starts[i]
        end = (
            self._block_starts[i + 1] if i + 1 < len(self._block_starts)
            else len(self._by_comm)
        )
        block = self._by_comm[start: min(end, start + max(int(k), 0))]
        return [(int(vtx), float(self.lof[vtx])) for vtx in block]

    # -- batched path ------------------------------------------------------
    def query_batch(self, vertices) -> dict:
        """Resolve a vector of vertex ids in one device gather.

        Returns ``{"vertex", "label", "component", "community_size",
        "lof"}`` as aligned arrays. Out-of-range ids raise (the HTTP
        layer turns that into a 400, never a wrong answer).
        """
        t0 = time.perf_counter()
        ids = _as_int_ids(vertices, "vertex").reshape(-1)
        if len(ids) and (ids.min() < 0 or ids.max() >= self.num_vertices):
            bad = ids[(ids < 0) | (ids >= self.num_vertices)]
            raise KeyError(
                f"{len(bad)} vertex id(s) not in [0, {self.num_vertices}): "
                f"{bad[:5].tolist()}..."
            )
        if self._dev is not None:
            # Pad to the next power-of-two bucket: clients send arbitrary
            # batch lengths, and jit retraces per shape — bucketing caps
            # the traces per engine at ~log2(max batch) instead of one
            # per distinct length (a synchronous XLA compile on the hot
            # path each time).
            n = len(ids)
            cap = 1 << max(0, (n - 1).bit_length())
            padded = np.zeros(cap, np.int32)
            padded[:n] = ids
            t1 = time.perf_counter()
            ints, lof = self._gather(self._dev[0], self._dev[1], padded)
            ints = np.asarray(ints)[:, :n]
            lof = np.asarray(lof)[:n]
        else:
            t1 = time.perf_counter()
            ints, lof = self._table[:, ids], self.lof[ids]
        t2 = time.perf_counter()
        out = {
            "vertex": ids,
            "label": ints[0],
            "component": ints[1],
            "community_size": ints[2],
            "lof": lof,
        }
        t3 = time.perf_counter()
        with self._stage_lock:
            self._stages["batches"] += 1
            self._stages["ids"] += len(ids)
            self._stages["pad_seconds"] += t1 - t0
            self._stages["gather_seconds"] += t2 - t1
            self._stages["host_seconds"] += t3 - t2
        return out
