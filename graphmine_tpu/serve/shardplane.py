"""Vertex-range sharded write plane + epoch-coordinated publish (r17).

Through r16 the entire write path funnels every delta through ONE writer
owning the whole graph: writer loss flips the whole fleet read-only
(loudly, after r9/r10 — but still whole-fleet). This module removes that
last single point of failure in three pieces (docs/SERVING.md "Sharded
write plane"):

- :class:`ShardPlan` — a deterministic partition of the vertex-id space
  into contiguous ranges (``GRAPHMINE_WRITER_SHARDS``; 1 = exact
  pre-shard behavior). **Edge ownership = dst range**; inserts whose dst
  lands past the plan's vertex space (graph growth) belong to the LAST
  shard — a fixed rule, so two processes holding the same plan always
  agree on every row's owner.

- :func:`split_delta` — the deterministic splitter at the front door: a
  batch touching k ranges becomes k sub-batches routed to their owner
  shards, each carrying the ORIGINAL row indices so
  :func:`merge_splits` reassembles the batch **bit-identically**. The
  idempotency key propagates as ``(delta_id, shard)``: each shard's own
  WAL dedupes the id independently, so a retry after a partial accept
  appends only to the shards that missed it — exactly-once per shard.

  Why split-then-apply equals whole-batch apply (the parity the
  randomized tests pin): sub-batches have disjoint dst ranges, so their
  delete keys ``(src, dst)`` are disjoint across shards, and
  :func:`~graphmine_tpu.serve.delta.splice_edges` deletes only target
  base arrays (never same-batch inserts) — per-shard applies commute,
  and the live apply path uses the reassembled (bit-identical) batch
  anyway, so splice bytes cannot differ by construction.

- :class:`ShardedWritePlane` — the r10 durability machinery instantiated
  **per range**, tenant-composed (tenancy splits by namespace, the plane
  splits each namespace's range space): every shard owns its OWN
  :class:`~graphmine_tpu.serve.wal.WriteAheadLog` (shard-labeled
  gauges), :class:`~graphmine_tpu.serve.admission.AdmissionController`
  ladder, :class:`~graphmine_tpu.serve.delta.RepairDebt` ledger and
  optional log-shipped standby copy. Shard death flips ONLY that range
  read-only; batches touching a dead range refuse 503 while untouched
  ranges keep accepting; a restart replays the shard's WAL tail (zero
  acked loss), and a standby promotion mints its epoch through the
  store's fence lock — the same serialization point as every other
  epoch transition.

- :class:`EpochCoordinator` — two-phase commit over the snapshot
  store's existing flock fence: shards **stage** per-range array files
  (per-shard manifests in the r2 sharded-checkpoint format — no
  gather-to-one-host), then the coordinator **commits** a durable
  ``publish_epoch`` record mapping epoch → per-shard version vector.
  Readers serve the *latest fully-committed epoch*: a multi-range batch
  becomes visible atomically or not at all. A coordinator crash between
  stage and commit (the ``shard_publish_commit`` fault seam /
  ``shard_publish_torn`` injector) leaves the previous epoch served and
  the staged generation recoverable — :meth:`EpochCoordinator.recover`
  finishes a complete stage or sweeps an incomplete one.

All records emit through :func:`emit_shard_record` — THE single builder
for ``shard_publish`` / ``epoch_commit`` / ``shard_degraded``
(tools/schema_lint.py flags inline emits elsewhere).
"""

from __future__ import annotations

import bisect as _bisect
import glob
import json
import os
import shutil
import threading
import time
from dataclasses import dataclass

import numpy as np

from graphmine_tpu.pipeline import resilience
from graphmine_tpu.pipeline.checkpoint import (
    _file_sha256,
    _fsync_dir,
    _fsync_file,
    _manifest_checksum,
)
from graphmine_tpu.serve.admission import AdmissionBounds, AdmissionController
from graphmine_tpu.serve.delta import EdgeDelta, RepairDebt
from graphmine_tpu.serve.snapshot import (
    EPOCHS_DIRNAME,
    MANIFEST_NAME,
    _NAME_RE,
)
from graphmine_tpu.serve.tenancy import DEFAULT_TENANT
from graphmine_tpu.serve.wal import WriteAheadLog

ENV_WRITER_SHARDS = "GRAPHMINE_WRITER_SHARDS"
SHARDS_DIRNAME = "shards"
_EPOCH_FMT = "epoch-%08d"
_FORMAT_VERSION = 1

# The record family this module owns; every emit goes through
# emit_shard_record so the schema contract has ONE enforcement point.
SHARD_RECORD_PHASES = frozenset(
    ("shard_publish", "epoch_commit", "shard_degraded")
)


def emit_shard_record(sink, phase: str, **kv) -> None:
    """THE single builder for the shard-plane record family. A phase
    outside :data:`SHARD_RECORD_PHASES` raises (a typo'd phase must die
    here, not rot the JSONL); a ``None`` sink is a no-op so plane code
    never branches on observability being attached."""
    if phase not in SHARD_RECORD_PHASES:
        raise ValueError(
            f"emit_shard_record owns only {sorted(SHARD_RECORD_PHASES)}, "
            f"not {phase!r}"
        )
    if sink is None:
        return
    sink.emit(phase, **kv)


class ShardRangeUnavailableError(RuntimeError):
    """A batch touched a vertex range whose writer shard is degraded
    (killed, read-only, awaiting promotion). Retryable — the HTTP layer
    answers 503 + Retry-After; batches touching only healthy ranges are
    unaffected, which is the whole point of range sharding."""

    def __init__(self, message: str, shards=()):
        super().__init__(message)
        self.shards = tuple(int(s) for s in shards)


def writer_shards_from_env(default: int = 1) -> int:
    """Resolve ``GRAPHMINE_WRITER_SHARDS`` (malformed values fail
    loudly — a typo'd shard count silently falling back to 1 would
    un-shard a deployment without a trace)."""
    raw = os.environ.get(ENV_WRITER_SHARDS)
    if raw is None:
        return int(default)
    try:
        n = int(raw)
    except ValueError as e:
        raise ValueError(f"{ENV_WRITER_SHARDS}={raw!r} is not an int") from e
    if n < 1:
        raise ValueError(f"{ENV_WRITER_SHARDS}={n} must be >= 1")
    return n


@dataclass(frozen=True)
class ShardPlan:
    """Contiguous vertex-range partition of ``[0, num_vertices)`` into
    ``num_shards`` ranges. Frozen: ranges never rebalance mid-flight —
    rows for vertices born after the plan (graph growth) belong to the
    LAST shard by rule, so every holder of the plan routes identically
    forever."""

    num_shards: int
    num_vertices: int
    boundaries: tuple  # len num_shards + 1; [0, ..., num_vertices]

    @classmethod
    def build(cls, num_shards: int, num_vertices: int) -> "ShardPlan":
        k = int(num_shards)
        if k < 1:
            raise ValueError(f"num_shards must be >= 1, got {k}")
        v = max(0, int(num_vertices))
        chunk = -(-v // k) if v else 0  # ceil-div, the r2 chunking rule
        bounds = [min(v, i * chunk) for i in range(k + 1)]
        bounds[-1] = v
        return cls(k, v, tuple(bounds))

    @classmethod
    def from_env(cls, num_vertices: int, default: int = 1) -> "ShardPlan":
        return cls.build(writer_shards_from_env(default), num_vertices)

    def owner_of(self, vertex: int) -> int:
        """The shard owning ``vertex``; ids at/past ``num_vertices``
        (growth) belong to the last shard."""
        i = _bisect.bisect_right(self.boundaries, int(vertex)) - 1
        return min(max(i, 0), self.num_shards - 1)

    def owners(self, vertices) -> np.ndarray:
        """Vectorized :meth:`owner_of` over a dst column."""
        v = np.asarray(vertices, np.int64)
        idx = (
            np.searchsorted(
                np.asarray(self.boundaries, np.int64), v, side="right"
            )
            - 1
        )
        return np.clip(idx, 0, self.num_shards - 1).astype(np.int64)

    def range_of(self, shard: int) -> tuple[int, int]:
        s = int(shard)
        if not 0 <= s < self.num_shards:
            raise IndexError(f"shard {s} outside plan of {self.num_shards}")
        return int(self.boundaries[s]), int(self.boundaries[s + 1])

    def ranges(self) -> list[dict]:
        """The range table (fleet_cli ``status --shards`` / serve_cli
        ``info`` render this)."""
        return [
            {
                "shard": s,
                "lo": self.boundaries[s],
                "hi": self.boundaries[s + 1],
                "owns_growth": s == self.num_shards - 1,
            }
            for s in range(self.num_shards)
        ]

    def snapshot(self) -> dict:
        return {
            "num_shards": self.num_shards,
            "num_vertices": self.num_vertices,
            "boundaries": list(self.boundaries),
        }


@dataclass(frozen=True)
class DeltaSplit:
    """One shard's sub-batch plus the ORIGINAL row indices it came from
    (positions into the unsplit batch's insert/delete arrays) — what
    makes :func:`merge_splits` a bit-exact inverse."""

    shard: int
    delta: EdgeDelta
    insert_index: np.ndarray
    delete_index: np.ndarray


def split_delta(delta: EdgeDelta, plan: ShardPlan) -> list[DeltaSplit]:
    """Deterministically split one batch by dst-range ownership.

    Cross-range deletes (src in range A, dst in range B) route to B —
    the dst owner, same rule as inserts, so the shard that owns an
    edge's insert also owns its delete. Returns only TOUCHED shards
    (ascending); an empty batch routes to shard 0 so its accounting has
    a home. ``plan.num_shards == 1`` short-circuits to one whole-batch
    split — the exact pre-shard path, zero array work."""
    n_ins, n_del = delta.num_inserts, delta.num_deletes
    if plan.num_shards == 1:
        return [
            DeltaSplit(
                0, delta,
                np.arange(n_ins, dtype=np.int64),
                np.arange(n_del, dtype=np.int64),
            )
        ]
    ins_owner = plan.owners(delta.insert_dst)
    del_owner = plan.owners(delta.delete_dst)
    out = []
    for s in range(plan.num_shards):
        ii = np.flatnonzero(ins_owner == s)
        di = np.flatnonzero(del_owner == s)
        if not len(ii) and not len(di):
            continue
        out.append(DeltaSplit(s, delta.take(ii, di), ii, di))
    if not out:
        out.append(
            DeltaSplit(
                0, delta,
                np.arange(0, dtype=np.int64), np.arange(0, dtype=np.int64),
            )
        )
    return out


def merge_splits(splits: list) -> EdgeDelta:
    """Reassemble the original batch from its splits, bit-identically:
    every row scatters back to its original position, weights included.
    The inverse of :func:`split_delta` — pinned by the randomized
    splitter-parity tests."""
    n_ins = sum(len(sp.insert_index) for sp in splits)
    n_del = sum(len(sp.delete_index) for sp in splits)
    isrc = np.empty(n_ins, np.int64)
    idst = np.empty(n_ins, np.int64)
    dsrc = np.empty(n_del, np.int64)
    ddst = np.empty(n_del, np.int64)
    weighted = any(sp.delta.insert_weight is not None for sp in splits)
    iw = np.empty(n_ins, np.float32) if weighted else None
    for sp in splits:
        ii, di = sp.insert_index, sp.delete_index
        isrc[ii] = sp.delta.insert_src
        idst[ii] = sp.delta.insert_dst
        dsrc[di] = sp.delta.delete_src
        ddst[di] = sp.delta.delete_dst
        if weighted:
            iw[ii] = (
                sp.delta.insert_weight
                if sp.delta.insert_weight is not None
                else np.ones(len(ii), np.float32)
            )
    return EdgeDelta(isrc, idst, dsrc, ddst, insert_weight=iw)


# ---- epoch-coordinated publish ---------------------------------------------


class EpochCoordinator:
    """Two-phase commit of per-range array generations over the snapshot
    store's flock fence.

    On-disk layout under ``<store.root>/epochs/``::

        epoch-00000007.stage/shard-000/{labels.npy, ..., manifest.json}
        epoch-00000007/       (renamed from .stage at commit)
        epoch-00000007.json   (the durable publish_epoch commit record)

    State machine (docs/SERVING.md "Sharded write plane"):

    1. :meth:`stage` writes every shard's arrays + an r2-style manifest
       (per-file sha256 + whole-manifest checksum, each file fsync'd)
       into the ``.stage`` directory. Nothing is visible yet.
    2. :meth:`commit` — under the store's fence lock — passes the
       ``shard_publish_commit`` fault seam, renames stage→final, fsyncs,
       then durably writes the ``publish_epoch`` commit record
       (tmp + fsync + rename). **The record IS the commit point**:
       readers key off :meth:`committed_epoch` = the highest epoch with
       a valid record, so a crash anywhere before the record leaves the
       previous epoch served, in full.
    3. :meth:`recover` (restart path, also under the fence lock)
       finishes a complete-but-uncommitted generation — re-running just
       the commit leg — or sweeps an incomplete stage. Either way the
       store converges on a committed epoch.

    One coordinator per store root is the concurrency contract (the
    fence lock serializes commits against promotions and each other).
    """

    RETAIN_EPOCHS = 2

    def __init__(self, store, plan: ShardPlan, sink=None):
        self.store = store
        self.plan = plan
        self.sink = sink
        self.root = os.path.join(store.root, EPOCHS_DIRNAME)

    # -- paths ------------------------------------------------------------
    def _final_dir(self, epoch: int) -> str:
        return os.path.join(self.root, _EPOCH_FMT % int(epoch))

    def _stage_dir(self, epoch: int) -> str:
        return self._final_dir(epoch) + ".stage"

    def _record_path(self, epoch: int) -> str:
        return self._final_dir(epoch) + ".json"

    # -- stage ------------------------------------------------------------
    def stage(
        self,
        epoch: int,
        shard_arrays: dict,
        versions: dict | None = None,
    ) -> str:
        """Write every shard's per-range arrays into the epoch's stage
        directory. ``shard_arrays`` maps shard → {name: np.ndarray};
        ``versions`` maps shard → the snapshot version whose apply last
        touched that range (the vector the commit record publishes).
        Emits one ``shard_publish`` record per shard. Restaging an
        epoch replaces its previous stage (a crashed attempt's leftovers
        never mix into a fresh one)."""
        epoch = int(epoch)
        versions = versions or {}
        stage = self._stage_dir(epoch)
        shutil.rmtree(stage, ignore_errors=True)
        os.makedirs(stage)
        for shard in sorted(shard_arrays):
            arrays = shard_arrays[shard]
            sdir = os.path.join(stage, f"shard-{int(shard):03d}")
            os.makedirs(sdir)
            entries, total = {}, 0
            for name in sorted(arrays):
                if not _NAME_RE.match(name):
                    raise ValueError(f"unsafe shard array name {name!r}")
                arr = np.asarray(arrays[name])
                fname = f"{name}.npy"
                path = os.path.join(sdir, fname)
                np.save(path, arr)
                _fsync_file(path)
                total += int(arr.nbytes)
                entries[name] = {
                    "file": fname,
                    "sha256": _file_sha256(path),
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }
            lo, hi = self.plan.range_of(shard)
            body = {
                "format_version": _FORMAT_VERSION,
                "epoch": epoch,
                "shard": int(shard),
                "num_shards": self.plan.num_shards,
                "range": [lo, hi],
                "version": int(versions.get(shard, 0)),
                "created": time.time(),
                "arrays": entries,
            }
            body["checksum"] = _manifest_checksum(body)
            man_tmp = os.path.join(sdir, MANIFEST_NAME + ".tmp")
            with open(man_tmp, "w") as f:
                json.dump(body, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(man_tmp, os.path.join(sdir, MANIFEST_NAME))
            _fsync_dir(sdir)
            emit_shard_record(
                self.sink, "shard_publish",
                epoch=epoch, shard=int(shard),
                version=int(versions.get(shard, 0)),
                arrays=sorted(arrays), bytes=total,
                range=[lo, hi],
            )
        _fsync_dir(stage)
        return stage

    # -- commit -----------------------------------------------------------
    def commit(self, epoch: int, version_vector: dict) -> dict:
        """Durably commit a staged epoch (two-phase commit, leg two).
        Serialized through the store's fence lock — the same lock every
        promotion's epoch mint takes, so a commit can never interleave
        with a fence transition. Raises if the stage is missing (a
        recover() swept it, or stage() was never called)."""
        epoch = int(epoch)
        stage, final = self._stage_dir(epoch), self._final_dir(epoch)
        with self.store.fence_lock():
            # Torn-publish seam (testing/faults.shard_publish_torn): a
            # coordinator crash injected HERE — every shard staged,
            # nothing committed — must leave the previous epoch served
            # and this generation recoverable. THE chaos-tier pin.
            resilience.fault_point("shard_publish_commit", epoch=epoch)
            if os.path.isdir(stage):
                shutil.rmtree(final, ignore_errors=True)
                os.replace(stage, final)
                _fsync_dir(self.root)
            elif not os.path.isdir(final):
                raise FileNotFoundError(
                    f"epoch {epoch} has no staged generation at {stage!r} "
                    "to commit (stage() first, or recover() swept an "
                    "incomplete one)"
                )
            record = self._write_record_locked(epoch, version_vector)
        emit_shard_record(
            self.sink, "epoch_commit",
            epoch=epoch,
            version_vector={str(k): int(v) for k, v in version_vector.items()},
            shards=self.plan.num_shards,
        )
        self._retire()
        return record

    def _write_record_locked(self, epoch: int, version_vector: dict) -> dict:
        record = {
            "record": "publish_epoch",
            "format_version": _FORMAT_VERSION,
            "epoch": int(epoch),
            "version_vector": {
                str(int(k)): int(v) for k, v in version_vector.items()
            },
            "num_shards": self.plan.num_shards,
            "ranges": self.plan.ranges(),
            "created": time.time(),
        }
        record["checksum"] = _manifest_checksum(record)
        path = self._record_path(epoch)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.root)
        return record

    # -- read -------------------------------------------------------------
    def _read_record(self, path: str) -> dict | None:
        try:
            with open(path) as f:
                body = json.load(f)
        except (OSError, ValueError):
            return None
        if body.get("checksum", "") != _manifest_checksum(body):
            return None
        if body.get("record") != "publish_epoch":
            return None
        return body

    def committed_epochs(self) -> list[int]:
        out = []
        for path in sorted(glob.glob(os.path.join(self.root, "epoch-*.json"))):
            rec = self._read_record(path)
            if rec is not None:
                out.append(int(rec["epoch"]))
        return sorted(out)

    def committed_epoch(self) -> int:
        """The highest epoch with a valid durable commit record — THE
        reader rule (0 = nothing committed yet). A staged-but-
        uncommitted generation is invisible here by construction."""
        epochs = self.committed_epochs()
        return epochs[-1] if epochs else 0

    def version_vector(self, epoch: int | None = None) -> dict:
        """The committed epoch's shard → version map (empty when nothing
        is committed)."""
        e = self.committed_epoch() if epoch is None else int(epoch)
        if e <= 0:
            return {}
        rec = self._read_record(self._record_path(e))
        if rec is None:
            return {}
        return {int(k): int(v) for k, v in rec["version_vector"].items()}

    def read_epoch(self, epoch: int | None = None) -> dict | None:
        """Load EVERY shard's arrays from one committed epoch directory,
        verifying each sha256 — the multi-host read surface. All shards
        come from the ONE epoch the commit record names, so a reader can
        never observe a half-visible multi-range batch: the no-mixed-
        epoch-reads guarantee is structural, not a convention. ``None``
        when nothing is committed; damaged bytes raise."""
        e = self.committed_epoch() if epoch is None else int(epoch)
        if e <= 0:
            return None
        rec = self._read_record(self._record_path(e))
        if rec is None:
            raise FileNotFoundError(
                f"epoch {e} has no valid commit record at "
                f"{self._record_path(e)!r} — it was never committed"
            )
        final = self._final_dir(e)
        shards = {}
        for sdir in sorted(glob.glob(os.path.join(final, "shard-*"))):
            with open(os.path.join(sdir, MANIFEST_NAME)) as f:
                body = json.load(f)
            if body.get("checksum", "") != _manifest_checksum(body):
                raise ValueError(
                    f"shard manifest at {sdir!r} failed its checksum"
                )
            arrays = {}
            for name, ent in body.get("arrays", {}).items():
                path = os.path.join(sdir, ent["file"])
                sha = _file_sha256(path)
                if sha != ent["sha256"]:
                    raise ValueError(
                        f"shard array {name!r} at {path!r} failed its "
                        f"sha256 ({sha[:12]}... != {ent['sha256'][:12]}...)"
                    )
                arrays[name] = np.load(path)
            shards[int(body["shard"])] = {
                "arrays": arrays,
                "version": int(body.get("version", 0)),
                "range": tuple(body.get("range", (0, 0))),
            }
        return {
            "epoch": e,
            "version_vector": {
                int(k): int(v) for k, v in rec["version_vector"].items()
            },
            "shards": shards,
        }

    def _stage_complete(self, stage: str) -> bool:
        """Every shard directory present with a checksum-valid manifest
        and all its (non-empty) array files — the recover() verdict on
        whether a torn stage can be finished."""
        sdirs = sorted(glob.glob(os.path.join(stage, "shard-*")))
        if not sdirs:
            return False
        for sdir in sdirs:
            try:
                with open(os.path.join(sdir, MANIFEST_NAME)) as f:
                    body = json.load(f)
            except (OSError, ValueError):
                return False
            if body.get("checksum", "") != _manifest_checksum(body):
                return False
            for ent in body.get("arrays", {}).values():
                try:
                    size = os.path.getsize(os.path.join(sdir, ent["file"]))
                except (OSError, KeyError, TypeError):
                    return False
                if size <= 0:
                    return False
        return True

    def recover(self) -> dict:
        """Restart-path convergence after a coordinator crash: finish
        any complete generation newer than the committed epoch (rename
        if still staged, then write the missing commit record — its
        version vector recovered from the per-shard manifests), sweep
        incomplete stages, and report what happened. Runs under the
        fence lock so a concurrently-restarted coordinator can't race
        the same generation."""
        recommitted, swept = [], []
        with self.store.fence_lock():
            committed = self.committed_epoch()
            # final dirs whose commit record is missing: the crash
            # landed between the rename and the record write
            for path in sorted(glob.glob(os.path.join(self.root, "epoch-*"))):
                base = os.path.basename(path)
                if base.endswith(".json") or base.endswith(".stage"):
                    continue
                if not os.path.isdir(path):
                    continue
                try:
                    e = int(base.split("-", 1)[1])
                except (IndexError, ValueError):
                    continue
                if e <= committed:
                    continue
                if self._read_record(self._record_path(e)) is not None:
                    continue
                if self._stage_complete(path):
                    self._write_record_locked(e, self._vector_from_dir(path))
                    recommitted.append(e)
                else:
                    shutil.rmtree(path, ignore_errors=True)
                    swept.append(e)
            for stage in sorted(
                glob.glob(os.path.join(self.root, "epoch-*.stage"))
            ):
                try:
                    e = int(
                        os.path.basename(stage)[: -len(".stage")].split(
                            "-", 1
                        )[1]
                    )
                except (IndexError, ValueError):
                    shutil.rmtree(stage, ignore_errors=True)
                    continue
                if e > committed and self._stage_complete(stage):
                    final = self._final_dir(e)
                    shutil.rmtree(final, ignore_errors=True)
                    os.replace(stage, final)
                    _fsync_dir(self.root)
                    self._write_record_locked(e, self._vector_from_dir(final))
                    recommitted.append(e)
                else:
                    shutil.rmtree(stage, ignore_errors=True)
                    swept.append(e)
        for e in recommitted:
            emit_shard_record(
                self.sink, "epoch_commit", epoch=e,
                version_vector={
                    str(k): v for k, v in self.version_vector(e).items()
                },
                shards=self.plan.num_shards, recovered=True,
            )
        return {
            "committed_epoch": self.committed_epoch(),
            "recommitted": sorted(set(recommitted)),
            "swept": sorted(set(swept)),
        }

    def _vector_from_dir(self, gen_dir: str) -> dict:
        vec = {}
        for sdir in sorted(glob.glob(os.path.join(gen_dir, "shard-*"))):
            try:
                with open(os.path.join(sdir, MANIFEST_NAME)) as f:
                    body = json.load(f)
                vec[int(body["shard"])] = int(body.get("version", 0))
            except (OSError, ValueError, KeyError):
                continue
        return vec

    def _retire(self) -> None:
        """Keep the newest :data:`RETAIN_EPOCHS` committed generations;
        older ones (dir + record) drop — the two-generation snapshot
        discipline applied to epochs."""
        epochs = self.committed_epochs()
        for e in epochs[: -self.RETAIN_EPOCHS]:
            shutil.rmtree(self._final_dir(e), ignore_errors=True)
            try:
                os.remove(self._record_path(e))
            except OSError:
                pass

    def snapshot(self) -> dict:
        e = self.committed_epoch()
        return {
            "committed_epoch": e,
            "version_vector": {
                str(k): v for k, v in self.version_vector(e).items()
            },
            "retained_epochs": self.committed_epochs(),
        }


# ---- the sharded write plane ------------------------------------------------


class _WriterShard:
    """One vertex range's writer state: its own WAL, admission ladder,
    debt ledger, availability verdict and optional standby WAL copy."""

    __slots__ = (
        "shard", "lo", "hi", "wal", "admission", "debt", "read_only",
        "reason", "standby", "version",
    )

    def __init__(self, shard, lo, hi, wal, admission, debt):
        self.shard = shard
        self.lo = lo
        self.hi = hi
        self.wal = wal
        self.admission = admission
        self.debt = debt
        self.read_only = False
        self.reason = ""
        self.standby: WriteAheadLog | None = None
        self.version = 0   # last published version that touched this range


class _ShardSink:
    """Sink proxy tagging every record with its shard — the per-range
    twin of the server's ``_TenantSink`` (absent key = unsharded, so
    every pre-shard record stays valid)."""

    __slots__ = ("_sink", "_shard")

    def __init__(self, sink, shard: int):
        self._sink = sink
        self._shard = int(shard)

    def emit(self, phase: str, **kv):
        kv.setdefault("shard", self._shard)
        return self._sink.emit(phase, **kv)

    def __getattr__(self, name):
        return getattr(self._sink, name)


class ShardedWritePlane:
    """Per-range writer shards for ONE tenant's namespace.

    Composition contract: tenancy splits the store by namespace, the
    plane splits each namespace's vertex-range space — so a 2-tenant /
    3-shard deployment runs 6 independent (WAL, admission, debt) triples
    and one coordinator per tenant. The plane owns durability and
    range-availability; the server's apply worker still owns the actual
    splice/repair (driving the ORIGINAL unsplit batch — see
    :func:`split_delta` for why that is bit-exact).
    """

    def __init__(
        self,
        store,
        plan: ShardPlan,
        sink=None,
        registry=None,
        tenant: str = DEFAULT_TENANT,
        wal_root: str | None = None,
        admission_bounds: AdmissionBounds | None = None,
    ):
        self.store = store
        self.plan = plan
        self.sink = sink
        self.registry = registry
        self.tenant = tenant or DEFAULT_TENANT
        self.coordinator = EpochCoordinator(store, plan, sink=sink)
        self._base = wal_root or os.path.join(store.root, SHARDS_DIRNAME)
        self._lock = threading.Lock()
        bounds = (
            admission_bounds if admission_bounds is not None
            else AdmissionBounds.from_env()
        )
        self.bounds = bounds
        self.shards: list[_WriterShard] = []
        for i in range(plan.num_shards):
            lo, hi = plan.range_of(i)
            shard_sink = None if sink is None else _ShardSink(sink, i)
            wal = WriteAheadLog(
                self._wal_dir(i), sink=shard_sink, registry=registry,
                shard=i,
            )
            adm = AdmissionController(
                bounds=bounds, sink=shard_sink, registry=None,
                tenant=self.tenant,
            )
            self.shards.append(
                _WriterShard(i, lo, hi, wal, adm, RepairDebt())
            )

    def _wal_dir(self, shard: int) -> str:
        return os.path.join(self._base, f"shard-{int(shard):03d}", "wal")

    def _standby_dir(self, shard: int) -> str:
        return os.path.join(
            self._base, f"shard-{int(shard):03d}", "standby-wal"
        )

    # -- write path --------------------------------------------------------
    def submit(
        self,
        delta: EdgeDelta,
        delta_id: str = "",
        deadline_s: float | None = None,
        queue_depth: int = 0,
        applying: bool = False,
        trace: str = "",
        replay: bool = False,
    ) -> dict:
        """Admit + durably log one batch across its owner shards.

        Returns ``{"verdict": ..., "splits": [...], "shard_seqs": {...}}``:

        - ``"refused"`` never happens silently — a dead range raises
          :class:`ShardRangeUnavailableError` (503; untouched ranges are
          unaffected because THEIR submit calls don't touch this one);
        - ``"shed"`` when any owner shard's admission ladder refuses
          (one saturated range sheds the whole batch — a partial accept
          would make the batch's visibility non-atomic);
        - ``"duplicate"`` when every touched shard already holds
          ``delta_id`` (a clean retry);
        - ``"accepted"`` with ``shard_seqs`` = {shard: seq} — the
          ``(delta_id, shard)`` dedupe pairs. A retry after a PARTIAL
          accept appends only to the shards that missed it, so each
          shard stays exactly-once.
        """
        splits = split_delta(delta, self.plan)
        touched = [sp.shard for sp in splits]
        dead = [s for s in touched if self.shards[s].read_only]
        if dead:
            parts = ", ".join(
                f"shard {s} [{self.shards[s].lo},{self.shards[s].hi})"
                f" ({self.shards[s].reason or 'read_only'})"
                for s in dead
            )
            raise ShardRangeUnavailableError(
                f"batch touches degraded vertex range(s): {parts}; "
                "untouched ranges keep accepting writes — retry after "
                "the range recovers or its standby promotes",
                shards=dead,
            )
        # Per-shard dedupe: (delta_id, shard) — each shard's own log is
        # the authority for its half of a retried batch.
        shard_seqs: dict[int, int] = {}
        missing = []
        for sp in splits:
            ws = self.shards[sp.shard]
            seq = ws.wal.lookup(delta_id, tenant=self.tenant) if delta_id else None
            if seq is not None:
                shard_seqs[sp.shard] = int(seq)
            else:
                missing.append(sp)
        if delta_id and not missing:
            return {
                "verdict": "duplicate",
                "splits": splits,
                "shard_seqs": shard_seqs,
                "applied": all(
                    self.shards[s].wal.seq_applied(q)
                    for s, q in shard_seqs.items()
                ),
            }
        # Admission: every missing shard's ladder must accept before any
        # append — all-or-nothing, so a shed can't strand a half-durable
        # batch.
        decisions = []
        for sp in missing:
            ws = self.shards[sp.shard]
            rows = sp.delta.num_inserts + sp.delta.num_deletes
            debt_at = ws.debt.snapshot()
            decision = ws.admission.resolve(
                rows=rows, queue_depth=queue_depth, debt=debt_at,
                applying=applying, emit=True, replay=replay,
            )
            decisions.append((sp, rows, decision, debt_at))
            if decision.verdict == "shed":
                ws.debt.shed(rows)
                ws.admission.record_shed(
                    decision.reason, rows, decision.queue_depth,
                    ws.debt.snapshot(),
                )
                return {
                    "verdict": "shed",
                    "shard": sp.shard,
                    "reason": (
                        f"shard {sp.shard} "
                        f"[{ws.lo},{ws.hi}): {decision.reason}"
                    ),
                    "retry_after_s": decision.retry_after_s,
                    "splits": splits,
                    "shard_seqs": {},
                }
        # Durability: append each sub-batch to its owner shard's WAL
        # (fsync per append — the shard's acceptance is on disk before
        # the caller hears "accepted").
        for sp, rows, decision, debt_at in decisions:
            ws = self.shards[sp.shard]
            payload = _split_payload(sp)
            seq, dup = ws.wal.append(
                payload, delta_id=delta_id or "", deadline_s=deadline_s,
                trace=trace, tenant=self.tenant,
            )
            shard_seqs[sp.shard] = int(seq)
            ws.debt.submitted(rows)
        return {
            "verdict": "accepted",
            "splits": splits,
            "shard_seqs": shard_seqs,
        }

    def commit_applied(self, shard_seqs: dict, version: int) -> None:
        """Per-shard watermark advance after the publish that absorbed
        these seqs — also records the version into each touched range's
        slot of the version vector."""
        for shard, seq in shard_seqs.items():
            ws = self.shards[int(shard)]
            seqs = seq if isinstance(seq, (list, tuple, set)) else [seq]
            ws.wal.commit_applied([int(s) for s in seqs], int(version))
            ws.version = int(version)

    def skip(self, shard_seqs: dict) -> None:
        """Tombstone a durable-but-shed batch on every shard that logged
        it (deadline expiry before apply)."""
        for shard, seq in shard_seqs.items():
            try:
                self.shards[int(shard)].wal.skip(int(seq))
            except OSError:
                pass  # best-effort, same as the single-WAL path

    def version_vector(self) -> dict:
        return {ws.shard: int(ws.version) for ws in self.shards}

    def note_versions(self, vector: dict) -> None:
        """Adopt a committed epoch's version vector (startup: the plane
        resumes where the last committed epoch left each range)."""
        for shard, v in vector.items():
            s = int(shard)
            if 0 <= s < len(self.shards):
                self.shards[s].version = int(v)

    # -- per-range failover ------------------------------------------------
    def kill_shard(self, shard: int, reason: str = "writer_shard_kill") -> None:
        """Simulated shard death (the ``writer_shard_kill`` injector's
        target): the shard's WAL handle closes un-flushed, the range
        flips read-only, every OTHER range keeps accepting. Durability
        holds by construction — every acked seq was fsync'd at append."""
        ws = self.shards[int(shard)]
        ws.wal.close()
        ws.read_only = True
        ws.reason = reason
        emit_shard_record(
            self.sink, "shard_degraded", shard=int(shard),
            status="read_only", reason=reason, range=[ws.lo, ws.hi],
            tenant=self.tenant,
        )

    def restart_shard(self, shard: int) -> list[dict]:
        """Reopen a dead shard's WAL (open-time recovery: torn tail
        tolerated, acked prefix intact) and return its accepted-but-
        unapplied entries — the replay work list the server re-enqueues.
        The range re-opens for writes."""
        ws = self.shards[int(shard)]
        ws.wal = WriteAheadLog(
            self._wal_dir(shard),
            sink=None if self.sink is None else _ShardSink(self.sink, shard),
            registry=self.registry, shard=int(shard),
        )
        pending = ws.wal.pending()
        ws.read_only = False
        ws.reason = ""
        emit_shard_record(
            self.sink, "shard_degraded", shard=int(shard),
            status="recovered", reason="wal replayed after restart",
            pending=len(pending), range=[ws.lo, ws.hi], tenant=self.tenant,
        )
        return pending

    def attach_standby(self, shard: int) -> WriteAheadLog:
        """Create/open the shard's log-shipped standby copy (same-
        filesystem deployment: the ship path is WAL.copy_from, the same
        verbatim-copy machinery LogShipper drives over HTTP)."""
        ws = self.shards[int(shard)]
        if ws.standby is None:
            ws.standby = WriteAheadLog(
                self._standby_dir(shard), sink=None, registry=None,
                shard=int(shard),
            )
        return ws.standby

    def ship_shard(self, shard: int) -> int:
        """One shipping pass: copy the shard's un-shipped tail into its
        standby verbatim (same seq, same id). Returns entries copied."""
        ws = self.shards[int(shard)]
        if ws.standby is None:
            return 0
        entries = ws.wal.entries(ws.standby.last_seq + 1)
        return ws.standby.copy_from(entries)

    def promote_shard(self, shard: int) -> dict:
        """Promote a dead shard's standby copy via the fenced path:
        mint the next writer epoch through the store's fence lock (the
        coordinator's serialization point — a deposed shard writer is
        fenced before the standby owns the range), swap the standby WAL
        in as the shard's log, re-open the range. Returns the pending
        entries to replay plus the minted epoch."""
        ws = self.shards[int(shard)]
        if ws.standby is None:
            raise ValueError(
                f"shard {int(shard)} has no standby to promote "
                "(attach_standby + ship_shard first)"
            )
        epoch = self.store.advance_epoch(
            sink=self.sink,
            reason=f"shard {int(shard)} standby promoted",
        )
        ws.wal = ws.standby
        ws.standby = None
        pending = ws.wal.pending()
        ws.read_only = False
        ws.reason = ""
        emit_shard_record(
            self.sink, "shard_degraded", shard=int(shard),
            status="promoted", reason=f"standby promoted at epoch {epoch}",
            epoch=int(epoch), pending=len(pending), range=[ws.lo, ws.hi],
            tenant=self.tenant,
        )
        return {"epoch": int(epoch), "pending": pending}

    # -- observability -----------------------------------------------------
    def snapshot(self) -> dict:
        """The plane's status page section: the range table with each
        shard's availability, WAL seqs/backlog and last-touch version,
        plus the committed epoch."""
        return {
            "num_shards": self.plan.num_shards,
            "plan": self.plan.snapshot(),
            "epoch": self.coordinator.committed_epoch(),
            "shards": [
                {
                    "shard": ws.shard,
                    "lo": ws.lo,
                    "hi": ws.hi,
                    "owns_growth": ws.shard == self.plan.num_shards - 1,
                    "read_only": ws.read_only,
                    "reason": ws.reason,
                    "version": int(ws.version),
                    "standby": ws.standby is not None,
                    "wal": ws.wal.snapshot(),
                    "admission": ws.admission.snapshot(),
                    "repair_debt": ws.debt.snapshot(),
                }
                for ws in self.shards
            ],
        }

    def close(self) -> None:
        for ws in self.shards:
            ws.wal.close()
            if ws.standby is not None:
                ws.standby.close()


def _split_payload(sp: DeltaSplit) -> dict:
    """The wire-shaped payload one shard's WAL frame carries: the
    sub-batch as insert/delete pair (or weighted-triple) lists, plus the
    original row indices so a replayed frame can participate in a
    bit-exact merge."""
    d = sp.delta
    if d.insert_weight is not None:
        insert = [
            [int(s), int(t), float(w)]
            for s, t, w in zip(d.insert_src, d.insert_dst, d.insert_weight)
        ]
    else:
        insert = [
            [int(s), int(t)] for s, t in zip(d.insert_src, d.insert_dst)
        ]
    return {
        "insert": insert,
        "delete": [
            [int(s), int(t)] for s, t in zip(d.delete_src, d.delete_dst)
        ],
        "shard": int(sp.shard),
        "insert_index": [int(i) for i in sp.insert_index],
        "delete_index": [int(i) for i in sp.delete_index],
    }
