"""Tenant namespace primitives — the identity layer of multi-tenant serving.

"Millions of users" is not one giant graph: it is many per-customer
community/outlier graphs behind ONE serving plane. This module owns the
two primitives every other serve/ layer builds on (ISSUE 16):

* **Tenant ids** are validated against a deliberately boring grammar
  (``[a-z0-9_-]{1,64}``, :data:`TENANT_RE`). Ids become path components
  under ``<root>/tenants/`` in the snapshot store and durable values in
  WAL frames and JSONL records, so the grammar admits no separators, no
  dots, no case-folding surprises — a hostile id (``../../etc``, an
  absolute path, a null byte) fails :func:`validate_tenant_id` with
  ``ValueError``, which the HTTP middleware maps to 400 before any path
  is built (pinned by tests/test_tenancy.py).

* **The** :class:`TenantRegistry` enumerates known tenants and owns the
  per-tenant policy that must NOT live in any single request path:
  per-tenant :class:`~graphmine_tpu.serve.admission.AdmissionBounds`
  overrides (defaults shared — ``GRAPHMINE_ADMIT_*`` stays the global
  baseline; a tenant's override dict adjusts only the named knobs) and
  per-tenant ``Snapshot.nbytes`` accounting so the serve memory model
  becomes the *packing oracle*: per-tenant bytes vs
  ``GRAPHMINE_SERVE_MEM_BUDGET_BYTES`` on ``/statusz`` while
  ``mem_headroom_low`` stays fleet-wide (one HBM budget, many tenants).

The default tenant (:data:`DEFAULT_TENANT`) is the back-compat spine:
every pre-tenancy store layout, WAL frame, record and endpoint maps to
it unchanged, so single-tenant deployments never see this module.

Per-tenant overrides can also be seeded from the environment:
``GRAPHMINE_TENANT_BOUNDS`` is a JSON object mapping tenant id to an
override dict, e.g. ``{"acme": {"max_pending_rows": 5000}}``.
"""

from __future__ import annotations

import json
import os
import re
import threading

DEFAULT_TENANT = "default"

# Tenant ids become path components and durable record values; the
# grammar is hostile-input-proof by construction — no separators, no
# dots, nothing a path traversal can ride. fullmatch only.
TENANT_RE = re.compile(r"[a-z0-9_-]{1,64}")

_ENV_BOUNDS = "GRAPHMINE_TENANT_BOUNDS"


class UnknownTenantError(KeyError):
    """A syntactically valid tenant id with no store namespace behind it.

    Distinct from ``ValueError`` (a hostile/malformed id — HTTP 400) on
    purpose: the serve middleware maps THIS to **404**, the same answer
    a valid vertex id under the wrong tenant gets — existence of other
    tenants' data must never be distinguishable from a miss."""


def validate_tenant_id(tenant) -> str:
    """Return ``tenant`` if it matches the tenant-id grammar; raise
    ``ValueError`` (the serve middleware's 400) otherwise. The check is
    ``fullmatch`` on purpose: a prefix-valid id like ``a/../b`` must
    die here, never reach ``os.path.join``."""
    if not isinstance(tenant, str) or not TENANT_RE.fullmatch(tenant):
        raise ValueError(
            f"invalid tenant id {tenant!r}: tenant ids must match "
            "[a-z0-9_-]{1,64}"
        )
    return tenant


class TenantRegistry:
    """Known tenants + per-tenant admission policy + per-tenant bytes.

    Thread-safe; one instance per server (the fleet router keeps none —
    tenancy is replica state, the router only relays the header). The
    registry is deliberately *not* the source of truth for which tenants
    exist on disk — :meth:`SnapshotStore.list_tenants
    <graphmine_tpu.serve.snapshot.SnapshotStore.list_tenants>` is — it
    tracks the tenants THIS process has served plus any with explicit
    overrides, so an empty store still answers policy questions.
    """

    def __init__(self, overrides: dict | None = None):
        self._lock = threading.Lock()
        self._overrides: dict[str, dict] = {}
        self._nbytes: dict[str, int] = {}
        self._known: set[str] = {DEFAULT_TENANT}
        env = os.environ.get(_ENV_BOUNDS, "")
        if env:
            try:
                parsed = json.loads(env)
                if not isinstance(parsed, dict):
                    raise ValueError("not a JSON object")
            except ValueError as e:
                raise ValueError(
                    f"{_ENV_BOUNDS} must be a JSON object mapping tenant id "
                    f"to an AdmissionBounds override dict: {e}"
                ) from e
            for tid, kv in parsed.items():
                self.set_overrides(tid, **dict(kv))
        for tid, kv in (overrides or {}).items():
            self.set_overrides(tid, **dict(kv))

    # -- enumeration -------------------------------------------------------
    def note(self, tenant: str) -> str:
        """Record that ``tenant`` exists (validated); returns the id."""
        tenant = validate_tenant_id(tenant)
        with self._lock:
            self._known.add(tenant)
        return tenant

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._known)

    # -- per-tenant admission policy ---------------------------------------
    def set_overrides(self, tenant: str, **bounds) -> None:
        """Replace ``tenant``'s AdmissionBounds overrides (validated
        keys happen at ``bounds_for`` time, where AdmissionBounds'
        dataclass signature is the schema)."""
        tenant = validate_tenant_id(tenant)
        with self._lock:
            self._known.add(tenant)
            if bounds:
                self._overrides[tenant] = dict(bounds)
            else:
                self._overrides.pop(tenant, None)

    def bounds_for(self, tenant: str):
        """The tenant's :class:`AdmissionBounds`: the shared env/default
        ladder with this tenant's overrides applied on top. Import is
        lazy to keep this module stdlib-only (snapshot.py imports it,
        and admission → delta → snapshot would otherwise cycle)."""
        from graphmine_tpu.serve.admission import AdmissionBounds

        tenant = validate_tenant_id(tenant)
        with self._lock:
            kv = dict(self._overrides.get(tenant, {}))
        return AdmissionBounds.from_env(**kv)

    def overrides_for(self, tenant: str) -> dict:
        tenant = validate_tenant_id(tenant)
        with self._lock:
            return dict(self._overrides.get(tenant, {}))

    # -- packing oracle ----------------------------------------------------
    def note_bytes(self, tenant: str, nbytes: int) -> None:
        """Record ``tenant``'s resident snapshot payload bytes (the
        server calls this on every engine swap)."""
        tenant = validate_tenant_id(tenant)
        with self._lock:
            self._known.add(tenant)
            self._nbytes[tenant] = int(nbytes)

    def bytes_by_tenant(self) -> dict[str, int]:
        with self._lock:
            return dict(self._nbytes)

    def memory_payload(self, budget_bytes: int | None) -> dict:
        """The packing-oracle view for ``/statusz``: per-tenant resident
        snapshot bytes against the ONE fleet-wide serve memory budget.
        ``fits`` answers "could I add tenant X's bytes to this replica"
        for a balancer; headroom stays fleet-wide because the budget
        is the machine's, not a tenant's."""
        with self._lock:
            per = dict(self._nbytes)
        total = int(sum(per.values()))
        out = {
            "tenants": {t: int(b) for t, b in sorted(per.items())},
            "total_snapshot_bytes": total,
        }
        if budget_bytes:
            out["budget_bytes"] = int(budget_bytes)
            out["headroom_bytes"] = int(budget_bytes) - total
            out["fits"] = total <= int(budget_bytes)
        return out

    def snapshot(self) -> dict:
        """Introspection payload (``/statusz`` ``tenancy`` section)."""
        with self._lock:
            return {
                "tenants": sorted(self._known),
                "overrides": {
                    t: dict(kv) for t, kv in sorted(self._overrides.items())
                },
                "snapshot_bytes": dict(self._nbytes),
            }
