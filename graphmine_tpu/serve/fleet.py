"""Replicated serving fleet: a front-tier router over N snapshot replicas.

One process serving snapshots (serve/server.py) is hardened end-to-end —
double-buffered swaps, admission control, deadline shedding — but the
moment the ROADMAP's "millions of users" need more than one process, the
failure domain moves to the *fleet*: a dead replica, a slow replica, or
a replica serving a stale snapshot version must never surface to readers
as an error or a mixed-version answer. This module is that tier
(docs/SERVING.md "Fleet"):

- :class:`ReplicaSet` — per-replica state machine (``joining`` →
  ``healthy`` → ``degraded`` → ``draining`` → ``down``) driven by a
  background prober reading the replicas' existing ``/healthz`` fields
  (``ready``, ``version``, ``overloaded``, ``snapshot_age_s``,
  ``lof_stale`` — the drain signals r6/r8 landed precisely so a
  balancer could act on them), plus the fleet's **committed version**:
  the max snapshot version held by a read quorum, monotonic by
  construction.
- **Consistent-version routing** — reads route ONLY to replicas at the
  committed version. Every response echoes ``X-Pinned-Version`` (the
  version it was served at); a replica that swapped mid-flight answers
  409 to the router's ``X-Serve-Version`` pin and the router retries
  elsewhere, so one request — and one client session across retries —
  never observes mixed versions. Committed is monotonic, so sessions
  get monotonic reads with no client-side state beyond the echo.
- **Per-replica circuit breakers** — an error/timeout-rate threshold
  opens the breaker (the replica stops receiving reads), a
  decorrelated-jitter backoff (the r3 retry policy,
  :func:`~graphmine_tpu.pipeline.resilience.backoff_s`) schedules a
  **half-open single probe** by the prober, and one clean probe closes
  it. Cross-replica retry is bounded by the propagated request deadline
  (``X-Deadline-Ms``, the r9 deadline semantics extended end-to-end);
  when no replica is eligible the router answers **503 + Retry-After**.
- **Single-writer forwarding** — POST ``/delta`` and ``/reload``
  forward to the designated writer replica (one publisher per store is
  the r7 contract). Writer loss degrades the fleet to READ-ONLY with a
  loud ``fleet_degraded`` record — never a second *concurrent* writer,
  never split-brain; the same writer coming back (same identity, not an
  election) restores writes with a matching record. Non-writer
  replicas catch up to the writer's publishes via the prober's
  ``/reload`` cadence.
- **Fenced failover onto a log-shipped standby** (r11, docs/SERVING.md
  "Replicated writers") — with a ``standby`` replica configured (one
  running ``standby_of=<writer url>``, tailing the writer's WAL), the
  read-only degradation is *transient*: the prober detects writer DOWN,
  POSTs the standby's ``/promote`` (fence the store epoch → replay the
  WAL tail → resume writes) and re-points write forwarding at it —
  bounded time-to-writable with zero acknowledged-delta loss, every
  step a ``writer_promote`` record. The deposed writer rejoining is
  just a read replica (and the new standby candidate); its comeback
  publish is refused AT THE STORE by the epoch fence
  (``publish_fenced``) — split-brain is impossible, not merely refused
  by convention. Without a standby, r10's loud read-only behavior is
  unchanged.
- **Zero-downtime rolling reload** — :meth:`FleetRouter.rolling_reload`
  drains one replica at a time (``draining`` replicas receive no
  reads), POSTs ``/reload``, re-probes until it is ready at the new
  version, and rejoins it — aborting the roll if draining would drop
  the fleet below ``min_healthy``. The writer rolls last so write
  availability is the last thing to blink.

Every router decision emits schema-registered provenance
(``replica_health``, ``breaker_transition``, ``fleet_route``,
``fleet_degraded`` — obs/schema.py), rendered by ``tools/obs_report.py``
as the fleet section. The chaos injectors (``testing/faults.py``:
``replica_kill`` / ``replica_slow`` / ``replica_stale``) and the 3-replica
acceptance test (``tests/test_fleet.py``, marker ``fleet``) pin the
contract: kill + slow + rolling reload under a live read hammer with
zero failed reads and zero mixed-version responses.

All router logic is stdlib + the repo's host-side modules (obs
registry, the r3 backoff policy) — no device work, no compiles, zero
jax calls on any router path. (Importing the package does pull the
usual ``graphmine_tpu`` import chain; the router just never touches a
device.)
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import error as urlerror
from urllib import request as urlrequest
from urllib.parse import urlparse

from graphmine_tpu.obs.histogram import Histogram
from graphmine_tpu.obs.memmodel import (
    export_memory_gauges,
    host_memory,
    serve_mem_budget_bytes,
)
from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.sketch import QuantileSketch
from graphmine_tpu.obs.spans import (
    TRACE_HEADER,
    TraceContext,
    sink_trace_header,
)
from graphmine_tpu.pipeline.resilience import ResilienceConfig, backoff_s

# Replica states (the per-replica machine the prober drives).
JOINING = "joining"      # known but not yet confirmed ready at a version
HEALTHY = "healthy"      # probed ok, ready, read-eligible
DEGRADED = "degraded"    # probed ok but flagged (not ready / overloaded /
#                          breaker open) — still read-eligible as a last
#                          resort, preferred below healthy replicas
DRAINING = "draining"    # receiving no new reads (rolling reload owns it)
DOWN = "down"            # consecutive probe failures; not routable

# Breaker states.
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_ENV = {
    "probe_interval_s": ("GRAPHMINE_FLEET_PROBE_INTERVAL_S", float),
    "probe_timeout_s": ("GRAPHMINE_FLEET_PROBE_TIMEOUT_S", float),
    "read_timeout_s": ("GRAPHMINE_FLEET_READ_TIMEOUT_S", float),
    "write_timeout_s": ("GRAPHMINE_FLEET_WRITE_TIMEOUT_S", float),
    "default_deadline_ms": ("GRAPHMINE_FLEET_DEFAULT_DEADLINE_MS", int),
    "retry_after_s": ("GRAPHMINE_FLEET_RETRY_AFTER_S", float),
    "down_after_probes": ("GRAPHMINE_FLEET_DOWN_AFTER_PROBES", int),
    "min_healthy": ("GRAPHMINE_FLEET_MIN_HEALTHY", int),
    "quorum": ("GRAPHMINE_FLEET_QUORUM", int),
    "reload_cadence_s": ("GRAPHMINE_FLEET_RELOAD_CADENCE_S", float),
    "reload_timeout_s": ("GRAPHMINE_FLEET_RELOAD_TIMEOUT_S", float),
    "rejoin_timeout_s": ("GRAPHMINE_FLEET_REJOIN_TIMEOUT_S", float),
    "drain_grace_s": ("GRAPHMINE_FLEET_DRAIN_GRACE_S", float),
    "breaker_window": ("GRAPHMINE_FLEET_BREAKER_WINDOW", int),
    "breaker_open_failures": ("GRAPHMINE_FLEET_BREAKER_OPEN_FAILURES", int),
    "breaker_open_rate": ("GRAPHMINE_FLEET_BREAKER_OPEN_RATE", float),
    "breaker_backoff_base_s": ("GRAPHMINE_FLEET_BREAKER_BACKOFF_BASE_S", float),
    "breaker_backoff_max_s": ("GRAPHMINE_FLEET_BREAKER_BACKOFF_MAX_S", float),
    "promote_timeout_s": ("GRAPHMINE_FLEET_PROMOTE_TIMEOUT_S", float),
}


@dataclass(frozen=True)
class FleetConfig:
    """The fleet envelope. Immutable — policy changes are a new config,
    not a mutated one (the AdmissionBounds contract). Every field is
    ``GRAPHMINE_FLEET_*`` env-overridable via :meth:`from_env`."""

    probe_interval_s: float = 0.25
    # The health probe is deliberately GENEROUS next to the data-plane
    # timeout: a slow replica still answers /healthz (alive), while its
    # data-plane timeouts open the breaker (unusable) — two different
    # verdicts, two different mechanisms.
    probe_timeout_s: float = 5.0
    read_timeout_s: float = 0.5       # per-attempt data-plane timeout
    write_timeout_s: float = 120.0    # forwarded /delta and /reload
    default_deadline_ms: int = 2000   # when the client sends no X-Deadline-Ms
    retry_after_s: float = 1.0        # the 503 hint when no replica is eligible
    down_after_probes: int = 2        # consecutive probe failures -> DOWN
    min_healthy: int = 1              # rolling reload aborts below this
    quorum: int = 0                   # 0 = majority of configured replicas
    reload_cadence_s: float = 0.25    # min gap between prober catch-up reloads
    reload_timeout_s: float = 30.0    # one forwarded/rolling /reload
    rejoin_timeout_s: float = 30.0    # rolled replica must re-probe ready
    drain_grace_s: float = 0.05       # in-flight settle before a rolled reload
    breaker_window: int = 8           # outcomes in the rolling window
    breaker_open_failures: int = 3    # min failures in window to open
    breaker_open_rate: float = 0.5    # min failure rate in window to open
    breaker_backoff_base_s: float = 0.5
    breaker_backoff_max_s: float = 8.0
    promote_timeout_s: float = 60.0   # one standby /promote exchange

    def __post_init__(self):
        if self.probe_interval_s <= 0 or self.probe_timeout_s <= 0:
            raise ValueError("probe interval/timeout must be > 0")
        if self.read_timeout_s <= 0 or self.write_timeout_s <= 0:
            raise ValueError("read/write timeouts must be > 0")
        if self.down_after_probes < 1 or self.min_healthy < 0:
            raise ValueError("down_after_probes >= 1, min_healthy >= 0")
        if self.quorum < 0:
            raise ValueError("quorum must be >= 0 (0 = majority)")
        if self.breaker_window < 1 or self.breaker_open_failures < 1:
            raise ValueError("breaker window/open_failures must be >= 1")
        if not 0 < self.breaker_open_rate <= 1:
            raise ValueError("breaker_open_rate must be in (0, 1]")

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        """``GRAPHMINE_FLEET_*`` env; explicit kwargs beat env; malformed
        env raises loudly (the AdmissionBounds rule)."""
        kv = {}
        for field_name, (var, parse) in _ENV.items():
            raw = os.environ.get(var)
            if raw is None or field_name in overrides:
                continue
            try:
                kv[field_name] = parse(raw)
            except ValueError as e:
                raise ValueError(
                    f"{var}={raw!r} is not a valid {parse.__name__}"
                ) from e
        kv.update(overrides)
        return cls(**kv)

    def snapshot(self) -> dict:
        return {k: getattr(self, k) for k in _ENV}


@dataclass(frozen=True)
class ReplicaSpec:
    """One replica's identity and address."""

    id: str
    host: str
    port: int


class CircuitBreaker:
    """Per-replica data-plane circuit breaker.

    ``closed``: requests flow; outcomes fill a rolling window, and a
    failure count + rate past the policy thresholds opens it.
    ``open``: no requests; a decorrelated-jitter backoff (the r3
    :func:`~graphmine_tpu.pipeline.resilience.backoff_s` policy, attempt
    = consecutive open episodes — seeded per replica+process so a fleet
    of breakers never re-probes in lockstep) schedules the half-open
    transition. ``half_open``: exactly one probe decides — success
    closes and resets, failure re-opens with a longer backoff.

    The data plane calls :meth:`allow_request` / :meth:`record_success`
    / :meth:`record_failure`; the prober calls :meth:`probe_due` and
    :meth:`probe_result` (the half-open single probe is out-of-band, so
    client traffic is never spent discovering that a replica is still
    bad). ``on_transition(from_state, to_state, reason)`` fires on every
    state change, outside the lock.
    """

    def __init__(
        self,
        replica_id: str,
        window: int = 8,
        open_failures: int = 3,
        open_rate: float = 0.5,
        backoff: ResilienceConfig | None = None,
        on_transition=None,
        clock=time.monotonic,
    ):
        self.replica_id = replica_id
        self.window = int(window)
        self.open_failures = int(open_failures)
        self.open_rate = float(open_rate)
        self.backoff = backoff if backoff is not None else ResilienceConfig()
        self.on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: deque = deque(maxlen=self.window)
        self._state = BREAKER_CLOSED
        self._opens = 0            # consecutive open episodes (backoff attempt)
        self._open_until = 0.0
        self._last_reason = ""     # why the last transition fired
        self._rng = random.Random(f"breaker:{replica_id}:{os.getpid()}")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow_request(self) -> bool:
        """May the data plane route to this replica right now? Half-open
        admits nothing — the recovery probe is the prober's, not a
        client's."""
        with self._lock:
            return self._state == BREAKER_CLOSED

    def record_success(self) -> None:
        with self._lock:
            self._outcomes.append(True)
            # The open-episode counter (the backoff attempt) fully
            # resets only after a sustained clean window — a replica
            # that flaps closed/open keeps an ESCALATING backoff
            # instead of re-entering rotation at base cadence forever.
            if (
                self._state == BREAKER_CLOSED
                and len(self._outcomes) == self.window
                and all(self._outcomes)
            ):
                self._opens = 0

    def record_failure(self, reason: str = "") -> None:
        fired = None
        with self._lock:
            self._outcomes.append(False)
            if self._state != BREAKER_CLOSED:
                return
            failures = sum(1 for ok in self._outcomes if not ok)
            rate = failures / len(self._outcomes)
            if failures >= self.open_failures and rate >= self.open_rate:
                fired = self._open_locked(
                    f"{failures} failures in last {len(self._outcomes)} "
                    f"(rate {rate:.2f}); last: {reason}"
                )
        self._fire(fired)

    def _open_locked(self, reason: str):
        self._opens += 1
        delay = backoff_s(self.backoff, self._opens, self._rng)
        self._open_until = self._clock() + delay
        prev, self._state = self._state, BREAKER_OPEN
        return (prev, BREAKER_OPEN,
                f"{reason}; half-open probe in {delay:.2f}s")

    def probe_due(self) -> bool:
        """Open and past its backoff? Transitions to half-open and
        returns True exactly once per episode — the caller owns the one
        probe it was just granted."""
        fired = None
        with self._lock:
            if self._state == BREAKER_OPEN and self._clock() >= self._open_until:
                self._state = BREAKER_HALF_OPEN
                fired = (BREAKER_OPEN, BREAKER_HALF_OPEN, "backoff elapsed")
        self._fire(fired)
        return fired is not None

    def probe_result(self, ok: bool, reason: str = "") -> None:
        """The half-open single probe's verdict: close on success,
        re-open (longer backoff) on failure."""
        fired = None
        with self._lock:
            if self._state != BREAKER_HALF_OPEN:
                return
            if ok:
                self._outcomes.clear()
                # decay, don't zero: a follow-up failure burst re-opens
                # with a longer backoff than the last episode's start
                # (record_success resets fully after a clean window)
                self._opens = max(0, self._opens - 1)
                self._state = BREAKER_CLOSED
                fired = (BREAKER_HALF_OPEN, BREAKER_CLOSED,
                         reason or "probe succeeded")
            else:
                self._state = BREAKER_OPEN  # _open_locked re-sets it; keep tidy
                fired = self._open_locked(reason or "probe failed")
                fired = (BREAKER_HALF_OPEN, BREAKER_OPEN, fired[2])
        self._fire(fired)

    def _fire(self, transition) -> None:
        if transition is None:
            return
        with self._lock:
            self._last_reason = transition[2]
        if self.on_transition is not None:
            self.on_transition(*transition)

    def snapshot(self) -> dict:
        with self._lock:
            failures = sum(1 for ok in self._outcomes if not ok)
            return {
                "state": self._state,
                "window": len(self._outcomes),
                "failures_in_window": failures,
                "open_episodes": self._opens,
                "last_transition_reason": self._last_reason,
                "reopen_in_s": round(max(0.0, self._open_until - self._clock()), 3)
                if self._state == BREAKER_OPEN else 0.0,
            }


class _Replica:
    """Mutable per-replica record inside a ReplicaSet (internal)."""

    def __init__(self, spec: ReplicaSpec, breaker: CircuitBreaker):
        self.spec = spec
        self.breaker = breaker
        self.state = JOINING
        self.state_since = time.monotonic()
        self.state_reason = ""         # why the last transition fired
        self.version: int | None = None
        self.last_health: dict = {}
        self.probe_failures = 0
        self.last_reload_post = 0.0
        self.reload_inflight = False   # one async catch-up POST at a time
        self.self_drained = False      # DRAINING came from its own /drain


class ReplicaSet:
    """The fleet's state: per-replica machines, breakers, the committed
    version, and the writer/read-only verdict. Pure host bookkeeping —
    all HTTP lives in :class:`FleetRouter`; every mutation here emits
    its provenance record (``replica_health`` / ``breaker_transition``
    / ``fleet_degraded``)."""

    def __init__(
        self,
        replicas,
        writer: str | None = None,
        config: FleetConfig | None = None,
        sink=None,
        registry: Registry | None = None,
        standby: str | None = None,
    ):
        specs = [
            r if isinstance(r, ReplicaSpec) else ReplicaSpec(*r)
            for r in replicas
        ]
        if not specs:
            raise ValueError("a fleet needs at least one replica")
        ids = [s.id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids in {ids}")
        self.config = config if config is not None else FleetConfig.from_env()
        self.sink = sink
        self.registry = registry if registry is not None else Registry()
        self.writer_id = writer if writer is not None else specs[0].id
        if self.writer_id not in ids:
            raise ValueError(
                f"writer {self.writer_id!r} is not a replica ({ids})"
            )
        # The log-shipped standby (r11): the replica the router promotes
        # on writer loss. None = the r10 behavior (writer loss is a
        # permanent read-only degradation until the same writer returns).
        self.standby_id = standby
        if standby is not None:
            if standby not in ids:
                raise ValueError(
                    f"standby {standby!r} is not a replica ({ids})"
                )
            if standby == self.writer_id:
                raise ValueError("the standby cannot be the writer")
        self.writer_epoch: int | None = None
        self._lock = threading.RLock()
        bk = ResilienceConfig(
            backoff_base_s=self.config.breaker_backoff_base_s,
            backoff_max_s=self.config.breaker_backoff_max_s,
        )
        self._replicas = {}
        for s in specs:
            breaker = CircuitBreaker(
                s.id,
                window=self.config.breaker_window,
                open_failures=self.config.breaker_open_failures,
                open_rate=self.config.breaker_open_rate,
                backoff=bk,
                on_transition=self._breaker_transition(s.id),
            )
            self._replicas[s.id] = _Replica(s, breaker)
        self._order = ids
        self._committed: int | None = None
        self._read_only = False
        self._rr = 0

    # -- provenance --------------------------------------------------------
    def _emit(self, phase: str, **kv) -> None:
        if self.sink is not None:
            self.sink.emit(phase, **kv)

    def _breaker_transition(self, replica_id: str):
        def on_transition(from_state: str, to_state: str, reason: str):
            if to_state == BREAKER_OPEN:
                self.registry.counter(
                    "graphmine_fleet_breaker_opens_total",
                    "circuit-breaker open transitions across the fleet",
                ).inc()
            self._emit(
                "breaker_transition", replica=replica_id,
                from_state=from_state, to_state=to_state, reason=reason,
            )
        return on_transition

    # -- accessors ---------------------------------------------------------
    def replica(self, replica_id: str) -> _Replica:
        return self._replicas[replica_id]

    def replicas(self) -> list:
        return [self._replicas[i] for i in self._order]

    @property
    def quorum(self) -> int:
        return self.config.quorum or (len(self._order) // 2 + 1)

    @property
    def read_only(self) -> bool:
        with self._lock:
            return self._read_only

    def committed_version(self) -> int | None:
        with self._lock:
            return self._committed

    # -- the state machine -------------------------------------------------
    def transition(self, rep: _Replica, to_state: str, reason: str) -> None:
        """One replica state change, with its ``replica_health`` record.
        Idempotent on no-op transitions (no record spam from steady
        probes)."""
        with self._lock:
            if rep.state == to_state:
                return
            from_state, rep.state = rep.state, to_state
            rep.state_since = time.monotonic()
            rep.state_reason = reason
        self._emit(
            "replica_health", replica=rep.spec.id, from_state=from_state,
            to_state=to_state, reason=reason, version=rep.version,
        )
        self._export()

    def apply_probe(self, rep: _Replica, health: dict | None, error: str = "") -> None:
        """Fold one health-probe outcome into the machine. ``health`` is
        the replica's ``/healthz`` body (None = probe failed). DRAINING
        is sticky for successes — the rolling reload owns that state —
        but a DRAINING replica that stops answering still goes DOWN."""
        if health is None:
            rep.probe_failures += 1
            if (
                rep.probe_failures >= self.config.down_after_probes
                and rep.state != DOWN
            ):
                self.transition(
                    rep, DOWN,
                    f"{rep.probe_failures} consecutive probe failures "
                    f"({error})",
                )
            self._recompute()
            return
        rep.probe_failures = 0
        rep.version = int(health.get("version", 0)) or rep.version
        rep.last_health = health
        ready = bool(health.get("ready", True))
        flagged = (
            not ready
            or bool(health.get("overloaded", False))
            or not rep.breaker.allow_request()
        )
        why = []
        if not ready:
            why.append("not ready")
        if health.get("overloaded"):
            why.append(f"overloaded: {health.get('overload_reason', '')}")
        if not rep.breaker.allow_request():
            why.append(f"breaker {rep.breaker.state}")
        if rep.state == DRAINING:
            # Router-initiated drains (rolling reload) are sticky — the
            # roll owns the rejoin. A SELF-drained replica (its own
            # POST /drain) rejoins when it stops reporting draining.
            if rep.self_drained and not health.get("draining", False):
                rep.self_drained = False
                self.transition(rep, JOINING, "replica undrained")
        elif health.get("draining", False):
            # The operator took it out of rotation at the replica
            # (POST /drain): honor it — a drained replica must receive
            # NO reads, not linger as a degraded last resort.
            rep.self_drained = True
            self.transition(
                rep, DRAINING, "replica reports draining (its /drain)"
            )
        elif rep.state == DOWN:
            self.transition(rep, JOINING, "probe succeeded; rejoining")
        elif rep.state == JOINING:
            if ready:
                self.transition(
                    rep, HEALTHY, f"ready at v{rep.version}"
                )
        elif flagged and rep.state == HEALTHY:
            self.transition(rep, DEGRADED, "; ".join(why))
        elif not flagged and rep.state == DEGRADED:
            self.transition(rep, HEALTHY, f"recovered at v{rep.version}")
        self._recompute()

    # -- committed version -------------------------------------------------
    def _recompute(self) -> None:
        """Committed = max version held by a read quorum of configured
        replicas (DOWN replicas hold nothing routable), MONOTONIC: once
        the fleet has served v, it never commits backwards — losing
        quorum makes the fleet unavailable-consistent (503s), never
        time-traveling."""
        with self._lock:
            versions = sorted(
                (
                    r.version for r in self._replicas.values()
                    if r.version is not None and r.state != DOWN
                ),
                reverse=True,
            )
            q = self.quorum
            if len(versions) >= q:
                cand = int(versions[q - 1])
                if self._committed is None or cand > self._committed:
                    self._committed = cand
        self._export()

    def update_read_only(self) -> None:
        """The writer-liveness verdict: writer DOWN → read-only fleet
        (loud ``fleet_degraded`` record). With no standby that is where
        it stays until the SAME writer returns (same identity is not an
        election — r10); with a standby configured the router's prober
        follows up with the fenced promotion, so read-only is the
        bounded transient between loss and time-to-writable."""
        with self._lock:
            # writer_id must resolve under the lock: a concurrent
            # promote_writer() re-points it, and judging the DEPOSED
            # replica's state here would flip the just-promoted fleet
            # back to read-only with a spurious fleet_degraded record
            writer_id = self.writer_id
            lost = self._replicas[writer_id].state == DOWN
            flip = lost != self._read_only
            if flip:
                self._read_only = lost
            standby = self.standby_id
        if flip:
            self._emit(
                "fleet_degraded", read_only=lost,
                reason=(
                    (
                        f"writer {writer_id} is down: fleet is "
                        "read-only "
                        + (
                            f"(standby {standby} promotion pending — "
                            "writes resume at the new epoch)"
                            if standby is not None else
                            "(writes 503 until the writer returns; no "
                            "failover without a standby — a second "
                            "unfenced writer on one store is split-brain)"
                        )
                    )
                    if lost else
                    f"writer {writer_id} recovered: writes restored"
                ),
                writer=writer_id,
            )
            self._export()

    def promote_writer(self, new_writer: str, epoch: int | None,
                       reason: str = "") -> None:
        """Re-point the fleet at the promoted standby: it becomes THE
        writer, the deposed writer becomes the standby candidate for
        the next failover, and writes reopen. The epoch fence at the
        store is what makes this safe — the deposed writer's comeback
        publish refuses regardless of what this router believes."""
        with self._lock:
            deposed = self.writer_id
            self.writer_id = new_writer
            self.standby_id = deposed
            self.writer_epoch = epoch
            self._read_only = False
        self._emit(
            "writer_promote",
            epoch=epoch,
            replica=new_writer,
            deposed=deposed,
            reason=reason or (
                f"standby {new_writer} promoted to writer at epoch "
                f"{epoch}; deposed {deposed} is fenced at the store and "
                "rejoins as the standby CANDIDATE — it is NOT "
                "log-shipping from the new writer until relaunched "
                f"with standby_of={new_writer}; until then a second "
                "failover's loss bound is the unapplied tail at the "
                "new writer's death, not the shipped lag (RUNBOOKS §10)"
            ),
        )
        self._emit(
            "fleet_degraded", read_only=False,
            reason=(
                f"writes restored on promoted writer {new_writer} "
                f"(epoch {epoch}); deposed {deposed} fenced"
            ),
            writer=new_writer,
        )
        self._export()

    # -- routing -----------------------------------------------------------
    def pick(self, version: int, exclude=()) -> _Replica | None:
        """One read-eligible replica at exactly ``version`` (round-robin,
        HEALTHY preferred over DEGRADED, open breakers and ``exclude``d
        ids skipped). Exact-version match is the consistency rule: a
        replica already past the committed version serves the NEWER
        snapshot, and routing to it would hand one client two versions
        across a retry."""
        with self._lock:
            eligible = [
                r for r in (self._replicas[i] for i in self._order)
                if r.state in (HEALTHY, DEGRADED)
                and r.version == version
                and r.spec.id not in exclude
                and r.breaker.allow_request()
            ]
            if not eligible:
                return None
            preferred = [r for r in eligible if r.state == HEALTHY] or eligible
            self._rr += 1
            return preferred[self._rr % len(preferred)]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(
                1 for r in self._replicas.values()
                if r.state in (HEALTHY, DEGRADED)
            )

    # -- surfaces ----------------------------------------------------------
    def _export(self) -> None:
        reg = self.registry
        with self._lock:
            committed = self._committed
            read_only = self._read_only
        reg.gauge(
            "graphmine_fleet_committed_version",
            "snapshot version the fleet routes reads at",
        ).set(committed if committed is not None else 0)
        reg.gauge(
            "graphmine_fleet_replicas_healthy",
            "replicas currently read-eligible (healthy or degraded)",
        ).set(self.healthy_count())
        reg.gauge(
            "graphmine_fleet_read_only",
            "1 while the writer is down and the fleet refuses writes",
        ).set(1 if read_only else 0)

    def snapshot(self) -> dict:
        """The ``/fleetz`` body: every replica's state/version/breaker
        plus the fleet verdicts."""
        with self._lock:
            committed = self._committed
            read_only = self._read_only
        return {
            "committed_version": committed,
            "quorum": self.quorum,
            "writer": self.writer_id,
            "standby": self.standby_id,
            "writer_epoch": self.writer_epoch,
            "read_only": read_only,
            "replicas": [
                {
                    "id": r.spec.id,
                    "host": r.spec.host,
                    "port": r.spec.port,
                    "state": r.state,
                    "state_reason": r.state_reason,
                    "version": r.version,
                    "writer": r.spec.id == self.writer_id,
                    "standby": r.spec.id == self.standby_id,
                    "writer_epoch": r.last_health.get("writer_epoch"),
                    "replication_lag_s": r.last_health.get(
                        "replication_lag_s"
                    ),
                    "breaker": r.breaker.snapshot(),
                    "state_age_s": round(
                        time.monotonic() - r.state_since, 3
                    ),
                    "snapshot_age_s": r.last_health.get("snapshot_age_s"),
                    "overloaded": r.last_health.get("overloaded"),
                    "lof_stale": r.last_health.get("lof_stale"),
                    "tenants": r.last_health.get("tenants"),
                    "tenant_versions": r.last_health.get("tenant_versions"),
                    # sharded write plane (r17): committed publish epoch
                    # + per-range version vector — fleet_cli status
                    # --shards collapses these into the range table
                    "epoch": r.last_health.get("epoch"),
                    "shard_versions": r.last_health.get("shard_versions"),
                    "writer_shards": r.last_health.get("writer_shards"),
                    "degraded_shards": r.last_health.get(
                        "degraded_shards"
                    ),
                }
                for r in self.replicas()
            ],
        }


# One route table per method (the serve/server.py discipline): the same
# table resolves the histogram endpoint label and dispatches, so a route
# can never exist in one place and not the other.
_PROXY_GET = ("/vertex", "/explain", "/neighbors", "/topk", "/snapshot")
_GET_ROUTES = {
    "/healthz": "_ep_healthz",
    "/fleetz": "_ep_fleetz",
    "/statusz": "_ep_statusz",
    "/metrics": "_ep_metrics",
    "/alertz": "_ep_alertz",
    **{p: "_ep_read" for p in _PROXY_GET},
}
_POST_ROUTES = {
    "/query": "_ep_read",
    "/delta": "_ep_write",
    "/reload": "_ep_write",
    "/roll": "_ep_roll",
    "/promote": "_ep_promote",
}


class FleetRouter:
    """The stdlib front tier: ThreadingHTTPServer (the serve/server.py
    idioms) routing reads across a :class:`ReplicaSet` and forwarding
    writes to the single writer. See the module docstring for the
    contract; ``tests/test_fleet.py`` for the chaos pins."""

    def __init__(
        self,
        replicas,
        writer: str | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        sink=None,
        config: FleetConfig | None = None,
        registry: Registry | None = None,
        standby: str | None = None,
    ):
        self.config = config if config is not None else FleetConfig.from_env()
        self.sink = sink
        self.registry = registry if registry is not None else (
            sink.registry if sink is not None else Registry()
        )
        # Memory budget (ISSUE 14): resolved ONCE at construction — the
        # SnapshotServer discipline — so a malformed env override fails
        # loudly here instead of 500ing every later /statusz scrape
        # (and /proc/meminfo is not re-parsed per scrape).
        self._mem_budget = serve_mem_budget_bytes()
        self.replica_set = ReplicaSet(
            replicas, writer=writer, config=self.config, sink=sink,
            registry=self.registry, standby=standby,
        )
        self._host, self._port = host, port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._prober: threading.Thread | None = None
        self._stop = threading.Event()
        self._roll_lock = threading.Lock()
        self._promote_lock = threading.Lock()
        # Per-delta time-to-visible tracking (ISSUE 11): version ->
        # {t0, trace, seen replicas, wall-clock created}. A forwarded
        # /delta that published version v starts an entry; the prober
        # marks each replica visible the first time it reports >= v,
        # observing graphmine_fleet_time_to_visible_seconds{replica=..}
        # and emitting a delta_visible record in the DELTA's trace.
        self._vis_lock = threading.Lock()
        self._visibility: dict = {}
        self._vis_max = 256            # bounded: old entries expire
        self._vis_expire_s = 600.0
        # TTL cache of the /alertz quality fan-out (ISSUE 13): one pass
        # serves /alertz + /statusz + /metrics reads within the window,
        # and the lock keeps a scrape burst from stampeding replicas.
        self._alertz_cache: tuple = (-1e9, {})
        self._alertz_cache_lock = threading.Lock()
        self._alertz_refreshing = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> tuple[str, int]:
        """Bind, serve on a daemon thread, start the health prober;
        returns (host, port)."""
        router = self

        class Handler(_FleetHandler):
            rtr = router

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="graphmine-fleet-router",
            daemon=True,
        )
        self._thread.start()
        self._stop.clear()
        self._prober = threading.Thread(
            target=self._probe_loop, name="graphmine-fleet-prober",
            daemon=True,
        )
        self._prober.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=30)
            self._prober = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- replica HTTP ------------------------------------------------------
    def _replica_call(
        self, rep: _Replica, method: str, path: str,
        body: bytes | None = None, timeout: float = 1.0,
        headers: dict | None = None,
    ) -> tuple[int, bytes, dict]:
        """One HTTP exchange with a replica -> (status, body, headers).
        4xx/5xx return their status; transport failures raise (the
        caller's breaker/retry logic classifies them)."""
        req = urlrequest.Request(
            f"http://{rep.spec.host}:{rep.spec.port}{path}",
            data=body, method=method,
        )
        if body is not None:
            req.add_header("Content-Type", "application/json")
        headers = dict(headers or {})
        # Trace propagation on EVERY replica exchange — data-plane
        # reads, writer forwards, probes, reloads, promotions: the
        # replica adopts this header and its records land in the same
        # trace (the per-request root span for client traffic, the
        # router's run trace for prober housekeeping).
        if TRACE_HEADER not in headers:
            th = self._trace_header()
            if th:
                headers[TRACE_HEADER] = th
        for name, value in headers.items():
            req.add_header(name, value)
        try:
            with urlrequest.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urlerror.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def _trace_header(self) -> str:
        """The calling thread's current span as a propagatable header
        ("" without a sink/tracer) — inside the request middleware this
        is the per-request root span."""
        return sink_trace_header(self.sink)

    def _probe_replica(self, rep: _Replica, timeout: float) -> dict | None:
        try:
            status, body, _ = self._replica_call(
                rep, "GET", "/healthz", timeout=timeout
            )
            if status != 200:
                return None
            return json.loads(body.decode())
        except Exception:  # noqa: BLE001 — any transport failure is a miss
            return None

    # -- the prober --------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the prober must never die
                pass
            self._stop.wait(self.config.probe_interval_s)

    def probe_once(self) -> None:
        """One full prober pass (public so tests drive the machine
        deterministically): half-open breaker probes, health probes +
        state transitions, the writer catch-up reload cadence, the
        writer-liveness/read-only verdict, committed recompute.
        Replicas are probed CONCURRENTLY — one hung replica eating its
        whole probe_timeout must not stall DOWN detection, half-open
        probes or the read-only verdict for the rest of the fleet."""
        cfg = self.config
        rs = self.replica_set

        def probe_one(rep: _Replica) -> None:
            # The half-open single probe: a DATA-PLANE read (/snapshot,
            # the cheapest proxied read endpoint) at the data-plane
            # timeout — a replica that answers health but serves reads
            # slowly OR erroringly stays open; /healthz alone would
            # miss the fast-500 failure shape.
            if rep.breaker.probe_due():
                try:
                    status, _, _ = self._replica_call(
                        rep, "GET", "/snapshot",
                        timeout=cfg.read_timeout_s,
                    )
                    ok = status < 500
                except Exception:  # noqa: BLE001 — timeout/refused
                    ok = False
                rep.breaker.probe_result(
                    ok,
                    f"half-open read probe "
                    f"{'served' if ok else 'failed'} within "
                    f"{cfg.read_timeout_s:g}s",
                )
            health = self._probe_replica(rep, cfg.probe_timeout_s)
            rs.apply_probe(
                rep, health,
                error="probe timed out or connection failed",
            )

        probers = [
            threading.Thread(
                target=probe_one, args=(rep,),
                name=f"graphmine-fleet-probe-{rep.spec.id}", daemon=True,
            )
            for rep in rs.replicas()
        ]
        for t in probers:
            t.start()
        for t in probers:
            t.join()
        # Catch-up reload cadence: the writer publishes, everyone else
        # follows. (A replica AHEAD of the writer — mid rolling reload —
        # is left alone; committed advances when quorum catches up.)
        writer = rs.replica(rs.writer_id)
        if writer.state not in (DOWN,) and writer.version is not None:
            now = time.monotonic()
            for rep in rs.replicas():
                if (
                    rep.spec.id != rs.writer_id
                    and rep.state in (HEALTHY, DEGRADED)
                    and rep.version is not None
                    and rep.version < writer.version
                    and not rep.reload_inflight
                    and now - rep.last_reload_post >= cfg.reload_cadence_s
                ):
                    rep.last_reload_post = now
                    rep.reload_inflight = True
                    # Fire-and-forget: a big snapshot's /reload can take
                    # many seconds, and blocking the prober on it would
                    # stall DOWN detection, half-open probes and the
                    # read-only verdict fleet-wide. The next probe pass
                    # reads the resulting version either way.
                    threading.Thread(
                        target=self._post_reload, args=(rep,),
                        name=f"graphmine-fleet-reload-{rep.spec.id}",
                        daemon=True,
                    ).start()
        self._check_visibility()
        rs.update_read_only()
        # Fenced failover (r11): a read-only fleet with a live standby
        # promotes it instead of staying degraded. Fire-and-forget like
        # the reload cadence — a slow /promote (WAL-tail replay) must
        # not stall DOWN detection; promote_standby's own lock keeps it
        # single-flight, and the next pass retries a failed attempt.
        if (
            rs.read_only
            and rs.standby_id is not None
            and rs.replica(rs.standby_id).state not in (DOWN,)
            and not self._stop.is_set()
        ):
            threading.Thread(
                target=self.promote_standby,
                name="graphmine-fleet-promote", daemon=True,
            ).start()

    def _post_reload(self, rep: _Replica) -> None:
        try:
            self._replica_call(
                rep, "POST", "/reload", body=b"{}",
                timeout=self.config.reload_timeout_s,
            )
        except Exception:  # noqa: BLE001 — the next probe sees the state
            pass
        finally:
            rep.reload_inflight = False

    # -- per-delta time-to-visible (ISSUE 11 SLO) --------------------------
    def _track_visibility(self, version: int, t0: float, trace: str) -> None:
        """Start tracking a just-published version: each replica's
        first probe at >= version closes its leg of the SLO."""
        with self._vis_lock:
            if version in self._visibility:
                return
            self._visibility[version] = {
                "t0": t0,
                "trace": trace,
                "seen": set(),
                "created": time.monotonic(),
            }
            if len(self._visibility) > self._vis_max:
                for v in sorted(self._visibility)[: -self._vis_max]:
                    self._visibility.pop(v, None)

    def _mark_visible(
        self, version: int, entry: dict, replica_id: str, now: float,
    ) -> None:
        seconds = max(0.0, now - entry["t0"])
        self.registry.histogram(
            "graphmine_fleet_time_to_visible_seconds",
            "delta accept at the router to each replica serving the "
            "version that absorbed it",
            replica=replica_id,
        ).observe(seconds)
        if self.sink is None:
            return
        ctx = (
            TraceContext.from_header(entry["trace"])
            if entry["trace"] else None
        )
        span = (
            self.sink.span(
                "delta_visible", emit=False, annotate=False, remote=ctx,
            )
            if ctx is not None else contextlib.nullcontext()
        )
        with span:
            self.sink.emit(
                "delta_visible", replica=replica_id, version=int(version),
                seconds=round(seconds, 6),
            )

    def _check_visibility(self) -> None:
        """Prober-pass sweep: close the (delta, replica) legs whose
        replica now serves the tracked version; expire stale entries
        (a replica that died before catching up must not pin an entry
        forever)."""
        rs = self.replica_set
        now = time.monotonic()
        all_ids = {r.spec.id for r in rs.replicas()}
        reps = [(r.spec.id, r.version, r.state) for r in rs.replicas()]
        marks = []
        # seen-set mutation stays under the lock (a test-driven
        # probe_once racing the prober thread must not double-observe a
        # leg); the sink emission happens after release — a record
        # fsync must not serialize the sweep.
        with self._vis_lock:
            for version, entry in list(self._visibility.items()):
                for rep_id, rep_version, rep_state in reps:
                    if (
                        rep_id in entry["seen"]
                        or rep_version is None
                        or rep_version < version
                        or rep_state == DOWN
                    ):
                        continue
                    entry["seen"].add(rep_id)
                    marks.append((version, dict(entry), rep_id))
                if (
                    entry["seen"] >= all_ids
                    or now - entry["created"] > self._vis_expire_s
                ):
                    self._visibility.pop(version, None)
        for version, entry, rep_id in marks:
            self._mark_visible(version, entry, rep_id, now)

    def time_to_visible_merged(self) -> Histogram | None:
        """All per-replica time-to-visible histograms folded counter-wise
        (:meth:`~graphmine_tpu.obs.histogram.Histogram.merge` — the
        mergeable-ladder rollup) into one fleet-level distribution; None
        before the first observation."""
        fam = self.registry.histogram_family(
            "graphmine_fleet_time_to_visible_seconds"
        )
        if fam is None:
            return None
        merged = Histogram(
            "graphmine_fleet_time_to_visible_merged_seconds",
            "time-to-visible across all replicas (counter-wise merge of "
            "the per-replica histograms)",
            buckets=fam.bounds,
        )
        for child in fam.children():
            merged.merge(child)
        return merged

    # -- read routing ------------------------------------------------------
    def route_read(
        self, method: str, path_qs: str, body: bytes | None,
        headers,
    ) -> tuple[int, bytes, dict]:
        """Consistent-version read with bounded cross-replica retry
        under the propagated deadline. Returns (status, body, headers)
        for the handler to relay."""
        cfg = self.config
        rs = self.replica_set
        endpoint = urlparse(path_qs).path.lstrip("/") or "?"
        t0 = time.monotonic()
        try:
            deadline_ms = int(headers.get("X-Deadline-Ms", ""))
        except ValueError:
            deadline_ms = cfg.default_deadline_ms
        deadline = t0 + max(1, deadline_ms) / 1000.0
        committed = rs.committed_version()
        if committed is None:
            return self._no_replica(
                endpoint, 0, None, "no committed version yet (fleet warming)"
            )
        pinned_hdr = headers.get("X-Pinned-Version", "")
        if pinned_hdr:
            try:
                pinned = int(pinned_hdr)
            except ValueError:
                pinned = committed
            if pinned > committed:
                # The session has seen a version this fleet can no
                # longer quorum on — answering an OLDER version would
                # break monotonic reads; refuse instead.
                self._emit_route(
                    endpoint, "stale_pin", 0, committed,
                    seconds=time.monotonic() - t0,
                )
                return self._shed(
                    f"fleet committed v{committed} is behind the session's "
                    f"pinned v{pinned}; retry after the fleet catches up"
                )
        tried: list = []
        attempts = 0
        last_error = "no eligible replica"
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                last_error = f"deadline {deadline_ms}ms exhausted"
                break
            rep = rs.pick(committed, exclude=tried)
            if rep is None:
                break
            attempts += 1
            attempt_timeout = min(cfg.read_timeout_s, remaining)
            try:
                status, resp_body, resp_headers = self._replica_call(
                    rep, method, path_qs, body=body,
                    timeout=attempt_timeout,
                    headers={
                        "X-Serve-Version": str(committed),
                        **(
                            {"X-Request-Id": headers["X-Request-Id"]}
                            if headers.get("X-Request-Id") else {}
                        ),
                        **(
                            {"X-Tenant-Id": headers["X-Tenant-Id"]}
                            if headers.get("X-Tenant-Id") else {}
                        ),
                    },
                )
            except Exception as e:  # noqa: BLE001 — timeout/refused/reset
                # Charge the breaker only when the replica had the FULL
                # read budget: a failure under a deadline-truncated
                # timeout is the client's budget running out, not
                # replica fault — tight-deadline traffic must not open
                # breakers on healthy replicas (dead ones are still
                # caught by the prober's DOWN detection).
                if attempt_timeout >= cfg.read_timeout_s:
                    rep.breaker.record_failure(repr(e))
                tried.append(rep.spec.id)
                last_error = f"{rep.spec.id}: {e!r}"
                continue
            if status == 409:
                # The replica swapped versions between pick and serve —
                # not a fault (no breaker hit), just not at our pin
                # anymore; the prober will re-read its version.
                tried.append(rep.spec.id)
                last_error = f"{rep.spec.id}: version moved (409)"
                continue
            if status >= 500:
                rep.breaker.record_failure(f"HTTP {status}")
                tried.append(rep.spec.id)
                last_error = f"{rep.spec.id}: HTTP {status}"
                continue
            rep.breaker.record_success()
            self.registry.counter(
                "graphmine_fleet_read_retries_total",
                "extra read attempts beyond the first, fleet-wide",
            ).inc(attempts - 1)
            self._emit_route(
                endpoint, "served", attempts, committed,
                replica=rep.spec.id, seconds=time.monotonic() - t0,
            )
            out_headers = {
                "Content-Type": resp_headers.get(
                    "Content-Type", "application/json"
                ),
                "X-Pinned-Version": str(committed),
                "X-Fleet-Replica": rep.spec.id,
            }
            # keep the replica's X-Request-Id echo: client-side trace
            # correlation must survive a router in front of the server
            if resp_headers.get("X-Request-Id"):
                out_headers["X-Request-Id"] = resp_headers["X-Request-Id"]
            return status, resp_body, out_headers
        return self._no_replica(endpoint, attempts, committed, last_error)

    def _no_replica(
        self, endpoint: str, attempts: int, version, reason: str,
    ) -> tuple[int, bytes, dict]:
        self.registry.counter(
            "graphmine_fleet_no_replica_total",
            "reads refused because no replica was eligible",
        ).inc()
        self._emit_route(endpoint, "no_replica", attempts, version,
                         reason=reason)
        return self._shed(f"no eligible replica: {reason}")

    def _shed(self, reason: str) -> tuple[int, bytes, dict]:
        body = json.dumps({
            "error": "fleet unavailable",
            "reason": reason,
            "retry_after_s": self.config.retry_after_s,
        }).encode()
        return 503, body, {
            "Content-Type": "application/json",
            "Retry-After": str(max(1, round(self.config.retry_after_s))),
        }

    def _emit_route(
        self, endpoint: str, verdict: str, attempts: int, version,
        **kv,
    ) -> None:
        if "seconds" in kv:
            kv["seconds"] = round(kv["seconds"], 6)
        if self.sink is not None:
            self.sink.emit(
                "fleet_route", endpoint=endpoint, verdict=verdict,
                attempts=attempts, version=version, **kv,
            )

    # -- fenced failover ---------------------------------------------------
    def promote_standby(self) -> dict:
        """Promote the configured standby to writer (single-flight; the
        prober fires it on writer loss, ``POST /promote`` and
        ``fleet_cli promote`` fire it manually): one ``/promote``
        exchange with the standby — it fences the store epoch, replays
        its WAL tail and resumes writes — then the fleet re-points write
        forwarding at it. On failure the fleet stays read-only and the
        next prober pass retries."""
        if not self._promote_lock.acquire(blocking=False):
            return {"ok": False, "reason": "a promotion is already in flight"}
        try:
            rs = self.replica_set
            if rs.standby_id is None:
                return {"ok": False, "reason": "no standby configured"}
            standby = rs.replica(rs.standby_id)
            if standby.state == DOWN:
                return {
                    "ok": False,
                    "reason": f"standby {standby.spec.id} is down",
                }
            try:
                status, body, _ = self._replica_call(
                    standby, "POST", "/promote", body=b"{}",
                    timeout=self.config.promote_timeout_s,
                )
            except Exception as e:  # noqa: BLE001 — retried next pass
                self._emit_route(
                    "promote", "promote_failed", 1, rs.committed_version(),
                    reason=repr(e),
                )
                return {"ok": False, "reason": repr(e)}
            if status != 200:
                self._emit_route(
                    "promote", "promote_failed", 1, rs.committed_version(),
                    reason=f"HTTP {status}",
                )
                return {"ok": False, "reason": f"/promote answered {status}"}
            out = json.loads(body.decode())
            epoch = out.get("epoch")
            rs.promote_writer(standby.spec.id, epoch)
            self.registry.counter(
                "graphmine_fleet_promotions_total",
                "standby-to-writer promotions",
            ).inc()
            return {
                "ok": True,
                "writer": standby.spec.id,
                "epoch": epoch,
                "replayed": out.get("replayed"),
                "copied_tail": out.get("copied_tail"),
            }
        finally:
            self._promote_lock.release()

    # -- write forwarding --------------------------------------------------
    def forward_write(
        self, path_qs: str, body: bytes | None, headers,
    ) -> tuple[int, bytes, dict]:
        """POST /delta and /reload go to THE writer (single-publisher
        contract); a read-only fleet (writer down) refuses with 503 +
        Retry-After rather than electing a second publisher."""
        rs = self.replica_set
        endpoint = urlparse(path_qs).path.lstrip("/") or "?"
        if rs.read_only:
            self._emit_route(endpoint, "read_only", 0, rs.committed_version())
            return self._shed(
                f"fleet is read-only: writer {rs.writer_id} is down "
                "(no failover; restore the writer)"
            )
        writer = rs.replica(rs.writer_id)
        fwd_headers = {}
        # X-Delta-Id / X-Delta-Ack ride through: the idempotency key and
        # the WAL-durable 202 contract are writer semantics the router
        # must not strip (r11, docs/SERVING.md "Replicated writers").
        # X-Tenant-Id too (ISSUE 16): tenant routing is writer
        # semantics — stripping it would land the delta on the default
        # namespace, a silent cross-tenant write.
        for name in ("X-Deadline-Ms", "X-Request-Id", "X-Delta-Id",
                     "X-Delta-Ack", "X-Tenant-Id"):
            if headers.get(name):
                fwd_headers[name] = headers[name]
        t0 = time.monotonic()
        try:
            status, resp_body, resp_headers = self._replica_call(
                writer, "POST", path_qs, body=body or b"{}",
                timeout=self.config.write_timeout_s, headers=fwd_headers,
            )
        except Exception as e:  # noqa: BLE001 — writer unreachable
            writer.breaker.record_failure(repr(e))
            self._emit_route(
                endpoint, "writer_unreachable", 1, rs.committed_version(),
                reason=repr(e),
            )
            return self._shed(f"writer {rs.writer_id} unreachable: {e!r}")
        if endpoint == "delta" and status == 200:
            # A synchronous apply published a version: start the
            # time-to-visible clock. The writer serves it already (the
            # 200 means the swap happened), so its leg closes here; the
            # prober closes each remaining replica's leg as it catches
            # up. (202 WAL-acks carry no version yet — their visibility
            # is bounded by the same publish this tracking catches when
            # the coalesced group lands via a later sync apply or the
            # reload cadence.)
            try:
                version = json.loads(resp_body.decode()).get("version")
            except (ValueError, UnicodeDecodeError):
                version = None
            if isinstance(version, int):
                self._track_visibility(
                    version, t0, self._trace_header()
                )
                with self._vis_lock:
                    entry = self._visibility.get(version)
                    # A prober sweep racing between _track_visibility
                    # and here may have closed the writer leg already —
                    # membership is the double-observe guard.
                    if (
                        entry is not None
                        and writer.spec.id not in entry["seen"]
                    ):
                        entry["seen"].add(writer.spec.id)
                        entry = dict(entry)
                    else:
                        entry = None
                if entry is not None:
                    self._mark_visible(
                        version, entry, writer.spec.id, time.monotonic()
                    )
        self._emit_route(
            endpoint, "forwarded", 1, rs.committed_version(),
            replica=writer.spec.id, status=status,
        )
        out_headers = {
            "Content-Type": resp_headers.get(
                "Content-Type", "application/json"
            ),
            "X-Fleet-Replica": writer.spec.id,
        }
        for passthrough in ("Retry-After", "X-Request-Id"):
            if resp_headers.get(passthrough):
                out_headers[passthrough] = resp_headers[passthrough]
        return status, resp_body, out_headers

    # -- rolling reload ----------------------------------------------------
    def rolling_reload(self) -> dict:
        """Drain → /reload → re-probe → rejoin, one replica at a time
        (writer LAST, so write availability is the last thing to
        blink), aborting if the fleet would drop below ``min_healthy``
        read-eligible replicas. Returns the roll report; one roll at a
        time per router."""
        if not self._roll_lock.acquire(blocking=False):
            return {"ok": False, "aborted": "a roll is already in progress"}
        try:
            return self._roll()
        finally:
            self._roll_lock.release()

    def _roll(self) -> dict:
        cfg = self.config
        rs = self.replica_set
        order = [r for r in rs.replicas() if r.spec.id != rs.writer_id]
        order.append(rs.replica(rs.writer_id))
        rolled = []
        for rep in order:
            if rep.state == DOWN:
                rolled.append({"id": rep.spec.id, "skipped": "down"})
                continue
            serving = rs.healthy_count()
            remaining = serving - (1 if rep.state in (HEALTHY, DEGRADED) else 0)
            if remaining < cfg.min_healthy:
                return {
                    "ok": False, "rolled": rolled,
                    "aborted": (
                        f"draining {rep.spec.id} would leave {remaining} "
                        f"serving replica(s) < min_healthy {cfg.min_healthy}"
                    ),
                }
            rs.transition(rep, DRAINING, "rolling reload")
            time.sleep(cfg.drain_grace_s)
            try:
                status, body, _ = self._replica_call(
                    rep, "POST", "/reload", body=b"{}",
                    timeout=cfg.reload_timeout_s,
                )
                if status != 200:
                    raise RuntimeError(f"/reload answered HTTP {status}")
                new_version = int(json.loads(body.decode())["version"])
            except Exception as e:  # noqa: BLE001 — abort, leave it DOWN
                rs.transition(rep, DOWN, f"rolling reload failed: {e!r}")
                return {
                    "ok": False, "rolled": rolled,
                    "aborted": f"reload of {rep.spec.id} failed: {e!r}",
                }
            # Per-tenant committed rule (ISSUE 16): /reload answers with
            # the default tenant's new version, but a multi-tenant
            # replica can come back caught up on that namespace and
            # STALE on another it also serves. Snapshot its pre-drain
            # tenant_versions and refuse rejoin until it is at-or-past
            # every one of them — behind on ANY tenant is catch-up-stale.
            before_tv = rep.last_health.get("tenant_versions")
            before_tv = dict(before_tv) if isinstance(before_tv, dict) else {}
            ok = False
            rejoin_deadline = time.monotonic() + cfg.rejoin_timeout_s
            while time.monotonic() < rejoin_deadline:
                health = self._probe_replica(rep, cfg.probe_timeout_s)
                tenants_ok = True
                if health is not None and before_tv:
                    after_tv = health.get("tenant_versions")
                    after_tv = after_tv if isinstance(after_tv, dict) else {}
                    try:
                        tenants_ok = all(
                            int(after_tv.get(t, -1)) >= int(v)
                            for t, v in before_tv.items()
                        )
                    except (TypeError, ValueError):
                        tenants_ok = False
                if (
                    health is not None
                    and bool(health.get("ready", True))
                    and int(health.get("version", 0)) == new_version
                    and tenants_ok
                ):
                    rep.version = new_version
                    rep.last_health = health
                    rep.probe_failures = 0
                    ok = True
                    break
                time.sleep(min(0.05, cfg.probe_interval_s))
            if not ok:
                rs.transition(
                    rep, DOWN,
                    f"did not re-probe ready at v{new_version} within "
                    f"{cfg.rejoin_timeout_s:g}s after reload",
                )
                return {
                    "ok": False, "rolled": rolled,
                    "aborted": f"{rep.spec.id} did not rejoin",
                }
            rs.transition(rep, HEALTHY, f"rolled to v{new_version}")
            rs._recompute()
            rolled.append({"id": rep.spec.id, "version": new_version})
        rs._recompute()
        return {
            "ok": True, "rolled": rolled,
            "committed_version": rs.committed_version(),
        }

    # -- surfaces ----------------------------------------------------------
    def healthz(self) -> dict:
        rs = self.replica_set
        committed = rs.committed_version()
        healthy = rs.healthy_count()
        out = {
            "ok": True,
            "role": "router",
            "committed_version": committed,
            "replicas_serving": healthy,
            "replicas_total": len(rs.replicas()),
            "writer": rs.writer_id,
            "standby": rs.standby_id,
            "writer_epoch": rs.writer_epoch,
            "read_only": rs.read_only,
            "ready": committed is not None
            and healthy >= max(1, self.config.min_healthy),
        }
        # Sharded write plane (r17): surface the writer's committed
        # publish epoch + per-range version vector, as last probed — the
        # fleet-facing "which epoch is served" answer the chaos tier's
        # no-mixed-epoch-reads assertion keys off.
        writer = (
            rs.replica(rs.writer_id) if rs.writer_id is not None else None
        )
        if writer is not None and writer.last_health.get("epoch") is not None:
            out["epoch"] = writer.last_health.get("epoch")
            out["shard_versions"] = writer.last_health.get("shard_versions")
            out["writer_shards"] = writer.last_health.get("writer_shards")
        return out

    def fleetz(self) -> dict:
        return {**self.replica_set.snapshot(),
                "config": self.config.snapshot()}

    # -- result quality & alerts (ISSUE 13) --------------------------------
    def _collect_alertz(self, max_age_s: float = 1.0) -> dict:
        """Best-effort ``GET /alertz`` fan-out to every not-DOWN
        replica: per-replica alert/quality payloads keyed by replica id.
        A replica that fails the call is simply absent (its prober
        verdict, not this page, owns its health story).

        TTL-cached (``max_age_s``): /alertz, /statusz and /metrics all
        read through here, and each fan-out is a serial HTTP pass whose
        per-replica timeout a hung-but-not-yet-DOWN replica can spend in
        full — a monitoring cycle hitting all three endpoints must cost
        ONE pass, not three, and a scrape burst must not multiply
        replica load. The per-replica timeout is the data-plane
        ``read_timeout_s`` (the quality state is cached on the replica
        engine — the prober's own /healthz reads already built it), so
        the worst-case stall is bounded by the same budget as any
        routed read."""
        with self._alertz_cache_lock:
            t_cached, cached = self._alertz_cache
            if time.monotonic() - t_cached <= max_age_s:
                return cached
            if self._alertz_refreshing:
                # Single-flight: one thread pays the fan-out; everyone
                # else gets the stale-but-bounded cached view instead of
                # queueing behind a hung replica's timeout (a sick
                # replica must not stall every /metrics scrape).
                return cached
            self._alertz_refreshing = True
        out = {}
        try:
            for rep in self.replica_set.replicas():
                if rep.state == DOWN:
                    continue
                try:
                    status, body, _ = self._replica_call(
                        rep, "GET", "/alertz",
                        timeout=self.config.read_timeout_s,
                    )
                    if status == 200:
                        out[rep.spec.id] = json.loads(body)
                except Exception:  # noqa: BLE001 — dead replica, not a 500
                    continue
        finally:
            with self._alertz_cache_lock:
                self._alertz_cache = (time.monotonic(), out)
                self._alertz_refreshing = False
        return out

    @staticmethod
    def _merge_sketches(payloads: dict, key: str) -> QuantileSketch | None:
        """Counter-wise merge of one sketch family across replica
        quality payloads — EXACTLY the ``Histogram.merge`` rollup the
        latency histograms use (associative, ladder-checked; pinned
        equal to the by-hand per-replica merge in the quality suite).
        Mismatched-ladder or torn payloads are skipped, never re-binned.
        """
        merged = None
        for payload in payloads.values():
            state = (payload.get("quality") or {}).get("state") or {}
            sk_state = state.get(key)
            if not sk_state:
                continue
            try:
                sk = QuantileSketch.from_state(sk_state, name=key)
                if merged is None:
                    merged = sk
                else:
                    merged.merge(sk)
            except (ValueError, TypeError):
                continue
        return merged

    def quality_merged(self, payloads: dict | None = None) -> dict:
        """The fleet-level quality view: per-replica firing counts plus
        the counter-wise merged LOF-score and community-size sketches."""
        if payloads is None:
            payloads = self._collect_alertz()
        merged = {}
        for key in ("lof_sketch", "size_sketch"):
            sk = self._merge_sketches(payloads, key)
            if sk is not None:
                merged[key] = sk.to_state()
        # No silent truncation: a replica whose /alertz fan-out call
        # failed (e.g. its first post-swap O(V) quality build outran the
        # read timeout) is NAMED, so a partial fleet distribution never
        # reads as a complete one.
        missing = sorted(
            rep.spec.id for rep in self.replica_set.replicas()
            if rep.state != DOWN and rep.spec.id not in payloads
        )
        return {
            **({"replicas_missing": missing} if missing else {}),
            "replicas": {
                rid: {
                    "firing": p.get("firing", 0),
                    "version": p.get("version"),
                    "anomaly_rate": (
                        (p.get("quality") or {}).get("state") or {}
                    ).get("anomaly_rate"),
                }
                for rid, p in payloads.items()
            },
            "firing_total": sum(p.get("firing", 0) for p in payloads.values()),
            "merged": merged,
        }

    def alertz(self) -> dict:
        """The router's ``/alertz``: every replica's alert level state
        plus the fleet-merged quality sketches."""
        payloads = self._collect_alertz()
        return {
            "role": "router",
            "replicas": payloads,
            "quality": self.quality_merged(payloads),
        }

    def statusz(self) -> dict:
        """The fleet SLO page, gap-filled in one place (ISSUE 11
        satellite): WAL state + settled ship lag, the current writer
        epoch, per-replica state/breaker with LAST TRANSITION REASONS,
        and the time-to-visible quantiles (per replica + fleet-merged) —
        previously split across the writer's /statusz and the router's
        /fleetz snapshot."""
        rs = self.replica_set
        fleet = rs.snapshot()
        writer = rs.replica(rs.writer_id)
        epoch = rs.writer_epoch
        if epoch is None:
            epoch = writer.last_health.get("writer_epoch")
        ttv: dict = {}
        fam = self.registry.histogram_family(
            "graphmine_fleet_time_to_visible_seconds"
        )
        if fam is not None:
            for child in fam.children():
                s = child.snapshot()
                if not s.count:
                    continue
                ttv[child.labels.get("replica", "?")] = s.summary()
        merged = self.time_to_visible_merged()
        if merged is not None and merged.count:
            ttv["merged"] = merged.snapshot().summary()
        out = {
            "role": "router",
            "committed_version": fleet["committed_version"],
            "writer": rs.writer_id,
            "standby": rs.standby_id,
            "writer_epoch": epoch,
            "read_only": fleet["read_only"],
            "replicas": fleet["replicas"],
            "time_to_visible": ttv,
            # The writer's durable-write state as last probed: the WAL
            # snapshot (pending/applied seqs) is the "settled ship lag"
            # numerator the standby's replication lag pairs with.
            "wal": writer.last_health.get("wal"),
            # fleet-merged result-quality view (ISSUE 13): counter-wise
            # sketch merge across replicas + per-replica firing counts
            "quality": self.quality_merged(),
            # router-process memory plane (ISSUE 14): the router holds
            # no snapshot, but its RSS/headroom ride the same section
            # shape as the replicas' so one dashboard reads the fleet
            "memory": self._memory_payload(),
        }
        if rs.standby_id is not None:
            sb = rs.replica(rs.standby_id).last_health
            out["replication"] = {
                "lag_entries": sb.get("replication_lag_entries"),
                "lag_s": sb.get("replication_lag_s"),
            }
        return out

    def _memory_payload(self) -> dict:
        """Router-side memory section (ISSUE 14): RSS + headroom against
        the process budget, exported as the same ``graphmine_memory_*``
        gauges the replicas serve — the low-headroom alert rule reads
        the identical metric name fleet-wide."""
        out = host_memory(self._mem_budget)
        export_memory_gauges(self.registry, out)
        return out

    def metrics_text(self) -> str:
        # refresh the router's graphmine_memory_* gauges on the scrape
        # itself — a deployment that only reads /metrics must not see
        # absent/stale RSS just because nobody opened /statusz
        self._memory_payload()
        tracer = getattr(self.sink, "tracer", None)
        labels = {"run_id": tracer.run_id} if tracer is not None else None
        text = self.registry.render_textfile(labels=labels)
        merged = self.time_to_visible_merged()
        if merged is not None and merged.count:
            # the fleet-merged rollup rides the same scrape: one
            # counter-wise Histogram.merge of the per-replica children,
            # exposed as its own metric name (one name, one meaning)
            lines = [
                f"# HELP {merged.name} {merged.help}",
                f"# TYPE {merged.name} histogram",
                *merged.render_lines(extra_labels=labels),
            ]
            text += "\n".join(lines) + "\n"
        # Fleet-merged LOF score distribution (ISSUE 13): the quality
        # sketch rolled up across replicas rides the scrape as a value-
        # domain histogram (buckets are LOF score bounds, not seconds).
        payloads = self._collect_alertz()
        sk = self._merge_sketches(payloads, "lof_sketch")
        if sk is not None and sk.count:
            sk.name = "graphmine_fleet_lof_score_sketch"
            lines = [
                f"# HELP {sk.name} fleet-merged LOF score distribution "
                "(counter-wise quality-sketch merge across replicas)",
                f"# TYPE {sk.name} histogram",
                *sk.render_lines(extra_labels=labels),
            ]
            text += "\n".join(lines) + "\n"
        return text

    def observe(self, endpoint: str, seconds: float, status: int) -> None:
        reg = self.registry
        reg.histogram(
            "graphmine_fleet_request_seconds",
            "router request wall time by endpoint",
            endpoint=endpoint,
        ).observe(seconds)
        reg.counter(
            "graphmine_fleet_requests_total", "requests through the router"
        ).inc()
        if status >= 400:
            reg.counter(
                "graphmine_fleet_errors_total",
                "router requests answered 4xx/5xx",
            ).inc()


class _FleetHandler(BaseHTTPRequestHandler):
    rtr: FleetRouter  # bound by FleetRouter.start

    def log_message(self, fmt, *args):  # noqa: A003 — records, not stderr
        pass

    def _send(
        self, code: int, body: bytes, headers: dict | None = None,
    ) -> None:
        self._status = code
        self.send_response(code)
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        hdrs["Content-Length"] = str(len(body))
        for name, value in hdrs.items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload).encode())

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length > 0 else b""

    def _serve(self, method: str, routes: dict) -> None:
        url = urlparse(self.path)
        handler = routes.get(url.path)
        endpoint = url.path.lstrip("/") if handler else "unknown"
        self._status = 500
        # Root span per request (ISSUE 11 fleet tracing): each request
        # through the router is its OWN trace — minted fresh, or adopted
        # from a client that already propagates traceparent. Every
        # replica call inside forwards the header (_replica_call), so
        # the whole fleet's handling of this request stitches into one
        # cross-process timeline.
        sink = self.rtr.sink
        span = contextlib.nullcontext()
        if sink is not None and getattr(sink, "tracer", None) is not None:
            ctx = TraceContext.from_header(
                self.headers.get(TRACE_HEADER, "")
            )
            span = sink.span(
                f"fleet:{endpoint}", emit=False, annotate=False,
                remote=ctx, new_trace=ctx is None,
            )
        t0 = time.perf_counter()
        with span:
            try:
                if handler is None:
                    self._reply_json(
                        404, {"error": f"unknown path {url.path!r}"}
                    )
                else:
                    getattr(self, handler)(url)
            except OSError:
                self._status = 499  # client closed; nothing more to send
            except Exception as e:  # noqa: BLE001 — the router must answer
                try:
                    self._reply_json(500, {"error": repr(e)})
                except OSError:
                    self._status = 499
            finally:
                self.rtr.observe(
                    endpoint, time.perf_counter() - t0, self._status
                )

    def do_GET(self) -> None:  # noqa: N802
        self._serve("GET", _GET_ROUTES)

    def do_POST(self) -> None:  # noqa: N802
        self._serve("POST", _POST_ROUTES)

    # -- routes ------------------------------------------------------------
    def _ep_healthz(self, url) -> None:
        self._reply_json(200, self.rtr.healthz())

    def _ep_fleetz(self, url) -> None:
        self._reply_json(200, self.rtr.fleetz())

    def _ep_statusz(self, url) -> None:
        self._reply_json(200, self.rtr.statusz())

    def _ep_alertz(self, url) -> None:
        self._reply_json(200, self.rtr.alertz())

    def _ep_metrics(self, url) -> None:
        self._send(
            200, self.rtr.metrics_text().encode(),
            {"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    def _ep_read(self, url) -> None:
        path_qs = url.path + (f"?{url.query}" if url.query else "")
        body = self._body() if self.command == "POST" else None
        status, resp, headers = self.rtr.route_read(
            self.command, path_qs, body, self.headers
        )
        self._send(status, resp, headers)

    def _ep_write(self, url) -> None:
        # keep the query string: ?tenant= is the header-less tenant
        # spelling and must survive the router hop like X-Tenant-Id does
        path_qs = url.path + (f"?{url.query}" if url.query else "")
        status, resp, headers = self.rtr.forward_write(
            path_qs, self._body(), self.headers
        )
        self._send(status, resp, headers)

    def _ep_roll(self, url) -> None:
        out = self.rtr.rolling_reload()
        self._reply_json(200 if out.get("ok") else 409, out)

    def _ep_promote(self, url) -> None:
        out = self.rtr.promote_standby()
        self._reply_json(200 if out.get("ok") else 409, out)
