"""GraphX ``LabelPropagation`` semantics oracle (host-side NumPy).

The north star (BASELINE.json) asks for "matching GraphFrames community
IDs on bundled data". GraphFrames 0.6.0 delegates to GraphX's
``LabelPropagation.run`` (reached from the reference at
``Graphframes.py:81``), whose Pregel program is:

- initial label = vertex id;
- ``sendMessage`` emits ``(src, {dstLabel: 1})`` and ``(dst, {srcLabel: 1})``
  for every edge triplet — i.e. undirected propagation over the directed
  edge list, duplicate edges counted with multiplicity;
- ``mergeMessage`` sums the per-label counts (map union);
- ``vertexProgram`` keeps the current label on an empty message and
  otherwise takes ``message.maxBy(_._2)._1`` — the FIRST maximal entry in
  the merged map's iteration order;
- Pregel first applies the vertex program with an empty initial message
  (a no-op), then runs exactly ``maxSteps`` send→merge→apply rounds with
  no convergence test; vertices that receive no messages keep their label.

This module reproduces that structure exactly, with the tie-break as an
explicit parameter — because GraphX's own tie-break is NOT a fixed rule:

``maxBy`` iterates a ``scala.collection.immutable.Map`` whose iteration
order depends on its concrete type. Merged maps of ≤4 entries are
``Map1``..``Map4`` (insertion order — determined by the order Spark's
shuffle combiners merged partial maps, which depends on partitioning and
scheduling), larger ones are hash tries (order determined by the improved
key hash). Exact label-for-label GraphX parity on tie-heavy graphs is
therefore machine- and partitioning-dependent *in the reference stack
itself*; the well-defined validation target is partition agreement under
canonicalization with measured tie sensitivity (SURVEY §6 "hard parts").

Tie rules provided:

- ``"smallest"`` — deterministic smallest label (this engine's rule,
  ``ops/segment.py:segment_mode``): enables exact label-for-label parity
  checks between this oracle and the TPU engine.
- ``"largest"`` — the opposite extreme, for tie-sensitivity bounds.
- ``"hash_order"`` — first max in Scala-2.11 ``HashMap`` trie iteration
  order (``improve(Long.##)`` hashed, 5-bit-chunk little-endian order):
  the order a large merged map would iterate in, i.e. the closest
  machine-independent approximation of GraphX's behavior.
"""

from __future__ import annotations

import numpy as np


def _scala_long_hashcode(v: np.ndarray) -> np.ndarray:
    """``java.lang.Long.hashCode``: ``(int)(value ^ (value >>> 32))``."""
    v = v.astype(np.int64)
    return (v ^ (v >> np.int64(32))).astype(np.uint32)


def _scala_improve(h: np.ndarray) -> np.ndarray:
    """Scala 2.11 ``immutable.HashMap.improve`` (bit-avalanche) on uint32."""
    h = h.astype(np.uint32)
    h = h + (~(h << np.uint32(9)))
    h = h ^ (h >> np.uint32(14))
    h = h + (h << np.uint32(4))
    h = h ^ (h >> np.uint32(10))
    return h


def scala_trie_order_key(labels: np.ndarray) -> np.ndarray:
    """Sort key reproducing Scala 2.11 ``HashMap`` trie iteration order.

    The trie consumes the improved hash in 5-bit chunks, least-significant
    first; siblings at each level iterate in ascending chunk value. The
    iteration order therefore compares keys lexicographically on the
    little-endian 5-bit digit sequence — equivalently, on the integer whose
    base-32 digits are reversed. uint64 holds the 7-digit reversal exactly.
    """
    h = _scala_improve(_scala_long_hashcode(labels)).astype(np.uint64)
    key = np.zeros_like(h)
    for i in range(7):  # ceil(32 / 5) digits
        key = (key << np.uint64(5)) | ((h >> np.uint64(5 * i)) & np.uint64(31))
    return key


def _tie_key(labels: np.ndarray, tie: str, rng) -> np.ndarray:
    if tie == "smallest":
        return labels.astype(np.uint64)
    if tie == "largest":
        return (np.iinfo(np.int64).max - labels).astype(np.uint64)
    if tie == "hash_order":
        return scala_trie_order_key(labels)
    if tie == "random":
        if labels.size == 0:
            return labels.astype(np.uint64)
        perm = rng.permutation(int(labels.max()) + 1).astype(np.uint64)
        return perm[labels]
    raise ValueError(f"unknown tie rule {tie!r}")


def graphx_label_propagation(
    src,
    dst,
    num_vertices: int,
    max_iter: int = 5,
    tie: str = "hash_order",
    seed: int = 0,
) -> np.ndarray:
    """Synchronous LPA with GraphX ``LabelPropagation.run`` structure.

    ``src``/``dst`` are int arrays of directed edge endpoints (duplicates
    kept, exactly as the reference builds them at ``Graphframes.py:70-74``).
    Returns int64 labels ``[num_vertices]``.
    """
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    v = int(num_vertices)
    rng = np.random.default_rng(seed)
    labels = np.arange(v, dtype=np.int64)

    # Both-direction message structure: receiver gets the sender's label.
    recv = np.concatenate([src, dst])
    send = np.concatenate([dst, src])

    for _ in range(max_iter):
        sent_labels = labels[send]
        # Count messages per (receiver, label) pair.
        pairs = recv * v + sent_labels
        uniq, cnt = np.unique(pairs, return_counts=True)
        r = uniq // v
        lab = uniq % v
        # vertexProgram: first maximal count in the tie rule's order.
        order = np.lexsort((_tie_key(lab, tie, rng), -cnt, r))
        r_sorted = r[order]
        first = np.ones(len(order), dtype=bool)
        first[1:] = r_sorted[1:] != r_sorted[:-1]
        new_labels = labels.copy()
        new_labels[r_sorted[first]] = lab[order][first]
        labels = new_labels
    return labels


def canonical_partition(labels) -> np.ndarray:
    """Host-side canonicalization: dense ids ordered by first member vertex
    (the NumPy twin of ``ops.lpa.canonicalize`` for oracle comparisons)."""
    labels = np.asarray(labels)
    v = labels.shape[0]
    first_member = np.full(v, v, dtype=np.int64)
    np.minimum.at(first_member, labels, np.arange(v, dtype=np.int64))
    rep = first_member[labels]
    _, dense = np.unique(rep, return_inverse=True)
    return dense.astype(np.int32)
