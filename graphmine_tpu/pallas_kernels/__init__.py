"""Pallas TPU kernels for the hot ops (SURVEY §7.5: "batched all-pairs
distance + top-k Pallas kernel").

Each kernel has an XLA reference implementation elsewhere in the package
(its oracle in tests) and is auto-dispatched on TPU backends.
"""

from graphmine_tpu.pallas_kernels.knn_pallas import knn_pallas  # noqa: F401
