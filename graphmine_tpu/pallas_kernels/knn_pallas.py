"""Fused all-pairs-distance + running top-k kNN Pallas kernel.

The BASELINE.json north star names this kernel explicitly: "a kNN-graph +
LOF outlier scorer as a batched all-pairs-distance + top-k Pallas kernel".
The reference project has no kNN at all (its outlier rule is a community
size threshold, ``Graphframes.py:135-136``); this is the upgrade path.

Design (TPU-first):

- 2-D sequential grid ``(row_tiles, col_tiles)``. Each step computes one
  ``[TM, TC]`` block of squared distances with a single MXU matmul
  (``rows @ cols.T``) and immediately folds it into a per-row running
  top-k held in VMEM scratch — the ``[N, N]`` distance matrix never
  exists in HBM, so the working set is ``O(TM * (TC + k))``.
- The fold is k rounds of min-extraction over the ``[TM, k + TC]``
  concatenation (VPU work comparable to the matmul's MXU work at
  k ≈ 16-64, TC = 256-512). ``lax.top_k`` is avoided: it has no TPU
  Pallas lowering, and extraction yields ascending order for free.
- Scratch persists across the column (innermost, "arbitrary") grid
  dimension; results are flushed to the output refs on the last column
  step. Row tiles are independent ("parallel").
- Self-matches and padding columns are masked to +inf before the fold.

The XLA implementation in :mod:`graphmine_tpu.ops.knn` is the oracle;
``tests/test_pallas.py`` checks exact index agreement on tie-free inputs
in interpreter mode (CPU) and the dispatcher picks this kernel on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _compiler_params_cls():
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:
        raise RuntimeError(
            "unsupported pallas version: pltpu exposes neither "
            "CompilerParams nor TPUCompilerParams"
        )
    return cls

_BIG = float("inf")


def _knn_kernel(rows_ref, cols_ref, out_d_ref, out_i_ref, best_d, best_i,
                *, k: int, n: int, tm: int, tc: int):
    i = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        best_d[:] = jnp.full_like(best_d, _BIG)
        best_i[:] = jnp.full_like(best_i, -1)

    rows = rows_ref[:]                                   # [TM, F]
    cols = cols_ref[:]                                   # [TC, F]
    # d2[a, b] = |r_a|^2 - 2 r_a . c_b + |c_b|^2 — the matmul is the MXU op.
    # precision=HIGHEST: match the XLA oracle's true-f32 products — the
    # MXU's default bf16 rounding diverged ~1e-2 from CPU (r4 audit).
    cross = jax.lax.dot_general(
        rows, cols,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                    # [TM, TC]
    row_sq = jnp.sum(rows * rows, axis=1, keepdims=True)
    col_sq = jnp.sum(cols * cols, axis=1)[None, :]
    d2 = jnp.maximum(row_sq - 2.0 * cross + col_sq, 0.0)

    row_ids = i * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, tc), 0)
    col_ids = j * tc + jax.lax.broadcasted_iota(jnp.int32, (tm, tc), 1)
    invalid = (row_ids == col_ids) | (col_ids >= n) | (row_ids >= n)
    d2 = jnp.where(invalid, _BIG, d2)

    # Fold the tile into the running top-k: k rounds of min-extraction over
    # the [TM, k + TC] concat. Ascending output order falls out of the
    # extraction order; ties break toward the candidate buffer's leftmost
    # column, i.e. toward the smallest global column id, matching the
    # ascending-index tie order of lax.top_k over -d2 in the XLA oracle.
    cat_d = jnp.concatenate([best_d[:], d2], axis=1)      # [TM, k + TC]
    cat_i = jnp.concatenate([best_i[:], col_ids], axis=1)
    width = k + tc
    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, width), 1)

    new_d = []
    new_i = []
    for _ in range(k):
        m = jnp.min(cat_d, axis=1, keepdims=True)               # [TM, 1]
        first = jnp.min(jnp.where(cat_d == m, lane, width), axis=1, keepdims=True)
        hit = lane == first                                      # one per row
        chosen_i = jnp.sum(jnp.where(hit, cat_i, 0), axis=1, keepdims=True)
        new_d.append(m)
        new_i.append(chosen_i)
        cat_d = jnp.where(hit, _BIG, cat_d)
    best_d[:] = jnp.concatenate(new_d, axis=1)
    best_i[:] = jnp.concatenate(new_i, axis=1)

    @pl.when(j == nj - 1)
    def _flush():
        out_d_ref[:] = best_d[:]
        out_i_ref[:] = best_i[:]


@functools.partial(
    jax.jit, static_argnames=("k", "row_tile", "col_tile", "interpret")
)
def knn_pallas(points: jax.Array, k: int, row_tile: int = 128,
               col_tile: int = 512, interpret: bool = False):
    """k nearest neighbors (squared Euclidean, self excluded), fused on TPU.

    Same contract as :func:`graphmine_tpu.ops.knn.knn`: returns
    ``(dists, idx)`` of shape ``[N, k]``, ascending by distance.
    """
    n, f = points.shape
    if k >= n:
        raise ValueError(f"k={k} must be < number of points {n}")
    if k > 128:
        raise ValueError("knn_pallas supports k <= 128")

    # Pad rows to the tile grid and features to the 128-lane layout; padding
    # rows/columns are masked inside the kernel, zero-padded features are
    # distance-neutral. n must pad to a common multiple of both tile sizes —
    # the grid divides by each independently.
    tile_lcm = math.lcm(row_tile, col_tile)
    n_pad = -(-n // tile_lcm) * tile_lcm
    f_pad = max(-(-f // 128) * 128, 128)
    pts = jnp.pad(points.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))

    grid = (n_pad // row_tile, n_pad // col_tile)
    kernel = functools.partial(
        _knn_kernel, k=k, n=n, tm=row_tile, tc=col_tile
    )
    out_d, out_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, f_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((col_tile, f_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((row_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((row_tile, k), jnp.float32),
            pltpu.VMEM((row_tile, k), jnp.int32),
        ],
        # renamed TPUCompilerParams -> CompilerParams across pallas releases
        compiler_params=_compiler_params_cls()(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(pts, pts)
    return out_d[:n], out_i[:n]
