"""Device-resident graph container.

The TPU-native replacement for the reference's GraphFrame
(``Graphframes.py:78``): instead of a pair of JVM DataFrames keyed by hash
strings, a graph is a set of dense int32 index arrays registered as a JAX
pytree. All superstep kernels (LPA, CC) consume the *message CSR*: the
2E-long (receiver, sender) array pair sorted by receiver, precomputed once
on host so every device-side iteration is gather → segment-reduce with
``indices_are_sorted=True``.

Message semantics match GraphX LPA as invoked at ``Graphframes.py:81``:
messages flow along **both** directions of every directed edge, and
duplicate edges are kept with multiplicity (``Graphframes.py:70-74``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Device kernels gather with int32 indices into the [M] message arrays;
# any per-device message count above this silently wraps (VERDICT r4
# weak 2). Guarded at device assembly (_graph_from_csr), at partition
# time (parallel/sharded.partition_graph), and modeled at plan time
# (pipeline/planner.plan_run).
_INT32_MAX = (1 << 31) - 1


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Graph:
    """Static-shape graph: edges + message CSR.

    Fields
    ------
    src, dst : int32 [E]    directed edge endpoints (dense vertex ids)
    msg_recv : int32 [M]    receiving vertex of each message, sorted ascending
    msg_send : int32 [M]    sending vertex of each message
    msg_ptr  : int32 [V+1]  CSR row pointers into msg_recv/msg_send
    num_vertices : int      static (pytree aux data)
    symmetric : bool        static; True when messages flow both directions
    """

    src: jax.Array
    dst: jax.Array
    msg_recv: jax.Array
    msg_send: jax.Array
    msg_ptr: jax.Array
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    symmetric: bool = dataclasses.field(metadata=dict(static=True), default=True)
    # Optional float32 [M] per-message weights in CSR order (both directions
    # of an edge carry its weight). Set via build_graph(edge_weights=...);
    # weighted LPA argmaxes the per-label weight sum instead of the count.
    msg_weight: jax.Array | None = None

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    @property
    def num_messages(self) -> int:
        return int(self.msg_recv.shape[0])

    def degrees(self) -> jax.Array:
        """Message-degree per vertex (undirected degree with multiplicity
        when ``symmetric``), the segment sizes of the message CSR."""
        return self.msg_ptr[1:] - self.msg_ptr[:-1]


def message_ptr(
    src, dst, num_vertices: int, symmetric: bool = True, recv=None
) -> np.ndarray:
    """CSR row pointers of the message layout (host-side int64 ``[V+1]``).

    The single source of truth for the message-CSR layout contract:
    receivers are ``concat(dst, src)`` when symmetric (both directions,
    duplicates kept), grouped by receiver. Shared by :func:`build_graph`
    and :meth:`~graphmine_tpu.ops.bucketed_mode.BucketedModePlan.from_edges`.
    ``recv``: the receiver concatenation, when the caller already built it
    (skips an O(M) re-concatenation).
    """
    if recv is None:
        src = np.asarray(src)
        dst = np.asarray(dst)
        recv = np.concatenate([dst, src]) if symmetric else dst
    counts = np.bincount(recv, minlength=num_vertices)
    ptr = np.zeros(num_vertices + 1, dtype=np.int64)
    np.cumsum(counts, out=ptr[1:])
    if ptr[-1] >= np.iinfo(np.int32).max:
        raise ValueError("message count exceeds int32; shard the build")
    return ptr


def _message_csr(src, dst, num_vertices, symmetric, use_native=True, weights=None):
    """(ptr int64 [V+1], recv_sorted, send_sorted int32 [M], w_sorted|None)
    — messages grouped by receiver, stable order. Native counting sort when
    available (incl. the weighted build since r2); both directions of an
    edge carry its weight."""
    if len(src) and (
        min(src.min(), dst.min()) < 0
        or max(src.max(), dst.max()) >= num_vertices
    ):
        raise ValueError(f"edge endpoint out of range [0, {num_vertices})")
    if use_native:
        from graphmine_tpu.io import native

        out = native.build_message_csr(
            src, dst, num_vertices, symmetric, weights=weights
        )
        if out is not None:
            # NB: no int32 message-count cap HERE — ptr is int64 and a
            # host-resident CSR beyond 2^31 messages is legal (it exists
            # to be partitioned; per-shard counts are guarded at the
            # device boundaries: _graph_from_csr and partition_graph).
            return out
    if symmetric:
        recv = np.concatenate([dst, src])
        send = np.concatenate([src, dst])
    else:
        recv, send = dst, src
    order = np.argsort(recv, kind="stable")
    ptr = message_ptr(src, dst, num_vertices, symmetric, recv=recv)
    w_sorted = None
    if weights is not None:
        w_all = np.concatenate([weights, weights]) if symmetric else weights
        w_sorted = w_all[order]
    return ptr, recv[order], send[order], w_sorted


def build_graph(
    src, dst, num_vertices: int | None = None, symmetric: bool = True,
    use_native: bool = True, edge_weights=None, to_device: bool = True,
) -> Graph:
    """Build a :class:`Graph` from endpoint arrays (host-side).

    ``symmetric=True`` reproduces the undirected message flow of GraphX LPA
    (both directions of every edge, duplicates kept — ``Graphframes.py:81``).
    The message grouping uses the native C++ counting-sort builder
    (``native/graph_builder.cpp``, O(M+V)) when built, else a NumPy stable
    argsort (O(M log M)); both produce byte-identical layouts (tested).

    ``edge_weights``: optional non-negative float [E] per-edge weights;
    both message directions of an edge carry its weight, and weighted LPA
    (:func:`~graphmine_tpu.ops.lpa.label_propagation`) argmaxes weight
    sums instead of counts.

    ``to_device=False`` keeps every array as host NumPy (r3): the layout
    for graphs that exist only to be PARTITIONED over a mesh — the memory
    planner may have just determined the whole graph cannot fit one
    device, so materializing it there before sharding would OOM the exact
    configs the ring schedule exists for. Host graphs work with
    ``partition_graph`` and the host paths of ``census_table``/degree
    helpers; device supersteps require ``to_device=True``.
    """
    src, dst, num_vertices = _prepare_edges(src, dst, num_vertices)
    w = _prepare_weights(edge_weights, src)
    ptr, recv, send, w_sorted = _message_csr(
        src, dst, num_vertices, symmetric, use_native, weights=w
    )
    if not to_device:
        # Host graphs keep int64 ptr past the int32 range: they exist to
        # be PARTITIONED (per-shard counts are re-checked exactly in
        # partition_graph); int32 below that saves half the ptr bytes.
        host_ptr = (
            ptr.astype(np.int32)
            if (len(ptr) == 0 or int(ptr[-1]) <= _INT32_MAX) else ptr
        )
        return Graph(
            src=src, dst=dst, msg_recv=recv, msg_send=send,
            msg_ptr=host_ptr, num_vertices=num_vertices,
            symmetric=symmetric, msg_weight=w_sorted,
        )
    return _graph_from_csr(
        src, dst, ptr, recv, send, num_vertices, symmetric, msg_weight=w_sorted
    )


def _prepare_weights(edge_weights, src):
    """Shared edge-weight coercion/validation (one float per edge, >= 0,
    not NaN) for the graph builders (here and ``build_graph_and_plan``)."""
    if edge_weights is None:
        return None
    w = np.asarray(edge_weights, dtype=np.float32)
    if w.shape != src.shape:
        raise ValueError("edge_weights must be one float per edge")
    if len(w) and not np.all(w >= 0):  # also catches NaN (NaN >= 0 is False)
        raise ValueError("edge_weights must be non-negative and not NaN")
    return w


def _prepare_edges(src, dst, num_vertices):
    """Shared endpoint coercion/validation/V-inference for graph builders."""
    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    if src.shape != dst.shape or src.ndim != 1:
        raise ValueError("src/dst must be equal-length 1-D arrays")
    if num_vertices is None:
        num_vertices = int(max(src.max(initial=-1), dst.max(initial=-1))) + 1
    return src, dst, num_vertices


def _graph_from_csr(
    src, dst, ptr, recv, send, num_vertices, symmetric, msg_weight=None
) -> Graph:
    """Assemble the device-resident Graph from a host-built message CSR.

    Loudly rejects CSRs past the int32 gather-index range: every
    device kernel (fused bucketed LPA, segment ops) emits int32 indices
    into the ``[M]`` message arrays, so ``M > 2^31 - 1`` on ONE device
    would overflow *silently* at gather time (VERDICT r4 weak 2). The
    planner models this bound at plan time (``pipeline/planner.py``);
    this is the hard backstop for direct ``build_graph`` callers.
    """
    if len(ptr) and int(ptr[-1]) > _INT32_MAX:
        raise ValueError(
            f"message count {int(ptr[-1]):,} exceeds the int32 gather-index "
            f"bound {_INT32_MAX:,} for a single device; partition the graph "
            f"over a mesh (partition_graph / schedule='ring') instead"
        )
    return Graph(
        src=jnp.asarray(src),
        dst=jnp.asarray(dst),
        msg_recv=jnp.asarray(recv),
        msg_send=jnp.asarray(send),
        msg_ptr=jnp.asarray(ptr.astype(np.int32)),
        num_vertices=num_vertices,
        symmetric=symmetric,
        msg_weight=None if msg_weight is None else jnp.asarray(msg_weight),
    )


def graph_from_edge_table(
    table, symmetric: bool = True, to_device: bool = True
) -> Graph:
    """Build a graph from an :class:`graphmine_tpu.io.edges.EdgeTable`;
    the table's optional per-edge ``weights`` carry through to weighted
    message flow (``load_edge_list(weight_col=...)``). ``to_device=False``
    keeps host NumPy arrays (see :func:`build_graph`)."""
    return build_graph(
        table.src, table.dst, num_vertices=table.num_vertices,
        symmetric=symmetric, edge_weights=getattr(table, "weights", None),
        to_device=to_device,
    )


def simple_undirected_edges(graph: Graph) -> tuple[np.ndarray, np.ndarray]:
    """Host-side simplification: distinct undirected edges, no self-loops.

    Returns ``(a, b)`` int32 arrays with ``a < b``, one row per undirected
    edge. The common preprocessing for ops defined on the simple graph
    (triangle counting, k-core — GraphFrames' ``triangleCount`` ignores
    direction and duplicates the same way).
    """
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    v = graph.num_vertices
    keep = src != dst
    a = np.minimum(src[keep], dst[keep]).astype(np.int64)
    b = np.maximum(src[keep], dst[keep]).astype(np.int64)
    und = np.unique(a * v + b)
    return (und // v).astype(np.int32), (und % v).astype(np.int32)
