from graphmine_tpu.graph.container import Graph, build_graph

__all__ = ["Graph", "build_graph"]
