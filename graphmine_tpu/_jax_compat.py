"""Version-bridging aliases for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace (jax >= 0.5), renaming ``check_rep`` to ``check_vma`` on
the way; the container pins 0.4.x where only the experimental spelling
exists. Every caller imports from here (using the NEW spelling) so the
bridge lives in exactly one place and deletes cleanly once the floor moves.
"""

from __future__ import annotations

try:  # jax >= 0.5: public API, check_vma kwarg
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental location, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # 0.4.x's replication checker has no rule for while-loops (ring
        # fixpoints, ppr batching); it is a static checker only, so default
        # it off rather than making every caller version-conditional.
        kwargs.setdefault("check_rep", False)
        return _shard_map(f, **kwargs)


try:  # jax >= 0.7: explicit varying-axes casts for the vma type system
    from jax.lax import pcast  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x has no vma tracking; the cast is identity
    def pcast(x, axes, *, to=None):
        del axes, to
        return x


__all__ = ["pcast", "shard_map"]
