"""Synthetic graph generators + the BASELINE scale ladder.

``BASELINE.json`` defines a benchmark ladder over SNAP graphs (ego-Facebook
→ com-Amazon → com-LiveJournal → Twitter-2010). This environment has no
network egress, so the ladder is served two ways: a real SNAP edge-list
file if one is present on disk (``load`` checks ``data_dir``), otherwise an
**R-MAT** synthetic stand-in matched to the target's vertex/edge scale.

R-MAT (Chakrabarti et al., SDM'04) is the standard web/social-graph
generator (Graph500 uses it): each edge picks its (src, dst) bit-by-bit by
recursively descending into one of four adjacency-matrix quadrants with
probabilities (a, b, c, d). The default (0.57, 0.19, 0.19, 0.05) yields
power-law degree skew comparable to the reference's CommonCrawl sample
(max degree 1,223 at 4.6K vertices — BASELINE.md).

Generation is fully vectorized host-side NumPy — ``scale`` rounds of
``2E`` Bernoulli draws, no per-edge Python — then handed to the device as
dense int32, matching the framework's ingestion contract.

Also here: structural-anomaly injection for the LOF AUROC harness
(BASELINE.json's second headline metric).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

__all__ = [
    "rmat", "LadderRung", "LADDER", "load", "snap_path",
    "inject_structural_anomalies", "planted_anomaly_graph",
]


def rmat(
    scale: int,
    edge_factor: float = 16.0,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    dedup: bool = False,
    permute: bool = True,
):
    """R-MAT edge list: ``2**scale`` vertices, ``edge_factor * 2**scale`` edges.

    Returns ``(src, dst)`` int32 arrays. ``permute`` relabels vertices with
    a random permutation (breaks the correlation between id and degree that
    raw R-MAT has). ``dedup`` drops duplicate directed pairs (Graph500
    keeps them; the reference also keeps duplicates — ``Graphframes.py:70-74``
    — so the default matches both).
    """
    if not 0 < a + b + c <= 1.0:
        raise ValueError("quadrant probabilities must satisfy 0 < a+b+c <= 1")
    v = 1 << scale
    e = int(edge_factor * v)
    rng = np.random.default_rng(seed)
    src = np.zeros(e, dtype=np.int64)
    dst = np.zeros(e, dtype=np.int64)
    for _ in range(scale):
        r = rng.random(e)
        # quadrant draw: [0,a) -> (0,0), [a,a+b) -> (0,1), [a+b,a+b+c) -> (1,0)
        src_bit = r >= a + b
        dst_bit = (r >= a) & (r < a + b) | (r >= a + b + c)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    if permute:
        perm = rng.permutation(v)
        src, dst = perm[src], perm[dst]
    if dedup:
        pairs = np.unique(src * v + dst)
        src, dst = pairs // v, pairs % v
    return src.astype(np.int32), dst.astype(np.int32)


def sbm(
    block_sizes,
    p_in: float,
    p_out: float,
    seed: int = 0,
    directed: bool = False,
):
    """Stochastic block model with planted communities — the ground-truth
    generator for community-detection *accuracy* evaluation (the axis the
    reference's ``Overview:9`` names but never measures).

    Returns ``(src, dst, blocks)``: int32 edge endpoints (no self-loops,
    deduplicated) and the int32 planted block id per vertex. Sampling is
    sparse — per block pair, the edge count is drawn ``Binomial(n_pairs,
    p)`` and that many endpoint pairs are sampled uniformly — so cost is
    O(edges), not O(V²).
    """
    sizes = np.asarray(block_sizes, dtype=np.int64)
    if (sizes <= 0).any():
        raise ValueError("block sizes must be positive")
    for name, p in (("p_in", p_in), ("p_out", p_out)):
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"{name} must be in [0, 1]")
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    blocks = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
    rng = np.random.default_rng(seed)
    srcs, dsts = [], []
    for i in range(len(sizes)):
        j_range = range(len(sizes)) if directed else range(i, len(sizes))
        for j in j_range:
            p = p_in if i == j else p_out
            if p <= 0.0:
                continue
            if i == j:
                # diagonal blocks: count *distinct* vertex pairs, else the
                # intra density doubles relative to p (both orientations of
                # a draw land on the same unordered edge)
                ni = int(sizes[i])
                n_pairs = ni * (ni - 1) if directed else ni * (ni - 1) // 2
            else:
                n_pairs = int(sizes[i] * sizes[j])
            m = rng.binomial(n_pairs, p)
            if m == 0:
                continue
            a = rng.integers(0, sizes[i], m) + offsets[i]
            b = rng.integers(0, sizes[j], m) + offsets[j]
            keep = a != b
            a, b = a[keep], b[keep]
            if i == j and not directed:
                a, b = np.minimum(a, b), np.maximum(a, b)  # canonical orientation
            srcs.append(a)
            dsts.append(b)
    if not srcs:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32), blocks)
    src = np.concatenate(srcs).astype(np.int64)
    dst = np.concatenate(dsts).astype(np.int64)
    v = int(offsets[-1])
    pairs = np.unique(src * v + dst)
    return (pairs // v).astype(np.int32), (pairs % v).astype(np.int32), blocks


@dataclass(frozen=True)
class LadderRung:
    """One rung of the BASELINE.json benchmark ladder."""

    name: str
    snap_file: str  # expected on-disk SNAP edge list name (if downloaded)
    scale: int  # rmat scale for the synthetic stand-in
    edge_factor: float
    description: str


# Sizes match BASELINE.json "configs" (±, rounded to powers of two).
LADDER: dict[str, LadderRung] = {
    r.name: r
    for r in [
        LadderRung(
            "ego-facebook", "facebook_combined.txt", 12, 21.5,
            "SNAP ego-Facebook: 4K nodes / 88K edges — LPA + CC",
        ),
        LadderRung(
            "com-amazon", "com-amazon.ungraph.txt", 18, 3.5,
            "SNAP com-Amazon: 335K nodes / 926K edges — Louvain vs LPA",
        ),
        LadderRung(
            "com-livejournal", "com-lj.ungraph.txt", 22, 8.3,
            "SNAP com-LiveJournal: 4M nodes / 34M edges — sharded CSR over the mesh",
        ),
        LadderRung(
            "twitter-2010", "twitter-2010.txt", 25, 42.0,
            "Twitter-2010: 41M nodes / 1.4B edges — streaming LOF at slice scale",
        ),
    ]
}


def snap_path(name: str, data_dir: str = "data") -> str | None:
    """Path to the rung's real SNAP edge list, or ``None`` when absent.

    The single source of truth for real-vs-stand-in resolution: ``load``
    uses it to pick the input and ``bench.py --tier snap`` uses it to
    label the record's ``source`` — the two can't desync.
    """
    rung = LADDER.get(name)
    if rung is None:
        raise KeyError(f"unknown ladder rung {name!r}; have {sorted(LADDER)}")
    path = os.path.join(data_dir, rung.snap_file)
    return path if os.path.exists(path) else None


def load(name: str, data_dir: str = "data", seed: int = 0, max_scale: int | None = None):
    """Load a ladder rung: the real SNAP file when present, else R-MAT.

    ``max_scale`` caps the synthetic size (e.g. for CI / single-chip runs);
    the real file, when found, is always loaded in full. Returns an
    :class:`~graphmine_tpu.io.edges.EdgeTable`.
    """
    rung = LADDER.get(name)
    if rung is None:
        raise KeyError(f"unknown ladder rung {name!r}; have {sorted(LADDER)}")
    path = snap_path(name, data_dir)
    if path is not None:
        from graphmine_tpu.io.edges import load_edge_list

        return load_edge_list(path)
    from graphmine_tpu.io.edges import from_arrays

    scale = rung.scale if max_scale is None else min(rung.scale, max_scale)
    ef = rung.edge_factor
    src, dst = rmat(scale, ef, seed=seed)
    return from_arrays(src, dst)


def planted_anomaly_graph(
    num_vertices: int,
    num_edges: int,
    n_communities: int | None = None,
    size_skew: float = 0.7,
    n_friends: int = 4,
    hub_skew: float = 1.3,
    hub_scale: float = 20.0,
    p_noise: float = 0.03,
    num_anomalies: int | None = None,
    edges_per_anomaly: int = 60,
    seed: int = 0,
):
    """Planted communities over a sparse hub skeleton + injected
    anomalies — the e2e bench dataset (VERDICT r5 weak-item 1: the old
    pure power-law draw collapsed under LPA to 3 giant communities, so
    the timed census / outlier chapters detected NOTHING and the
    flagship number measured a vacuous pipeline).

    Construction (fully vectorized, O(V + E) host work):

    - vertices land in ``n_communities`` planted blocks with Zipf-ish
      sizes (``(1+i)^-size_skew``, normalized);
    - each vertex draws a fixed pool of ``n_friends`` partners within
      its block, pareto-skewed toward the block's first rows (consistent
      per-block hubs, the reference data's CommonCrawl pattern); every
      edge anchors a uniform vertex and picks uniformly from the
      anchor's pool. The edge *budget* lands as duplicate multiplicity
      (reference parity — duplicates kept, ``Graphframes.py:70-74``)
      while the DISTINCT-pair skeleton stays sparse. That sparsity is
      load-bearing for the outlier chapter: 5-superstep LPA genuinely
      does not converge on a large-diameter sparse skeleton, so the
      top-level census finds a long-tailed thousands-of-communities
      partition (like the reference data: 4.6K vertices → ~650
      communities) and the recursive masked re-run fragments each
      sizable parent into many sub-communities — populating the
      bottom-decile rule (``Graphframes.py:135-136``) the dense
      all-pairs draw starved (a dense block re-converges identically in
      both passes; measured flagged=0 across every dense knob setting);
    - a ``p_noise`` fraction of partners is re-drawn uniformly across
      the graph: cross-community weather, non-trivial boundaries;
    - ``inject_structural_anomalies`` wires ``num_anomalies`` vertices
      (default ``max(32, V/2000)``) to uniform endpoints — the held-out
      ground truth the LOF chapter must detect.

    Returns ``(src, dst, is_anomaly, communities)``: int32 edge arrays
    (directed, duplicates kept), the bool anomaly mask, and the planted
    block id per vertex.
    """
    rng = np.random.default_rng(seed)
    v, e = num_vertices, num_edges
    if n_communities is None:
        n_communities = max(8, v >> 9)
    w = (1.0 + np.arange(n_communities)) ** -size_skew
    w /= w.sum()
    comm = rng.choice(n_communities, size=v, p=w).astype(np.int32)
    order = np.argsort(comm, kind="stable")
    sizes = np.bincount(comm, minlength=n_communities).astype(np.int64)
    starts = np.zeros(n_communities, np.int64)
    np.cumsum(sizes[:-1], out=starts[1:])

    sz = sizes[comm]  # >= 1: the vertex itself lives in its block
    raw = rng.pareto(hub_skew, size=(v, n_friends))
    loc = np.minimum(
        (raw * sz[:, None] / hub_scale).astype(np.int64), (sz - 1)[:, None]
    )
    friends = order[starts[comm][:, None] + loc]  # [V, n_friends]

    anchors = rng.integers(0, v, e)
    partners = friends[anchors, rng.integers(0, n_friends, e)]
    noise = rng.random(e) < p_noise
    partners[noise] = rng.integers(0, v, int(noise.sum()))

    src = anchors.astype(np.int32)
    dst = partners.astype(np.int32)
    if num_anomalies is None:
        num_anomalies = max(32, v // 2000)
    src, dst, is_anomaly = inject_structural_anomalies(
        src, dst, v, num_anomalies=num_anomalies,
        edges_per_anomaly=edges_per_anomaly, seed=seed + 1,
    )
    return src, dst, is_anomaly, comm


def inject_structural_anomalies(
    src: np.ndarray,
    dst: np.ndarray,
    num_vertices: int,
    num_anomalies: int,
    edges_per_anomaly: int = 20,
    seed: int = 0,
):
    """Wire ``num_anomalies`` random existing vertices to uniform-random
    endpoints, making them community-bridging hubs — the held-out outliers
    of the LOF AUROC harness (BASELINE.json metric). Uniform cross-graph
    edges put the anomaly in no community's neighborhood, which is exactly
    the structural signature the feature/LOF pipeline scores.

    Returns ``(src, dst, is_anomaly)`` with the new edges appended;
    ``is_anomaly`` is a bool ``[num_vertices]`` ground-truth mask.
    """
    rng = np.random.default_rng(seed)
    anomalies = rng.choice(num_vertices, size=num_anomalies, replace=False)
    a_src = np.repeat(anomalies, edges_per_anomaly)
    a_dst = rng.integers(0, num_vertices, num_anomalies * edges_per_anomaly)
    out_src = np.concatenate([src, a_src]).astype(np.int32)
    out_dst = np.concatenate([dst, a_dst]).astype(np.int32)
    mask = np.zeros(num_vertices, dtype=bool)
    mask[anomalies] = True
    return out_src, out_dst, mask
