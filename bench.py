"""Headline benchmark: LPA edges/sec/chip (BASELINE.json "metric").

Runs synchronous label propagation on a synthetic power-law graph sized for
one chip, times the compiled superstep loop, and prints ONE JSON line.

Baseline derivation (the reference publishes no numbers — BASELINE.md):
the north-star target is "LPA on a 100M-edge graph converges < 60 s on a
TPU v4-8" (8 chips). Reading that conservatively as 5 supersteps (the
reference's maxIter, Graphframes.py:81) in 60 s: 100e6 edges x 5 iters /
(60 s x 8 chips) ≈ 1.04e6 edges/sec/chip. vs_baseline > 1 beats it.
"""

import json
import os
import time

import numpy as np

BASELINE_EDGES_PER_SEC_PER_CHIP = 100e6 * 5 / (60.0 * 8)

# Sized for a single chip: ~8.4M directed edges -> 16.8M messages.
NUM_VERTICES = 1 << 20
NUM_EDGES = 1 << 23
ITERS = 10


def powerlaw_edges(v: int, e: int, seed: int = 0):
    """Preferential-attachment-flavored endpoints: degree skew comparable to
    web graphs (the bundled data's hub pattern, BASELINE.md)."""
    rng = np.random.default_rng(seed)
    # Zipf-ish endpoint draw via inverse-CDF on a pareto tail, clipped.
    raw = rng.pareto(1.2, size=2 * e)
    ids = np.minimum((raw * v / 50).astype(np.int64), v - 1).astype(np.int32)
    perm = rng.permutation(v).astype(np.int32)  # decorrelate id order
    ids = perm[ids]
    return ids[:e], ids[e:]


def main() -> None:
    import jax
    import jax.numpy as jnp

    # Persistent compile cache: the superstep program at this size is
    # expensive to compile on TPU; repeat bench runs should pay it once.
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )

    src, dst = powerlaw_edges(NUM_VERTICES, NUM_EDGES)
    # Fused degree-bucketed kernel (ops/bucketed_mode.py): ~3x the sort-
    # based superstep at this scale, bit-identical labels (tested). Graph
    # and plan share one host message-CSR build (native counting sort).
    graph, plan = build_graph_and_plan(src, dst, num_vertices=NUM_VERTICES)

    # Compile a single superstep once; the timed loop feeds labels back so
    # every iteration computes on fresh data (steady-state throughput).
    raw_step = jax.jit(lpa_superstep_bucketed)
    step = lambda lbl, g: raw_step(lbl, g, plan)
    labels = jnp.arange(NUM_VERTICES, dtype=jnp.int32)
    labels = step(labels, graph)
    np.asarray(labels[:8])

    # Completion signal: a tiny device->host fetch of a slice that depends
    # on the final labels. On the tunneled axon TPU backend,
    # block_until_ready() was observed returning before the computation
    # finished (33us/iter for a 16M-element sort loop — physically
    # impossible); a data fetch cannot be early. The 32-byte transfer adds
    # negligible time to the window.
    t0 = time.perf_counter()
    for _ in range(ITERS):
        labels = step(labels, graph)
    np.asarray(labels[:8])
    dt = time.perf_counter() - t0

    # The timed loop is a plain jit on one device; normalizing by the full
    # device count would understate the per-chip number on multi-chip hosts.
    chips = 1
    eps_chip = NUM_EDGES * ITERS / dt / chips
    print(
        json.dumps(
            {
                "metric": "lpa_edges_per_sec_per_chip",
                "value": round(eps_chip),
                "unit": "edges/s/chip",
                "vs_baseline": round(eps_chip / BASELINE_EDGES_PER_SEC_PER_CHIP, 3),
                "detail": {
                    "num_vertices": NUM_VERTICES,
                    "num_edges": NUM_EDGES,
                    "iters": ITERS,
                    "seconds": round(dt, 3),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
