"""Headline benchmark: LPA edges/sec/chip (BASELINE.json "metric").

Runs synchronous label propagation on a synthetic power-law graph sized for
one chip, times the compiled superstep loop, and prints ONE JSON line.

Baseline derivation (the reference publishes no numbers — BASELINE.md):
the north-star target is "LPA on a 100M-edge graph converges < 60 s on a
TPU v4-8" (8 chips). Reading that conservatively as 5 supersteps (the
reference's maxIter, Graphframes.py:81) in 60 s: 100e6 edges x 5 iters /
(60 s x 8 chips) ≈ 1.04e6 edges/sec/chip. vs_baseline > 1 beats it.

``--tier northstar`` runs the north-star config itself — 100M directed
edges, LPA(maxIter=5) — as a single-device jit and reports seconds for
the five compiled supersteps (host build and first-compile broken out in
``detail``); under 60 is the target BASELINE.json budgets EIGHT v4 chips
for.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_EDGES_PER_SEC_PER_CHIP = 100e6 * 5 / (60.0 * 8)

# Default tier, sized for a single chip: ~8.4M directed edges -> 16.8M
# messages. The northstar tier overrides these; the CPU-fallback capture
# path (see orchestrate()) shrinks them so a degraded run still finishes.
NUM_VERTICES = 1 << 20
NUM_EDGES = 1 << 23
ITERS = 10

_CPU_FALLBACK = os.environ.get("GRAPHMINE_BENCH_CPU_FALLBACK") == "1"
if _CPU_FALLBACK:
    NUM_VERTICES = 1 << 17
    NUM_EDGES = 1 << 20
    ITERS = 5


def _bench_run_identity() -> tuple[str, str]:
    """One (run_id, trace_id) pair per bench invocation, inherited by
    measurement children via the environment (ISSUE 11 satellite): every
    printed record — and the BENCH_*.json header built from the suite
    summary — carries the same identity, so a bench run joins the
    obs_report/trace_stitch timeline of any serving/pipeline JSONL
    captured in the same window (the silicon-capture backlog's
    log-correlation ask)."""
    rid = os.environ.get("GRAPHMINE_BENCH_RUN_ID")
    tid = os.environ.get("GRAPHMINE_BENCH_TRACE_ID")
    if not rid or not tid:
        from graphmine_tpu.obs.spans import _new_id, new_run_id

        rid = rid or new_run_id()
        tid = tid or _new_id(8)
        os.environ["GRAPHMINE_BENCH_RUN_ID"] = rid
        os.environ["GRAPHMINE_BENCH_TRACE_ID"] = tid
    return rid, tid


def powerlaw_edges(v: int, e: int, seed: int = 0):
    """Preferential-attachment-flavored endpoints: degree skew comparable to
    web graphs (the bundled data's hub pattern, BASELINE.md)."""
    rng = np.random.default_rng(seed)
    # Zipf-ish endpoint draw via inverse-CDF on a pareto tail, clipped.
    raw = rng.pareto(1.2, size=2 * e)
    ids = np.minimum((raw * v / 50).astype(np.int64), v - 1).astype(np.int32)
    perm = rng.permutation(v).astype(np.int32)  # decorrelate id order
    ids = perm[ids]
    return ids[:e], ids[e:]


def _setup_jax_cache():
    """Persistent compile cache (repo-local dir so repeat bench runs pay
    compilation once). Returns the fused-kernel entry points both tiers
    use."""
    from graphmine_tpu.compile_cache import enable_compile_cache

    enable_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    )

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )

    return build_graph_and_plan, lpa_superstep_bucketed


def main_northstar() -> None:
    """North-star config (BASELINE.json): LPA(maxIter=5) over 100M edges.

    Single-device jit on jax.devices()[0] (chips=1 in the output records
    that; the budgeted target hardware is a v4-8). The headline value is
    the five compiled supersteps only — host graph generation/build and
    the one-off first compile are reported separately in ``detail``."""
    import jax
    import jax.numpy as jnp

    build_graph_and_plan, lpa_superstep_bucketed = _setup_jax_cache()

    v, e, iters = 1 << 24, 100_000_000, 5
    if _CPU_FALLBACK:
        # Degraded capture: 1/16 scale so the record exists at all; the
        # capture annotation marks it as not the real north-star run.
        v, e = 1 << 20, 6_250_000
    t0 = time.perf_counter()
    src, dst = powerlaw_edges(v, e)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    graph, plan = build_graph_and_plan(src, dst, num_vertices=v)
    t_build = time.perf_counter() - t0

    raw_step = jax.jit(lpa_superstep_bucketed)
    labels = jnp.arange(v, dtype=jnp.int32)
    t0 = time.perf_counter()
    labels = raw_step(labels, graph, plan)   # includes compile
    np.asarray(labels[:8])
    t_compile = time.perf_counter() - t0

    labels = jnp.arange(v, dtype=jnp.int32)
    t0 = time.perf_counter()
    for _ in range(iters):
        labels = raw_step(labels, graph, plan)
    np.asarray(labels[:8])
    dt = time.perf_counter() - t0

    chips = 1
    print(
        json.dumps(
            {
                # A degraded 1/16-scale CPU-fallback run must not claim the
                # 100M-edge metric name or its 60s-target ratio.
                "metric": (
                    "lpa_6m_maxiter5_seconds_cpu_fallback"
                    if _CPU_FALLBACK else "lpa_100m_maxiter5_seconds"
                ),
                "value": round(dt, 3),
                "unit": "s",
                # target: < 60 s on a v4-8 (8 chips). vs_baseline is the
                # plain 60s-target ratio; "chips" below records that this
                # run used a fraction of the budgeted hardware.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(60.0 / dt, 3),
                "detail": {
                    "num_vertices": v,
                    "num_edges": e,
                    "iters": iters,
                    "chips": chips,
                    "edges_per_sec_per_chip": round(e * iters / dt / chips),
                    "gen_seconds": round(t_gen, 1),
                    "build_seconds": round(t_build, 1),
                    "first_iter_with_compile_seconds": round(t_compile, 1),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_lof() -> None:
    """Second driver metric (BASELINE.json): LOF AUROC on held-out
    structural outliers. Full pipeline on device — LPA communities →
    vertex features → kNN/LOF scores — against injected ground truth."""
    import jax

    _setup_jax_cache()

    from graphmine_tpu.datasets import inject_structural_anomalies, rmat
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.features import standardize, vertex_features
    from graphmine_tpu.ops.lof import auroc, lof_scores
    from graphmine_tpu.ops.lpa import label_propagation

    scale, v, anomalies = 16, 1 << 16, 64
    if _CPU_FALLBACK:
        scale, v, anomalies = 14, 1 << 14, 16
    src, dst = rmat(scale, edge_factor=16, seed=1)
    src, dst, truth = inject_structural_anomalies(
        src, dst, v, num_anomalies=anomalies, edges_per_anomaly=60, seed=2
    )
    g = build_graph(src, dst, num_vertices=v)
    t0 = time.perf_counter()
    labels = label_propagation(g, max_iter=5)
    feats = standardize(vertex_features(g, labels))
    # LOF's k must exceed the size of any clustered anomaly group (64
    # injected hubs with near-identical features), else their kNN
    # neighborhoods are each other and they score as inliers: k=20 gives
    # AUROC ~0.49 here (docs/DESIGN.md); k=128 measured best across seeds
    # with the 8-feature set (0.91-0.93 vs 0.89-0.91 at 6 features/k=100).
    scores = np.asarray(lof_scores(feats, k=128))
    dt = time.perf_counter() - t0
    score = float(auroc(scores, truth))

    # Scale-out feature configs, scored on the SAME graph/truth so the
    # as-deployed quality is a recorded measurement, not a proxy band
    # (VERDICT r3 item 5): host-7 (clustering zeroed) and host-8 with the
    # wedge-SAMPLED clustering column (what scale-out mode actually runs).
    from graphmine_tpu.ops.features import vertex_features_host

    host_g = build_graph(src, dst, num_vertices=v, to_device=False)
    np_labels = np.asarray(labels)
    auroc_7 = float(auroc(np.asarray(lof_scores(standardize(
        vertex_features_host(host_g, np_labels, include_clustering=False)
    ), k=128)), truth))
    auroc_8s = float(auroc(np.asarray(lof_scores(standardize(
        vertex_features_host(host_g, np_labels, include_clustering="sampled")
    ), k=128)), truth))

    # Pallas-vs-XLA kNN on the SAME feature matrix this tier scores with
    # (VERDICT r4 item 5): the r1-r4 auto-policy assumed Pallas wins on
    # TPU for any k <= 128; the r5 silicon sweep measured XLA's tiled
    # dot+top_k FASTER for every k > 8 (ops/knn.py provenance table), so
    # impl="auto" now deploys XLA at this tier's k=128. This block
    # regenerates both ends of that decision each capture: the deployed
    # k=128 point and the k=8 crossover point where Pallas still wins.
    # Timed on the real backend only (no Mosaic kernel on CPU fallback).
    knn_timing = None
    if not _CPU_FALLBACK and jax.default_backend() == "tpu":
        from graphmine_tpu.ops.knn import knn as knn_fn

        feats_dev = jax.device_put(np.asarray(feats))
        knn_timing = {"points": int(feats_dev.shape[0]), "by_k": {}}
        for kk in (8, 128):
            row = {}
            for impl in ("pallas", "xla"):
                d2, _ = knn_fn(feats_dev, k=kk, impl=impl)
                np.asarray(d2[:1])  # compile + settle
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    d2, _ = knn_fn(feats_dev, k=kk, impl=impl)
                    np.asarray(d2[:1])
                    best = min(best, time.perf_counter() - t0)
                row[f"{impl}_seconds"] = round(best, 4)
            row["pallas_speedup_vs_xla"] = round(
                row["xla_seconds"] / row["pallas_seconds"], 3
            )
            knn_timing["by_k"][str(kk)] = row

        # IVF-flat approximate path (r5): AUROC + wall on the SAME
        # cloud/truth. At this 65K harness scale the index overheads
        # make it SLOWER than exact (its design point is ~250K+ where
        # exact hit the top_k roofline: 9.0 s vs 27.8 s at 262K,
        # recall 0.9999 — docs/ROUND5.md); recorded here so the
        # quality cost stays a measured number every capture.
        s0 = lof_scores(feats_dev, k=128, impl="ivf")
        np.asarray(s0[:1])
        t0 = time.perf_counter()
        s_ivf = np.asarray(lof_scores(feats_dev, k=128, impl="ivf"))
        knn_timing["ivf_lof"] = {
            "seconds": round(time.perf_counter() - t0, 2),
            "auroc": round(float(auroc(s_ivf, truth)), 4),
        }
    print(
        json.dumps(
            {
                "metric": (
                    "lof_auroc_injected_outliers_cpu_fallback"
                    if _CPU_FALLBACK else "lof_auroc_injected_outliers"
                ),
                "value": round(score, 4),
                "unit": "auroc",
                # baseline: 0.5 = chance; the harness target is > 0.8.
                # Fallback runs at reduced scale: no target ratio claimed.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(score / 0.8, 3),
                "detail": {
                    "num_vertices": v,
                    "num_edges": int(len(src)),
                    "num_anomalies": anomalies,
                    # first run includes jit compiles (persistently cached)
                    "seconds_with_compile": round(dt, 2),
                    # scale-out feature configs on the same graph/truth:
                    # host-7 (clustering zeroed) and the as-deployed
                    # host-8 with sampled clustering (VERDICT r3 item 5)
                    "auroc_host_7feat": round(auroc_7, 4),
                    "auroc_host_8feat_sampled": round(auroc_8s, 4),
                    # real-silicon Pallas-vs-XLA kNN at the deployed k=128
                    # and the k=8 crossover (r4 item 5); None off-TPU —
                    # the full policy citation lives in ops/knn.py
                    "knn_impl_timing": knn_timing,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def _run_snap_rung(
    name, data_dir, max_scale, build_graph_and_plan, lpa_superstep_bucketed
):
    """Measure one ladder rung; returns its record dict.

    Schedules via the memory planner: small rungs run the single-device
    fused kernel; a rung too big for one chip (the Twitter-2010 top rung)
    dispatches to the planner-selected replicated/ring schedule over the
    visible mesh — the same dispatch the pipeline driver uses — and a rung
    no schedule fits gets a numeric ``skipped`` record, never a crash."""
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.datasets import load, snap_path
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.louvain import louvain
    from graphmine_tpu.ops.lpa import num_communities
    from graphmine_tpu.pipeline.driver import device_hbm_bytes
    from graphmine_tpu.pipeline.planner import (
        PlanError,
        hbm_bytes_per_device,
        plan_run,
    )

    real = snap_path(name, data_dir) is not None
    et = load(name, data_dir=data_dir, max_scale=max_scale)
    v, e = et.num_vertices, int(len(et.src))
    base = {
        "rung": name,
        "source": "snap" if real else "rmat-standin",
        "vertices": v,
        "edges": e,
    }

    try:
        # Same budget chain as the driver: env → device memory_stats
        # (lazy: skipped when the env override wins) → 16 GiB default
        # (VERDICT r3 item 3).
        rp = plan_run(
            v, e, len(jax.devices()),
            hbm=hbm_bytes_per_device(device_hbm_bytes),
        )
    except PlanError as ex:
        return dict(base, skipped=str(ex)[:400])

    if rp.schedule != "single":
        # Multi-device rung: planner-selected replicated/ring schedule.
        # EVERY per-rung op stays distributed (LPA *and* CC) — the planner
        # just said the unsharded graph does not fit one device, so the
        # single-device connected_components below would OOM after a
        # successful LPA. Keeps the full shard set (no lpa_only trimming):
        # the sharded CC bodies read the sort-body message CSR.
        from graphmine_tpu.graph.container import build_graph
        from graphmine_tpu.parallel.mesh import make_mesh
        from graphmine_tpu.parallel.ring import (
            ring_connected_components,
            ring_label_propagation,
        )
        from graphmine_tpu.parallel.sharded import (
            partition_graph,
            shard_graph_arrays,
            sharded_connected_components,
            sharded_label_propagation,
        )

        t0 = time.perf_counter()
        # Host-resident build: the planner just said the unsharded graph
        # exceeds one device — partitioning slices host arrays straight
        # onto the mesh (same discipline as the driver's scale-out mode).
        graph = build_graph(et.src, et.dst, num_vertices=v, to_device=False)
        mesh = make_mesh()
        sg = shard_graph_arrays(
            partition_graph(
                graph, mesh=mesh,
                build_bucket_plan=rp.schedule == "replicated",
            ),
            mesh,
        )
        t_build = time.perf_counter() - t0
        ring = rp.schedule == "ring"
        lp = ring_label_propagation if ring else sharded_label_propagation
        cc_fn = (
            ring_connected_components if ring else sharded_connected_components
        )
        # Warm up with the SAME static signature as the timed call:
        # max_iter is a static argument of the jitted scan program, so a
        # max_iter=1 warm-up would leave the max_iter=5 compile inside the
        # timed region.
        labels = lp(sg, mesh, max_iter=5)
        np.asarray(labels[:4])
        t0 = time.perf_counter()
        labels = lp(sg, mesh, max_iter=5)
        np.asarray(labels[:4])
        t_lpa = time.perf_counter() - t0

        t0 = time.perf_counter()
        cc = cc_fn(sg, mesh)
        n_cc = int(num_communities(cc))
        t_cc = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        graph, plan = build_graph_and_plan(et.src, et.dst, num_vertices=v)
        t_build = time.perf_counter() - t0

        step = jax.jit(lpa_superstep_bucketed)
        labels = step(jnp.arange(v, dtype=jnp.int32), graph, plan)
        np.asarray(labels[:4])  # compile + settle
        labels = jnp.arange(v, dtype=jnp.int32)
        t0 = time.perf_counter()
        for _ in range(5):
            labels = step(labels, graph, plan)
        np.asarray(labels[:4])
        t_lpa = time.perf_counter() - t0

        t0 = time.perf_counter()
        # fused-plan min supersteps (r5): the plan is already built for
        # LPA above; the cc tier's detail records the measured
        # bucketed-vs-segment_min speedup on the same silicon
        cc = connected_components(graph, plan=plan)
        n_cc = int(num_communities(cc))
        t_cc = time.perf_counter() - t0

    rec = dict(
        base,
        schedule=rp.schedule,
        build_seconds=round(t_build, 2),
        lpa5_seconds=round(t_lpa, 3),
        lpa_edges_per_sec=round(e * 5 / t_lpa),
        lpa_communities=int(num_communities(labels)),
        cc_seconds=round(t_cc, 2),
        components=n_cc,
    )
    if e <= 2_000_000 and (
        rp.schedule == "single"
        or rp.estimates["single"] <= rp.hbm_bytes
    ):
        # Louvain is single-device only. On a multi-device rung the graph
        # is host-resident; running louvain implicitly materializes it on
        # device 0 — fine exactly when the planner's always-computed
        # single-device estimate fits the budget, and the OOM the branch
        # exists to avoid otherwise (ADVICE r3; the schedule alone is the
        # wrong gate — plan_run never returns "single" for D > 1 even
        # when the graph trivially fits one device, code-review r4).
        t0 = time.perf_counter()
        _, q = louvain(graph)
        rec["louvain_seconds"] = round(time.perf_counter() - t0, 2)
        rec["louvain_modularity"] = round(float(q), 4)
    return rec


def main_snap() -> None:
    """SNAP ladder tier (BASELINE.json "configs"; VERDICT r1 item 4).

    LPA(maxIter=5) + connected components on every rung through
    com-LiveJournal (34M edges — single-chip scale), plus Louvain on
    rungs up to 2M edges. Real SNAP edge lists are used automatically when present
    under ``$GRAPHMINE_SNAP_DIR`` or ``./data`` (drop e.g.
    ``com-lj.ungraph.txt`` there); this environment has zero network
    egress and no vendored SNAP files, so absent files run the R-MAT
    stand-in at the rung's true scale with ``source="rmat-standin"``
    recorded — same sizes, same skew family, honestly labeled."""
    import jax
    import jax.numpy as jnp

    build_graph_and_plan, lpa_superstep_bucketed = _setup_jax_cache()

    from graphmine_tpu.datasets import load, snap_path
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.louvain import louvain
    from graphmine_tpu.ops.lpa import num_communities

    data_dir = os.environ.get(
        "GRAPHMINE_SNAP_DIR", os.path.join(_REPO_DIR, "data")
    )
    rungs = ["ego-facebook", "com-amazon", "com-livejournal"]
    max_scale = None
    if _CPU_FALLBACK:
        rungs = rungs[:2]
        max_scale = 16
    elif snap_path("twitter-2010", data_dir) is not None:
        # Top rung (r3): Twitter-2010 (1.4B edges) runs end-to-end when the
        # real file is present — streaming native ingestion (io/edges.py
        # chunked parse), then planner-dispatched LPA (single chip cannot
        # hold 1.4B edges; the planner routes to replicated/ring over the
        # visible mesh or records a numeric rejection). Never synthesized:
        # an R-MAT stand-in at this scale would claim top-rung evidence
        # the hardware didn't produce.
        rungs.append("twitter-2010")
    out = []
    for name in rungs:
        rec = _run_snap_rung(
            name, data_dir, max_scale, build_graph_and_plan,
            lpa_superstep_bucketed,
        )
        out.append(rec)
        print(json.dumps({"progress": rec}), file=sys.stderr, flush=True)

    measured = [r for r in out if "lpa_edges_per_sec" in r]
    if not measured:
        # Every rung planner-skipped (e.g. a tiny GRAPHMINE_HBM_BYTES):
        # still print a parseable record carrying the numeric reasons.
        print(json.dumps({
            "metric": "snap_ladder_all_rungs_skipped",
            "value": 0.0,
            "unit": "edges/s",
            "vs_baseline": 0.0,
            "detail": {"rungs": out, "data_dir": data_dir},
        }))
        return
    top = measured[-1]  # a planner-skipped top rung never carries the headline
    eps = top["lpa_edges_per_sec"]
    print(
        json.dumps(
            {
                "metric": (
                    "snap_ladder_lpa_edges_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "snap_ladder_lpa_edges_per_sec_per_chip"
                ),
                "value": eps,
                "unit": "edges/s" if _CPU_FALLBACK else "edges/s/chip",
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(
                    eps / BASELINE_EDGES_PER_SEC_PER_CHIP, 3
                ),
                "detail": {
                    "headline_rung": top["rung"],
                    "rungs": out,
                    "data_dir": data_dir,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


# Quality-tier SBM configs: (name, block_sizes, p_in, p_out). The LAST
# entry is always the headline — the detectability-MARGIN config whose
# best-ARI sits mid-band (~0.75-0.95; tests/test_bench_capture.py pins the
# seed band on the real margin-20k parameters). Exported as constants so
# the band test asserts on the exact deployed parameters, not a copy.
QUALITY_CONFIGS = [
    ("sbm-2k", [100] * 20, 0.1, 0.002),
    ("sbm-20k", [400] * 50, 0.04, 0.0004),
    ("sbm-margin-20k", [400] * 50, 0.028, 0.0008),
]
QUALITY_CONFIGS_FALLBACK = [
    ("sbm-2k", [100] * 20, 0.1, 0.002),
    ("sbm-margin-2k", [100] * 20, 0.08, 0.008),
]


def main_quality() -> None:
    """Quality tier (VERDICT r1 item 8): community-detection *accuracy* —
    the ``Overview:9`` axis the reference names but never measures.

    ARI/NMI against SBM planted truth plus modularity, for LPA vs Louvain
    vs Leiden. Headline value (r5, VERDICT r4 item 4): best ARI on the
    detectability-MARGIN SBM — the r1-r4 headline configs have 50-100x
    p_in/p_out ratios that any good method fully recovers (ARI 1.0, a
    ceiling that can't show a regression, the same defect the r4 stream
    fix removed). The margin config balances in-block degree ~11 against
    out-block degree ~16, right above the recovery threshold: the r5 CPU
    sweep measured best-ARI {0.83, 0.84, 0.81, 0.94} across seeds 3/4/5/11
    (p_in=0.026 collapses to 0.54-0.87, p_in=0.03 saturates at 0.98), so
    the recorded value sits mid-band with room to regress in both
    directions; tests pin the seed band. The easy configs stay in detail
    as the recoverable-regime parity check."""
    import jax

    _setup_jax_cache()

    from graphmine_tpu.datasets import sbm
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.cluster_metrics import (
        adjusted_rand_index,
        normalized_mutual_info,
    )
    from graphmine_tpu.ops.louvain import leiden, louvain
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.ops.modularity import modularity

    seed = int(os.environ.get("GRAPHMINE_QUALITY_SEED", "3"))
    configs = QUALITY_CONFIGS
    if _CPU_FALLBACK:
        # Reduced scale, but keep a margin config so even the degraded
        # record carries a non-saturated value (best-ARI ~0.5-0.8 — the
        # 2k blocks are too small for a tight band; the pinned band test
        # runs the REAL margin-20k config instead).
        configs = QUALITY_CONFIGS_FALLBACK
    out = []
    for name, sizes, p_in, p_out in configs:
        src, dst, truth = sbm(sizes, p_in, p_out, seed=seed)
        v = int(truth.shape[0])
        g = build_graph(src, dst, num_vertices=v)
        rec = {"config": name, "vertices": v, "edges": int(len(src)), "algos": {}}
        runs = {
            "lpa": lambda: (label_propagation(g, max_iter=5), None),
            "louvain": lambda: louvain(g),
            "leiden": lambda: leiden(g),
        }
        for algo, fn in runs.items():
            t0 = time.perf_counter()
            labels, q = fn()
            labels = np.asarray(labels)
            dt = time.perf_counter() - t0
            if q is None:
                q = float(modularity(labels, g))
            rec["algos"][algo] = {
                "ari": round(float(adjusted_rand_index(labels, truth)), 4),
                "nmi": round(float(normalized_mutual_info(labels, truth)), 4),
                "modularity": round(float(q), 4),
                "communities": int(len(np.unique(labels))),
                "seconds": round(dt, 2),
            }
        out.append(rec)
        print(json.dumps({"progress": rec}), file=sys.stderr, flush=True)

    # Headline: the MARGIN config (always last) — the only one whose value
    # can move in either direction. The easy configs ride in detail.
    margin = out[-1]
    best = max(a["ari"] for a in margin["algos"].values())
    print(
        json.dumps(
            {
                "metric": (
                    "community_quality_best_ari_cpu_fallback"
                    if _CPU_FALLBACK else "community_quality_best_ari"
                ),
                "value": best,
                "unit": "ari",
                # baseline 0.5: mid-quality recovery at the detectability
                # margin. Expected band ~0.75-0.95 (seed-swept, pinned in
                # tests) — NOT 1.0; a saturated value here is a bug, not
                # a win. Fallback runs reduced scale: no ratio claimed.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(best / 0.5, 3),
                "detail": {
                    "headline_config": margin["config"],
                    "configs": out,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_stream() -> None:
    """Streaming-LOF throughput — the Twitter-2010 rung's capability
    (BASELINE.json: "streaming LOF on v5p-64"; all-pairs LOF is O(N^2)
    and off the table at 41M vertices). Feeds a feature stream through
    the fixed-capacity reference window (one compile for the whole
    stream) and reports points/sec plus the detection AUROC on injected
    outliers riding the stream."""
    import jax

    _setup_jax_cache()

    from graphmine_tpu.ops.lof import auroc
    from graphmine_tpu.ops.streaming_lof import StreamingLOF

    rng = np.random.default_rng(
        int(os.environ.get("GRAPHMINE_STREAM_SEED", "11"))
    )
    n, f, chunk, cap = (1 << 20, 8, 1 << 14, 1 << 15)
    if _CPU_FALLBACK:
        # Scale EVERY dimension down — the window is the dominant cost
        # term (each re-fit is a cap x cap kNN).
        n, chunk, cap = 1 << 17, 1 << 12, 1 << 12
    # CI band caps (the AUROC stability test runs the real body smaller).
    n = int(os.environ.get("GRAPHMINE_STREAM_POINTS", n))
    chunk = int(os.environ.get("GRAPHMINE_STREAM_CHUNK", chunk))
    cap = int(os.environ.get("GRAPHMINE_STREAM_WINDOW", cap))
    if n < 2 * chunk or n % chunk:
        # the warmup consumes two full chunks and the timed loop assumes
        # uniform chunk shapes (one compile for the whole stream)
        raise ValueError(
            f"stream sizes need n >= 2*chunk and chunk | n (n={n}, "
            f"chunk={chunk}); fix the GRAPHMINE_STREAM_* overrides"
        )
    k = 32
    # stream: mixture-of-blobs inliers + 0.5% shell outliers. Inlier radii
    # around each center follow a chi(f=8) law (mean ~2.83, 99.9th pct
    # ~4.4); outliers sit on a uniform [4, 6] radial shell JUST outside
    # that envelope, so the detection axis is a real measurement — the
    # old +/-12 uniform box saturated auroc_injected at exactly 1.0 and
    # carried no information (VERDICT r3 item 6). Measured: ~0.986-0.989
    # across seeds at both CPU-fallback and band-test scales.
    centers = rng.normal(size=(32, f)).astype(np.float32) * 4
    assign = rng.integers(0, 32, n)
    pts = (centers[assign] + rng.normal(size=(n, f)).astype(np.float32))
    is_out = rng.random(n) < 0.005
    n_out = int(is_out.sum())
    direction = rng.normal(size=(n_out, f)).astype(np.float32)
    direction /= np.linalg.norm(direction, axis=1, keepdims=True)
    radius = rng.uniform(4.0, 6.0, (n_out, 1)).astype(np.float32)
    pts[is_out] = centers[assign[is_out]] + direction * radius

    # Warmup with identical shapes on a scratch instance: compiles the
    # bootstrap scorer, the cross-kNN scorer, and the window fit so the
    # timed loop measures steady-state throughput (chip-tier convention).
    scratch = StreamingLOF(k=k, capacity=cap)
    scratch.update(pts[:chunk])
    scratch.update(pts[chunk:2 * chunk])
    scratch.sync()

    s = StreamingLOF(k=k, capacity=cap)
    scores = np.empty(n, np.float32)
    t0 = time.perf_counter()
    for lo in range(0, n, chunk):
        scores[lo:lo + chunk] = s.update(pts[lo:lo + chunk])
    s.sync()  # the last re-fit's device time belongs in the window
    dt = time.perf_counter() - t0
    # the first window-fill's scores come from a still-warming model
    warm = slice(cap, None)
    det = float(auroc(scores[warm], is_out[warm]))
    pps = n / dt

    # IVF index-reuse micro-bench (r6): the window re-fit is the stream's
    # dominant cost term (a [cap, cap] self-kNN per admitted chunk).
    # Measure one re-fit three ways on the final window state — exact,
    # IVF with a cold-trained index, IVF with reused centers (what
    # StreamingLOF(impl="ivf") runs steady-state) — plus a full
    # impl="ivf" stream pass, so the reuse win (or regression) and its
    # AUROC cost are captured numbers every run, not an assumption.
    import jax as _jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.ann import default_n_clusters, ivf_knn, kmeans
    from graphmine_tpu.ops.streaming_lof import fit_lof

    window = np.array(s._refs)
    mask = s._mask()
    n_clusters = default_n_clusters(cap)

    def best_of(fn, reps=3):
        fn()  # compile / settle
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_exact = best_of(lambda: _jax.block_until_ready(
        fit_lof(jnp.asarray(window), jnp.asarray(mask), k=k)
    ))
    t_cold = best_of(lambda: _jax.block_until_ready(ivf_knn(
        window[mask], k=k,
        centers=kmeans(window[mask], n_clusters, seed=0),
    )))
    centers = kmeans(window[mask], n_clusters, seed=0)
    t_reuse = best_of(lambda: _jax.block_until_ready(
        ivf_knn(window[mask], k=k, centers=centers)
    ))

    s_ivf = StreamingLOF(k=k, capacity=cap, impl="ivf")
    scores_ivf = np.empty(n, np.float32)
    t0 = time.perf_counter()
    for lo in range(0, n, chunk):
        scores_ivf[lo:lo + chunk] = s_ivf.update(pts[lo:lo + chunk])
    s_ivf.sync()
    dt_ivf = time.perf_counter() - t0
    ivf_detail = {
        "refit_exact_seconds": round(t_exact, 3),
        "refit_ivf_cold_seconds": round(t_cold, 3),
        "refit_ivf_reuse_seconds": round(t_reuse, 3),
        "reuse_speedup_vs_exact": round(t_exact / t_reuse, 2),
        "reuse_speedup_vs_cold": round(t_cold / t_reuse, 2),
        "stream_points_per_sec": round(n / dt_ivf),
        "stream_speedup_vs_exact": round(dt / dt_ivf, 2),
        "auroc_injected": round(
            float(auroc(scores_ivf[warm], is_out[warm])), 4
        ),
        "kmeans_trainings": s_ivf.ivf_retrains,
    }
    print(
        json.dumps(
            {
                "metric": (
                    "streaming_lof_points_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "streaming_lof_points_per_sec_per_chip"
                ),
                "value": round(pps),
                "unit": "points/s" if _CPU_FALLBACK else "points/s/chip",
                # baseline: Twitter-2010's 41M vertices in a 10-minute
                # scoring budget on the 64 budgeted chips ~ 1.1e3
                # points/s/chip. Degraded runs claim no ratio.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(pps / 1.1e3, 1),
                "detail": {
                    "points": n,
                    "features": f,
                    "chunk": chunk,
                    "window": cap,
                    "k": k,
                    "seconds": round(dt, 2),
                    "auroc_injected": round(det, 4),
                    # index-reuse micro-bench (r6): per-refit and
                    # full-stream IVF-vs-exact numbers, captured per run
                    "ivf_reuse": ivf_detail,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def _serve_write_load(tmp, src, dst, labels, cc, lof, fp, v):
    """The serve tier's sustained-write-load sub-record: fire burst
    batches from concurrent submitters at 3 intensities and record the
    admission outcome mix. Bounds scale with intensity so the high rung
    actually sheds — the record captures degradation BEHAVIOR, not just
    throughput."""
    import threading

    from graphmine_tpu.serve.admission import (
        AdmissionBounds,
        AdmissionController,
    )
    from graphmine_tpu.serve.server import SnapshotServer
    from graphmine_tpu.serve.snapshot import SnapshotStore
    from graphmine_tpu.testing import faults as _faults

    intensities = (
        ("low", 6, 20), ("medium", 10, 60), ("high", 14, 180),
    )
    if not _CPU_FALLBACK:
        intensities = (
            ("low", 8, 100), ("medium", 12, 400), ("high", 16, 1600),
        )
    out = []
    arrays = {
        "src": src, "dst": dst, "labels": labels, "cc_labels": cc, "lof": lof,
    }
    for name, batches, rows in intensities:
        root = os.path.join(tmp, f"wl_{name}")
        store = SnapshotStore(root)
        store.publish(arrays, fingerprint=fp)
        bounds = AdmissionBounds(
            max_pending_rows=max(rows * batches // 2, rows + 1),
            max_queue_depth=4,
            deadline_s=120.0,
        )
        server = SnapshotServer(
            store, admission=AdmissionController(bounds=bounds)
        )
        payloads = _faults.delta_burst(
            v, batches=batches, rows_per_batch=rows, seed=13,
            delete_frac=0.2, base_src=src, base_dst=dst,
        )
        debt_high = [0]
        stop = threading.Event()

        def _sample():
            while not stop.is_set():
                debt_high[0] = max(
                    debt_high[0], server.debt.snapshot()["pending_rows"]
                )
                time.sleep(0.005)

        results = []
        t0 = time.perf_counter()
        sampler = threading.Thread(target=_sample)
        sampler.start()
        threads = []
        for p in payloads:
            t = threading.Thread(
                target=lambda pl=p: results.append(server.apply_delta(pl))
            )
            t.start()
            threads.append(t)
            time.sleep(0.002)
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        stop.set()
        sampler.join()
        server.stop()
        verdicts = server.admission.snapshot()["verdicts"]
        debt = server.debt.snapshot()
        applies = debt["applies_warm"] + debt["applies_cold"]
        shed = sum(1 for r in results if r.get("verdict") == "shed")
        out.append({
            "intensity": name,
            "batches": batches,
            "rows_per_batch": rows,
            "seconds": round(elapsed, 3),
            "accepted_batches": len(results) - shed,
            "shed_batches": shed,
            "verdicts": verdicts,
            "applies": applies,
            "publishes_per_sec": round(applies / elapsed, 3)
            if elapsed > 0 else 0.0,
            "accepted_rows_per_sec": round(
                debt["rows_applied_total"] / elapsed
            ) if elapsed > 0 else 0,
            "coalesced_into": round(
                (len(results) - shed) / applies, 2
            ) if applies else None,
            "debt_high_water_rows": debt_high[0],
            "debt_bound_rows": bounds.max_pending_rows,
            "warm_ratio": debt["warm_ratio"],
            "lof_deferred": server.admission.snapshot()["lof_deferred"],
        })
    return out


def _serve_multi_tenant(tmp, arrays, fp, v):
    """The serve tier's multi-tenant isolation sub-record (ISSUE 16,
    docs/SERVING.md "Multi-tenant serving"): three namespaces behind ONE
    server, one tenant firing an order of magnitude more rows than the
    two victims under a tight per-tenant quota — the record is the
    noisy-neighbor bound itself: the abuser's shed mix, the victims'
    zero-shed apply counts and their read p99 measured DURING the
    flood."""
    import threading

    from graphmine_tpu.serve.server import SnapshotServer
    from graphmine_tpu.serve.snapshot import SnapshotStore
    from graphmine_tpu.testing import faults as _faults

    root = os.path.join(tmp, "mt")
    store = SnapshotStore(root)
    store.publish(arrays, fingerprint=fp)
    tenants = ("abuser", "victim_b", "victim_c")
    for t in tenants:
        store.for_tenant(t).publish(arrays, fingerprint=fp)
    abuse = (20, 120) if _CPU_FALLBACK else (40, 400)
    quiet = (6, 24) if _CPU_FALLBACK else (12, 80)
    server = SnapshotServer(store)
    # per-tenant quota: the abuser's pending-row budget is a fraction of
    # its own burst, so ITS overflow sheds; the victims' budgets clear
    # their bursts whole
    server.tenancy.set_overrides(
        "abuser", max_pending_rows=abuse[1] * 4, max_queue_depth=4,
        deadline_s=120.0,
    )
    for t in tenants[1:]:
        server.tenancy.set_overrides(
            t, max_pending_rows=quiet[0] * quiet[1] * 2,
            max_queue_depth=max(8, quiet[0]), deadline_s=120.0,
        )
    bursts = {
        "abuser": _faults.delta_burst(
            v, batches=abuse[0], rows_per_batch=abuse[1], seed=21
        ),
        "victim_b": _faults.delta_burst(
            v, batches=quiet[0], rows_per_batch=quiet[1], seed=22
        ),
        "victim_c": _faults.delta_burst(
            v, batches=quiet[0], rows_per_batch=quiet[1], seed=23
        ),
    }
    results = {t: [] for t in tenants}
    read_lat = {t: [] for t in tenants[1:]}
    stop = threading.Event()

    def _reader(tenant):
        while not stop.is_set():
            t_op = time.perf_counter()
            server.engine_for(tenant).membership(0)
            read_lat[tenant].append(time.perf_counter() - t_op)
            time.sleep(0.002)

    readers = [
        threading.Thread(target=_reader, args=(t,)) for t in tenants[1:]
    ]
    t0 = time.perf_counter()
    for r in readers:
        r.start()
    threads = []
    for t in tenants:
        for p in bursts[t]:
            th = threading.Thread(
                target=lambda pl=p, tn=t: results[tn].append(
                    server.apply_delta(pl, tenant=tn)
                )
            )
            th.start()
            threads.append(th)
            time.sleep(0.001)
    for th in threads:
        th.join()
    server.wait_applied(timeout=120.0)
    elapsed = time.perf_counter() - t0
    stop.set()
    for r in readers:
        r.join()
    per_tenant = {}
    for t in tenants:
        shed = sum(1 for r in results[t] if r.get("verdict") == "shed")
        adm = server._tenants[t].admission.snapshot()
        per_tenant[t] = {
            "submitted": len(bursts[t]),
            "accepted_batches": len(results[t]) - shed,
            "shed_batches": shed,
            "verdicts": adm["verdicts"],
            "version": server.engine_for(t).version,
        }
    server.stop()

    def _p99_us(lat):
        if not lat:
            return None
        return round(float(np.percentile(np.array(lat), 99)) * 1e6, 2)

    return {
        "seconds": round(elapsed, 3),
        "fair_quantum_rows": server._fair_quantum_rows,
        "tenants": per_tenant,
        "victim_read_p99_us": {t: _p99_us(read_lat[t]) for t in read_lat},
        # the isolation verdicts bench_diff watches: victims shed
        # nothing and kept publishing while the abuser was throttled
        "victims_shed_batches": sum(
            per_tenant[t]["shed_batches"] for t in tenants[1:]
        ),
        "abuser_shed_batches": per_tenant["abuser"]["shed_batches"],
    }


def _serve_sharded_write(tmp, arrays, fp, v):
    """The serve tier's sharded-write-plane sub-record (r17,
    docs/SERVING.md "Sharded write plane"): the SAME concurrent delta
    burst against one server at 1 vs 3 writer shards — accepted
    deltas/s, publish (epoch) cadence, and the per-range apply split
    (how evenly dst-ownership spread the burst). On the CPU fallback
    all shards share one interpreter, so the honest headline is the
    split/append-path overhead vs the single-WAL write path — per-range
    parallel fsync scaling is a multi-spindle number (ROADMAP silicon
    backlog); the record shape is what the capture pipeline tracks
    either way."""
    import threading

    from graphmine_tpu.serve.admission import (
        AdmissionBounds,
        AdmissionController,
    )
    from graphmine_tpu.serve.server import SnapshotServer
    from graphmine_tpu.serve.snapshot import SnapshotStore
    from graphmine_tpu.testing import faults as _faults

    batches, rows = (12, 48) if _CPU_FALLBACK else (40, 256)
    # generous envelope so neither run sheds: the record compares the
    # durability path (1 WAL append vs split + per-shard appends), and a
    # shed batch skips that path entirely, skewing the ratio
    bounds = AdmissionBounds(
        max_pending_rows=batches * rows * 2,
        max_queue_depth=batches + 4,
        deadline_s=120.0,
    )
    out = []
    for shards in (1, 3):
        root = os.path.join(tmp, f"sharded_write_{shards}")
        store = SnapshotStore(root)
        store.publish(arrays, fingerprint=fp)
        server = SnapshotServer(
            store,
            admission=AdmissionController(bounds=bounds),
            # durability-matched baseline: 1 shard runs the classic
            # single-WAL writer (plane mode forbids wal=), so both rungs
            # pay an fsync'd append per accepted batch
            wal=os.path.join(root, "wal") if shards == 1 else None,
            writer_shards=shards,
        )
        payloads = _faults.delta_burst(
            v, batches=batches, rows_per_batch=rows, seed=29,
        )
        results = []
        t0 = time.perf_counter()
        threads = []
        for p in payloads:
            th = threading.Thread(
                target=lambda pl=p: results.append(server.apply_delta(pl))
            )
            th.start()
            threads.append(th)
            time.sleep(0.002)
        for th in threads:
            th.join()
        elapsed = time.perf_counter() - t0
        accepted = sum(
            1 for r in results if r.get("verdict") != "shed"
        )
        rec = {
            "writer_shards": shards,
            "batches": batches,
            "rows_per_batch": rows,
            "seconds": round(elapsed, 3),
            "accepted_batches": accepted,
            "accepted_deltas_per_sec": round(accepted / elapsed, 2)
            if elapsed > 0 else 0.0,
        }
        debt = server.debt.snapshot()
        applies = debt["applies_warm"] + debt["applies_cold"]
        rec["applies"] = applies
        rec["accepted_rows_per_sec"] = round(
            debt["rows_applied_total"] / elapsed
        ) if elapsed > 0 else 0
        ts = server._tenants["default"]
        if ts.plane is not None:
            plane = ts.plane.snapshot()
            epoch = plane["epoch"]
            rec["committed_epoch"] = epoch
            rec["publishes_per_sec"] = round(epoch / elapsed, 2) \
                if elapsed > 0 else 0.0
            # per-range apply split: each shard's appended sub-batch
            # count — dst-ownership's actual spread of the burst
            rec["per_shard_appends"] = {
                str(s["shard"]): s["wal"]["last_seq"]
                for s in plane["shards"]
            }
        else:
            rec["publishes_per_sec"] = round(applies / elapsed, 2) \
                if elapsed > 0 else 0.0
        server.stop()
        out.append(rec)
    return out


def _serve_replicated_read(tmp, arrays, fp, v):
    """The serve tier's replicated-read sub-record (r10): hammer the
    SAME batched-query workload through the fleet router at 1 vs 3
    replicas and record qps + tail latency. On the CPU fallback all
    replicas share one interpreter (GIL), so the honest headline is the
    ROUTER PATH's overhead and shape — per-process replica scaling is a
    silicon/multi-host number (ROADMAP backlog); the record shape is
    what the capture pipeline needs to exist either way."""
    import threading

    from graphmine_tpu.serve.fleet import (
        FleetConfig,
        FleetRouter,
        ReplicaSpec,
    )
    from graphmine_tpu.serve.server import SnapshotServer
    from graphmine_tpu.serve.snapshot import SnapshotStore

    requests, hammer_threads, batch = (120, 4, 64)
    if not _CPU_FALLBACK:
        requests, hammer_threads, batch = (800, 8, 256)
    rng = np.random.default_rng(17)
    ids = rng.integers(0, v, batch).tolist()
    payload = json.dumps({"vertices": ids}).encode()
    out = []
    for nrep in (1, 3):
        root = os.path.join(tmp, f"replicated_{nrep}")
        store = SnapshotStore(root)
        store.publish(arrays, fingerprint=fp)
        servers = [SnapshotServer(store) for _ in range(nrep)]
        addrs = [s.start() for s in servers]
        specs = [
            ReplicaSpec(f"r{i}", h, p) for i, (h, p) in enumerate(addrs)
        ]
        router = FleetRouter(
            specs, writer="r0",
            config=FleetConfig(probe_interval_s=0.05, quorum=1,
                               read_timeout_s=5.0),
        )
        rh, rp = router.start()
        deadline = time.monotonic() + 30
        while (
            router.replica_set.committed_version() is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        import urllib.request

        lat_lock = threading.Lock()
        latencies = []
        errors = [0]

        def hammer(n, rh=rh, rp=rp):
            local, errs = [], 0
            for _ in range(n):
                t0 = time.perf_counter()
                try:
                    req = urllib.request.Request(
                        f"http://{rh}:{rp}/query", data=payload,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=60) as r:
                        r.read()
                    local.append(time.perf_counter() - t0)
                except Exception:  # noqa: BLE001 — count, keep hammering
                    errs += 1
            with lat_lock:
                latencies.extend(local)
                errors[0] += errs

        per = requests // hammer_threads
        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=hammer, args=(per,))
            for _ in range(hammer_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        router.stop()
        for s in servers:
            s.stop()
        ok_requests = len(latencies)
        if ok_requests:
            lat = np.asarray(sorted(latencies))
            p50, p99 = np.percentile(lat, [50, 99])
        else:  # every request failed: an honest zero row, not a crash
            p50 = p99 = 0.0
        out.append({
            "replicas": nrep,
            "requests": per * hammer_threads,
            "ok": ok_requests,
            "errors": errors[0],
            "batch": batch,
            "seconds": round(elapsed, 3),
            "lookups_per_sec": round(ok_requests * batch / elapsed)
            if elapsed > 0 else 0,
            "p50_ms": round(float(p50) * 1e3, 2),
            "p99_ms": round(float(p99) * 1e3, 2),
        })
    return {
        "rungs": out,
        "qps_3_over_1": round(
            out[1]["lookups_per_sec"] / out[0]["lookups_per_sec"], 2
        ) if out[0]["lookups_per_sec"] else None,
    }


def _serve_writer_failover(tmp, arrays, fp, v):
    """The serve tier's writer-failover sub-record (r11): the three
    durability numbers docs/SERVING.md "Replicated writers" promises —
    (a) WAL-append overhead on the accepted-delta acknowledgement
    (fsync p50/p99 of the 202 path), (b) steady-state replication lag
    of the log-shipped standby, (c) time-to-writable: SIGKILL-shaped
    writer loss with an acked-but-unapplied tail → promote → every
    acknowledged delta queryable at the new writer, with the lost count
    recorded (it must be 0 — the record carries the proof, not just the
    timing)."""
    from graphmine_tpu.serve.server import SnapshotServer
    from graphmine_tpu.serve.snapshot import SnapshotStore
    from graphmine_tpu.testing import faults as _faults

    appends, tail = (12, 4) if _CPU_FALLBACK else (64, 16)
    root = os.path.join(tmp, "failover")
    store = SnapshotStore(root)
    store.publish(arrays, fingerprint=fp)
    primary = SnapshotServer(
        store, wal=os.path.join(root, "wal-primary"),
    )
    host, port = primary.start()
    standby = SnapshotServer(
        store, wal=os.path.join(root, "wal-standby"),
        standby_of=f"http://{host}:{port}",
        primary_wal=os.path.join(root, "wal-primary"),
        ship_interval_s=0.05,
    )
    standby.start()

    # (a) WAL-durable acknowledgement latency: admission + fsync append,
    # the full 202 path a client actually waits on.
    rng = np.random.default_rng(23)
    ack_lat = []
    acked = []
    for i in range(appends):
        pair = [int(rng.integers(0, v)), int(rng.integers(0, v))]
        t0 = time.perf_counter()
        out = primary.apply_delta(
            {"insert": [pair]}, delta_id=f"bench-{i}", ack="wal",
        )
        ack_lat.append(time.perf_counter() - t0)
        acked.append((f"bench-{i}", tuple(pair)))
    primary.wait_applied(120)

    # (b) replication lag after the burst settles
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        ship = standby._shipper.snapshot()
        if ship["lag_entries"] == 0 and ship["primary_last_seq"] > 0:
            break
        time.sleep(0.02)
    ship = standby._shipper.snapshot()

    # (c) kill with an acked-but-unapplied tail, then promote
    tail_ids = []
    for i in range(tail):
        pair = [int(rng.integers(0, v)), int(rng.integers(0, v))]
        primary.apply_delta(
            {"insert": [pair]}, delta_id=f"tail-{i}", ack="wal",
        )
        acked.append((f"tail-{i}", tuple(pair)))
        tail_ids.append(tuple(pair))
    _faults.writer_kill_mid_apply(primary)
    t0 = time.perf_counter()
    promote = standby.promote()
    t_writable = time.perf_counter() - t0
    standby.wait_applied(300)
    t_caught_up = time.perf_counter() - t0
    eng = standby.engine
    edges = {}
    for s, d in zip(
        np.asarray(eng.snapshot["src"]).tolist(),
        np.asarray(eng.snapshot["dst"]).tolist(),
    ):
        edges[(s, d)] = edges.get((s, d), 0) + 1
    lost = sum(1 for _, pair in acked if pair not in edges)
    standby.stop()
    try:
        primary.stop()
    except Exception:  # noqa: BLE001 — listener already killed
        pass
    lat = np.asarray(sorted(ack_lat))
    p50, p99 = np.percentile(lat, [50, 99])
    return {
        "acked_deltas": len(acked),
        "wal_ack_p50_ms": round(float(p50) * 1e3, 3),
        "wal_ack_p99_ms": round(float(p99) * 1e3, 3),
        "replication_lag_entries_settled": ship["lag_entries"],
        "shipper_polls": ship["polls"],
        "tail_at_kill": len(tail_ids),
        "promote_replayed": promote["replayed"],
        "promote_copied_tail": promote["copied_tail"],
        "time_to_writable_s": round(t_writable, 3),
        "time_to_caught_up_s": round(t_caught_up, 3),
        "promoted_epoch": promote["epoch"],
        "acked_deltas_lost": lost,  # the zero-loss proof
    }


def _serve_quality_pass(rng):
    """The serve tier's quality_pass sub-record (ISSUE 13): publish-time
    quality-pass seconds at three graph sizes — the bounded-cost proof
    for the per-publish result-quality pass (state sketches + drift vs
    parent + the frozen canary probe re-score). Host-side numbers,
    honest without silicon; the canary's one-time scorer compile is
    warmed OUTSIDE the timed windows (steady-state shape: a long-lived
    writer compiles once per process)."""
    from graphmine_tpu.obs.quality import CanaryProbe, run_quality_pass

    canary = CanaryProbe.generate(seed=7)
    canary.score()  # warm the LOF compile outside the timed windows
    sizes = (1 << 14, 1 << 17, 1 << 20)
    if _CPU_FALLBACK:
        sizes = (1 << 12, 1 << 14, 1 << 16)
    rows = []
    for v in sizes:
        n_comm = max(16, v >> 7)
        parent_labels = rng.integers(0, n_comm, v).astype(np.int32)
        parent_lof = rng.gamma(2.0, 0.6, v).astype(np.float32)
        # a ~1% churned child: the drift path does real work, not the
        # all-buckets-equal fast case
        labels = parent_labels.copy()
        idx = rng.integers(0, v, max(8, v // 100))
        labels[idx] = rng.integers(0, n_comm, len(idx)).astype(np.int32)
        lof = parent_lof.copy()
        lof[idx] += 1.0
        t0 = time.perf_counter()
        rep = run_quality_pass(
            labels, lof, 2, parent_labels=parent_labels,
            parent_lof=parent_lof, parent_version=1, canary=canary,
        )
        rows.append({
            "num_vertices": int(v),
            "pass_seconds": round(time.perf_counter() - t0, 4),
            "canary_seconds": rep.canary["seconds"],
            "canary_recall": rep.canary["recall_at_k"],
            "churn_frac": rep.drift["churn_frac"],
        })
    return {"sizes": rows}


def main_serve() -> None:
    """Serving tier (r7, docs/SERVING.md): the steady-state numbers the
    serve/ subsystem exists for — query resolve throughput (single-vertex
    loop vs the one-device-gather batched path), delta-apply latency vs a
    cold full recompute at three delta sizes, and snapshot publish/load
    wall time. The headline is batched lookups/sec; ``vs_baseline`` is
    the batched-over-single speedup (the whole point of the vectorized
    path), and the delta ladder records warm-repair seconds next to the
    cold-recompute seconds it replaces."""
    import shutil
    import tempfile

    import jax

    _setup_jax_cache()

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
    from graphmine_tpu.serve import (
        DeltaIngestor,
        EdgeDelta,
        QueryEngine,
        SnapshotStore,
    )
    from graphmine_tpu.serve.delta import cold_recompute, splice_edges

    # Community-structured graph (SBM, the quality tier's generator): the
    # serving workload's shape. A pure power-law draw livelocks
    # synchronous LPA (period-2), which routes EVERY delta to the
    # fallback — that path is measured too (repair_method in the ladder
    # says which one each row took), but the steady-state warm-repair
    # story needs a graph whose LPA actually fixpoints.
    from graphmine_tpu.datasets import sbm

    blocks, p_in, p_out = ([400] * 120, 0.04, 0.0002)
    if _CPU_FALLBACK:
        blocks, p_in, p_out = ([100] * 20, 0.1, 0.002)
    rng = np.random.default_rng(7)
    src, dst, _blocks = sbm(blocks, p_in, p_out, seed=7)
    v, e = int(np.sum(blocks)), len(src)
    g = build_graph(src, dst, num_vertices=v)
    t0 = time.perf_counter()
    labels, cc, _ = cold_recompute(g)
    t_cold_base = time.perf_counter() - t0

    tmp = tempfile.mkdtemp(prefix="graphmine_serve_")
    try:
        store = SnapshotStore(os.path.join(tmp, "snap"))
        fp = graph_fingerprint(src, dst)
        lof = rng.random(v).astype(np.float32)
        arrays = {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": lof,
        }
        t0 = time.perf_counter()
        store.publish(arrays, fingerprint=fp)
        t_publish = time.perf_counter() - t0
        t0 = time.perf_counter()
        snap = store.load(fingerprint=fp)
        t_load = time.perf_counter() - t0
        engine = QueryEngine(snap)

        # single-vertex loop (the naive client) vs the batched gather;
        # per-op latencies are kept so the record carries QUANTILES, not
        # just the mean — the tail is the serving SLO number, and the
        # next silicon window should capture p99 alongside throughput
        # (ROADMAP silicon-capture backlog).
        ids = rng.integers(0, v, 1 << 12).astype(np.int64)
        for vtx in ids[:64]:  # warm caches/compiles outside the window
            engine.membership(int(vtx))
        engine.query_batch(ids)
        single_lat = np.empty(len(ids))
        t0 = time.perf_counter()
        for i, vtx in enumerate(ids):
            t_op = time.perf_counter()
            engine.membership(int(vtx))
            engine.score(int(vtx))
            single_lat[i] = time.perf_counter() - t_op
        single_qps = len(ids) / (time.perf_counter() - t0)
        reps = 32
        batch_lat = np.empty(reps)
        t0 = time.perf_counter()
        for i in range(reps):
            t_op = time.perf_counter()
            engine.query_batch(ids)
            batch_lat[i] = time.perf_counter() - t_op
        batched_qps = reps * len(ids) / (time.perf_counter() - t0)

        def _quantiles(lat):
            p50, p95, p99 = np.percentile(lat, [50, 95, 99])
            return {
                "p50_us": round(float(p50) * 1e6, 2),
                "p95_us": round(float(p95) * 1e6, 2),
                "p99_us": round(float(p99) * 1e6, 2),
            }

        # delta-apply vs cold recompute at three delta sizes. ONE
        # ingestor across the ladder — the steady-state shape: the LOF
        # stream bootstraps once (paid by the warmup delta below), then
        # each batch scores only its affected vertices.
        from graphmine_tpu.obs.spans import Tracer
        from graphmine_tpu.pipeline.metrics import MetricsSink

        # the orchestrator's run identity (env) so this tier's records
        # join the same obs timeline as the printed bench records
        sink = MetricsSink(tracer=Tracer(
            run_id=os.environ.get("GRAPHMINE_BENCH_RUN_ID")
        ))
        ing = DeltaIngestor(store, sink=sink, lof_k=16, check_samples=64)
        ing.apply(EdgeDelta.from_pairs(insert=[(0, 1)]))  # LOF bootstrap
        ladder = []
        for frac in (0.0005, 0.005, 0.05):
            n_d = max(8, int(e * frac))
            cur_v = ing.num_vertices
            ins = np.stack(
                [rng.integers(0, cur_v, n_d), rng.integers(0, cur_v, n_d)],
                axis=1,
            )
            dele_idx = rng.integers(0, len(ing.src), n_d // 2)
            delta = EdgeDelta(
                ins[:, 0], ins[:, 1],
                ing.src[dele_idx].astype(np.int64),
                ing.dst[dele_idx].astype(np.int64),
            )
            src_c, dst_c = ing.src.copy(), ing.dst.copy()
            t0 = time.perf_counter()
            ing.apply(delta)
            t_apply = time.perf_counter() - t0
            rec = [
                r for r in sink.records if r.get("phase") == "delta_apply"
            ][-1]
            s2, d2, v2, _ = splice_edges(src_c, dst_c, cur_v, delta)
            g2 = build_graph(s2, d2, num_vertices=v2)
            t0 = time.perf_counter()
            cold_recompute(g2)
            t_cold = time.perf_counter() - t0
            repair_s = rec["repair_seconds"]
            ladder.append({
                "delta_edges": n_d + n_d // 2,
                "apply_seconds": round(t_apply, 3),
                "repair_seconds": repair_s,
                "lof_seconds": rec["lof_seconds"],
                "repair_method": rec["method"],
                "cold_recompute_seconds": round(t_cold, 3),
                # the like-for-like term: warm label repair vs the cold
                # label recompute it replaces
                "repair_speedup_vs_cold": round(t_cold / repair_s, 2)
                if repair_s > 0 else None,
                "version": rec["version"],
            })

        # sustained write load through the admission path (r8): concurrent
        # burst submitters against one server at three intensities —
        # accepted/coalesced/shed mix, publish cadence and the repair-debt
        # high-water mark are the overload numbers the next silicon window
        # should capture alongside the delta ladder (ROADMAP silicon
        # backlog). In-process apply_delta (no HTTP) so the measured path
        # is admission + coalesce + repair, not socket handling.
        write_load = _serve_write_load(tmp, src, dst, labels, cc, lof, fp, v)

        # replicated reads through the fleet router (r10): 1 vs 3
        # replicas behind consistent-version routing — the router-path
        # qps/p99 record the silicon backlog window should capture
        # alongside write_load (CPU-fallback: replicas share the GIL,
        # so this measures the routing tier, not replica scaling).
        replicated_read = _serve_replicated_read(tmp, arrays, fp, v)

        # writer failover (r11): WAL-append overhead on the accepted-
        # delta ack, log-shipped replication lag, and SIGKILL-shaped
        # time-to-writable with the zero-acked-loss proof. Runs in the
        # CPU-fallback order too — durability numbers are host-side and
        # honest without silicon.
        writer_failover = _serve_writer_failover(tmp, arrays, fp, v)

        # result-quality pass cost at three graph sizes (ISSUE 13): the
        # bounded-cost claim for the per-publish quality pass, tracked
        # by bench_diff's manifest + regression gate.
        quality_pass = _serve_quality_pass(rng)

        # tenant isolation under an abusive co-tenant (ISSUE 16): three
        # namespaces on one server, per-tenant quotas + weighted-fair
        # apply — the victims' read p99 and zero-shed apply counts ARE
        # the noisy-neighbor bound the manifest tracks.
        multi_tenant = _serve_multi_tenant(tmp, arrays, fp, v)

        # sharded write plane (r17): the same burst at 1 vs 3 writer
        # shards — accepted deltas/s, epoch-publish cadence and the
        # per-range apply split. CPU-fallback shares one interpreter, so
        # this prices the split/per-shard-append overhead; parallel
        # per-range fsync scaling is a silicon-backlog number.
        sharded_write = _serve_sharded_write(tmp, arrays, fp, v)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": (
                    "serve_batched_lookups_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "serve_batched_lookups_per_sec"
                ),
                "value": round(batched_qps),
                "unit": "lookups/s",
                # batched-over-single speedup: the one-device-gather
                # path's win over per-vertex resolution
                "vs_baseline": round(batched_qps / single_qps, 2)
                if single_qps > 0 else 0.0,
                "detail": {
                    "num_vertices": v,
                    "num_edges": e,
                    "single_qps": round(single_qps),
                    "batched_qps": round(batched_qps),
                    "batch_size": len(ids),
                    "snapshot_publish_seconds": round(t_publish, 3),
                    "snapshot_load_seconds": round(t_load, 3),
                    "cold_pipeline_seconds": round(t_cold_base, 2),
                    # the SLO view of the same workload: tail latency per
                    # single-vertex lookup PAIR (each timed window is one
                    # membership + one score call, matching single_qps's
                    # per-iteration unit) and per batched resolve
                    # (seconds -> microseconds), plus the engine's
                    # pad/gather/host stage split over the batched window
                    "latency_quantiles": {
                        "single_lookup_pair": _quantiles(single_lat),
                        "batched_resolve": _quantiles(batch_lat),
                    },
                    "query_stages": engine.stage_snapshot(),
                    "delta_ladder": ladder,
                    # admission-path degradation under sustained write
                    # bursts (accepted/coalesced/shed mix, publish
                    # cadence, debt high-water vs bound per intensity)
                    "write_load": write_load,
                    # fleet-router read path at 1 vs 3 replicas (r10)
                    "replicated_read": replicated_read,
                    # WAL durability + fenced failover numbers (r11)
                    "writer_failover": writer_failover,
                    # per-publish quality-pass cost ladder (ISSUE 13)
                    "quality_pass": quality_pass,
                    # noisy-neighbor isolation bound (ISSUE 16)
                    "multi_tenant": multi_tenant,
                    # 1 vs 3 writer shards: split overhead + epoch
                    # cadence + per-range apply spread (r17)
                    "sharded_write": sharded_write,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def _run_chip_tier(weighted: bool) -> None:
    """Shared chip-tier measurement: fused-kernel LPA supersteps on the
    standard power-law graph, one timing path for the unweighted and
    weighted (r2) metrics. Same graph/size either way so the weighted/
    unweighted cost ratio is directly readable."""
    import jax
    import jax.numpy as jnp

    build_graph_and_plan, lpa_superstep_bucketed = _setup_jax_cache()

    def mark(msg):
        # Phase markers on stderr: the orchestrator forwards the child's
        # last stderr lines, so a timed-out run says WHERE it died
        # (the r4 weighted-tier 900s timeouts were undiagnosable).
        print(f"[tier {time.strftime('%H:%M:%S')}] {msg}",
              file=sys.stderr, flush=True)

    src, dst = powerlaw_edges(NUM_VERTICES, NUM_EDGES)
    w = None
    if weighted:
        # Quarters: exactly representable, sums exact in float32 — the
        # same convention the weighted parity tests use.
        rng = np.random.default_rng(7)
        w = (rng.integers(1, 16, NUM_EDGES) / 4.0).astype(np.float32)
    mark("edges generated")
    # Fused degree-bucketed kernel (ops/bucketed_mode.py): ~3x the sort-
    # based superstep at this scale, bit-identical labels (tested). Graph
    # and plan share one host message-CSR build (native counting sort).
    graph, plan = build_graph_and_plan(
        src, dst, num_vertices=NUM_VERTICES, edge_weights=w
    )
    mark("graph+plan built")

    # Compile a single superstep once; the timed loop feeds labels back so
    # every iteration computes on fresh data (steady-state throughput).
    raw_step = jax.jit(lpa_superstep_bucketed)
    step = lambda lbl: raw_step(lbl, graph, plan)
    labels = step(jnp.arange(NUM_VERTICES, dtype=jnp.int32))
    np.asarray(labels[:8])
    mark("first superstep done (compile included)")

    # Completion signal: a tiny device->host fetch of a slice that depends
    # on the final labels. On the tunneled axon TPU backend,
    # block_until_ready() was observed returning before the computation
    # finished (33us/iter for a 16M-element sort loop — physically
    # impossible); a data fetch cannot be early. The 32-byte transfer adds
    # negligible time to the window.
    labels = jnp.arange(NUM_VERTICES, dtype=jnp.int32)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        labels = step(labels)
    np.asarray(labels[:8])
    dt = time.perf_counter() - t0

    # The timed loop is a plain jit on one device; normalizing by the full
    # device count would understate the per-chip number on multi-chip hosts.
    chips = 1
    eps_chip = NUM_EDGES * ITERS / dt / chips
    prefix = "weighted_lpa" if weighted else "lpa"
    print(
        json.dumps(
            {
                "metric": (
                    f"{prefix}_edges_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else f"{prefix}_edges_per_sec_per_chip"
                ),
                "value": round(eps_chip),
                "unit": "edges/s" if _CPU_FALLBACK else "edges/s/chip",
                # A degraded CPU record must not report a ratio against
                # the TPU per-chip baseline (same rule as northstar).
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(
                    eps_chip / BASELINE_EDGES_PER_SEC_PER_CHIP, 3
                ),
                "detail": {
                    "num_vertices": NUM_VERTICES,
                    "num_edges": NUM_EDGES,
                    "iters": ITERS,
                    "seconds": round(dt, 3),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_roofline() -> None:
    """Roofline micro-tier (VERDICT r2 item 5): measure the primitive rates
    the kernel design is built on (docs/DESIGN.md "measured hardware
    model") on the *current* backend, and report model-vs-measured.

    Primitives: random 1-D int32 gather (the LPA superstep's bottleneck),
    scatter-add, row-wise bucket sort, segment-sum. Each timed loop feeds
    its result back through the next iteration's operand so XLA cannot
    hoist the loop-invariant work (DESIGN.md's microbenchmark warning).
    """
    import jax
    import jax.numpy as jnp

    _setup_jax_cache()

    # DESIGN.md model (r1 interactive measurements, all three REPRODUCED
    # by the r4 robust loop on a real v5e: gather 131-135M, scatter
    # ~141M, sort 1.85-2.6G — bench_r4_roofline_robust.log). Measurement
    # provenance matters on this tunneled device: a naive loop reads the
    # sort 10-40x LOW because per-iteration dispatch (~0.1 s) and a
    # full-operand completion fetch (32 MB through the tunnel) swamp the
    # ~4 ms of actual sort compute — hence timed() runs every iteration
    # inside ONE fori_loop dispatch and fetches a device-side slice.
    model = {
        "gather_slots_per_sec": 125e6,
        "scatter_add_per_sec": 135e6,
        "row_sort_elems_per_sec": 1.6e9,
    }

    # 30 chained iterations inside one dispatch: the remote-tunnel fetch
    # latency (~0.1 s) is a fixed tax on the timing window, so more
    # device work per window tightens the estimate (~3 s per primitive).
    v, m = 1 << 20, 1 << 23
    iters = 30
    if _CPU_FALLBACK:
        v, m, iters = 1 << 17, 1 << 20, 5
    # CI smoke caps (VERDICT r3 item 4): the ACTUAL measurement body must
    # be executable at tiny scale on CPU, so the tier can never fail its
    # first contact inside a real TPU window
    # (tests/test_bench_capture.py::test_roofline_body_cpu_smoke).
    v = int(os.environ.get("GRAPHMINE_ROOFLINE_TABLE", v))
    # round slots up to a whole number of 128-wide sort rows, so the
    # row-sort rate divides by exactly the elements it sorted; when this
    # adjusts an exact env-requested count, the record says so (ADVICE r4)
    m_requested = int(os.environ.get("GRAPHMINE_ROOFLINE_SLOTS", m))
    m = -(-max(m_requested, 128) // 128) * 128
    slots_adjusted = m != m_requested
    if slots_adjusted:
        print(
            f"[roofline] GRAPHMINE_ROOFLINE_SLOTS={m_requested} rounded up "
            f"to {m} (whole 128-wide sort rows)", file=sys.stderr, flush=True,
        )
    iters = int(os.environ.get("GRAPHMINE_ROOFLINE_ITERS", iters))
    rng = np.random.default_rng(5)
    idx = jnp.asarray(rng.integers(0, v, m).astype(np.int32))
    table0 = jnp.asarray(rng.integers(0, v, v).astype(np.int32))

    def timed(step, x0, elems):
        """Steady-state rate of ``step`` chained through its own output.

        All ``iters`` repetitions run inside ONE jitted ``fori_loop`` so
        the window holds exactly one dispatch: per-call tunnel/host
        latency (~100 ms on the axon TPU path) was large enough relative
        to the ~100 ms compute of a 10-iteration Python loop to swing the
        measured gather rate 110M→67M slots/s between otherwise identical
        r4 runs. The data-dependence chaining (each iteration consumes
        the previous result) still prevents hoisting."""
        loop = jax.jit(
            lambda x: jax.lax.fori_loop(0, iters, lambda i, y: step(y), x)
        )

        def fetch(x):
            # completion signal: slice ON DEVICE, then pull ~bytes — a
            # full-leaf np.asarray would drag the whole (up to 32 MB)
            # operand through the tunnel inside the timing window
            np.asarray(jax.tree_util.tree_leaves(x)[0][:1])

        fetch(loop(x0))  # compile + settle
        best = float("inf")
        for _ in range(3):
            # best-of-3 windows: the tunneled device's timing jitters
            # ±20% between identical windows; the fastest window is the
            # least-interrupted one (standard microbenchmark practice).
            t0 = time.perf_counter()
            x = loop(x0)
            fetch(x)
            best = min(best, time.perf_counter() - t0)
        return elems * iters / best

    # Random gather: the checksum write into slot 0 makes iteration i+1's
    # gather depend on iteration i's result.
    gather = jax.jit(lambda t: t.at[0].set(t[idx].sum() & 0x7FFFFFF))
    gather_rate = timed(gather, table0, m)

    # Scatter-add into a [V] accumulator, feedback via the accumulator.
    scatter = jax.jit(lambda acc: acc.at[idx].add(1))
    scatter_rate = timed(scatter, jnp.zeros((v,), jnp.int32), m)

    # Row-wise sort of [n, w] buckets (the LPA mode kernel's width-class
    # shape). The re-scramble between rounds is an odd-multiplier
    # bijection (wraps mod 2^32): a plain XOR of the previous SORTED
    # output leaves piecewise-sorted runs that an adaptive sort exploits
    # unevenly — measured 26M-175M elem/s swings between identical runs —
    # while the multiply destroys the order entirely, so every iteration
    # sorts genuinely shuffled data.
    rows = jnp.asarray(
        rng.integers(0, 1 << 30, (m // 128, 128)).astype(np.int32)
    )
    row_sort = jax.jit(
        lambda x: jnp.sort(
            x * jnp.int32(-1640531527) + jnp.int32(0x5A5A5A5A), axis=-1
        )
    )
    sort_rate = timed(row_sort, rows, m)

    # Segment-sum over sorted ids (the census/reduce primitive).
    seg = jnp.sort(idx)
    data0 = jnp.asarray(rng.integers(0, 100, m).astype(np.int32))
    segsum = jax.jit(
        lambda d: d.at[0].set(
            jax.ops.segment_sum(d, seg, num_segments=v).sum() & 0x7FFFFFF
        )
    )
    seg_rate = timed(segsum, data0, m)

    measured = {
        "gather_slots_per_sec": round(gather_rate),
        "scatter_add_per_sec": round(scatter_rate),
        "row_sort_elems_per_sec": round(sort_rate),
        "segment_sum_elems_per_sec": round(seg_rate),
    }
    # The fused bucketed kernel gathers ~2.37 slots/edge on the bench graph
    # (19.9M slots / 8.4M edges, DESIGN.md) — the gather roofline implies
    # this ceiling on the chip tier's edges/s/chip number.
    slots_per_edge = 19.9e6 / 8.39e6
    print(
        json.dumps(
            {
                "metric": (
                    "roofline_gather_slots_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "roofline_gather_slots_per_sec"
                ),
                "value": round(gather_rate),
                "unit": "slots/s",
                # ratio vs the DESIGN.md model this tier exists to validate;
                # CPU fallback rates say nothing about the TPU model.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(
                    gather_rate / model["gather_slots_per_sec"], 3
                ),
                "detail": {
                    "measured": measured,
                    "model": model,
                    "measured_vs_model": {
                        k: round(measured[k] / model[k], 3)
                        for k in model
                    },
                    "implied_lpa_ceiling_edges_per_sec": round(
                        gather_rate / slots_per_edge
                    ),
                    "gather_table_elems": v,
                    "gather_slots": m,
                    # only present when an env override was rounded up
                    **(
                        {"gather_slots_requested": m_requested}
                        if slots_adjusted else {}
                    ),
                    "iters": iters,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_blocking() -> None:
    """Propagation-blocking micro-tier (ISSUE 7): measure the sequential
    binned-pass slots/s against the random-gather slots/s on the SAME
    message volume, so the blocked-family crossover constant
    (``ops/blocking.py``: BLOCKED_MIN_VERTICES / BLOCKED_MIN_MESSAGES) is
    anchored to a hardware measurement instead of a capacity model.

    Three chained-feedback loops (the roofline tier's measurement
    discipline — one fori_loop dispatch, best-of-3 windows, data
    dependence so XLA cannot hoist):

    * ``random_gather``: ``t[idx]`` with uniform-random idx — the fused
      bucketed kernel's access pattern, the measured ~130M slots/s wall;
    * ``monotone_gather``: ``t[src_sorted]`` with sorted indices — the
      blocked bin phase's sequential value stream, isolated;
    * ``binned_pass``: the full bin phase over a REAL power-law message
      CSR's BlockedPlan — monotone gather + destination-binned scatter.
      Each pass delivers M messages whichever layout runs, so slots/s =
      messages delivered per second is the apples-to-apples rate (the
      binned pass touches ~2x the bytes per slot; the bet it measures is
      that sequential+bin-local traffic is cheaper per slot than random).
    """
    import jax
    import jax.numpy as jnp

    _setup_jax_cache()

    v, e, iters = 1 << 20, 1 << 22, 30          # M = 2e = 2^23 slots
    if _CPU_FALLBACK:
        v, e, iters = 1 << 17, 1 << 19, 5
    # CI smoke caps (the roofline tier's convention): the ACTUAL
    # measurement body must be executable at tiny scale on CPU
    # (tests/test_blocking.py::test_blocking_tier_body_cpu_smoke).
    v = int(os.environ.get("GRAPHMINE_BLOCKING_VERTICES", v))
    e = int(os.environ.get("GRAPHMINE_BLOCKING_EDGES", e))
    iters = int(os.environ.get("GRAPHMINE_BLOCKING_ITERS", iters))

    from graphmine_tpu.graph.container import _message_csr
    from graphmine_tpu.ops.blocking import BlockedPlan

    src, dst = powerlaw_edges(v, e, seed=7)
    t0 = time.perf_counter()
    ptr, _, send, _ = _message_csr(src, dst, v, True)
    plan = BlockedPlan.from_ptr(ptr, v, send)
    plan_seconds = time.perf_counter() - t0
    m = plan.num_messages

    rng = np.random.default_rng(11)
    idx_rand = jnp.asarray(rng.integers(0, v, m).astype(np.int32))
    table0 = jnp.asarray(rng.integers(0, v, v).astype(np.int32))

    def timed(step, x0, elems):
        """Best-of-3 steady-state rate, all iterations in ONE dispatch
        (see main_roofline for why: per-call tunnel latency swamps the
        compute otherwise)."""
        loop = jax.jit(
            lambda x: jax.lax.fori_loop(0, iters, lambda i, y: step(y), x)
        )

        def fetch(x):
            np.asarray(jax.tree_util.tree_leaves(x)[0][:1])

        fetch(loop(x0))  # compile + settle
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fetch(loop(x0))
            best = min(best, time.perf_counter() - t0)
        return elems * iters / best

    # Checksum-into-slot-0 feedback makes iteration i+1 depend on i.
    random_rate = timed(
        jax.jit(lambda t: t.at[0].set(t[idx_rand].sum() & 0x7FFFFFF)),
        table0, m,
    )
    mono_rate = timed(
        jax.jit(lambda t: t.at[0].set(t[plan.src_sorted].sum() & 0x7FFFFFF)),
        table0, m,
    )

    def binned(t):
        vals = t[plan.src_sorted]                       # monotone stream
        tile = jnp.zeros((plan.tile_alloc,), jnp.int32).at[
            plan.scatter_pos
        ].set(vals, unique_indices=True)                # destination bins
        return t.at[0].set(tile.sum() & 0x7FFFFFF)

    binned_rate = timed(jax.jit(binned), table0, m)
    ratio = binned_rate / max(random_rate, 1e-9)

    print(
        json.dumps(
            {
                "metric": (
                    "blocking_binned_slots_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "blocking_binned_slots_per_sec"
                ),
                "value": round(binned_rate),
                "unit": "slots/s",
                # ratio of the binned pass over the random gather on the
                # same message volume — >1 means the blocked layout beats
                # the gather roofline and the crossover constants hold;
                # CPU-fallback ratios say nothing about the TPU model.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(ratio, 3),
                "detail": {
                    "random_gather_slots_per_sec": round(random_rate),
                    "monotone_gather_slots_per_sec": round(mono_rate),
                    "binned_pass_slots_per_sec": round(binned_rate),
                    "binned_vs_random_gather": round(ratio, 3),
                    "num_vertices": v,
                    "num_edges": e,
                    "messages": m,
                    "num_bins": plan.num_bins,
                    "tile_slots": plan.tile_slots,
                    "plan_build_seconds": round(plan_seconds, 3),
                    "iters": iters,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_exchange() -> None:
    """Exchange micro-tier (ISSUE 15): bytes-on-the-wire and superstep
    seconds for the one-all_gather label exchange vs the 2D
    neighbor-only boundary exchange, at D ∈ {2, 4, 8}.

    Each mesh size partitions the SAME power-law graph twice — the
    blocked one-all_gather family and the 2D family
    (``partition_graph(build_plan2d=True)``) — runs a fixed LPA
    superstep count through each (bit-parity asserted), and reads the
    modeled per-chip exchange bytes off the cost model
    (``sharded_superstep_cost``: ``4·Vc·(D-1)`` vs
    ``4·Σ_peer |boundary|``). The headline is the neighbor/all_gather
    bytes fraction at the largest measured D; ``detail`` carries the
    per-D seconds, bytes and boundary fractions the crossover policy
    (``ops/blocking.SHARDED2D_MIN_*``) should eventually be re-seeded
    from.

    Honest-capture note: multi-device meshes need actual devices, so
    the orchestrator runs this tier on an 8-virtual-CPU-device mesh
    (CPU-fallback record shape — the modeled BYTES are exact either
    way; only the seconds are CPU numbers) unless
    ``GRAPHMINE_EXCHANGE_REAL_MESH=1`` marks a real multi-chip window
    (the silicon capture ``--list-missing`` keeps pending until then).
    """
    import jax

    _setup_jax_cache()

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.obs.costmodel import sharded_superstep_cost
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    v, e, iters = 1 << 16, 1 << 17, 5
    if _CPU_FALLBACK:
        v, e = 1 << 14, 1 << 15
    v = int(os.environ.get("GRAPHMINE_EXCHANGE_VERTICES", v))
    e = int(os.environ.get("GRAPHMINE_EXCHANGE_EDGES", e))
    iters = int(os.environ.get("GRAPHMINE_EXCHANGE_ITERS", iters))

    src, dst = powerlaw_edges(v, e, seed=5)
    host_g = build_graph(src, dst, num_vertices=v, to_device=False)
    avail = len(jax.devices())

    def timed(fn):
        fetch = lambda r: np.asarray(r[:4])
        fetch(fn())  # compile
        t0 = time.perf_counter()
        fetch(fn())
        return time.perf_counter() - t0

    per_d = {}
    skipped = []
    for d in (2, 4, 8):
        if d > avail:
            skipped.append(d)
            continue
        mesh = make_mesh(d)
        sg_1d = shard_graph_arrays(
            partition_graph(host_g, mesh=mesh, build_blocked_plan=True), mesh
        )
        sg_2d = shard_graph_arrays(
            partition_graph(host_g, mesh=mesh, build_plan2d=True), mesh
        )
        lbl_1d = sharded_label_propagation(sg_1d, mesh, max_iter=iters)
        lbl_2d = sharded_label_propagation(sg_2d, mesh, max_iter=iters)
        agree = bool(np.array_equal(np.asarray(lbl_1d), np.asarray(lbl_2d)))
        if not agree:
            # a bytes-saving headline measured on a computation that no
            # longer matches the oracle would be worse than no record
            _print_error_record(
                "exchange",
                [f"2D labels diverged from the one-all_gather family at "
                 f"D={d} — bit-parity contract broken; no rate published"],
            )
            return
        t_1d = timed(
            lambda: sharded_label_propagation(sg_1d, mesh, max_iter=iters)
        )
        t_2d = timed(
            lambda: sharded_label_propagation(sg_2d, mesh, max_iter=iters)
        )
        from graphmine_tpu.obs.costmodel import neighbor_frontier_bytes

        cost_1d = sharded_superstep_cost("lpa_superstep", sg_1d, e)
        cost_2d = sharded_superstep_cost("lpa_superstep", sg_2d, e)
        row = {
            "allgather_seconds": round(t_1d, 4),
            "neighbor_seconds": round(t_2d, 4),
            "allgather_exchange_bytes": cost_1d.exchange_bytes,
            # WIRE bytes: padded shared-width buffers, what ships
            "neighbor_exchange_bytes": cost_2d.exchange_bytes,
            # the unpadded boundary content (the frontier floor)
            "neighbor_frontier_bytes": neighbor_frontier_bytes(sg_2d),
            "bytes_frac": round(
                cost_2d.exchange_bytes / max(cost_1d.exchange_bytes, 1), 4
            ),
            "boundary_slots": sg_2d.x2d_boundary_total,
            "padded_boundary": sg_2d.x2d_boundary,
            "agree": agree,
        }
        per_d[str(d)] = row
        print(json.dumps({"progress": {f"exchange_d{d}": row}}),
              file=sys.stderr, flush=True)

    if not per_d:
        _print_error_record(
            "exchange",
            [f"needs >= 2 devices (have {avail}); no mesh measured"],
        )
        return
    d_max = max(per_d, key=int)
    frac = per_d[d_max]["bytes_frac"]
    virtual = jax.devices()[0].platform != "tpu"
    print(
        json.dumps(
            {
                "metric": (
                    "exchange_neighbor_bytes_frac_cpu_fallback"
                    if (_CPU_FALLBACK or virtual)
                    else "exchange_neighbor_bytes_frac"
                ),
                # the headline: neighbor-exchange bytes as a fraction of
                # the all_gather ladder at the largest measured D —
                # LOWER is better; the modeled bytes are exact on any
                # backend (only the seconds are CPU numbers on the
                # virtual mesh)
                "value": frac,
                "unit": "frac",
                "vs_baseline": 0.0,
                "detail": {
                    "num_vertices": v,
                    "num_edges": e,
                    "iters": iters,
                    "per_devices": per_d,
                    # tracked sub-record (tools/bench_diff.py manifest):
                    # the neighbor/all_gather WALL ratio at the largest
                    # D — the number a real-ICI window must capture to
                    # re-seed exchange_bytes_per_sec and the crossover
                    "neighbor_vs_allgather": round(
                        per_d[d_max]["allgather_seconds"]
                        / max(per_d[d_max]["neighbor_seconds"], 1e-9), 3
                    ),
                    "skipped_devices": skipped,
                    "virtual_mesh": virtual,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main() -> None:
    _run_chip_tier(weighted=False)


def main_weighted() -> None:
    """Weighted-LPA throughput (r2: weighted rides the fused bucketed
    kernel — argmax of per-label weight sums)."""
    _run_chip_tier(weighted=True)


def main_cc() -> None:
    """Connected-components perf tier (VERDICT r4 item 2).

    BASELINE.json's north star names "labelPropagation and
    connectedComponents" as the two kernels to rebuild
    (``Graphframes.py:78``'s GraphFrame exposes both); four rounds timed
    LPA only. This tier runs CC **to convergence** (pointer-jumped
    min-label propagation, ``ops/cc.py``) on the 100M-edge north-star
    graph plus the com-livejournal ladder rung, reporting edges/s/chip
    = E x supersteps / seconds with the iterations-to-fixpoint count.
    The whole fixpoint loop is ONE ``lax.while_loop`` dispatch; the
    completion signal is a device-slice fetch (chip-tier convention for
    the tunneled device)."""
    import jax

    build_graph_and_plan, _ = _setup_jax_cache()

    from graphmine_tpu.datasets import load
    from graphmine_tpu.ops.cc import connected_components

    def measure(src, dst, v):
        e = int(len(src))
        t0 = time.perf_counter()
        # One shared message-CSR pass builds graph AND the fused plan —
        # the bucketed-min superstep (r5, cc_superstep_bucketed) is the
        # headline path; the segment_min path is timed alongside so the
        # record carries the measured speedup that justifies it.
        g, plan = build_graph_and_plan(src, dst, num_vertices=v)
        t_build = time.perf_counter() - t0

        def timed_cc(**kw):
            labels, iters = connected_components(
                g, return_iterations=True, **kw
            )
            np.asarray(labels[:4])  # compile + converge (cold)
            t0 = time.perf_counter()
            labels, iters = connected_components(
                g, return_iterations=True, **kw
            )
            np.asarray(labels[:4])
            return labels, int(iters), time.perf_counter() - t0

        labels, it, dt = timed_cc(plan=plan)
        seg_labels, seg_it, seg_dt = timed_cc(plan=None)  # segment_min path
        assert np.array_equal(np.asarray(labels), np.asarray(seg_labels))
        return {
            "vertices": v,
            "edges": e,
            "iterations_to_fixpoint": it,
            "seconds": round(dt, 3),
            "segment_path_seconds": round(seg_dt, 3),
            "bucketed_speedup": round(seg_dt / dt, 2),
            "build_seconds": round(t_build, 1),
            "edges_per_sec_per_chip": round(e * it / dt),
            "components": int(len(np.unique(np.asarray(labels)))),
        }

    v, e = 1 << 24, 100_000_000
    if _CPU_FALLBACK:
        v, e = 1 << 20, 6_250_000
    src, dst = powerlaw_edges(v, e)
    northstar = measure(src, dst, v)
    print(json.dumps({"progress": {"northstar_cc": northstar}}),
          file=sys.stderr, flush=True)

    # One SNAP ladder rung (real file when present, honest R-MAT stand-in
    # otherwise — same policy as the snap tier).
    data_dir = os.environ.get(
        "GRAPHMINE_SNAP_DIR", os.path.join(_REPO_DIR, "data")
    )
    rung_name = "com-amazon" if _CPU_FALLBACK else "com-livejournal"
    et = load(rung_name, data_dir=data_dir,
              max_scale=16 if _CPU_FALLBACK else None)
    rung = dict(
        rung=rung_name,
        **measure(et.src, et.dst, et.num_vertices),
    )

    eps = northstar["edges_per_sec_per_chip"]
    print(
        json.dumps(
            {
                "metric": (
                    "cc_edges_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "cc_edges_per_sec_per_chip"
                ),
                "value": eps,
                "unit": "edges/s" if _CPU_FALLBACK else "edges/s/chip",
                # BASELINE.json gives CC no separate number; the bar is
                # the same reference-derived per-chip rate the LPA tiers
                # use (north-star 60 s budget, BASELINE.md derivation).
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(
                    eps / BASELINE_EDGES_PER_SEC_PER_CHIP, 3
                ),
                "detail": {
                    "northstar_100m": northstar,
                    "snap_rung": rung,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_sharded() -> None:
    """Distributed-schedules-on-silicon tier (VERDICT r4 item 1 — the top
    item): every shard_map/ring program had only ever compiled on XLA:CPU
    virtual meshes; r4's first hardware contact proved that evidence class
    finds real bugs (Mosaic compile blowup, MXU bf16 rounding) that CPU CI
    structurally cannot. A 1-device ``make_mesh(1)`` on the real chip
    compiles and executes the IDENTICAL shard_map programs — same bodies,
    same collectives, same specs — so this tier runs the full distributed
    family there and cross-checks each against its single-device twin:

      * sharded_label_propagation (bucketed fast path) — label-exact
      * ring_label_propagation — label-exact
      * sharded_connected_components / ring variant — label-exact
      * sharded_pagerank — allclose
      * sharded_lof (ring kNN + distributed LOF) — allclose
      * recursive_lpa_outliers_sharded — flag-exact

    Headline: sharded-LPA edges/s/chip on the 1-device mesh; detail
    carries each program's seconds and its agreement bit plus the
    sharded/fused throughput ratio (the shard_map dispatch overhead)."""
    import jax

    _setup_jax_cache()

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.ops.outliers import (
        recursive_lpa_outliers,
        recursive_lpa_outliers_sharded,
    )
    from graphmine_tpu.ops.pagerank import pagerank
    from graphmine_tpu.ops.lof import lof_scores
    from graphmine_tpu.parallel.knn import sharded_lof
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.ring import (
        ring_connected_components,
        ring_label_propagation,
    )
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_connected_components,
        sharded_label_propagation,
        sharded_pagerank,
    )

    v, e = NUM_VERTICES, NUM_EDGES          # chip-tier graph
    lof_n, lof_k = 1 << 16, 32
    if _CPU_FALLBACK:
        lof_n = 1 << 13
    src, dst = powerlaw_edges(v, e)
    host_g = build_graph(src, dst, num_vertices=v, to_device=False)
    mesh = make_mesh(1)
    sg_rep = shard_graph_arrays(
        partition_graph(host_g, mesh=mesh, build_bucket_plan=True), mesh
    )
    sg_ring = shard_graph_arrays(partition_graph(host_g, mesh=mesh), mesh)

    detail = {"num_vertices": v, "num_edges": e, "mesh_devices": 1}
    agree_all = True

    def timed(tag, fn, fetch=lambda r: np.asarray(r[:4])):
        """Warm-up (compile) then one timed run; returns (result, secs)."""
        fetch(fn())
        t0 = time.perf_counter()
        r = fn()
        fetch(r)
        return r, time.perf_counter() - t0

    def mark(tag, secs, agree):
        nonlocal agree_all
        agree_all &= bool(agree)
        detail[tag] = {"seconds": round(secs, 3), "agree": bool(agree)}
        print(json.dumps({"progress": {tag: detail[tag]}}),
              file=sys.stderr, flush=True)

    # Single-device twins (the oracles — also run on this same silicon).
    dev_g = build_graph(src, dst, num_vertices=v)
    want_lpa, t_lpa_1dev = timed(
        "fused", lambda: label_propagation(dev_g, max_iter=5)
    )
    want_lpa = np.asarray(want_lpa)
    want_cc = np.asarray(connected_components(dev_g))
    # PageRank is a directed-graph op: its own build + partition.
    from graphmine_tpu.ops.degrees import out_degrees

    dev_gd = build_graph(src, dst, num_vertices=v, symmetric=False)
    od = out_degrees(dev_gd)
    want_pr = np.asarray(pagerank(dev_gd, max_iter=20))
    host_gd = build_graph(
        src, dst, num_vertices=v, to_device=False, symmetric=False
    )
    sg_pr = shard_graph_arrays(partition_graph(host_gd, mesh=mesh), mesh)

    lbl, secs = timed(
        "sharded_lpa", lambda: sharded_label_propagation(sg_rep, mesh, max_iter=5)
    )
    mark("sharded_lpa", secs, np.array_equal(np.asarray(lbl), want_lpa))
    sharded_lpa_secs = secs

    lbl, secs = timed(
        "ring_lpa", lambda: ring_label_propagation(sg_ring, mesh, max_iter=5)
    )
    mark("ring_lpa", secs, np.array_equal(np.asarray(lbl), want_lpa))

    lbl, secs = timed(
        "sharded_cc", lambda: sharded_connected_components(sg_rep, mesh)
    )
    mark("sharded_cc", secs, np.array_equal(np.asarray(lbl), want_cc))

    lbl, secs = timed(
        "ring_cc", lambda: ring_connected_components(sg_ring, mesh)
    )
    mark("ring_cc", secs, np.array_equal(np.asarray(lbl), want_cc))

    pr, secs = timed(
        "sharded_pagerank",
        lambda: sharded_pagerank(sg_pr, mesh, od, max_iter=20),
    )
    mark("sharded_pagerank", secs,
         np.allclose(np.asarray(pr), want_pr, rtol=2e-4, atol=1e-6))

    rng = np.random.default_rng(13)
    pts = rng.normal(size=(lof_n, 8)).astype(np.float32)
    want_lof = np.asarray(lof_scores(pts, k=lof_k, impl="xla"))
    sc, secs = timed(
        "sharded_lof", lambda: sharded_lof(pts, mesh, k=lof_k),
        fetch=lambda r: np.asarray(r[:4]),
    )
    # rtol matches the sharded-kNN parity tests: the ring path's
    # per-chunk top-k merge reorders float reductions.
    mark("sharded_lof", secs,
         np.allclose(np.asarray(sc), want_lof, rtol=1e-3, atol=1e-5))
    detail["sharded_lof"]["points"] = lof_n

    want_out = recursive_lpa_outliers(dev_g, want_lpa)
    rep, secs = timed(
        "sharded_outliers",
        lambda: recursive_lpa_outliers_sharded(
            host_g, want_lpa, mesh, schedule="replicated"
        ),
        fetch=lambda r: r.outlier_vertices[:4],
    )
    mark("sharded_outliers", secs, np.array_equal(
        np.asarray(rep.outlier_vertices),
        np.asarray(want_out.outlier_vertices),
    ))

    eps = e * 5 / sharded_lpa_secs
    detail["fused_lpa5_seconds"] = round(t_lpa_1dev, 3)
    detail["sharded_over_fused"] = round(sharded_lpa_secs / t_lpa_1dev, 3)
    detail["all_agree"] = agree_all
    detail["device"] = str(jax.devices()[0])
    print(
        json.dumps(
            {
                "metric": (
                    "sharded_lpa_edges_per_sec_cpu_fallback"
                    if _CPU_FALLBACK else "sharded_lpa_edges_per_sec_per_chip"
                ),
                # a silent disagreement must not report healthy throughput
                "value": round(eps) if agree_all else 0.0,
                "unit": "edges/s" if _CPU_FALLBACK else "edges/s/chip",
                "vs_baseline": 0.0 if (_CPU_FALLBACK or not agree_all)
                else round(eps / BASELINE_EDGES_PER_SEC_PER_CHIP, 3),
                "detail": detail,
            }
        )
    )


def main_e2e() -> None:
    """End-to-end pipeline tier (VERDICT r4 item 3): the reference's five
    chapters — CS-1 ingest, CS-2 build, CS-3 LPA, CS-4 census, CS-5
    outliers (recursive-LPA decile + LOF), ``Graphframes.py:12-137`` —
    as ONE ``run_pipeline`` wall-clock on the real chip, per-phase
    seconds in the record, cold-compile and warm-cache runs separated.

    The dataset is a generated string-domain parquet (the reference's
    ingestion format: domain-string columns ``_c1``/``_c2``, built
    columnar via Arrow dictionary arrays) at 25M edges / 262K vertices —
    inside the 10-50M band the verdict asked for, and sized so the LOF
    chapter stays feasible on one chip.

    r6 (VERDICT r5 weak-item 1): the graph is
    ``datasets.planted_anomaly_graph`` — planted communities over a
    sparse hub skeleton plus injected structural anomalies — instead of
    the pure power-law draw LPA collapsed to 3 communities. The timed
    chapters now DETECT: the record asserts nonzero recursive-decile
    flags, >= 10 parents with populated deciles, nonzero LOF>1.5, and
    carries the injected-anomaly AUROC, so the flagship number times the
    five chapters of ``Graphframes.py:12-137`` *doing their job*."""
    import jax

    _setup_jax_cache()

    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from graphmine_tpu.datasets import planted_anomaly_graph
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    v, e = 1 << 18, 25_000_000
    if _CPU_FALLBACK:
        v, e = 1 << 13, 400_000
    t0 = time.perf_counter()
    src, dst, is_anomaly, _planted = planted_anomaly_graph(v, e, seed=9)
    names = pa.array([f"d{i:07d}.example" for i in range(v)])
    col = lambda ids: pa.DictionaryArray.from_arrays(
        pa.array(ids, pa.int32()), names
    ).cast(pa.string())
    tmp = tempfile.mkdtemp(prefix="graphmine_e2e_")
    try:
        pq.write_table(
            pa.table({"_c1": col(src), "_c2": col(dst)}),
            os.path.join(tmp, "edges.parquet"),
        )
        t_dataset = time.perf_counter() - t0

        cfg = PipelineConfig(
            data_path=os.path.join(tmp, "edges.parquet"),
            batch_rows=4_000_000,   # streaming interner (CS-1 slicer path)
            max_iter=5,
            outlier_method="both",
        )

        def one_run():
            t0 = time.perf_counter()
            res = run_pipeline(cfg)
            wall = time.perf_counter() - t0
            phases = {}
            for r in res.metrics.records:
                if "seconds" in r:
                    phases[r["phase"]] = round(
                        phases.get(r["phase"], 0.0) + r["seconds"], 2
                    )
            return wall, phases, res

        cold_wall, cold_phases, res_cold = one_run()
        print(json.dumps({"progress": {"cold": cold_phases}}),
              file=sys.stderr, flush=True)
        warm_wall, warm_phases, res = one_run()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # The two runs are the determinism check: identical partitions.
    deterministic = (
        res.num_communities == res_cold.num_communities
        and np.array_equal(res.labels, res_cold.labels)
    )
    # Ingestion re-factorizes vertex ids in name-appearance order; map the
    # pipeline's id space back to the generator's for the ground-truth
    # join (names are "d%07d.example", so the original id is in the name).
    orig_of = np.array(
        [int(n[1:8]) for n in res.edge_table.names], dtype=np.int64
    )
    from graphmine_tpu.ops.lof import auroc

    lof_auroc = (
        round(float(auroc(res.lof, is_anomaly[orig_of])), 4)
        if res.lof is not None else None
    )
    impl_sel = [
        r for r in res.metrics.records if r.get("phase") == "impl_selected"
    ]
    print(
        json.dumps(
            {
                "metric": (
                    "e2e_pipeline_seconds_cpu_fallback"
                    if _CPU_FALLBACK else "e2e_pipeline_25m_warm_seconds"
                ),
                "value": round(warm_wall, 2),
                "unit": "s",
                # The bar: the reference-derived per-chip LPA rate implies
                # 25M x 5 / 1.042M/s = 120 s for the LPA chapter ALONE on
                # one chip (BASELINE.md derivation) — vs_baseline > 1
                # means the WHOLE five-chapter pipeline (ingest through
                # LOF) beats the budget the reference math gives just the
                # propagation loop.
                "vs_baseline": 0.0 if _CPU_FALLBACK else round(
                    (e * 5 / BASELINE_EDGES_PER_SEC_PER_CHIP) / warm_wall, 3
                ),
                "detail": {
                    "num_vertices": v,
                    "num_edges": e,
                    "dataset_gen_seconds": round(t_dataset, 1),
                    "cold_wall_seconds": round(cold_wall, 2),
                    "warm_phases": warm_phases,
                    "cold_phases": cold_phases,
                    "communities": res.num_communities,
                    "outliers_flagged": int(
                        res.outliers.outlier_vertices.sum()
                    ) if res.outliers is not None else None,
                    # detection evidence (r6): the decile chapter's
                    # populated-parent count, the injected ground truth,
                    # and which kNN impl the LOF phase deployed
                    "decile_parents": len(res.outliers.thresholds)
                    if res.outliers is not None else None,
                    "sub_communities": len(res.outliers.sub_sizes)
                    if res.outliers is not None else None,
                    "num_anomalies_injected": int(is_anomaly.sum()),
                    "lof_auroc_injected": lof_auroc,
                    "lof_over_1_5": int((res.lof > 1.5).sum())
                    if res.lof is not None else None,
                    "lof_impl_selected": (
                        impl_sel[-1]["impl"] if impl_sel else None
                    ),
                    "deterministic_rerun": bool(deterministic),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


# ---------------------------------------------------------------------------
# Capture orchestration.
#
# Round-1 postmortem (VERDICT.md): the driver's bench invocation produced no
# artifact twice — once rc=1 on a flaky axon init, once a >9-minute silent
# hang. Round 2 fixed the capture path (child watchdogs, retry, scrubbed CPU
# fallback) but captured only ONE tier and gave up probing after two
# back-to-back attempts — so a tunnel that flapped up mid-budget was missed
# (VERDICT r2 weak 1-2). Round 3:
#
#   * no-args `python bench.py` = --tier all: on a healthy TPU it runs EVERY
#     tier, one JSON line per tier, each child bounded;
#   * probing is SPACED across the budget (default every 3 min inside a
#     probe window) with a timestamped reachability trace recorded in
#     detail.capture.trace — a dead-all-round tunnel leaves proof that the
#     environment, not the code, was the blocker;
#   * tunnel dead: reduced-scale scrubbed-CPU fallback records for all
#     tiers (chip first — same driver-parsed record as before).
#
# Every path prints at least one parseable JSON line on stdout, and each
# tier's line is flushed the moment it exists (a mid-run kill loses only
# later tiers). Round 4: the LAST line of every orchestrated run is a
# compact suite-summary record (<1600 chars, `_suite_summary`) — the r3
# artifact proved the driver keeps a ~2000-char stdout *tail* and parses
# the LAST record, so BENCH_r03.json's headline was the stream tier and
# the chip number scrolled out of the artifact entirely.
# ---------------------------------------------------------------------------

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))

_CHILD_TIMEOUT_S = {
    "chip": 900.0,
    "roofline": 900.0,
    "blocking": 900.0,
    "northstar": 2700.0,
    "sharded": 1800.0,
    "exchange": 900.0,
    "cc": 1800.0,
    "e2e": 2400.0,
    "lof": 1200.0,
    "snap": 2400.0,
    "quality": 1200.0,
    "weighted": 900.0,
    "stream": 1200.0,
    # serve grew the replicated_read fleet sub-record in r10 (1- and
    # 3-replica router hammers on top of write_load)
    "serve": 1500.0,
}

# Healthy-TPU capture order: chip first (its number headlines the final
# suite-summary record — the LAST line, which is what the driver's
# 2000-char-tail artifact actually parses; r3 learned this the hard way),
# roofline second (validates the hardware model right next to the chip
# number), then the remaining tiers by evidence value.
_TIER_ORDER = [
    "chip", "roofline", "blocking", "northstar", "sharded", "exchange",
    "cc", "e2e", "lof", "snap", "quality", "weighted", "stream", "serve",
]
# Dead-tunnel fallback order: every tier has a reduced-scale CPU variant
# except roofline (CPU primitive rates say nothing about the TPU model).
# (blocking IS here, unlike roofline: its headline is the binned-vs-
# gather RATIO record shape, which the capture pipeline needs to exist
# even when the rates themselves are CPU numbers.)
_FALLBACK_TIERS = [
    "chip", "northstar", "blocking", "sharded", "exchange", "cc", "e2e",
    "lof", "snap", "quality", "weighted", "stream", "serve",
]

# Indirection so orchestration tests can stub the inter-probe wait.
_sleep = time.sleep


def _tier_child_env(tier, env):
    """Per-tier child environment. The ``exchange`` tier measures D ∈
    {2, 4, 8} meshes, which need actual devices: unless the operator
    marks a real multi-chip window (``GRAPHMINE_EXCHANGE_REAL_MESH=1``),
    its child runs on an 8-virtual-CPU-device mesh with the honest
    CPU-fallback record shape (the modeled exchange BYTES are exact on
    any backend; only the seconds are CPU numbers)."""
    if (
        tier == "exchange"
        and os.environ.get("GRAPHMINE_EXCHANGE_REAL_MESH") != "1"
    ):
        env = _virtual_cpu_env(8)
        env["GRAPHMINE_BENCH_CPU_FALLBACK"] = "1"
    return env


def _virtual_cpu_env(n_devices):
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    import __graft_entry__

    return __graft_entry__._load_envscrub().virtual_cpu_env(n_devices)


def _probe_tpu(timeout_s=None):
    """Bounded backend-init probe in a throwaway child.

    -> (ok, platform | None, info). ``platform`` is what the default
    backend actually is ("tpu", "cpu", ...) so the caller can distinguish
    a healthy accelerator from an accidental CPU-only environment.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("GRAPHMINE_BENCH_PROBE_TIMEOUT", "120"))
    code = (
        "import jax; d = jax.devices(); "
        "print(d[0].platform, len(d), str(d[0]))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, None, f"backend init timed out after {timeout_s:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return False, None, f"backend init rc={p.returncode}: {tail[0][:200]}"
    info = (p.stdout or "").strip()[:200]
    platform = info.split()[0] if info else "unknown"
    return True, platform, info


def _children_maxrss_bytes():
    """Cumulative reaped-children peak RSS in bytes, or None off-POSIX.
    ru_maxrss is KiB on Linux but already bytes on macOS — scale by
    platform so a darwin capture doesn't record 1024x-inflated peaks
    into the bench_diff memory gate."""
    try:
        import resource

        raw = int(resource.getrusage(resource.RUSAGE_CHILDREN).ru_maxrss)
    except Exception:
        return None
    return raw if sys.platform == "darwin" else raw * 1024


def _tier_memory_subrecord(record, before):
    """The per-tier ``memory`` sub-record (ISSUE 14): the measurement
    child's peak RSS plus the memmodel estimate when the record's detail
    names the workload size. ``before`` is the cumulative
    reaped-children max sampled just BEFORE this child spawned —
    getrusage(RUSAGE_CHILDREN) is a running max over ALL children
    (probe children, the backend audit), so a tier whose child did not
    raise it reports the bound with upper_bound=true and the bench_diff
    memory gate never attributes another child's peak to this tier.
    Tracked in tools/bench_diff.py's silicon manifest; peak bytes
    regress UP in its gate. None off-POSIX."""
    peak = _children_maxrss_bytes()
    if peak is None or before is None:
        return None
    out = {
        "peak_rss_bytes": peak,
        "upper_bound": peak <= before,
        "source": "rusage_children",
    }
    det = record.get("detail") or {}
    v, e = det.get("num_vertices"), det.get("num_edges")
    if isinstance(v, int) and isinstance(e, int) and v > 0 and e > 0:
        # stdlib-only import — safe even when jax is unreachable
        from graphmine_tpu.obs.memmodel import schedule_bytes_per_device

        out["model_bytes"] = schedule_bytes_per_device("single", v, e, 1)
    return out


def _run_child(tier, env, timeout_s):
    """Run one measurement child. -> (record dict | None, error | None)."""
    env = dict(env, _GRAPHMINE_BENCH_CHILD="1")
    rss_before = _children_maxrss_bytes()
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--tier", tier],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=_REPO_DIR,
        )
    except subprocess.TimeoutExpired:
        return None, f"measurement timed out after {timeout_s:.0f}s (killed)"
    # Forward child diagnostics without polluting the one-JSON-line stdout.
    for line in (p.stderr or "").strip().splitlines()[-15:]:
        print(f"[child stderr] {line}", file=sys.stderr)
    record = None
    for line in (p.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                cand = None
            if isinstance(cand, dict) and "metric" in cand:
                record = cand
                continue
        if line:
            print(f"[child stdout] {line}", file=sys.stderr)
    if record is None:
        if p.returncode != 0:
            return None, f"measurement child rc={p.returncode}"
        return None, "child produced no JSON record"
    if p.returncode != 0:
        # The measurement completed and printed its record before the
        # interpreter died (the round-1 flaky-teardown class): keep the
        # real data, disclose the exit code.
        print(
            f"[capture] child rc={p.returncode} after printing its record; "
            "record salvaged", file=sys.stderr,
        )
        record.setdefault("detail", {})["child_rc"] = p.returncode
    mem = _tier_memory_subrecord(record, rss_before)
    if mem is not None:
        # per-tier memory sub-record (ISSUE 14): model + measured peak,
        # tracked by bench_diff's manifest and regression gate
        record.setdefault("detail", {}).setdefault("memory", mem)
    return record, None


def _run_backend_audit(timeout_s=300.0):
    """Cross-backend numerical audit (tools/tpu_backend_audit.py): the
    default backend (real TPU, incl. the Pallas kNN kernel) vs a CPU
    reference. Returns a short status string for the capture record."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.join(_REPO_DIR, "tools", "tpu_backend_audit.py")],
            capture_output=True, text=True, timeout=timeout_s, cwd=_REPO_DIR,
        )
    except subprocess.TimeoutExpired:
        return f"timeout after {timeout_s:.0f}s"
    if p.returncode == 0 and "all backends agree" in (p.stdout or ""):
        return "agree"
    tail = ((p.stderr or "") + (p.stdout or "")).strip().splitlines()[-1:]
    return f"rc={p.returncode}: {tail[0][:200] if tail else 'no output'}"


def _print_record(record):
    rid, tid = _bench_run_identity()
    record.setdefault("run_id", rid)
    record.setdefault("trace_id", tid)
    print(json.dumps(record), flush=True)


def _error_record(tier, reasons):
    return {
        "metric": f"bench_{tier}_capture_failed",
        "value": 0.0,
        "unit": "error",
        "vs_baseline": 0.0,
        "error": "; ".join(reasons)[:800],
    }


def _print_error_record(tier, reasons):
    rec = _error_record(tier, reasons)
    _print_record(rec)
    return rec


def _suite_summary(suite, platform, tpu_info, trace):
    """The compact suite-summary record printed as the LAST stdout line of
    every orchestrated run (VERDICT r3 item 1).

    The driver's artifact keeps a ~2000-char stdout *tail* and parses the
    LAST JSON record — BENCH_r03.json proved it: chip was printed first
    "for the driver" and scrolled out; the parsed headline was the stream
    tier. This one bounded line therefore carries the whole round:

      * headline fields (metric/value/unit/vs_baseline) copied verbatim
        from the chip record when it produced a real measurement (else the
        first real tier record, else the first error record) — so the
        driver-parsed number IS the chip edges/s figure;
      * ``suite.tiers``: per-tier {m,v,u,vs} (or a truncated ``err``);
      * ``suite.platform`` + ``suite.probes``: first/last probe + counts,
        a digest of the full trace that rides the first tier record.

    ``suite`` is the ordered list of (tier, record) printed this run.
    Everything is truncated to keep the line well inside the 2000-char
    artifact tail (pinned <1600 in tests).
    """
    def is_real(rec):
        return "error" not in rec

    headline = None
    for t, rec in suite:
        if t == "chip" and is_real(rec):
            headline = rec
            break
    if headline is None:
        headline = next((r for _, r in suite if is_real(r)), None)
    if headline is None:
        headline = suite[0][1] if suite else _error_record(
            "suite", ["no tier records"]
        )

    tiers = {}
    for t, rec in suite:
        if is_real(rec):
            tiers[t] = {
                "m": rec.get("metric"),
                "v": rec.get("value"),
                "u": rec.get("unit"),
                "vs": rec.get("vs_baseline"),
            }
        else:
            tiers[t] = {"err": str(rec.get("error", ""))[:80]}

    def probe_digest(entry):
        return {
            "t": entry.get("t"),
            "utc": entry.get("utc"),
            "ok": entry.get("ok"),
            "info": str(entry.get("info", ""))[:90],
        }

    probes = {"n": len(trace), "ok": sum(1 for e in trace if e.get("ok"))}
    if trace:
        probes["first"] = probe_digest(trace[0])
        if len(trace) > 1:
            probes["last"] = probe_digest(trace[-1])
    rid, tid = _bench_run_identity()
    return {
        "metric": headline.get("metric"),
        "value": headline.get("value"),
        "unit": headline.get("unit"),
        "vs_baseline": headline.get("vs_baseline"),
        "suite": {
            # the BENCH_*.json header identity: joins this capture to
            # any obs JSONL recorded in the same window
            "run_id": rid,
            "trace_id": tid,
            "tiers": tiers,
            "platform": platform or "unreachable",
            "tpu_probe": (tpu_info or "")[:90] or None,
            "probes": probes,
        },
    }


def orchestrate(tier):
    """Capture driver. ``tier`` is a tier name or ``"all"`` (the no-args
    default): all-tiers on a healthy TPU, all-tiers reduced-scale CPU
    fallback on a dead tunnel. Returns 0 if at least one real measurement
    record was printed."""
    all_mode = tier == "all"
    # Mint the run identity BEFORE any child spawns: children inherit
    # GRAPHMINE_BENCH_RUN_ID/TRACE_ID through the environment, so the
    # records a tier prints (and any MetricsSink a tier builds) carry
    # the same ids this orchestrator stamps on the suite summary.
    _bench_run_identity()
    if all_mode:
        # Healthy-TPU tiers are minutes each (persistent compile cache);
        # the budget covers the realistic sum, not the worst-case child
        # timeouts. Each tier's line flushes on completion, so even an
        # external kill mid-run keeps everything captured so far.
        budget_s = float(os.environ.get("GRAPHMINE_BENCH_BUDGET", "5400"))
        fallback_reserve = 1500.0
    else:
        timeout_s = _CHILD_TIMEOUT_S.get(tier, 900.0)
        budget_s = float(
            os.environ.get("GRAPHMINE_BENCH_BUDGET", str(timeout_s + 900.0))
        )
        fallback_reserve = 420.0
    t_start = time.perf_counter()

    def elapsed():
        return time.perf_counter() - t_start

    def remaining(reserve=0.0):
        return budget_s - reserve - elapsed()

    # --- reachability: spaced probes across the window (VERDICT r2 #2) ---
    trace = []

    def probe_and_log():
        ok, platform, info = _probe_tpu()
        trace.append({
            "t": round(elapsed(), 1),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "ok": ok,
            "info": info,
        })
        return ok, platform, info

    probe_interval = max(1.0, float(
        os.environ.get("GRAPHMINE_BENCH_PROBE_INTERVAL", "180")
    ))
    probe_timeout = float(
        os.environ.get("GRAPHMINE_BENCH_PROBE_TIMEOUT", "120")
    )
    probe_window = float(os.environ.get(
        "GRAPHMINE_BENCH_PROBE_WINDOW",
        str(min(1380.0, max(0.0, budget_s - fallback_reserve))),
    ))
    max_probes = max(1, int(probe_window / probe_interval) + 1)

    probe_reasons = []
    ok = False
    platform = None
    tpu_info = None
    if remaining(fallback_reserve) < 60.0:
        probe_reasons.append("probe: skipped, budget exhausted")
    else:
        for n in range(max_probes):
            t_probe = elapsed()
            ok, platform, info = probe_and_log()
            if ok:
                tpu_info = info
                break
            probe_reasons.append(f"probe{n + 1}@{int(t_probe)}s: {info}")
            next_start = t_probe + probe_interval
            if (
                next_start + probe_timeout > probe_window
                or remaining(fallback_reserve) < probe_interval + probe_timeout
            ):
                break
            _sleep(max(0.0, next_start - elapsed()))

    printed_real = 0
    # Ordered (tier, record) pairs — every printed record, real or error —
    # feeding the final suite-summary line (the record the driver parses).
    suite = []

    def finish_suite():
        _print_record(_suite_summary(suite, platform, tpu_info, trace))
        return 0 if printed_real else 1

    def emit_error(t, reasons):
        suite.append((t, _print_error_record(t, reasons)))

    def finish_capture(first, fallback, failures):
        """Capture annotation for one tier's record. Only the FIRST record
        carries the probe trace and probe-phase failures; later tiers
        report their own failures only (clean tiers report none)."""
        cap = {
            "attempts": 0,
            "platform": platform,
            "tpu_probe": tpu_info,
            "cpu_fallback": fallback,
            "failures": (probe_reasons + failures if first else failures)
            or None,
        }
        if first:
            cap["trace"] = trace
        return cap

    # --- healthy-TPU path: every tier, chip first ------------------------
    if ok and platform == "tpu":
        backend_dead = False
        tiers = _TIER_ORDER if all_mode else [tier]
        for i, t in enumerate(tiers):
            first = i == 0
            t_timeout = _CHILD_TIMEOUT_S.get(t, 900.0)
            if backend_dead:
                emit_error(t, ["skipped: backend unreachable mid-capture"])
                continue
            if remaining() < 120.0:
                emit_error(t, ["skipped: budget exhausted"])
                continue
            tier_reasons = []
            record = None
            attempts = 0
            for attempt in (1, 2):
                if attempt == 2:
                    # Re-probe before burning another child timeout: a
                    # tunnel that died mid-capture fails fast here and
                    # marks the remaining tiers skipped instead of each
                    # eating its own timeout.
                    ok2, plat2, info2 = probe_and_log()
                    if not ok2 or plat2 != "tpu":
                        tier_reasons.append(f"reprobe: {info2}")
                        backend_dead = True
                        break
                attempts = attempt
                record, err = _run_child(
                    t, _tier_child_env(t, dict(os.environ)),
                    min(t_timeout, max(remaining(60.0), 60.0)),
                )
                if record is not None:
                    break
                tier_reasons.append(f"run{attempt}: {err}")
            fallback = None
            if record is None and first:
                # Give the suite-summary headline a real chip number via
                # the scrubbed reduced-scale CPU fallback (r2 behavior;
                # the driver parses the LAST line — the summary).
                env = _virtual_cpu_env(1)
                env["GRAPHMINE_BENCH_CPU_FALLBACK"] = "1"
                record, err = _run_child(
                    t, env, min(t_timeout, max(remaining(), 120.0))
                )
                if record is not None:
                    fallback = (
                        "; ".join(probe_reasons + tier_reasons)
                        or "tpu unreachable"
                    )
                else:
                    tier_reasons.append(f"cpu-fallback: {err}")
            if record is None:
                # Even a dead FIRST tier must not abort the suite: the
                # backend is up and later tiers may still capture — the
                # summary headline then falls back to the first real tier.
                emit_error(
                    t,
                    (probe_reasons + tier_reasons if first else tier_reasons)
                    or ["no record"],
                )
                continue
            cap = finish_capture(first, fallback, tier_reasons)
            cap["attempts"] = attempts
            # Cross-backend numerical audit rides the healthy chip capture
            # (a CPU fallback would vacuously compare CPU against itself).
            if (
                t == "chip"
                and fallback is None
                and os.environ.get("GRAPHMINE_BENCH_AUDIT", "1") != "0"
                and remaining() > 330.0
            ):
                cap["backend_audit"] = _run_backend_audit(
                    timeout_s=min(300.0, remaining() - 30.0)
                )
            record.setdefault("detail", {})["capture"] = cap
            _print_record(record)
            suite.append((t, record))
            printed_real += 1
        return finish_suite()

    # --- dead tunnel / CPU-only environment: reduced-scale fallback ------
    if ok and platform != "tpu":
        # No accelerator here: don't run full-scale tiers under the TPU
        # metric names (and don't burn the budget on e.g. a 100M-edge CPU
        # northstar) — go straight to honest reduced-scale records.
        probe_reasons.append(f"probe: default backend is '{platform}', not tpu")
    env = _virtual_cpu_env(1)
    env["GRAPHMINE_BENCH_CPU_FALLBACK"] = "1"
    fb_tiers = _FALLBACK_TIERS if all_mode else [tier]
    fallback_msg = "; ".join(probe_reasons) or "tpu unreachable"
    for i, t in enumerate(fb_tiers):
        first = i == 0
        t_timeout = _CHILD_TIMEOUT_S.get(t, 900.0)
        if not first and remaining() < 180.0:
            emit_error(t, ["skipped: budget exhausted"])
            continue
        record, err = _run_child(
            t, _tier_child_env(t, env),
            min(t_timeout, max(remaining(), 120.0)),
        )
        if record is None:
            # A dead first fallback tier still must not abort the suite:
            # later reduced-scale tiers may succeed on their own.
            emit_error(
                t,
                (probe_reasons + [f"cpu-fallback: {err}"]) if first
                else [f"cpu-fallback: {err}"],
            )
            continue
        record.setdefault("detail", {})["capture"] = finish_capture(
            first, fallback_msg, []
        )
        _print_record(record)
        suite.append((t, record))
        printed_real += 1
    return finish_suite()


def list_missing(strict: bool) -> int:
    """``--list-missing`` (ISSUE 12): print the silicon-capture manifest
    over every committed ``BENCH_*.json`` — which tiers/sub-records still
    exist only as ``*_cpu_fallback`` records (or not at all). This IS the
    "Silicon capture backlog" ROADMAP used to maintain as prose; with
    ``--strict`` a non-empty backlog exits 1 (a healthy-TPU CI window can
    gate on it). Delegates to ``tools/bench_diff.py`` (stdlib-only; no
    jax/backend probe, so this path is safe on any box)."""
    tools_dir = os.path.join(_REPO_DIR, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import bench_diff

    paths = bench_diff.committed_bench_files(_REPO_DIR)
    captures = []
    for p in paths:
        try:
            captures.append(bench_diff.load_bench(p))
        except bench_diff.BenchLoadError:
            continue
    manifest = bench_diff.silicon_manifest(captures)
    print(json.dumps(manifest, indent=2))
    if manifest["pending"]:
        print(
            f"bench: {len(manifest['pending'])} tier(s)/sub-record(s) "
            "pending silicon capture", file=sys.stderr,
        )
        return 1 if strict else 0
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--tier",
        choices=[
            "all", "chip", "roofline", "blocking", "northstar", "sharded",
            "exchange", "cc", "e2e", "lof", "snap", "quality", "weighted",
            "stream", "serve",
        ],
        # No-args (the driver's invocation) = the full evidence suite: one
        # healthy TPU window turns every README performance claim into a
        # driver-captured record (VERDICT r2 item 1).
        default="all",
    )
    ap.add_argument(
        "--list-missing", action="store_true",
        help="print the silicon-capture manifest over committed "
        "BENCH_*.json (tiers/sub-records with only CPU-fallback records) "
        "and exit — no measurement runs",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="with --list-missing: exit 1 when the manifest is non-empty",
    )
    args = ap.parse_args()
    if args.list_missing:
        sys.exit(list_missing(args.strict))
    _TIERS = {
        "chip": main,
        "roofline": main_roofline,
        "blocking": main_blocking,
        "northstar": main_northstar,
        "sharded": main_sharded,
        "exchange": main_exchange,
        "cc": main_cc,
        "e2e": main_e2e,
        "lof": main_lof,
        "snap": main_snap,
        "quality": main_quality,
        "weighted": main_weighted,
        "stream": main_stream,
        "serve": main_serve,
    }
    if os.environ.get("_GRAPHMINE_BENCH_CHILD") == "1":
        fn = _TIERS.get(args.tier)
        if fn is None:
            # A leaked _GRAPHMINE_BENCH_CHILD with the "all" default must
            # still print a parseable line, not die on a KeyError.
            _print_error_record(
                args.tier, [f"tier {args.tier!r} is not a measurement tier"]
            )
            sys.exit(2)
        fn()
    else:
        sys.exit(orchestrate(args.tier))
