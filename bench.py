"""Headline benchmark: LPA edges/sec/chip (BASELINE.json "metric").

Runs synchronous label propagation on a synthetic power-law graph sized for
one chip, times the compiled superstep loop, and prints ONE JSON line.

Baseline derivation (the reference publishes no numbers — BASELINE.md):
the north-star target is "LPA on a 100M-edge graph converges < 60 s on a
TPU v4-8" (8 chips). Reading that conservatively as 5 supersteps (the
reference's maxIter, Graphframes.py:81) in 60 s: 100e6 edges x 5 iters /
(60 s x 8 chips) ≈ 1.04e6 edges/sec/chip. vs_baseline > 1 beats it.

``--tier northstar`` runs the north-star config itself — 100M directed
edges, LPA(maxIter=5) — as a single-device jit and reports seconds for
the five compiled supersteps (host build and first-compile broken out in
``detail``); under 60 is the target BASELINE.json budgets EIGHT v4 chips
for.
"""

import argparse
import json
import os
import time

import numpy as np

BASELINE_EDGES_PER_SEC_PER_CHIP = 100e6 * 5 / (60.0 * 8)

# Default tier, sized for a single chip: ~8.4M directed edges -> 16.8M
# messages. The northstar tier overrides these.
NUM_VERTICES = 1 << 20
NUM_EDGES = 1 << 23
ITERS = 10


def powerlaw_edges(v: int, e: int, seed: int = 0):
    """Preferential-attachment-flavored endpoints: degree skew comparable to
    web graphs (the bundled data's hub pattern, BASELINE.md)."""
    rng = np.random.default_rng(seed)
    # Zipf-ish endpoint draw via inverse-CDF on a pareto tail, clipped.
    raw = rng.pareto(1.2, size=2 * e)
    ids = np.minimum((raw * v / 50).astype(np.int64), v - 1).astype(np.int32)
    perm = rng.permutation(v).astype(np.int32)  # decorrelate id order
    ids = perm[ids]
    return ids[:e], ids[e:]


def _setup_jax_cache():
    """Persistent compile cache (repo-local dir so repeat bench runs pay
    compilation once). Returns the fused-kernel entry points both tiers
    use."""
    from graphmine_tpu.compile_cache import enable_compile_cache

    enable_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    )

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )

    return build_graph_and_plan, lpa_superstep_bucketed


def main_northstar() -> None:
    """North-star config (BASELINE.json): LPA(maxIter=5) over 100M edges.

    Single-device jit on jax.devices()[0] (chips=1 in the output records
    that; the budgeted target hardware is a v4-8). The headline value is
    the five compiled supersteps only — host graph generation/build and
    the one-off first compile are reported separately in ``detail``."""
    import jax
    import jax.numpy as jnp

    build_graph_and_plan, lpa_superstep_bucketed = _setup_jax_cache()

    v, e, iters = 1 << 24, 100_000_000, 5
    t0 = time.perf_counter()
    src, dst = powerlaw_edges(v, e)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    graph, plan = build_graph_and_plan(src, dst, num_vertices=v)
    t_build = time.perf_counter() - t0

    raw_step = jax.jit(lpa_superstep_bucketed)
    labels = jnp.arange(v, dtype=jnp.int32)
    t0 = time.perf_counter()
    labels = raw_step(labels, graph, plan)   # includes compile
    np.asarray(labels[:8])
    t_compile = time.perf_counter() - t0

    labels = jnp.arange(v, dtype=jnp.int32)
    t0 = time.perf_counter()
    for _ in range(iters):
        labels = raw_step(labels, graph, plan)
    np.asarray(labels[:8])
    dt = time.perf_counter() - t0

    chips = 1
    print(
        json.dumps(
            {
                "metric": "lpa_100m_maxiter5_seconds",
                "value": round(dt, 3),
                "unit": "s",
                # target: < 60 s on a v4-8 (8 chips). vs_baseline is the
                # plain 60s-target ratio; "chips" below records that this
                # run used a fraction of the budgeted hardware.
                "vs_baseline": round(60.0 / dt, 3),
                "detail": {
                    "num_vertices": v,
                    "num_edges": e,
                    "iters": iters,
                    "chips": chips,
                    "edges_per_sec_per_chip": round(e * iters / dt / chips),
                    "gen_seconds": round(t_gen, 1),
                    "build_seconds": round(t_build, 1),
                    "first_iter_with_compile_seconds": round(t_compile, 1),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main_lof() -> None:
    """Second driver metric (BASELINE.json): LOF AUROC on held-out
    structural outliers. Full pipeline on device — LPA communities →
    vertex features → kNN/LOF scores — against injected ground truth."""
    import jax

    _setup_jax_cache()

    from graphmine_tpu.datasets import inject_structural_anomalies, rmat
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.features import standardize, vertex_features
    from graphmine_tpu.ops.lof import auroc, lof_scores
    from graphmine_tpu.ops.lpa import label_propagation

    scale, v = 16, 1 << 16
    src, dst = rmat(scale, edge_factor=16, seed=1)
    src, dst, truth = inject_structural_anomalies(
        src, dst, v, num_anomalies=64, edges_per_anomaly=60, seed=2
    )
    g = build_graph(src, dst, num_vertices=v)
    t0 = time.perf_counter()
    labels = label_propagation(g, max_iter=5)
    feats = standardize(vertex_features(g, labels))
    # LOF's k must exceed the size of any clustered anomaly group (64
    # injected hubs with near-identical features), else their kNN
    # neighborhoods are each other and they score as inliers: k=20 gives
    # AUROC ~0.49 here (docs/DESIGN.md); k=128 measured best across seeds
    # with the 8-feature set (0.91-0.93 vs 0.89-0.91 at 6 features/k=100).
    scores = np.asarray(lof_scores(feats, k=128))
    dt = time.perf_counter() - t0
    score = float(auroc(scores, truth))
    print(
        json.dumps(
            {
                "metric": "lof_auroc_injected_outliers",
                "value": round(score, 4),
                "unit": "auroc",
                # baseline: 0.5 = chance; the harness target is > 0.8
                "vs_baseline": round(score / 0.8, 3),
                "detail": {
                    "num_vertices": v,
                    "num_edges": int(len(src)),
                    "num_anomalies": 64,
                    # first run includes jit compiles (persistently cached)
                    "seconds_with_compile": round(dt, 2),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def main() -> None:
    import jax
    import jax.numpy as jnp

    build_graph_and_plan, lpa_superstep_bucketed = _setup_jax_cache()

    src, dst = powerlaw_edges(NUM_VERTICES, NUM_EDGES)
    # Fused degree-bucketed kernel (ops/bucketed_mode.py): ~3x the sort-
    # based superstep at this scale, bit-identical labels (tested). Graph
    # and plan share one host message-CSR build (native counting sort).
    graph, plan = build_graph_and_plan(src, dst, num_vertices=NUM_VERTICES)

    # Compile a single superstep once; the timed loop feeds labels back so
    # every iteration computes on fresh data (steady-state throughput).
    raw_step = jax.jit(lpa_superstep_bucketed)
    step = lambda lbl, g: raw_step(lbl, g, plan)
    labels = jnp.arange(NUM_VERTICES, dtype=jnp.int32)
    labels = step(labels, graph)
    np.asarray(labels[:8])

    # Completion signal: a tiny device->host fetch of a slice that depends
    # on the final labels. On the tunneled axon TPU backend,
    # block_until_ready() was observed returning before the computation
    # finished (33us/iter for a 16M-element sort loop — physically
    # impossible); a data fetch cannot be early. The 32-byte transfer adds
    # negligible time to the window.
    t0 = time.perf_counter()
    for _ in range(ITERS):
        labels = step(labels, graph)
    np.asarray(labels[:8])
    dt = time.perf_counter() - t0

    # The timed loop is a plain jit on one device; normalizing by the full
    # device count would understate the per-chip number on multi-chip hosts.
    chips = 1
    eps_chip = NUM_EDGES * ITERS / dt / chips
    print(
        json.dumps(
            {
                "metric": "lpa_edges_per_sec_per_chip",
                "value": round(eps_chip),
                "unit": "edges/s/chip",
                "vs_baseline": round(eps_chip / BASELINE_EDGES_PER_SEC_PER_CHIP, 3),
                "detail": {
                    "num_vertices": NUM_VERTICES,
                    "num_edges": NUM_EDGES,
                    "iters": ITERS,
                    "seconds": round(dt, 3),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier", choices=["chip", "northstar", "lof"], default="chip")
    args = ap.parse_args()
    {"chip": main, "northstar": main_northstar, "lof": main_lof}[args.tier]()
