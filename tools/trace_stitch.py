#!/usr/bin/env python
"""Stitch a fleet's per-process JSONL shards into cross-process trace
timelines.

The federated metrics plane (ISSUE 11, docs/OBSERVABILITY.md "Fleet
tracing") has every process — router, replicas, writer, standby, chaos
drivers — stream its records to its own shard under one ``--obs-dir``
(``<role>-<pid>.jsonl``). Each record carries trace identity
(``trace_id``/``span_id``/``span_path``), and the fleet propagates one
``traceparent``-style header across every hop, so a single client
request's records are scattered across shards but share one
``trace_id``. This tool is the join:

- **per-delta timelines** — a delta's full life across processes:
  router root span → writer admission verdict → WAL fsync
  (``wal_append``) → apply/publish (``delta_stages`` with the per-stage
  split, ``delta_apply``, ``snapshot_publish``) → each replica's
  reload-to-queryable (``delta_visible``), each line attributed to the
  shard (= process) that emitted it, with a COMPLETE / partial verdict
  per timeline;
- the **failover sequence** — ``fleet_degraded`` → ``writer_promote`` →
  ``publish_fenced`` → ``wal_replay`` in causal order across shards
  (the epoch-fence story RUNBOOKS §10 reads);
- the **rolling-reload walk** — per-replica drain → reload → rejoin
  transitions merged onto one clock.

Validation is a first-class output: every record is checked against the
schema registry (``obs/schema.py``), including the all-or-nothing trace
identity rule — a half-stamped record would silently fall out of the
join, so by default the exit code is **3** when any violation exists
(``--lenient`` downgrades to a warning). CI runs this right after the
fleet chaos e2e as a stamping gate.

Usage::

    python tools/trace_stitch.py OBS_DIR_OR_SHARD [more shards...]
        [--trace TRACE_ID] [--max-traces N] [--lenient] [--out PATH]

Exit codes: 0 clean, 2 unreadable/empty input, 3 schema or
trace-stamping violations (unless ``--lenient``). Stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/trace_stitch.py` anywhere
    sys.path.insert(0, _REPO)

from graphmine_tpu.obs.schema import validate_record  # noqa: E402

# The phases that make a per-delta timeline, in causal order. A timeline
# is COMPLETE when every STAGE below has at least one record (multiple
# phases can witness one stage — e.g. delta_apply and snapshot_publish
# both witness the publish, whichever the coalesced group's leader trace
# carried).
_DELTA_STAGES = (
    ("admission", ("admission",)),
    ("wal_fsync", ("wal_append",)),
    ("apply", ("delta_stages", "delta_apply")),
    ("publish", ("snapshot_publish", "delta_stages")),
    ("replica_visible", ("delta_visible",)),
)
_DELTA_PHASES = frozenset(p for _, ps in _DELTA_STAGES for p in ps)

_FAILOVER_PHASES = ("fleet_degraded", "writer_promote", "publish_fenced",
                    "wal_replay", "ship_lag")


def load_shards(paths) -> tuple[list, int, list]:
    """Read shard files (or whole directories of ``*.jsonl``) into one
    record list, each record tagged with its shard name under ``_src``.
    Torn/unparseable lines are counted, not fatal (a SIGKILLed process's
    final line is exactly the stream this tool reads). Returns
    ``(records, bad_lines, problems)`` where ``problems`` are schema /
    trace-stamping violations."""
    files: list = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(".jsonl")
            )
        else:
            files.append(p)
    records, bad, problems = [], 0, []
    for path in files:
        src = os.path.basename(path)
        if src.endswith(".jsonl"):
            src = src[: -len(".jsonl")]
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError as e:
            problems.append(f"{src}: unreadable shard: {e}")
            continue
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if not isinstance(rec, dict) or "phase" not in rec:
                bad += 1
                continue
            rec["_src"] = src
            records.append(rec)
            for prob in validate_record(
                {k: v for k, v in rec.items() if k != "_src"}
            ):
                problems.append(f"{src}:{i + 1}: {prob}")
    records.sort(key=lambda r: r.get("t", 0.0))
    return records, bad, problems


def stitch(records) -> dict:
    """Group records by ``trace_id`` (records with no trace identity are
    per-process housekeeping and stay out of the join)."""
    traces: dict = {}
    for rec in records:
        tid = rec.get("trace_id")
        if tid is None:
            continue
        traces.setdefault(tid, []).append(rec)
    return traces


def delta_traces(traces: dict) -> dict:
    """The subset of traces that carry a delta's life: trace_id ->
    (records, stage verdicts)."""
    out: dict = {}
    for tid, recs in traces.items():
        phases = {r.get("phase") for r in recs}
        if "run_start" in phases or "run_end" in phases:
            # A process's run-wide root trace: EVERY record of a
            # single-process stream shares it, so classifying it as one
            # "delta timeline" would render the whole stream inline.
            # Per-request traces (router new_trace / adopted remote)
            # never carry the run lifecycle.
            continue
        if not (phases & _DELTA_PHASES):
            continue
        stages = {}
        for stage, witnesses in _DELTA_STAGES:
            stages[stage] = any(p in phases for p in witnesses)
        out[tid] = (recs, stages)
    return out


_DETAIL = {
    "admission": ("verdict", "rows", "queue_depth"),
    "wal_append": ("seq", "rows", "bytes", "seconds"),
    "delta_stages": ("version", "seq", "coalesced", "stages"),
    "delta_apply": ("version", "method", "iterations", "seconds"),
    "snapshot_publish": ("version", "bytes", "seconds"),
    "snapshot_load": ("version", "seconds"),
    "delta_visible": ("replica", "version", "seconds"),
    "access_log": ("method", "endpoint", "status", "seconds"),
    "fleet_route": ("endpoint", "verdict", "attempts", "replica"),
    "query_batch": ("endpoint", "n", "seconds"),
    "delta_shed": ("stage", "reason"),
    "delta_coalesce": ("batches", "rows_in", "rows_out"),
    "fleet_degraded": ("read_only", "writer", "reason"),
    "writer_promote": ("epoch", "replica", "replayed", "copied_tail"),
    "publish_fenced": ("attempted_epoch", "store_epoch"),
    "wal_replay": ("entries", "from_seq", "source"),
    "ship_lag": ("lag_entries", "lag_s"),
    "replica_health": ("replica", "from_state", "to_state", "reason"),
    "profile_capture": ("dir", "ok"),
    "span": ("name", "seconds", "status"),
    "ivf_fallback": ("guard",),
}


def _line(rec, t0) -> str:
    phase = rec.get("phase", "?")
    keys = _DETAIL.get(phase, ())
    detail = "  ".join(
        f"{k}={rec[k]}" for k in keys if k in rec and rec[k] is not None
    )
    return (
        f"  +{rec.get('t', t0) - t0:7.3f}s  [{rec.get('_src', '?'):<18}]"
        f"  {phase:<17}  {detail}"
    )


def render_trace(tid: str, recs, stages: dict | None = None,
                 max_records: int = 60) -> list:
    t0 = min(r.get("t", 0.0) for r in recs)
    out = [f"trace {tid}  ({len(recs)} records, "
           f"{len({r.get('_src') for r in recs})} process(es))"]
    for rec in recs[:max_records]:
        out.append(_line(rec, t0))
    if len(recs) > max_records:
        out.append(
            f"  ... and {len(recs) - max_records} more record(s) in "
            "this trace"
        )
    if stages is not None:
        missing = [s for s, ok in stages.items() if not ok]
        out.append(
            "  verdict: COMPLETE (admission -> wal fsync -> apply -> "
            "publish -> replica visible)" if not missing
            else f"  verdict: partial (missing: {', '.join(missing)})"
        )
    return out


def failover_section(records) -> list:
    events = [r for r in records if r.get("phase") in _FAILOVER_PHASES]
    if not events:
        return []
    t0 = min(r.get("t", 0.0) for r in events)
    out = ["== failover sequence (all shards, one clock) =="]
    for rec in events:
        out.append(_line(rec, t0))
    return out


def rolling_reload_section(records) -> list:
    moves = [
        r for r in records
        if r.get("phase") == "replica_health"
        and ("roll" in str(r.get("reason", "")).lower()
             or r.get("to_state") == "draining")
    ]
    if not moves:
        return []
    t0 = min(r.get("t", 0.0) for r in moves)
    out = ["== rolling reload walk =="]
    for rec in moves:
        out.append(_line(rec, t0))
    return out


def build_report(records, bad: int, problems, max_traces: int = 8,
                 only_trace: str | None = None) -> str:
    traces = stitch(records)
    deltas = delta_traces(traces)
    shards = sorted({r.get("_src", "?") for r in records})
    lines = ["== graphmine_tpu fleet trace stitch =="]
    lines.append(
        f"shards: {len(shards)} ({', '.join(shards)})  records: "
        f"{len(records)}  traces: {len(traces)}  delta traces: "
        f"{len(deltas)}"
    )
    if bad:
        lines.append(f"note: {bad} unparseable line(s) skipped")
    if problems:
        lines.append(
            f"VIOLATIONS: {len(problems)} schema/trace-stamping "
            "problem(s):"
        )
        lines.extend(f"  {p}" for p in problems[:40])
        if len(problems) > 40:
            lines.append(f"  ... and {len(problems) - 40} more")
    if only_trace is not None:
        recs = traces.get(only_trace)
        if recs is None:
            lines.append(f"trace {only_trace!r} not found")
        else:
            stages = deltas.get(only_trace, (None, None))[1]
            lines.append("")
            lines.extend(render_trace(only_trace, recs, stages))
        return "\n".join(lines) + "\n"
    complete = sorted(
        (tid for tid, (_, st) in deltas.items() if all(st.values())),
    )
    if deltas:
        lines.append(
            f"complete per-delta timelines: {len(complete)}/{len(deltas)}"
        )
        lines.append("")
        lines.append("== per-delta timelines ==")
        # complete timelines first — they are the ones worth reading
        ordered = complete + [t for t in deltas if t not in set(complete)]
        for tid in ordered[:max_traces]:
            recs, stages = deltas[tid]
            lines.extend(render_trace(tid, recs, stages))
            lines.append("")
        if len(deltas) > max_traces:
            lines.append(
                f"({len(deltas) - max_traces} more delta trace(s); "
                "--max-traces or --trace ID to see them)"
            )
    failover = failover_section(records)
    if failover:
        lines.append("")
        lines.extend(failover)
    roll = rolling_reload_section(records)
    if roll:
        lines.append("")
        lines.extend(roll)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("shards", nargs="+",
                    help="shard files and/or --obs-dir directories")
    ap.add_argument("--trace", default=None,
                    help="render only this trace_id")
    ap.add_argument("--max-traces", type=int, default=8,
                    help="delta timelines to render (default 8)")
    ap.add_argument("--lenient", action="store_true",
                    help="report schema/stamping violations but exit 0")
    ap.add_argument("--out", default=None,
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)
    records, bad, problems = load_shards(args.shards)
    if not records:
        print(
            f"trace_stitch: no records in {', '.join(args.shards)}",
            file=sys.stderr,
        )
        return 2
    report = build_report(
        records, bad, problems, max_traces=args.max_traces,
        only_trace=args.trace,
    )
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
    else:
        sys.stdout.write(report)
    if problems and not args.lenient:
        print(
            f"trace_stitch: {len(problems)} schema/trace-stamping "
            "violation(s) — failing (use --lenient to downgrade)",
            file=sys.stderr,
        )
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
