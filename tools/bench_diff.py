#!/usr/bin/env python
"""Bench trajectory tooling: diff, regression gate, silicon manifest.

The repo's perf record is a pile of ``BENCH_*.json`` driver artifacts
read by humans (ISSUE 12): nothing compares two captures, renders the
multi-round trajectory, or tracks which tiers still lack a silicon
capture (ROADMAP carried that backlog as prose). This tool closes all
three gaps, **stdlib-only** (no jax, no numpy — runnable on any box
holding the artifacts):

- **Trajectory table**: every tier's headline metric across every given
  capture, CPU-fallback and error records marked as such — the perf
  record as one table instead of N files.
- **Regression gate**: the two newest captures (by the artifact's ``n``
  round number) compared metric-by-metric with per-tier noise
  tolerances (:data:`TIER_TOLERANCE`; direction-aware — seconds regress
  UP, throughput regresses DOWN). Exit 1 names every metric past
  tolerance, so CI can gate on a fresh ``bench.py`` run vs the newest
  committed file.
- **Silicon-capture manifest** (``--manifest``; also behind
  ``bench.py --list-missing``): which tiers/sub-records exist ONLY as
  ``*_cpu_fallback`` records (or not at all) across the whole
  trajectory — the machine-readable replacement for ROADMAP's
  hand-maintained "Silicon capture backlog" list.
- **Crossover suggestion**: when a real (non-fallback) ``blocking``
  capture lands, its ``detail.binned_vs_random_gather`` ratio is
  compared against the VMEM-capacity-model constants in
  ``ops/blocking.py`` (parsed from source — this tool must not import
  jax) and a concrete ``BLOCKED_MIN_*`` update is suggested, closing
  the loop ROADMAP names.

Inputs: ``BENCH_*.json`` driver artifacts (``{n, cmd, rc, tail,
parsed}`` — ``tail`` holds the stdout tail's JSON record lines,
``parsed`` the final suite-summary record) or a fresh ``bench.py``
stdout capture (plain JSON-lines). With no file arguments, every
``BENCH_*.json`` next to the repo's ``bench.py`` is loaded; a single
file argument is gated against the newest committed artifact.

Usage::

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py                    # full committed trajectory
    python tools/bench_diff.py fresh_run.jsonl    # fresh vs newest committed
    python tools/bench_diff.py --manifest         # + pending-capture manifest

Exit codes: 0 clean, 1 regression past tolerance (or non-empty manifest
under ``--strict``), 2 usage/load error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The tier universe — mirrors bench.py's _TIER_ORDER (pinned equal by
# tests/test_costmodel.py so the two can never drift; bench.py imports
# numpy at module load, which this stdlib-only tool must not).
ALL_TIERS = (
    "chip", "roofline", "blocking", "northstar", "sharded", "exchange",
    "cc", "e2e", "lof", "snap", "quality", "weighted", "stream", "serve",
)

# Detail sub-records the manifest tracks per tier: each ships inside its
# tier's record `detail` and counts as silicon-captured only when seen in
# a NON-fallback record (the ROADMAP backlog named exactly these).
SUB_RECORDS = {
    "blocking": ("binned_vs_random_gather",),
    # the neighbor-exchange vs all_gather WALL ratio needs a real
    # multi-chip ICI window (the committed records are virtual-mesh CPU
    # fallbacks whose modeled bytes are exact but whose seconds are not)
    "exchange": ("neighbor_vs_allgather",),
    "stream": ("ivf_reuse",),
    "serve": ("write_load", "replicated_read", "writer_failover",
              "latency_quantiles", "quality_pass", "multi_tenant",
              "sharded_write", "memory"),
    # the per-tier memory sub-record (ISSUE 14: model + measured child
    # peak RSS) is tracked on the headline tier; every tier carries it,
    # but one manifest row is the signal "this round recorded memory"
    "chip": ("memory",),
}

# metric-name prefix -> tier, for records read from a tail where no
# suite summary maps them (a fresh bench stdout mid-run, old artifacts).
_METRIC_TIER_PREFIXES = (
    ("lpa_100m", "northstar"),
    ("lpa_", "chip"),
    ("roofline_", "roofline"),
    ("blocking_", "blocking"),
    ("sharded_lpa", "sharded"),
    ("exchange_", "exchange"),
    ("cc_", "cc"),
    ("e2e_", "e2e"),
    ("lof_", "lof"),
    ("snap_", "snap"),
    ("community_quality", "quality"),
    ("weighted_lpa", "weighted"),
    ("streaming_lof", "stream"),
    ("serve_", "serve"),
    ("bench_", None),  # bench_<tier>_capture_failed error records
)

# Per-tier noise tolerance (fraction of the older value). Defaults to
# DEFAULT_TOLERANCE; overrides document WHY they are looser:
DEFAULT_TOLERANCE = 0.10
TIER_TOLERANCE = {
    # best-ARI over few seeds is seed-noisy at toy scale: the committed
    # r04→r05 silicon pair itself swings 1.0 → 0.827 (-17%).
    "quality": 0.30,
    # whole-pipeline wall time: host phases (wedge probe, parquet IO)
    # add machine-load jitter beyond the kernel noise band.
    "e2e": 0.15,
    # window-chunked streaming scorer: chunk boundaries beat against the
    # window size.
    "stream": 0.15,
    # qps through a live HTTP stack: scheduler noise.
    "serve": 0.25,
}

# Units where DOWN is an improvement (everything else: up is better).
# "frac" is the exchange tier's neighbor/all_gather bytes fraction —
# fewer bytes on the wire is the whole point of the 2D family.
LOWER_BETTER_UNITS = frozenset(("s", "seconds", "ms", "us", "frac"))

# Per-tier memory sub-record gate (ISSUE 14): peak bytes regress UP.
# Child RSS is noisier than kernel rates (allocator arenas, import
# order), hence the looser default; override with --tolerance memory=F.
MEMORY_TOLERANCE = 0.25


class BenchLoadError(Exception):
    pass


def _tier_of_metric(metric: str):
    if not isinstance(metric, str):
        return None
    for prefix, tier in _METRIC_TIER_PREFIXES:
        if metric.startswith(prefix):
            if tier is None:  # bench_<tier>_capture_failed
                m = re.match(r"bench_(\w+)_capture_failed", metric)
                return m.group(1) if m and m.group(1) in ALL_TIERS else None
            return tier
    return None


def _records_from_lines(text: str) -> list:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def _is_fallback(rec: dict) -> bool:
    metric = rec.get("metric", "")
    if isinstance(metric, str) and metric.endswith("_cpu_fallback"):
        return True
    cap = (rec.get("detail") or {}).get("capture") or {}
    return bool(cap.get("cpu_fallback"))


def load_bench(path: str) -> dict:
    """One capture, normalized: ``{label, n, tiers, records}`` where
    ``tiers[tier] = {"metric", "value", "unit", "vs", "err"?,
    "cpu_fallback"}``. Accepts a driver artifact (``{n, tail, parsed}``)
    or a raw bench.py stdout / JSONL capture."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise BenchLoadError(f"cannot read {path}: {e}") from e
    label = os.path.basename(path)
    m = re.search(r"BENCH_r?0*(\d+)", label)
    n = None
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict) and "tail" in obj:
        records = _records_from_lines(obj.get("tail") or "")
        parsed = obj.get("parsed")
        n = obj.get("n", int(m.group(1)) if m else None)
    else:
        # raw stdout / JSONL: every line is its own record; the suite
        # summary (if the run finished) is the last record with "suite"
        records = _records_from_lines(text)
        parsed = next(
            (r for r in reversed(records) if "suite" in r), None
        )
        n = int(m.group(1)) if m else None
    if not records and not (
        isinstance(parsed, dict) and isinstance(parsed.get("suite"), dict)
    ):
        raise BenchLoadError(
            f"{path}: no bench records found (not a BENCH_*.json artifact "
            "or a bench.py stdout capture, or the capture failed before "
            "any tier record — see the artifact's rc/tail)"
        )

    tiers: dict = {}
    # 1) the suite summary knows every tier, including ones whose full
    # records scrolled out of the artifact's bounded stdout tail
    if isinstance(parsed, dict):
        for tier, entry in (
            (parsed.get("suite") or {}).get("tiers") or {}
        ).items():
            if "err" in entry:
                tiers[tier] = {"err": entry["err"]}
                continue
            metric = entry.get("m")
            tiers[tier] = {
                "metric": metric,
                "value": entry.get("v"),
                "unit": entry.get("u"),
                "vs": entry.get("vs"),
                "cpu_fallback": bool(
                    isinstance(metric, str)
                    and metric.endswith("_cpu_fallback")
                ),
            }
    # 2) overlay full tail records (carry detail; fallback flag is
    # authoritative there via detail.capture)
    for rec in records:
        if "suite" in rec:
            continue
        metric = rec.get("metric", "")
        tier = _tier_of_metric(metric)
        if tier is None:
            continue
        if "error" in rec:
            tiers.setdefault(tier, {"err": str(rec["error"])[:120]})
            continue
        tiers[tier] = {
            "metric": metric,
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "vs": rec.get("vs_baseline"),
            "cpu_fallback": _is_fallback(rec),
            "detail": rec.get("detail") or {},
        }
    return {"label": label, "path": path, "n": n, "tiers": tiers,
            "records": records}


# ---- trajectory table ------------------------------------------------------


def _fmt_value(entry) -> str:
    if entry is None:
        return "-"
    if "err" in entry:
        return "ERR"
    v, unit = entry.get("value"), entry.get("unit") or ""
    if v is None:
        return "?"
    if isinstance(v, (int, float)) and abs(v) >= 1e6:
        s = f"{v / 1e6:.1f}M"
    elif isinstance(v, (int, float)) and abs(v) >= 1e4:
        s = f"{v / 1e3:.0f}K"
    elif isinstance(v, float):
        s = f"{v:.3g}"
    else:
        s = str(v)
    if unit and unit not in ("error",):
        s += {"edges/s/chip": "", "slots/s": "", "points/s/chip": ""}.get(
            unit, unit if unit == "s" else f" {unit}"
        )
    if entry.get("cpu_fallback"):
        s += "*"
    return s


def trajectory_table(captures: list) -> list:
    """The full multi-capture table, one row per tier (``*`` marks a
    CPU-fallback value, ``ERR`` a failed capture, ``-`` a tier that did
    not exist that round)."""
    cols = [c["label"].replace("BENCH_", "").replace(".json", "")
            for c in captures]
    seen = [t for t in ALL_TIERS
            if any(t in c["tiers"] for c in captures)]
    width = max([len(t) for t in seen] + [6])
    cw = [max(len(col), 10) for col in cols]
    lines = [
        "  " + " " * width + "  "
        + "  ".join(col.rjust(w) for col, w in zip(cols, cw))
    ]
    for tier in seen:
        cells = [
            _fmt_value(c["tiers"].get(tier)).rjust(w)
            for c, w in zip(captures, cw)
        ]
        lines.append(f"  {tier:<{width}}  " + "  ".join(cells))
    lines.append("  (* = CPU-fallback record, not a silicon number)")
    return lines


# ---- regression gate -------------------------------------------------------


def diff_captures(old: dict, new: dict, tolerances: dict | None = None):
    """Metric-by-metric comparison -> (rows, regressions). Each row is a
    human line; ``regressions`` lists the offending metric names (the
    exit-1 payload). Capture-status changes (silicon → fallback/error)
    are reported but gate only under --strict-capture (callers append
    them from the returned ``capture_changes``)."""
    tol_map = dict(TIER_TOLERANCE)
    tol_map.update(tolerances or {})
    rows, regressions, capture_changes = [], [], []
    for tier in ALL_TIERS:
        o, nw = old["tiers"].get(tier), new["tiers"].get(tier)
        if o is None and nw is None:
            continue
        if o is None:
            rows.append(f"  {tier:<10} NEW       {_fmt_value(nw)}")
            continue
        if nw is None:
            capture_changes.append(
                f"{tier}: present in {old['label']} but missing in "
                f"{new['label']}"
            )
            rows.append(f"  {tier:<10} GONE      (was {_fmt_value(o)})")
            continue
        o_err, n_err = "err" in o, "err" in nw
        if o_err and n_err:
            rows.append(f"  {tier:<10} ERR->ERR")
            continue
        if n_err:
            capture_changes.append(
                f"{tier}: captured in {old['label']} but ERR in "
                f"{new['label']} ({nw['err']})"
            )
            rows.append(f"  {tier:<10} CAPTURE   ok -> ERR")
            continue
        if o_err:
            rows.append(f"  {tier:<10} FIXED     ERR -> {_fmt_value(nw)}")
            continue
        if bool(o.get("cpu_fallback")) != bool(nw.get("cpu_fallback")):
            direction = (
                "cpu_fallback -> silicon" if o.get("cpu_fallback")
                else "silicon -> cpu_fallback"
            )
            if not o.get("cpu_fallback"):
                capture_changes.append(
                    f"{tier}: {direction} — values not comparable"
                )
            rows.append(
                f"  {tier:<10} CAPTURE   {direction} (values not compared)"
            )
            continue
        ov, nv = o.get("value"), nw.get("value")
        if not isinstance(ov, (int, float)) or not isinstance(
            nv, (int, float)
        ) or ov == 0:
            rows.append(f"  {tier:<10} ?         {ov} -> {nv}")
            # the memory gate is independent of headline-value validity:
            # a tier with a broken headline can still regress its bytes
            _memory_gate(tier, o, nw, tol_map, rows, regressions)
            continue
        unit = nw.get("unit") or o.get("unit") or ""
        lower_better = unit in LOWER_BETTER_UNITS
        delta = (nv - ov) / abs(ov)
        tol = tol_map.get(tier, DEFAULT_TOLERANCE)
        worse = delta > tol if lower_better else delta < -tol
        verdict = "REGRESSED" if worse else (
            "improved" if (delta < 0) == lower_better and delta != 0
            else "ok"
        )
        rows.append(
            f"  {tier:<10} {verdict:<9} {_fmt_value(o)} -> {_fmt_value(nw)}"
            f"  ({delta:+.1%}, tol ±{tol:.0%}{', lower=better' if lower_better else ''})"
        )
        if worse:
            regressions.append(
                f"{nw.get('metric', tier)}: {ov} -> {nv} ({delta:+.1%} "
                f"past the ±{tol:.0%} {tier} tolerance)"
            )
        _memory_gate(tier, o, nw, tol_map, rows, regressions)
    return rows, regressions, capture_changes


def _memory_gate(tier, o, nw, tol_map, rows, regressions) -> None:
    """Memory sub-record gate (ISSUE 14): per-tier measured peak bytes
    regress UP. Upper-bound samples (the child did not raise the
    cumulative rusage max — another child's peak, not this tier's) are
    not comparable and never gate. Runs for every tier whose BOTH
    captures carry a comparable sample, independently of the headline
    value's validity (callers skip it only where values are cross-
    platform incomparable: err records, fallback-status mismatches)."""
    om = (o.get("detail") or {}).get("memory") or {}
    nm = (nw.get("detail") or {}).get("memory") or {}
    opk, npk = om.get("peak_rss_bytes"), nm.get("peak_rss_bytes")
    if not (
        isinstance(opk, (int, float)) and isinstance(npk, (int, float))
        and opk > 0
        and not om.get("upper_bound") and not nm.get("upper_bound")
    ):
        return
    mdelta = (npk - opk) / opk
    mtol = tol_map.get("memory", MEMORY_TOLERANCE)
    mworse = mdelta > mtol
    verdict = "MEM-REGRESS" if mworse else "mem-ok"
    rows.append(
        f"  {tier:<10} {verdict:<9} peak "
        f"{opk / (1 << 20):,.0f}MiB -> {npk / (1 << 20):,.0f}MiB"
        f"  ({mdelta:+.1%}, tol ±{mtol:.0%}, lower=better)"
    )
    if mworse:
        regressions.append(
            f"{tier}.memory.peak_rss_bytes: {opk} -> {npk} "
            f"({mdelta:+.1%} past the ±{mtol:.0%} memory "
            "tolerance — bytes regress UP)"
        )


# ---- silicon-capture manifest ---------------------------------------------


def silicon_manifest(captures: list) -> dict:
    """Machine-readable capture status across the whole trajectory — the
    ROADMAP "Silicon capture backlog" replacement. A tier (or tracked
    sub-record) is ``silicon`` once ANY capture holds a real record for
    it; ``cpu_fallback`` when only fallback records exist; ``missing``
    when it predates every given capture. ``pending`` lists everything
    not yet silicon — the work list for the next healthy-TPU window."""
    status = {t: "missing" for t in ALL_TIERS}
    subs = {
        f"{t}.{s}": "missing" for t, names in SUB_RECORDS.items()
        for s in names
    }
    for cap in captures:
        for tier, entry in cap["tiers"].items():
            if tier not in status or "err" in entry:
                continue
            if entry.get("cpu_fallback"):
                if status[tier] == "missing":
                    status[tier] = "cpu_fallback"
            else:
                status[tier] = "silicon"
            detail = entry.get("detail") or {}
            for s in SUB_RECORDS.get(tier, ()):
                if s in detail:
                    key = f"{tier}.{s}"
                    if entry.get("cpu_fallback"):
                        if subs[key] == "missing":
                            subs[key] = "cpu_fallback"
                    else:
                        subs[key] = "silicon"
    pending = sorted(
        [t for t, st in status.items() if st != "silicon"]
        + [k for k, st in subs.items() if st != "silicon"]
    )
    return {
        "captures": [c["label"] for c in captures],
        "tiers": status,
        "sub_records": subs,
        "pending": pending,
        "hint": (
            "one healthy-TPU window: `python bench.py` (tier all) refreshes "
            "BENCH_*.json + bench_logs/; see ROADMAP.md 'Silicon capture "
            "backlog'"
        ),
    }


# ---- crossover suggestion --------------------------------------------------


def _current_blocked_constants() -> dict:
    """BLOCKED_MIN_* parsed from ops/blocking.py SOURCE (this tool is
    stdlib-only and must not import the jax-loading ops layer)."""
    path = os.path.join(_REPO, "graphmine_tpu", "ops", "blocking.py")
    out = {}
    try:
        with open(path) as f:
            src = f.read()
        for name in ("BLOCKED_MIN_MESSAGES", "BLOCKED_MIN_VERTICES"):
            m = re.search(rf"^{name}\s*=\s*(.+)$", src, re.M)
            if m:
                out[name] = int(eval(m.group(1), {"__builtins__": {}}))  # noqa: S307 — literal like `1 << 22` from our own source
    except OSError:
        pass
    return out


def crossover_suggestion(captures: list) -> list:
    """When a real (non-fallback) ``blocking`` capture carries
    ``detail.binned_vs_random_gather``, suggest what the measured ratio
    means for the ``BLOCKED_MIN_*`` crossover constants (which today
    encode a VMEM capacity model, not a measurement — ROADMAP names this
    exact loop). Empty list until that capture lands."""
    best = None
    for cap in reversed(captures):  # newest capture wins
        entry = cap["tiers"].get("blocking")
        if not entry or "err" in entry or entry.get("cpu_fallback"):
            continue
        ratio = (entry.get("detail") or {}).get("binned_vs_random_gather")
        if isinstance(ratio, (int, float)):
            best = (cap["label"], float(ratio))
            break
    if best is None:
        return []
    label, ratio = best
    consts = _current_blocked_constants()
    cur = ", ".join(f"{k}={v:,}" for k, v in consts.items()) or "(unparsed)"
    lines = [
        f"  silicon blocking capture in {label}: "
        f"binned_vs_random_gather = {ratio:.2f}x",
        f"  current crossover constants (ops/blocking.py): {cur}",
    ]
    if ratio >= 1.05:
        lines.append(
            "  suggestion: the binned pass BEATS the random gather on "
            "silicon — lower BLOCKED_MIN_VERTICES/BLOCKED_MIN_MESSAGES "
            "(or set GRAPHMINE_BLOCKED_MIN_* to deploy first) so the "
            "blocked family engages below the VMEM-model wall; re-run "
            "the blocking tier at the candidate sizes to place the new "
            "crossover"
        )
    elif ratio <= 0.95:
        lines.append(
            "  suggestion: the binned pass LOSES to the random gather at "
            "the measured size — raise BLOCKED_MIN_* (the VMEM model was "
            "optimistic) and re-measure at larger V before deploying "
            "blocked by default"
        )
    else:
        lines.append(
            "  suggestion: measured ratio is within noise of 1.0 — keep "
            "the VMEM-model constants; the crossover decision needs a "
            "larger-V capture"
        )
    return lines


# ---- CLI -------------------------------------------------------------------


def committed_bench_files(repo_dir: str = _REPO) -> list:
    return sorted(glob.glob(os.path.join(repo_dir, "BENCH_*.json")))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*", help="BENCH_*.json artifacts or "
                    "fresh bench.py stdout captures (default: every "
                    "committed BENCH_*.json; one file gates against the "
                    "newest committed)")
    ap.add_argument("--manifest", action="store_true",
                    help="also print the silicon-capture manifest (JSON)")
    ap.add_argument("--strict", action="store_true",
                    help="with --manifest: exit 1 when pending is non-empty")
    ap.add_argument("--no-gate", action="store_true",
                    help="trajectory table only; skip the regression gate")
    ap.add_argument("--strict-capture", action="store_true",
                    help="capture downgrades (silicon -> cpu_fallback/ERR/"
                    "gone) gate like value regressions")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="TIER=FRAC",
                    help="override a tier's noise tolerance, e.g. chip=0.05")
    args = ap.parse_args(argv)

    tolerances = {}
    for spec in args.tolerance:
        tier, _, frac = spec.partition("=")
        try:
            tolerances[tier] = float(frac)
        except ValueError:
            print(f"bench_diff: bad --tolerance {spec!r}", file=sys.stderr)
            return 2

    paths = list(args.files)
    gate_path = None  # single-file mode: this file MUST be the gate's new side
    if not paths:
        paths = committed_bench_files()
    elif len(paths) == 1:
        gate_path = os.path.abspath(paths[0])
        committed = [
            p for p in committed_bench_files()
            if os.path.abspath(p) != gate_path
        ]
        paths = committed + paths  # the lone file is the newest capture
    if not paths:
        print("bench_diff: no BENCH_*.json files found", file=sys.stderr)
        return 2

    captures = []
    for p in paths:
        try:
            captures.append(load_bench(p))
        except BenchLoadError as e:
            # A capture round that produced NO records (BENCH_r01: dead
            # tunnel, rc=1) is part of the trajectory's history, not a
            # tooling error — keep an empty column for it, in round
            # order (the filename still knows its n).
            print(f"bench_diff: note: {e}", file=sys.stderr)
            label = os.path.basename(p)
            m = re.search(r"BENCH_r?0*(\d+)", label)
            captures.append({
                "label": label, "path": p,
                "n": int(m.group(1)) if m else None,
                "tiers": {}, "records": [],
            })
    if not captures:
        return 2
    # stable order: round number when known; a fresh capture without one
    # sorts last (= the newest side of the gate). In single-file mode
    # the named file is PINNED last regardless of its parsed round
    # number — the user asked to gate THAT capture, and a re-run of an
    # old round (BENCH_r03 re-captured) must not silently fall out of
    # the comparison.
    captures.sort(
        key=lambda c: (1 << 30) if c["n"] is None else int(c["n"])
    )
    if gate_path is not None:
        pinned = [
            c for c in captures if os.path.abspath(c["path"]) == gate_path
        ]
        captures = [
            c for c in captures if os.path.abspath(c["path"]) != gate_path
        ] + pinned

    print("== bench trajectory ==")
    for line in trajectory_table(captures):
        print(line)

    rc = 0
    gated = [c for c in captures if c["tiers"]]
    if not args.no_gate and len(gated) >= 2:
        old, new = gated[-2], gated[-1]
        print(f"\n== regression gate: {old['label']} -> {new['label']} ==")
        rows, regressions, capture_changes = diff_captures(
            old, new, tolerances
        )
        for r in rows:
            print(r)
        if capture_changes:
            print("  capture changes:")
            for c in capture_changes:
                print(f"    {c}")
        if regressions or (args.strict_capture and capture_changes):
            print(
                f"\nbench_diff: {len(regressions) + (len(capture_changes) if args.strict_capture else 0)} "
                "regression(s) past tolerance:", file=sys.stderr,
            )
            for r in regressions:
                print(f"  REGRESSION {r}", file=sys.stderr)
            if args.strict_capture:
                for c in capture_changes:
                    print(f"  CAPTURE    {c}", file=sys.stderr)
            rc = 1
        else:
            print("  gate: clean (no regression past tolerance)")

    suggestion = crossover_suggestion(captures)
    if suggestion:
        print("\n== blocked-crossover suggestion ==")
        for line in suggestion:
            print(line)

    if args.manifest:
        manifest = silicon_manifest(captures)
        print("\n== silicon-capture manifest ==")
        print(json.dumps(manifest, indent=2))
        if args.strict and manifest["pending"]:
            print(
                f"bench_diff: --strict: {len(manifest['pending'])} "
                "tier(s)/sub-record(s) still pending silicon capture",
                file=sys.stderr,
            )
            rc = max(rc, 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
