"""Portable GraphFrames crosscheck (VERDICT r2 item 6).

Closes the north-star clause "matching GraphFrames community IDs on
bundled data" (BASELINE.json; call site ``Graphframes.py:78-81``) in ANY
environment that has the reference's stack installed:

    pip install pyspark graphframes   # (or the reference README's pins)
    python tools/spark_crosscheck.py

What it does:
  1. loads the bundled parquet (or ``--data`` / an edge list),
  2. runs the REAL JVM ``GraphFrame.labelPropagation`` through the
     pipeline's plugin boundary (``pipeline/backends.py:lpa_graphframes``
     — this is the path that has never executed in the no-JVM sandbox),
  3. runs this engine's LPA and the GraphX-structure oracle,
  4. compares canonical partitions (``ops/lpa.py:canonicalize`` — SURVEY
     §6: validate partitions, not raw label values).

Pass criterion: exact canonical-partition agreement, OR agreement within
the measured tie-sensitivity envelope — GraphX's own tie-break is
machine-dependent (``oracle.py`` module docstring), so the oracle's
smallest-vs-largest tie extremes bound how far two legitimate runs of the
*reference stack itself* can diverge; the JVM-vs-engine ARI must be >=
that envelope's ARI.

Exit codes: 0 = agree (within envelope), 1 = disagreement beyond the tie
envelope, 2 = config error (an explicitly passed --data path is absent),
3 = skipped (pyspark/graphframes not installed, or the DEFAULT data path
is absent — both CI-skippable).

Prints one JSON line either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_DEFAULT_DATA = "/root/reference/CommunityDetection/data/outlinks_pq"


def evaluate_crosscheck(jvm_labels, eng_canonical, src, dst, num_vertices,
                        max_iter):
    """The pass criterion, factored out so it is TESTABLE without a JVM
    (VERDICT r3 item 8): exact canonical-partition agreement, OR
    JVM-vs-engine ARI >= the oracle's smallest-vs-largest tie-extreme ARI
    (the envelope two legitimate runs of the reference stack itself can
    span — GraphX's tie-break is machine-dependent, ``oracle.py``).

    Validated in CI both ways (``tests/test_pipeline.py``): the oracle
    under a seeded random-among-modes tie rule — a stand-in for any
    legitimate JVM tie order — must be accepted across seeds, and a
    label-shuffled broken engine must be rejected.

    Returns ``(ok, result-fields dict)``.
    """
    from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index
    from graphmine_tpu.oracle import canonical_partition, graphx_label_propagation

    lo = graphx_label_propagation(
        src, dst, num_vertices, max_iter, tie="smallest"
    )
    hi = graphx_label_propagation(
        src, dst, num_vertices, max_iter, tie="largest"
    )
    envelope_ari = float(adjusted_rand_index(
        canonical_partition(lo), canonical_partition(hi)
    ))

    jvm_canon = canonical_partition(jvm_labels)
    exact = bool(np.array_equal(jvm_canon, eng_canonical))
    ari = float(adjusted_rand_index(jvm_canon, eng_canonical))
    ok = exact or ari >= envelope_ari
    return ok, {
        "exact_canonical_match": exact,
        "ari_jvm_vs_engine": round(ari, 6),
        "tie_envelope_ari": round(envelope_ari, 6),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=_DEFAULT_DATA,
                    help="parquet dir/glob or .txt edge list")
    ap.add_argument("--max-iter", type=int, default=5)
    args = ap.parse_args()

    try:
        import pyspark  # noqa: F401
        from graphframes import GraphFrame  # noqa: F401
    except ImportError:
        print(json.dumps({
            "crosscheck": "skipped",
            "reason": "pyspark/graphframes not installed "
                      "(pip install pyspark graphframes)",
        }))
        return 3

    if not os.path.exists(args.data):
        # The default points at the reference checkout's bundled parquet;
        # in another environment, pass --data <parquet dir or .txt edge
        # list>. A missing DEFAULT is a clean skip (same CI semantics as
        # no-JVM); an explicitly passed path that is absent is an error.
        explicit = args.data != _DEFAULT_DATA
        print(json.dumps({
            "crosscheck": "skipped" if not explicit else "error",
            "reason": f"data not found at {args.data!r}"
                      + ("" if explicit else
                         " — pass --data <bundled outlinks parquet or"
                         " edge list>"),
        }))
        return 2 if explicit else 3

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.io.edges import load_edge_list, load_parquet_edges
    from graphmine_tpu.ops.lpa import canonicalize, label_propagation
    from graphmine_tpu.pipeline.backends import lpa_graphframes

    if args.data.endswith(".txt"):
        et = load_edge_list(args.data)
    else:
        et = load_parquet_edges(args.data)

    # 1. the real JVM engine, through the plugin boundary
    jvm_labels = lpa_graphframes(et, args.max_iter)

    # 2. this engine
    g = build_graph(et.src, et.dst, num_vertices=et.num_vertices)
    eng_labels = np.asarray(
        canonicalize(label_propagation(g, max_iter=args.max_iter))
    )

    # 3. the CI-validated pass criterion (tie-sensitivity envelope)
    ok, fields = evaluate_crosscheck(
        jvm_labels, eng_labels, et.src, et.dst, et.num_vertices,
        args.max_iter,
    )

    print(json.dumps({
        "crosscheck": "agree" if ok else "DISAGREE",
        **fields,
        "jvm_communities": int(len(np.unique(jvm_labels))),
        "engine_communities": int(len(np.unique(eng_labels))),
        "vertices": et.num_vertices,
        "edges": et.num_edges,
        "max_iter": args.max_iter,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
