#!/usr/bin/env python
"""Offline triage: join a metrics JSONL into a human report.

The metrics stream (``--metrics-out``) is an append-mode JSONL whose
records carry run/trace/span identity (docs/OBSERVABILITY.md). This tool
reconstructs, **from the JSONL alone** (no repo state, no checkpoint
dir):

- the run header: run_id, start time, wall clock, and the liveness
  verdict — ``ok`` / ``error`` from the ``run_end`` record, or, when the
  stream just *ends*, ``HUNG`` (heartbeats outlived the last phase
  record) vs ``DEAD`` (everything stopped together);
- the **phase waterfall** from ``span`` records (offset + duration bars);
- the **per-superstep throughput table** (``lpa_iter``: labels changed,
  seconds, edges/sec/chip with a trend bar);
- **superstep telemetry**: frontier size and per-shard load-imbalance
  ratios at the tripwire/checkpoint cadence;
- the **roofline** section (ISSUE 12): achieved-vs-cost-model throughput
  per ``superstep_timing`` window, with an achieved-fraction column and
  loud flags on windows below ``--roofline-min-frac`` of model — the
  triage step RUNBOOKS §12 offers before "blame the device";
- the **memory** section (ISSUE 14): the per-phase predicted-vs-peak
  waterfall from ``memory_watermark`` records, flagged under-estimates,
  a recalibration suggestion for the ``obs/memmodel.py`` byte seeds,
  and every memory-attributed degrade (plan-time pre-degrades, reactive
  OOMs with their last watermark) — RUNBOOKS §14's "read the waterfall
  before shrinking the graph" view;
- the **recovery timeline**: every retry / degrade / mesh_degrade /
  tripwire / watchdog_timeout / checkpoint rollback / resume, in causal
  order, each with its span path — *which* incident hit *which* phase on
  *which* mesh rung;
- the **serving SLO** section: per-endpoint latency quantiles
  (nearest-rank over raw ``access_log`` seconds — the exact offline
  twin of the server's live bucket estimates), error/slow-request
  rates, the repair-debt timeline each ``delta_apply``'s ledger
  snapshot traces out, and (r9) the **admission timeline** beside it —
  every accept/queue/coalesce/shed verdict with the debt state that
  decided it, coalesce merges, and shed events (RUNBOOKS §8 keys its
  triage off this view);
- the **fleet** section (r10): replica health-state transitions,
  the circuit-breaker timeline, fleet-degraded (read-only) flips, and
  the route-verdict mix — which replica states and breaker episodes
  explain the 503s a reader saw (RUNBOOKS §9 keys its triage off this
  view);
- the **writer failover** section (r11): the WAL append/replay
  aggregate, ship-lag episodes, every ``writer_promote`` step and every
  ``publish_fenced`` refusal, in causal order — the promotion timeline
  RUNBOOKS §10 says to read before forcing writes on a read-only
  fleet;
- the **fleet traces** section (ISSUE 11): the ``trace_stitch``
  cross-process join rendered inline — complete per-delta timelines
  (admission → WAL fsync → apply → publish → each replica visible, each
  line attributed to the emitting process) and the failover epoch-fence
  sequence;
- the **quality & alerts** section (ISSUE 13): the result-quality
  timeline — one row per published version joining
  ``quality_snapshot`` / ``quality_drift`` / ``canary_score`` (anomaly
  rate, churn, PSI sketch drift, canary recall, pass seconds), sketch
  quantiles of the latest snapshot, and every alert firing/resolved
  transition (RUNBOOKS §13 keys its triage off this view).

Usage::

    python tools/obs_report.py METRICS.jsonl [--run-id ID] [--out PATH]
    python tools/obs_report.py OBS_DIR           # a fleet --obs-dir

A directory argument is treated as a fleet ``--obs-dir``: every
``*.jsonl`` shard inside is merged into one report view (the fleet is
one logical run, so ``--run-id`` selection is skipped).

A reused metrics file holds several ``run_start``-delimited segments; the
default is the most recent run (``--run-id`` selects another). Exit code
0 on success, 2 when the file is missing/empty or the run id is unknown,
**3 when the reported run carries schema violations or half-stamped
trace records** (the all-or-nothing identity rule in ``obs/schema.py``),
**4 when the stream ends with a firing page-severity alert** (the
canary scorer-regression rule is the built-in page — the result-quality
CI gate, distinct from 3 so CI can tell "telemetry rotted" from "the
scorer regressed") — so CI can run this as a post-e2e gate;
``--lenient`` downgrades both to a report note. Stdlib-only (usable on
a machine with no jax at all).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/obs_report.py` from anywhere
    sys.path.insert(0, _REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:  # sibling import when loaded as a module
    sys.path.insert(0, _TOOLS)

from graphmine_tpu.obs.schema import (  # noqa: E402
    RECOVERY_PHASES,
    validate_record,
    validate_records,
)

import trace_stitch  # noqa: E402  — the cross-process join (ISSUE 11)

BAR = "█"
BAR_WIDTH = 30


def load_records(path: str):
    """Parse a JSONL file tolerantly: unparseable/unknown-shape lines are
    counted, not fatal — a torn final line (the process died mid-write)
    is exactly the stream this tool exists to read."""
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if not isinstance(rec, dict) or "phase" not in rec:
                bad += 1
                continue
            records.append(rec)
    return records, bad


def split_runs(records):
    """Group records into runs. Preferred key: ``run_id`` (order of first
    appearance). Records with no run_id (pre-tracing streams) fall into
    segments delimited by ``run_start`` records, keyed ``segment-N``."""
    runs: dict = {}
    order: list = []
    seg_key = None
    seg = 0
    for rec in records:
        rid = rec.get("run_id")
        if rid is None:
            if rec.get("phase") == "run_start" or seg_key is None:
                seg += 1
                seg_key = f"segment-{seg}"
            rid = seg_key
        if rid not in runs:
            runs[rid] = []
            order.append(rid)
        runs[rid].append(rec)
    return runs, order


def _fmt_offset(rec, t0):
    return f"+{rec.get('t', t0) - t0:8.2f}s"


def _short_path(rec):
    path = rec.get("span_path", "")
    return path[4:] if path.startswith("run/") else path  # strip "run/"


def _bar(frac: float, width: int = BAR_WIDTH) -> str:
    n = max(0, min(width, round(frac * width)))
    return BAR * n


def _phase_waterfall(records, t0):
    spans = [r for r in records if r.get("phase") == "span"]
    rows = []
    if spans:
        for r in spans:
            secs = float(r.get("seconds", 0.0))
            start = float(r.get("t", t0)) - secs - t0
            rows.append((start, r.get("name", "?"), secs,
                         r.get("status", "ok"), _short_path(r)))
    else:  # pre-span streams: fall back to timed phase records
        for r in records:
            if "seconds" in r and r.get("phase") not in (
                "lpa_iter", "span", "superstep_telemetry"
            ):
                secs = float(r["seconds"])
                rows.append((float(r.get("t", t0)) - secs - t0,
                             r["phase"], secs, "ok", ""))
    if not rows:
        return ["  (no phase records)"]
    rows.sort()
    total = max((s + d for s, _, d, _, _ in rows), default=1.0) or 1.0
    width = max(len(n) for _, n, _, _, _ in rows)
    out = []
    for start, name, secs, status, _ in rows:
        flag = "" if status == "ok" else f"  [{status.upper()}]"
        out.append(
            f"  {name:<{width}}  {start:8.2f}s  {secs:8.2f}s  "
            f"{_bar(secs / total)}{flag}"
        )
    # implementation selections (r6): which kNN family the LOF phase
    # actually deployed (the auto-policy's measured-crossover decision)
    # belongs next to the waterfall bar it explains — WITH the deciding
    # crossover constants and the model's numbers (ISSUE 12 small fix:
    # a policy flip must be explainable from the JSONL alone).
    for r in records:
        if r.get("phase") == "impl_selected":
            out.append(
                f"  [impl_selected] {r.get('op', '?')}: {r.get('impl', '?')}"
                f" (n={r.get('n', '?')}, k={r.get('k', '?')}) — "
                f"{r.get('reason', '')}"
            )
            extra = _decision_evidence(r)
            if extra:
                out.append(f"      {extra}")
    # plan builds (r7): the host cost of materializing a superstep plan
    # (bins/buckets + padded slots/edge) — visible here instead of
    # hiding inside first-call latency.
    for r in records:
        if r.get("phase") == "plan_build":
            cached = " (cached)" if r.get("cached") else ""
            out.append(
                f"  [plan_build] {r.get('op', '?')}: {r.get('family', '?')}"
                f" in {float(r.get('seconds', 0.0)):.3f}s{cached} — "
                f"bins={r.get('bins', '?')}, "
                f"classes={r.get('width_classes', '?')}, "
                f"slots/edge={r.get('padded_slots_per_edge', '?')}"
            )
            extra = _decision_evidence(r, thresholds=False)
            if extra:
                out.append(f"      {extra}")
    return out


def _decision_evidence(r, thresholds: bool = True) -> str:
    """The crossover constants + cost-model numbers an auto decision
    shipped (ISSUE 12): rendered under its waterfall line so "why did
    the policy flip" never requires repo state — older streams without
    the keys render nothing."""
    def num(v, spec=","):
        return format(v, spec) if isinstance(v, (int, float)) else str(v)

    bits = []
    thr = r.get("thresholds")
    if thresholds and isinstance(thr, dict):
        bits.append(
            "thresholds: "
            + ", ".join(f"{k}={num(v)}" for k, v in sorted(thr.items()))
        )
    cost = r.get("cost")
    if isinstance(cost, dict):
        bits.append(
            f"model: {num(cost.get('predicted_per_chip', 0), ',.0f')} "
            f"{cost.get('unit', '?')} "
            f"(padded x{cost.get('padding_overhead', '?')}, "
            f"{num(cost.get('bytes_gathered', 0))} B gathered"
            + (
                f", {num(cost.get('exchange_bytes', 0))} B ICI"
                if cost.get("exchange_bytes") else ""
            )
            + ")"
        )
    return "  ".join(bits)


def _superstep_table(records):
    iters = [r for r in records if r.get("phase") == "lpa_iter"]
    if not iters:
        return ["  (no lpa_iter records)"]
    peak = max(r.get("edges_per_sec_per_chip", 0) for r in iters) or 1
    out = ["  it  changed   seconds   edges/sec/chip"]
    for r in iters:
        eps = r.get("edges_per_sec_per_chip", 0)
        out.append(
            f"  {r.get('iteration', '?'):>2}  {r.get('labels_changed', 0):>7}"
            f"  {r.get('seconds', 0):>8.4f}  {eps:>14,}  {_bar(eps / peak, 20)}"
        )
    return out


def _roofline_section(records, min_frac: float):
    """Achieved-vs-model roofline attribution (ISSUE 12): one row per
    ``superstep_timing`` window — achieved edges/s/chip next to the
    analytical cost model's prediction, with the achieved fraction and a
    loud flag on windows below ``min_frac`` of model (the RUNBOOKS §12
    "read this before blaming the device" signal). The exchange-vs-
    compute split comes from the window's cost sub-record. Empty list =
    no superstep_timing records (pre-ISSUE-12 stream)."""
    timings = [r for r in records if r.get("phase") == "superstep_timing"]
    if not timings:
        return []
    out = [
        "  op               it  win  family/variant     "
        "achieved/chip      model/chip   frac  exch%"
    ]
    flagged = 0
    exchange_windows = 0
    for r in timings:
        frac = float(r.get("achieved_fraction", 0.0) or 0.0)
        # a window that paid an XLA trace+compile (the ops seams mark
        # it) reads far below model on healthy hardware — report the
        # honest number, but never raise the triage flag on it
        cold = bool(r.get("cold_compile"))
        below = frac < min_frac and not cold
        flagged += below
        fam = f"{r.get('family', '?')}/{r.get('variant', '?')}"
        if int(r.get("devices", 1) or 1) > 1:
            fam += f"@{r['devices']}dev"
        note = ""
        if below:
            note = f"  << below {min_frac:g}x model"
        elif cold and frac < min_frac:
            note = "  (window includes XLA compile — not flagged)"
        # exchange column (ISSUE 15): the model's exchange share of the
        # window — the "is this superstep exchange-bound" number the §15
        # runbook reads before blaming the ICI
        cost = r.get("cost")
        exch_col, split = "    -", None
        if isinstance(cost, dict) and cost.get("exchange_bytes"):
            cs = float(cost.get("compute_seconds", 0.0) or 0.0)
            es = float(cost.get("exchange_seconds", 0.0) or 0.0)
            tot = (cs + es) or 1.0
            exch_col = f"{100 * es / tot:>4.0f}%"
            split = (
                f"      model split: compute {100 * cs / tot:.0f}% / "
                f"exchange {100 * es / tot:.0f}% "
                f"({cost['exchange_bytes']:,} B ICI per superstep)"
            )
            exchange_windows += 1
        out.append(
            f"  {str(r.get('op', '?')):<15} {r.get('iteration', '?'):>3}"
            f"  {r.get('window', '?'):>3}  {fam:<17}"
            f"  {int(r.get('edges_per_sec_per_chip', 0) or 0):>13,}"
            f"  {int(r.get('predicted_edges_per_sec_per_chip', 0) or 0):>14,}"
            f"  {frac:>5.2f}  {exch_col}{note}"
        )
        if split:
            out.append(split)
    if flagged:
        out.append(
            f"  {flagged} window(s) below {min_frac:g}x of model — read "
            "the telemetry/imbalance tables above before blaming the "
            "device (docs/RUNBOOKS.md §12)"
        )
    roof = next(
        (
            r["cost"]["roofline"] for r in reversed(timings)
            if isinstance(r.get("cost"), dict)
            and isinstance(r["cost"].get("roofline"), dict)
        ),
        None,
    )
    if roof:
        anchors = ", ".join(
            f"{k}={v:,.3g}" for k, v in sorted(roof.items())
            if isinstance(v, (int, float))
        )
        out.append(f"  model anchors: {anchors}")
        if roof.get("provenance"):
            out.append(f"  anchor provenance: {roof['provenance']}")
        # Exchange-anchor provenance flag (ISSUE 15 small fix): the
        # exchange split above divides by `exchange_bytes_per_sec`,
        # which has never been measured on silicon — a window reading
        # below model because of an optimistic exchange seed is a model
        # problem, not a device problem, and the verdict must say so
        # instead of letting a below-model flag rest silently on an
        # unmeasured anchor.
        prov = str(roof.get("provenance") or "")
        if exchange_windows and "exchange_bytes_per_sec: model seed" in prov:
            out.append(
                f"  !! {exchange_windows} window(s) carry an exchange "
                "split anchored to the UNMEASURED exchange_bytes_per_sec "
                "model seed — capture the sharded/exchange bench tiers "
                "(and re-seed via GRAPHMINE_ROOFLINE_FILE) before "
                "trusting a below-model exchange verdict "
                "(docs/RUNBOOKS.md §15)"
            )
    return out


def _fmt_bytes(b) -> str:
    if not isinstance(b, (int, float)):
        return "-"
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(b) >= div:
            return f"{b / div:.1f}{unit}"
    return f"{int(b)}B"


def _memory_section(records, t0):
    """Memory-plane triage (ISSUE 14, docs/OBSERVABILITY.md "Memory
    plane"): the per-phase predicted-vs-peak waterfall from
    ``memory_watermark`` records, flagged under-estimates, a concrete
    recalibration suggestion for the ``obs/memmodel.py`` byte seeds
    (the bench_diff crossover-suggestion pattern), and every
    memory-attributed degrade — plan-time pre-degrades and reactive
    OOMs with their attached last watermark, joinable back to the full
    record by span path. Empty list = no memory-plane records
    (pre-ISSUE-14 stream)."""
    marks = [r for r in records if r.get("phase") == "memory_watermark"]
    # device-loss degrades (kind="device") also carry the mem context —
    # the driver attaches it to every degrade — but they belong to the
    # elastic ladder's triage (§3), not the memory section: labeling a
    # dead chip "OOM" would send the operator down the wrong runbook.
    mem_degrades = [
        r for r in records
        if r.get("phase") == "degrade"
        and r.get("kind") != "device"
        and (isinstance(r.get("mem"), dict) or r.get("kind") == "mem_plan"
             or isinstance(r.get("last_watermark"), dict))
    ]
    if not (marks or mem_degrades):
        return []
    out = []
    def _num(v):
        # non-numeric-tolerant (the r12 roofline discipline): schema
        # validation checks key presence, not types — a malformed
        # record must degrade to a hole in the table, never a crashed
        # report (the exit-3 path still names the violation)
        return int(v) if isinstance(v, (int, float)) else 0

    if marks:
        # grouped per (op, source): one transient rss-fallback sample
        # mid-run must never contaminate a device group's peak/ratio —
        # RSS vs HBM model is exactly the comparison the recalibration
        # rule below refuses to make
        groups: dict = {}
        for r in marks:
            key = (r.get("op", "?"), r.get("source", "?"))
            g = groups.setdefault(key, {
                "pred": 0, "peak": 0, "head": None, "n": 0,
            })
            g["pred"] = max(g["pred"], _num(r.get("predicted_bytes")))
            g["peak"] = max(g["peak"], _num(r.get("achieved_bytes")))
            h = r.get("headroom_frac")
            if isinstance(h, (int, float)):
                g["head"] = h if g["head"] is None else min(g["head"], h)
            g["n"] += 1
        out.append(
            "  op               predicted       peak  peak/model"
            "  headroom  src     marks"
        )
        peak_max = max(g["peak"] for g in groups.values()) or 1
        worst = None  # (ratio, op) over device-sourced groups
        for (op, src), g in sorted(groups.items()):
            ratio = g["peak"] / g["pred"] if g["pred"] else 0.0
            head = f"{g['head']:.2f}" if g["head"] is not None else "-"
            flag = ""
            if src == "device" and g["pred"] and ratio > 1.1:
                flag = "  << model under-estimates"
            if src == "device" and (worst is None or ratio > worst[0]):
                worst = (ratio, op)
            out.append(
                f"  {op:<15} {_fmt_bytes(g['pred']):>10}"
                f" {_fmt_bytes(g['peak']):>10}  {ratio:>9.2f}x"
                f"  {head:>8}  {src:<6}  {g['n']:>4}"
                f"  {_bar(g['peak'] / peak_max, 16)}{flag}"
            )
        # Recalibration suggestion (the bench_diff crossover-suggestion
        # pattern): what the measured peaks mean for the byte seeds the
        # planner AND the model read (one owner — obs/memmodel.py).
        try:
            from graphmine_tpu.obs.memmodel import BYTES_PER_EDGE
        except Exception:  # pragma: no cover — report must still render
            BYTES_PER_EDGE = None
        cur = (
            f"(current seed: BYTES_PER_EDGE={BYTES_PER_EDGE:.0f})"
            if BYTES_PER_EDGE is not None else ""
        )
        if worst is None:
            out.append(
                "  recalibration: watermarks carry host-RSS only (no "
                "device allocator on this backend) — RSS is not "
                "comparable to the HBM model; re-run on silicon to "
                f"recalibrate the obs/memmodel.py byte seeds {cur}"
            )
        elif worst[0] > 1.05:
            scaled = (
                f" (e.g. BYTES_PER_EDGE {BYTES_PER_EDGE:.0f} -> "
                f"{BYTES_PER_EDGE * worst[0]:.0f})"
                if BYTES_PER_EDGE is not None else ""
            )
            out.append(
                f"  recalibration: measured peak is {worst[0]:.2f}x the "
                f"modeled footprint for {worst[1]} — raise the "
                f"obs/memmodel.py byte seeds{scaled} so the planner "
                "stops accepting schedules the allocator rejects; the "
                "planner moves with the model (one owner)"
            )
        elif worst[0] < 0.7:
            out.append(
                f"  recalibration: measured peak is only {worst[0]:.2f}x "
                f"model for {worst[1]} — the seeds are conservative; "
                "lowering them (obs/memmodel.py) would admit larger "
                f"graphs per device {cur}"
            )
        else:
            out.append(
                f"  recalibration: measured peak within noise of model "
                f"(worst {worst[0]:.2f}x at {worst[1]}) — keep the "
                f"obs/memmodel.py byte seeds {cur}"
            )
    for r in mem_degrades:
        kind = (
            "PLAN PRE-DEGRADE" if r.get("kind") == "mem_plan"
            else "OOM DEGRADE"
        )
        mem = r.get("mem") if isinstance(r.get("mem"), dict) else {}
        line = (
            f"  {_fmt_offset(r, t0)}  {kind}  stage={r.get('stage', '?')}"
            f"  to={r.get('to', '?')}"
        )
        if mem:
            line += (
                f"  modeled={_fmt_bytes(mem.get('total_bytes'))}"
                f" ({mem.get('family', '?')})"
            )
        out.append(line)
        w = r.get("last_watermark")
        if isinstance(w, dict):
            out.append(
                f"      last watermark: "
                f"{_fmt_bytes(w.get('achieved_bytes'))} measured"
                f" ({w.get('source', '?')}) vs "
                f"{_fmt_bytes(w.get('predicted_bytes'))} model"
                f"  headroom={w.get('headroom_frac', '?')}"
                f"  @ {w.get('span_path', '?')}"
            )
        inv = mem.get("inventory")
        if isinstance(inv, dict) and inv:
            top = sorted(inv.items(), key=lambda kv: -_num(kv[1]))[:4]
            out.append(
                "      inventory: "
                + ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in top)
                + (f", … ({len(inv)} components)" if len(inv) > 4 else "")
            )
    return out


def _telemetry_table(records):
    tele = [r for r in records if r.get("phase") == "superstep_telemetry"]
    if not tele:
        return ["  (no superstep_telemetry records)"]
    out = ["  it  frontier  shards  shard min/max  imbalance  variant"]
    for r in tele:
        out.append(
            f"  {r.get('iteration', '?'):>2}  {r.get('frontier', 0):>8}"
            f"  {r.get('devices', '?'):>6}"
            f"  {r.get('shard_min', '?'):>6}/{r.get('shard_max', '?'):<6}"
            f"  {r.get('imbalance', '?'):>9}  {r.get('variant', '?')}"
        )
    return out


_DETAIL_KEYS = {
    "retry": ("stage", "attempt", "backoff_s"),
    "retries_exhausted": ("stage", "attempts"),
    "degrade": ("stage", "to", "kind"),
    "mesh_degrade": ("from_devices", "to_devices", "iteration",
                     "resumed_from", "dead_devices"),
    "tripwire": ("kind", "shard", "iteration"),
    "watchdog_timeout": ("stage", "timeout_s", "checkpointed"),
    "resume": ("iteration", "reason"),
    "checkpoint_rollback": ("path",),
    "checkpoint_rollback_ok": ("path", "iteration"),
    "ivf_fallback": ("guard",),
    "quarantine": (),
    "repair_fallback": ("stage", "reason"),
    "breaker_transition": ("replica", "from_state", "to_state"),
    "fleet_degraded": ("read_only", "writer"),
    "wal_replay": ("entries", "from_seq", "source"),
    "writer_promote": ("epoch", "replica", "replayed"),
    "publish_fenced": ("attempted_epoch", "store_epoch"),
}

_SERVING_PHASES = ("snapshot_publish", "snapshot_load", "delta_apply",
                   "query_batch")


def _serving_table(records, t0):
    """Serving-layer timeline (r7): snapshot publishes/loads and delta
    applies as rows, query_batch records aggregated per endpoint —
    100k lookups must not become 100k report lines."""
    rows, queries = [], {}
    for r in records:
        phase = r.get("phase")
        if phase == "query_batch":
            agg = queries.setdefault(
                r.get("endpoint", "?"), {"batches": 0, "n": 0, "seconds": 0.0}
            )
            agg["batches"] += 1
            agg["n"] += int(r.get("n", 0))
            agg["seconds"] += float(r.get("seconds", 0.0))
        elif phase == "snapshot_publish":
            rows.append(
                f"  {_fmt_offset(r, t0)}  snapshot_publish  "
                f"v{r.get('version', '?')}  {r.get('bytes', 0):,} B  "
                f"{r.get('seconds', 0):.3f}s  arrays={len(r.get('arrays', []))}"
            )
        elif phase == "snapshot_load":
            rows.append(
                f"  {_fmt_offset(r, t0)}  snapshot_load     "
                f"v{r.get('version', '?')}  {r.get('seconds', 0):.3f}s"
            )
        elif phase == "delta_apply":
            q = r.get("quarantine", {})
            quarantined = sum(q.values()) if isinstance(q, dict) else 0
            rows.append(
                f"  {_fmt_offset(r, t0)}  delta_apply       "
                f"v{r.get('version', '?')}  +{r.get('inserts', 0)}/-"
                f"{r.get('deletes', 0)} edges  {r.get('method', '?')} "
                f"({r.get('iterations', '?')} supersteps)  "
                f"quarantined={quarantined}  {r.get('seconds', 0):.3f}s"
            )
    for endpoint, agg in sorted(queries.items()):
        qps = agg["n"] / agg["seconds"] if agg["seconds"] > 0 else 0.0
        rows.append(
            f"  queries[{endpoint}]: {agg['n']:,} lookups in "
            f"{agg['batches']} batch(es), {agg['seconds']:.3f}s resolve "
            f"time ({qps:,.0f}/s)"
        )
    return rows


def _percentile(sorted_vals, q):
    """Nearest-rank percentile over a sorted list — the stdlib-exact
    offline quantile the live bucket estimate (``/statusz``) is checked
    against (agreement within one histogram bucket, tests/test_slo.py)."""
    if not sorted_vals:
        return 0.0
    import math

    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _slo_section(records, t0):
    """Serving SLO, reconstructed from the JSONL alone: per-endpoint
    latency quantiles + error rates from ``access_log`` records, and the
    repair-debt timeline from the ledger snapshots each ``delta_apply``
    carries. Empty list = no serving-SLO records (batch-only stream)."""
    access = [r for r in records if r.get("phase") == "access_log"]
    applies = [
        r for r in records
        if r.get("phase") == "delta_apply"
        and isinstance(r.get("repair_debt"), dict)
    ]
    out = []
    if access:
        per: dict = {}
        for r in access:
            d = per.setdefault(
                r.get("endpoint", "?"), {"secs": [], "errors": 0, "slow": 0}
            )
            d["secs"].append(float(r.get("seconds", 0.0)))
            if int(r.get("status", 0)) >= 400:
                d["errors"] += 1
            if r.get("slow"):
                d["slow"] += 1
        out.append(
            "  endpoint          n    err%  slow       p50       p95"
            "       p99"
        )
        for ep, d in sorted(per.items()):
            s = sorted(d["secs"])
            n = len(s)
            out.append(
                f"  {ep:<14} {n:>5}  {100.0 * d['errors'] / n:>5.1f}%"
                f"  {d['slow']:>4}"
                f"  {_percentile(s, 0.50) * 1e3:>7.2f}ms"
                f"  {_percentile(s, 0.95) * 1e3:>7.2f}ms"
                f"  {_percentile(s, 0.99) * 1e3:>7.2f}ms"
            )
    if applies:
        out.append("  repair-debt timeline:")
        for r in applies:
            debt = r["repair_debt"]
            budget = r.get("budget", "?")
            row = (
                f"  {_fmt_offset(r, t0)}  v{r.get('version', '?')}"
                f"  {r.get('method', '?'):<15}"
                f"  supersteps={r.get('iterations', '?')}/{budget}"
                f"  pending_rows={debt.get('pending_rows', '?')}"
                f"  lag={debt.get('ingest_lag_s', '?')}s"
                f"  warm_ratio={debt.get('warm_ratio', '?')}"
            )
            if int(r.get("batches", 1) or 1) > 1:
                row += f"  coalesced={r['batches']}"
            if r.get("lof_stale"):
                row += "  LOF-STALE"
            out.append(row)
    out.extend(_admission_timeline(records, t0))
    return out


def _admission_timeline(records, t0):
    """Admission-control timeline (r8, docs/SERVING.md "admission
    control"): every resolve verdict with the debt state that decided
    it, coalesce merges, and shed events — the first thing RUNBOOKS §8
    says to read when /delta starts returning 503s. Rendered next to the
    repair-debt timeline so "why did it shed" and "how far behind was
    repair" line up on one clock."""
    events = [
        r for r in records
        if r.get("phase") in ("admission", "delta_coalesce", "delta_shed")
    ]
    if not events:
        return []
    out = ["  admission timeline:"]
    verdicts: dict = {}
    for r in events:
        phase = r["phase"]
        debt = r.get("repair_debt") or {}
        if phase == "admission":
            verdicts[r.get("verdict", "?")] = (
                verdicts.get(r.get("verdict", "?"), 0) + 1
            )
            out.append(
                f"  {_fmt_offset(r, t0)}  admission  "
                f"{r.get('verdict', '?'):<8}"
                f"  rows={r.get('rows', '?')}"
                f"  queue={r.get('queue_depth', '?')}"
                f"  pending_rows={debt.get('pending_rows', '?')}"
                f"  lag={debt.get('ingest_lag_s', '?')}s"
                + (
                    f"  [{r.get('reason', '')}]"
                    if r.get("verdict") in ("shed",) else ""
                )
            )
        elif phase == "delta_coalesce":
            out.append(
                f"  {_fmt_offset(r, t0)}  coalesce   "
                f"{r.get('batches', '?')} batches -> "
                f"+{r.get('inserts', '?')}/-{r.get('deletes', '?')} rows "
                f"(cancelled={r.get('cancelled_pairs', 0)}, "
                f"rows {r.get('rows_in', '?')}->{r.get('rows_out', '?')})"
            )
        else:  # delta_shed
            out.append(
                f"  {_fmt_offset(r, t0)}  SHED       "
                f"stage={r.get('stage', '?')}  rows={r.get('rows', '?')}"
                f"  retry_after={r.get('retry_after_s', '?')}s"
                f"  [{r.get('reason', '')}]"
            )
    if verdicts:
        total = sum(verdicts.values())
        mix = "  ".join(f"{k}={v}" for k, v in sorted(verdicts.items()))
        out.append(f"  admission verdicts: {total} resolutions ({mix})")
    return out


def _fleet_section(records, t0):
    """Replicated-fleet timeline (r10, docs/SERVING.md "Fleet"): replica
    state-machine transitions, the breaker timeline, read-only flips and
    the route-verdict mix — RUNBOOKS §9's "read the fleet timeline
    before restarting anything" view. Empty list = no fleet records
    (single-process stream)."""
    health = [r for r in records if r.get("phase") == "replica_health"]
    breakers = [r for r in records if r.get("phase") == "breaker_transition"]
    degraded = [r for r in records if r.get("phase") == "fleet_degraded"]
    routes = [r for r in records if r.get("phase") == "fleet_route"]
    if not (health or breakers or degraded or routes):
        return []
    out = []
    if health:
        out.append("  replica health transitions:")
        for r in health:
            v = r.get("version")
            out.append(
                f"  {_fmt_offset(r, t0)}  {r.get('replica', '?'):<12}"
                f"  {r.get('from_state', '?'):>8} -> "
                f"{r.get('to_state', '?'):<8}"
                f"{f'  v{v}' if v is not None else ''}"
                f"  [{r.get('reason', '')}]"
            )
    if breakers:
        out.append("  breaker timeline:")
        for r in breakers:
            out.append(
                f"  {_fmt_offset(r, t0)}  {r.get('replica', '?'):<12}"
                f"  {r.get('from_state', '?'):>9} -> "
                f"{r.get('to_state', '?'):<9}"
                f"  [{r.get('reason', '')}]"
            )
    for r in degraded:
        verdict = (
            "FLEET READ-ONLY" if r.get("read_only") else "fleet writes restored"
        )
        out.append(
            f"  {_fmt_offset(r, t0)}  {verdict}  [{r.get('reason', '')}]"
        )
    if routes:
        verdicts: dict = {}
        attempts_total = 0
        retried = 0
        for r in routes:
            verdicts[r.get("verdict", "?")] = (
                verdicts.get(r.get("verdict", "?"), 0) + 1
            )
            a = int(r.get("attempts", 0) or 0)
            attempts_total += a
            if a > 1:
                retried += 1
        mix = "  ".join(f"{k}={v}" for k, v in sorted(verdicts.items()))
        out.append(
            f"  route verdicts: {len(routes)} requests ({mix}); "
            f"{attempts_total} replica attempts, {retried} needed retry"
        )
    return out


def _failover_section(records, t0):
    """Writer-failover timeline (r11, docs/SERVING.md "Replicated
    writers"): the WAL durability aggregate, ship-lag episodes, every
    promotion step and every fenced publish — RUNBOOKS §10's "read the
    promotion timeline before forcing writes" view. Empty list = no
    durable-write-path records in the stream."""
    appends = [r for r in records if r.get("phase") == "wal_append"]
    replays = [r for r in records if r.get("phase") == "wal_replay"]
    lags = [r for r in records if r.get("phase") == "ship_lag"]
    promotes = [r for r in records if r.get("phase") == "writer_promote"]
    fenced = [r for r in records if r.get("phase") == "publish_fenced"]
    if not (appends or replays or lags or promotes or fenced):
        return []
    out = []
    if appends:
        secs = sorted(float(r.get("seconds", 0.0)) for r in appends)
        rows = sum(int(r.get("rows", 0)) for r in appends)
        total = sum(int(r.get("bytes", 0)) for r in appends)
        out.append(
            f"  wal appends: {len(appends)} entries, {rows} rows, "
            f"{total:,} B; fsync p50 "
            f"{_percentile(secs, 0.50) * 1e3:.2f}ms / p99 "
            f"{_percentile(secs, 0.99) * 1e3:.2f}ms"
        )
    for r in replays:
        if r.get("torn_tail"):
            out.append(
                f"  {_fmt_offset(r, t0)}  WAL TORN TAIL  truncated at "
                f"{r.get('truncated_to', '?')} B  [{r['torn_tail']}]"
            )
            continue
        out.append(
            f"  {_fmt_offset(r, t0)}  wal_replay  "
            f"{r.get('entries', '?')} entr(ies) "
            f"seq {r.get('from_seq', '?')}..{r.get('to_seq', '?')}  "
            f"source={r.get('source', '?')}"
        )
    if lags:
        worst = max(lags, key=lambda r: float(r.get("lag_s", 0.0) or 0.0))
        out.append(
            f"  ship lag: {len(lags)} behind-sample(s); worst "
            f"{worst.get('lag_entries', '?')} entries / "
            f"{worst.get('lag_s', '?')}s behind "
            f"(primary seq {worst.get('primary_last_seq', '?')}, "
            f"shipped {worst.get('shipped_seq', '?')})"
        )
    for r in promotes:
        bits = [f"epoch {r.get('epoch', '?')}"]
        if r.get("replica"):
            bits.append(f"writer={r['replica']}")
        if r.get("deposed"):
            bits.append(f"deposed={r['deposed']}")
        if r.get("replayed") is not None:
            bits.append(f"replayed={r['replayed']}")
        if r.get("copied_tail") is not None:
            bits.append(f"copied_tail={r['copied_tail']}")
        if r.get("seconds") is not None:
            bits.append(f"{r['seconds']}s")
        out.append(
            f"  {_fmt_offset(r, t0)}  WRITER PROMOTE  {'  '.join(bits)}"
        )
    for r in fenced:
        out.append(
            f"  {_fmt_offset(r, t0)}  PUBLISH FENCED  attempted epoch "
            f"{r.get('attempted_epoch', '?')} < store epoch "
            f"{r.get('store_epoch', '?')}  [{r.get('reason', '')}]"
        )
    return out


def _writer_shards_section(records, t0):
    """Sharded-write-plane timeline (r17, docs/SERVING.md "Sharded write
    plane"): per-range admission verdict mix, every epoch commit, every
    per-shard stage publish, and each range's degrade/recover/promote
    line — the §17 runbook's "which range is read-only, which epoch is
    stuck" view. Empty list = no shard-plane records in the stream."""
    publishes = [r for r in records if r.get("phase") == "shard_publish"]
    commits = [r for r in records if r.get("phase") == "epoch_commit"]
    degraded = [r for r in records if r.get("phase") == "shard_degraded"]
    admissions = [
        r for r in records
        if r.get("phase") == "admission" and r.get("shard") is not None
    ]
    if not (publishes or commits or degraded):
        return []
    out = []
    if admissions:
        # per-range verdict mix: one line per shard, the range-level
        # answer to "who is shedding"
        by_shard: dict = {}
        for r in admissions:
            mix = by_shard.setdefault(int(r["shard"]), {})
            v = r.get("verdict", "?")
            mix[v] = mix.get(v, 0) + 1
        for shard in sorted(by_shard):
            mix = by_shard[shard]
            parts = "  ".join(
                f"{v}={mix[v]}" for v in sorted(mix)
            )
            out.append(f"  shard {shard} admission: {parts}")
    if publishes:
        by_shard = {}
        for r in publishes:
            by_shard.setdefault(int(r.get("shard", -1)), []).append(r)
        staged = ", ".join(
            f"shard {s}×{len(rs)}" for s, rs in sorted(by_shard.items())
        )
        out.append(f"  stage publishes: {len(publishes)} ({staged})")
    for r in commits:
        vec = r.get("version_vector") or {}
        vv = " ".join(
            f"{k}:{vec[k]}" for k in sorted(vec, key=lambda x: int(x))
        )
        tag = "  (recovered)" if r.get("recovered") else ""
        out.append(
            f"  {_fmt_offset(r, t0)}  EPOCH COMMIT  epoch "
            f"{r.get('epoch', '?')}  versions [{vv}]{tag}"
        )
    for r in degraded:
        status = str(r.get("status", "?")).upper()
        rng = r.get("range")
        rng_s = f" [{rng[0]},{rng[1]})" if isinstance(rng, list) else ""
        out.append(
            f"  {_fmt_offset(r, t0)}  SHARD {status}  shard "
            f"{r.get('shard', '?')}{rng_s}  [{r.get('reason', '')}]"
        )
    return out


def _sketch_quantiles(state) -> str:
    """p50/p90/p99 of a sketch state dict — rebuilt through the one
    shared QuantileSketch machinery so the report's numbers can never
    drift from the live /statusz estimates."""
    try:
        from graphmine_tpu.obs.sketch import QuantileSketch

        sk = QuantileSketch.from_state(state)
        if not sk.count:
            return "(empty)"
        return (
            f"p50 {sk.quantile(0.50):.3g} / p90 {sk.quantile(0.90):.3g}"
            f" / p99 {sk.quantile(0.99):.3g}"
        )
    except (ValueError, KeyError, TypeError):
        return "(malformed sketch)"


def _quality_section(records, t0):
    """Result-quality timeline (ISSUE 13, docs/OBSERVABILITY.md "Result
    quality"): one row per published version joining quality_snapshot /
    quality_drift / canary_score, then every alert transition — the
    RUNBOOKS §13 "read the quality timeline before blaming the data"
    view, rendered from the JSONL shards alone. Empty = no quality
    records in the stream."""
    snaps = [r for r in records if r.get("phase") == "quality_snapshot"]
    drifts = {
        r.get("version"): r for r in records
        if r.get("phase") == "quality_drift"
    }
    canaries = {
        r.get("version"): r for r in records
        if r.get("phase") == "canary_score"
    }
    alerts = [r for r in records if r.get("phase") == "alert"]
    if not (snaps or alerts):
        return []
    out = []
    if snaps:
        out.append(
            "  version  communities  anomaly%   churn   lof_psi  size_psi"
            "  canary@k  pass_s"
        )
        for r in snaps:
            ver = r.get("version")
            d = drifts.get(ver, {})
            c = canaries.get(ver, {})

            def num(src, key, fmt, absent="      -"):
                v = src.get(key)
                if not isinstance(v, (int, float)):
                    return absent
                return fmt.format(v)

            out.append(
                f"  v{ver!s:<7} {r.get('num_communities', '?'):>11}  "
                f"{num(r, 'anomaly_rate', '{:7.2%}')} "
                f"{num(d, 'churn_frac', '{:7.2%}')} "
                f"{num(d, 'lof_psi', '{:9.3f}')} "
                f"{num(d, 'size_psi', '{:9.3f}')} "
                f"{num(c, 'recall_at_k', '{:9.2f}')} "
                f"{num(r, 'seconds', '{:7.3f}')}"
            )
        last = snaps[-1]
        for key, label in (("lof_sketch", "lof scores"),
                           ("size_sketch", "community sizes")):
            state = last.get(key)
            if isinstance(state, dict):
                out.append(
                    f"  latest {label:<16} {_sketch_quantiles(state)}"
                )
    for r in alerts:
        mark = "ALERT FIRING" if r.get("state") == "firing" else "resolved"
        out.append(
            f"  {_fmt_offset(r, t0)}  {mark:<12} {r.get('name', '?')}"
            f"  [{r.get('severity', '?')}]  {r.get('metric', '?')}"
            f" {r.get('op', '')} {r.get('threshold', '?')}"
            f"  value={r.get('value', '?')}"
        )
    return out


def _tenant_section(records, t0):
    """Per-tenant serving rollup (ISSUE 16, docs/SERVING.md "Multi-tenant
    serving"): group the write-path and alert records by the ``tenant``
    they carry — one row per namespace with its admission verdict mix,
    applied volume, sheds and firing alerts, so a noisy-neighbor
    incident reads as "tenant A shed, tenant B clean" instead of one
    blended stream. Records without a tenant stamp are the default
    namespace. Empty when the stream is single-tenant (no record
    carries a tenant key)."""
    phases = ("admission", "delta_apply", "delta_shed", "delta_coalesce",
              "access_log", "alert", "quality_drift", "canary_score")
    tagged = [r for r in records if r.get("phase") in phases]
    if not any("tenant" in r for r in tagged):
        return []
    groups: dict = {}
    for r in tagged:
        groups.setdefault(r.get("tenant") or "default", []).append(r)
    out = [
        "  tenant            deltas    rows  sheds  admission verdicts"
        "        firing"
    ]
    for tenant in sorted(groups):
        rs = groups[tenant]
        applies = [r for r in rs if r["phase"] == "delta_apply"]
        rows = sum(
            int(r.get("inserts", 0) or 0) + int(r.get("deletes", 0) or 0)
            for r in applies
        )
        verdicts: dict = {}
        for r in rs:
            if r["phase"] == "admission":
                v = str(r.get("verdict", "?"))
                verdicts[v] = verdicts.get(v, 0) + 1
        mix = " ".join(
            f"{k}:{n}" for k, n in sorted(verdicts.items())
        ) or "-"
        sheds = sum(1 for r in rs if r["phase"] == "delta_shed")
        last_alert: dict = {}
        for r in rs:
            if r["phase"] == "alert" and r.get("name"):
                last_alert[r["name"]] = r.get("state")
        firing = sorted(
            n for n, st in last_alert.items() if st == "firing"
        )
        out.append(
            f"  {tenant:<16} {len(applies):>7} {rows:>7} {sheds:>6}  "
            f"{mix:<24}  {', '.join(firing) or '-'}"
        )
    transitions = [
        r for r in tagged
        if r["phase"] == "alert" and "tenant" in r
    ]
    for r in transitions:
        mark = "ALERT FIRING" if r.get("state") == "firing" else "resolved"
        out.append(
            f"  {_fmt_offset(r, t0)}  [{r.get('tenant', '?')}]  {mark:<12}"
            f" {r.get('name', '?')}  value={r.get('value', '?')}"
        )
    return out


def gating_alerts(records) -> list:
    """Alert names whose LAST transition in the stream is a firing
    page-severity alert (the canary rule is the built-in page) — the CI
    gate: ``main`` exits 4 when this is non-empty, alongside the
    schema-violation exit 3 (docs/OBSERVABILITY.md "Result quality")."""
    last: dict = {}
    for r in records:
        if r.get("phase") == "alert" and r.get("name"):
            last[r["name"]] = r
    return sorted(
        name for name, r in last.items()
        if r.get("state") == "firing" and r.get("severity") == "page"
    )


def _recovery_timeline(records, t0):
    events = [r for r in records if r.get("phase") in RECOVERY_PHASES]
    if not events:
        return ["  (clean run: no recovery events)"]
    out = []
    for r in events:
        keys = _DETAIL_KEYS.get(r["phase"], ())
        detail = "  ".join(
            f"{k}={r[k]}" for k in keys if k in r and r[k] is not None
        )
        err = r.get("error")
        if err and r["phase"] in ("retry", "retries_exhausted", "degrade"):
            err = str(err)
            detail += f"  error={err[:70]}{'…' if len(err) > 70 else ''}"
        where = _short_path(r)
        out.append(
            f"  {_fmt_offset(r, t0)}  {r['phase']:<22}"
            f"{('[' + where + ']  ') if where else ''}{detail}"
        )
    return out


def _liveness(records, t0):
    end = next((r for r in records if r.get("phase") == "run_end"), None)
    if end is not None:
        if end.get("ok"):
            return "ok", f"completed in {end.get('t', t0) - t0:.2f}s"
        detail = end.get("error_detail", end.get("error", ""))
        return "error", f"failed ({end.get('error', '?')}): {detail}"
    # no run_end: the process died or hung. Heartbeats disambiguate.
    beats = [r for r in records if r.get("phase") == "heartbeat"]
    others = [r for r in records if r.get("phase") not in ("heartbeat",)]
    last_t = max((r.get("t", t0) for r in others), default=t0)
    if beats and beats[-1].get("t", t0) > last_t + 1.0:
        busy = beats[-1].get("busy", "?")
        return "HUNG", (
            f"no run_end, but heartbeats continued {beats[-1]['t'] - last_t:.1f}s "
            f"past the last phase record (last busy: {busy}) — the process "
            "was alive but stuck"
        )
    return "DEAD", (
        "no run_end and no trailing heartbeats — the process was killed "
        "(preemption / OOM-kill) or crashed without cleanup"
    )


def _heartbeat_summary(records, t0):
    beats = [r for r in records if r.get("phase") == "heartbeat"]
    if not beats:
        return ["  (heartbeat disabled)"]
    ts = [r.get("t", t0) for r in beats]
    gaps = [b - a for a, b in zip(ts, ts[1:])]
    rss = [r["rss_mb"] for r in beats if "rss_mb" in r]
    line = (f"  {len(beats)} beats, last +{ts[-1] - t0:.2f}s,"
            f" max gap {max(gaps):.2f}s" if gaps else
            f"  {len(beats)} beat(s)")
    if rss:
        line += f", peak RSS {max(rss):.0f} MiB"
    return [line]


def _fleet_trace_section(records, max_traces: int = 4):
    """Cross-process trace timelines (ISSUE 11): the ``trace_stitch``
    join rendered inline — complete per-delta timelines first (each with
    its COMPLETE/partial verdict), then the failover epoch-fence
    sequence. Empty list when no record carries a delta or failover
    trace; records from a single-process stream render with their one
    shard name, a merged ``--obs-dir`` view attributes every line to the
    emitting process."""
    recs = [dict(r) for r in records if r.get("trace_id") is not None
            or r.get("phase") in trace_stitch._FAILOVER_PHASES]
    if not recs:
        return []
    for r in recs:
        r.setdefault("_src", "this-process")
    traces = trace_stitch.stitch(recs)
    deltas = trace_stitch.delta_traces(traces)
    lines: list = []
    complete = sorted(
        tid for tid, (_, st) in deltas.items() if all(st.values())
    )
    if deltas:
        lines.append(
            f"complete per-delta timelines: {len(complete)}/{len(deltas)}"
        )
        ordered = complete + [t for t in deltas if t not in set(complete)]
        for tid in ordered[:max_traces]:
            trecs, stages = deltas[tid]
            lines.extend(trace_stitch.render_trace(tid, trecs, stages))
        if len(deltas) > max_traces:
            lines.append(
                f"({len(deltas) - max_traces} more delta trace(s); "
                "tools/trace_stitch.py renders them all)"
            )
    lines.extend(trace_stitch.failover_section(recs))
    return lines


def build_report(
    records, source: str = "", bad_lines: int = 0,
    roofline_min_frac: float = 0.5,
) -> str:
    """Render one run's records (already filtered to a single run_id)."""
    start = next((r for r in records if r.get("phase") == "run_start"), None)
    t0 = records[0].get("t", 0.0) if records else 0.0
    run_id = records[0].get("run_id", "?") if records else "?"
    unknown = sum(
        1 for r in records
        if any("unknown phase" in p for p in validate_record(r))
    )
    status, verdict = _liveness(records, t0)
    import time as _time

    started = _time.strftime("%Y-%m-%d %H:%M:%S UTC", _time.gmtime(t0))
    lines = ["== graphmine_tpu run report =="]
    if source:
        lines.append(f"source: {source}")
    lines.append(f"run_id: {run_id}    started: {started}")
    if start is not None:
        cfgbits = "  ".join(
            f"{k}={start[k]}" for k in
            ("backend", "schedule", "community_method", "max_iter", "pid")
            if k in start
        )
        lines.append(f"config: {cfgbits}")
        lines.append(f"data:   {start.get('data_path', '?')}")
    lines.append(f"status: {status} — {verdict}")
    note = []
    if bad_lines:
        note.append(f"{bad_lines} unparseable line(s)")
    if unknown:
        note.append(f"{unknown} unknown-schema record(s)")
    lines.append(
        f"records: {len(records)}" + (f"  ({', '.join(note)})" if note else "")
    )
    lines.append("")
    lines.append("-- phase waterfall --")
    lines.extend(_phase_waterfall(records, t0))
    lines.append("")
    lines.append("-- lpa supersteps --")
    lines.extend(_superstep_table(records))
    lines.append("")
    lines.append("-- superstep telemetry (load imbalance) --")
    lines.extend(_telemetry_table(records))
    roofline = _roofline_section(records, roofline_min_frac)
    if roofline:  # pre-ISSUE-12 streams carry no superstep_timing
        lines.append("")
        lines.append("-- roofline (achieved vs cost model) --")
        lines.extend(roofline)
    memory = _memory_section(records, t0)
    if memory:  # pre-ISSUE-14 streams carry no memory_watermark
        lines.append("")
        lines.append("-- memory (predicted vs peak) --")
        lines.extend(memory)
    serving = _serving_table(records, t0)
    if serving:  # serving is opt-in; batch-only streams skip the section
        lines.append("")
        lines.append("-- serving (snapshots / deltas / queries) --")
        lines.extend(serving)
    slo = _slo_section(records, t0)
    if slo:
        lines.append("")
        lines.append("-- serving SLO (latency / errors / repair debt) --")
        lines.extend(slo)
    fleet = _fleet_section(records, t0)
    if fleet:
        lines.append("")
        lines.append("-- fleet (replica health / breakers / routing) --")
        lines.extend(fleet)
    qual = _quality_section(records, t0)
    if qual:
        lines.append("")
        lines.append("-- quality & alerts (result drift / canary) --")
        lines.extend(qual)
    tenants = _tenant_section(records, t0)
    if tenants:  # single-tenant streams carry no tenant stamps
        lines.append("")
        lines.append("-- tenants (per-namespace serving rollup) --")
        lines.extend(tenants)
    ftrace = _fleet_trace_section(records)
    if ftrace:
        lines.append("")
        lines.append("-- fleet traces (cross-process timelines) --")
        lines.extend(ftrace)
    failover = _failover_section(records, t0)
    if failover:
        lines.append("")
        lines.append("-- writer failover (WAL / promotion / fencing) --")
        lines.extend(failover)
    shards = _writer_shards_section(records, t0)
    if shards:
        lines.append("")
        lines.append(
            "-- writer shards (ranges / epochs / per-range failover) --"
        )
        lines.extend(shards)
    lines.append("")
    lines.append("-- recovery timeline --")
    lines.extend(_recovery_timeline(records, t0))
    lines.append("")
    lines.append("-- heartbeats --")
    lines.extend(_heartbeat_summary(records, t0))
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("metrics", help="metrics JSONL (--metrics-out of a "
                    "run) or a fleet --obs-dir directory of shards")
    ap.add_argument("--run-id", default=None,
                    help="report this run (default: the most recent)")
    ap.add_argument("--out", default=None, help="write the report here "
                    "instead of stdout")
    ap.add_argument("--lenient", action="store_true",
                    help="note schema/trace-stamping violations instead "
                    "of failing with exit code 3")
    ap.add_argument("--roofline-min-frac", type=float, default=0.5,
                    help="flag superstep_timing windows whose achieved "
                    "throughput is below this fraction of the cost "
                    "model (default 0.5)")
    args = ap.parse_args(argv)
    if os.path.isdir(args.metrics):
        # A fleet --obs-dir: merge every process shard into ONE report
        # view (each record keeps its shard under _src, so the fleet-
        # trace section attributes lines to the emitting process). The
        # fleet is one logical run — per-process run_ids would each
        # select a sliver, so run splitting is skipped.
        records, bad, dir_problems = trace_stitch.load_shards(
            [args.metrics]
        )
        if not records:
            print(
                f"obs_report: no records in {args.metrics}",
                file=sys.stderr,
            )
            return 2
        runs, order = {"fleet": records}, ["fleet"]
        rid = "fleet"
    else:
        dir_problems = None
        try:
            records, bad = load_records(args.metrics)
        except OSError as e:
            print(
                f"obs_report: cannot read {args.metrics}: {e}",
                file=sys.stderr,
            )
            return 2
        if not records:
            print(
                f"obs_report: no records in {args.metrics}",
                file=sys.stderr,
            )
            return 2
        runs, order = split_runs(records)
        rid = args.run_id or order[-1]
    if rid not in runs:
        print(
            f"obs_report: run_id {rid!r} not in {args.metrics} "
            f"(have: {', '.join(order)})", file=sys.stderr,
        )
        return 2
    report = build_report(
        runs[rid], source=args.metrics, bad_lines=bad,
        roofline_min_frac=args.roofline_min_frac,
    )
    if len(order) > 1:
        report += (f"\n({len(order)} runs in this file: "
                   + ", ".join(order) + ")\n")
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
    else:
        sys.stdout.write(report)
    # The post-e2e gate (ISSUE 11 satellite): a stream whose selected
    # run carries unknown phases, records missing required keys, or
    # HALF-STAMPED trace identity (some of run/trace/span ids, not all —
    # those records silently fall out of every timeline join) fails
    # loudly so schema rot can't accumulate between e2e runs.
    # Directory mode reuses the violations load_shards already computed
    # ("shard:line: problem" — _src-stripped there); a single file runs
    # the shared schema sweep once here.
    problems = (
        dir_problems if dir_problems is not None
        else validate_records(runs[rid])
    )
    if problems:
        print(
            f"obs_report: {len(problems)} schema/trace-stamping "
            f"violation(s) in run {rid!r}:", file=sys.stderr,
        )
        for p in problems[:20]:
            print(f"  {p}", file=sys.stderr)
        if len(problems) > 20:
            print(f"  ... and {len(problems) - 20} more", file=sys.stderr)
        if not args.lenient:
            return 3
    # The quality CI gate (ISSUE 13): a stream that ENDS with a firing
    # page-severity alert (the canary scorer-regression rule is the
    # built-in page) fails with exit 4 — distinct from the schema exit 3
    # so CI can tell "the telemetry rotted" from "the scorer regressed".
    # --lenient downgrades both.
    firing = gating_alerts(runs[rid])
    if firing:
        print(
            f"obs_report: {len(firing)} page-severity alert(s) still "
            f"firing at end of stream: {', '.join(firing)}",
            file=sys.stderr,
        )
        if not args.lenient:
            return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
