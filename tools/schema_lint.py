#!/usr/bin/env python
"""Schema-rot lint: every phase literal emitted anywhere in
``graphmine_tpu/`` must be registered in ``obs/schema.py``.

Runtime validation (``validate_records`` over e2e streams) only covers
phases that HAPPEN to fire in a test run — an emit call on a cold path
(a rare failover branch, a fault-only record) can carry a typo'd or
unregistered phase for months before an incident finally exercises it,
and then the triage tooling drops exactly the record the operator
needs. This lint closes that gap statically: it scans the package
source for first-argument string literals of the record-emitting calls
(``.emit("...")``, ``.timed("...")``, ``._emit("...")``) and fails on
any phase missing from the schema registry.

Limitations, by design: phases passed as variables are invisible here —
they remain covered by the runtime validation path (``MetricsSink``
consumers assert ``validate_records == []`` over e2e streams), so the
two checks together cover both shapes.

Usage::

    python tools/schema_lint.py          # exit 1 on violations
    python tools/schema_lint.py --list   # also print every found phase

Wired into tier-1 via
``tests/test_trace.py::test_schema_lint_package_is_clean``.
Stdlib-only.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/schema_lint.py` anywhere
    sys.path.insert(0, _REPO)

from graphmine_tpu.obs.schema import SCHEMAS  # noqa: E402

# First-arg string literal of a record-emitting call. `\s*` crosses
# newlines, so multi-line call formatting is caught; `emit=False`-style
# kwargs don't match (no `(` after the word); `emit_admission(...)`
# doesn't match (the word boundary is inside the identifier).
_EMIT_RE = re.compile(
    r"\b(?:emit|timed|_emit)\(\s*[\"']([A-Za-z_][A-Za-z0-9_]*)[\"']"
)

# Inline cost sub-record construction (ISSUE 12): the `cost` payload has
# ONE builder — obs/costmodel.CostEstimate.record(), whose shape the
# runtime validator pins against schema.COST_KEYS. A hand-rolled
# `cost={...}` / `cost=dict(...)` at an emit site would drift from that
# shape silently on cold paths, exactly the rot this lint exists for.
_INLINE_COST_RE = re.compile(r"\bcost\s*=\s*(?:\{|dict\()")
_COST_OWNER = os.path.join("graphmine_tpu", "obs", "costmodel.py")

# Inline mem sub-record construction (ISSUE 14): the `mem` payload has
# ONE builder — obs/memmodel.MemEstimate.record(), whose shape the
# runtime validator pins against schema.MEM_KEYS. A hand-rolled
# `mem={...}` at an emit site would drift from the memory-plane
# tooling's expectations silently on cold paths — the cost-lint rot
# class, applied to the memory plane.
_INLINE_MEM_RE = re.compile(r"\bmem\s*=\s*(?:\{|dict\()")
_MEM_OWNER = os.path.join("graphmine_tpu", "obs", "memmodel.py")

# Inline sketch sub-record construction (ISSUE 13): `*_sketch` payloads
# have ONE builder — obs/sketch.QuantileSketch.to_state(), whose shape
# the runtime validator pins against schema.SKETCH_KEYS. A hand-rolled
# `lof_sketch={...}` at an emit site would drift from the merge/report
# tooling's expectations silently on cold paths — same rot class as the
# cost lint above.
_INLINE_SKETCH_RE = re.compile(r"\b\w+_sketch\s*=\s*(?:\{|dict\()")
_SKETCH_OWNERS = (
    os.path.join("graphmine_tpu", "obs", "sketch.py"),
    os.path.join("graphmine_tpu", "obs", "quality.py"),
)

# Inline shard-plane record emission (ISSUE 17): the shard_publish /
# epoch_commit / shard_degraded family has ONE builder —
# serve/shardplane.emit_shard_record(), which validates the phase name
# before anything reaches the sink. A raw sink.emit("shard_publish",...)
# elsewhere would bypass that gate and drift from the registered shapes.
_INLINE_SHARD_RE = re.compile(
    r"emit\(\s*[\"'](?:shard_publish|epoch_commit|shard_degraded)[\"']"
)
_SHARD_OWNER = os.path.join("graphmine_tpu", "serve", "shardplane.py")

PACKAGE_DIR = os.path.join(_REPO, "graphmine_tpu")


def scan(root: str = PACKAGE_DIR) -> list:
    """All (phase, file, line) triples of string-literal phase emits."""
    found = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path) as f:
                text = f.read()
            for m in _EMIT_RE.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                found.append((
                    m.group(1), os.path.relpath(path, _REPO), line,
                ))
    return found


def _scan_inline(root, pattern, owners) -> list:
    """``(file, line)`` pairs of an inline sub-record kwarg literal
    outside its owning builder module(s)."""
    found = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, _REPO)
            if rel in owners:
                continue
            with open(path) as f:
                lines = f.readlines()
            for i, raw in enumerate(lines, 1):
                # crude comment strip: good enough for a kwarg lint (a
                # '#' inside a string arg would hide a same-line match,
                # which no real emit call shape does)
                code = raw.split("#", 1)[0]
                if pattern.search(code):
                    found.append((rel, i))
    return found


def scan_inline_costs(root: str = PACKAGE_DIR) -> list:
    """``(file, line)`` pairs of inline ``cost={...}``/``cost=dict(...)``
    literals outside the single builder (obs/costmodel.py)."""
    return _scan_inline(root, _INLINE_COST_RE, (_COST_OWNER,))


def scan_inline_mems(root: str = PACKAGE_DIR) -> list:
    """``(file, line)`` pairs of inline ``mem={...}``/``mem=dict(...)``
    literals outside the single builder (obs/memmodel.py)."""
    return _scan_inline(root, _INLINE_MEM_RE, (_MEM_OWNER,))


def scan_inline_sketches(root: str = PACKAGE_DIR) -> list:
    """``(file, line)`` pairs of inline ``*_sketch={...}`` literals
    outside the sketch builders (obs/sketch.py, obs/quality.py)."""
    return _scan_inline(root, _INLINE_SKETCH_RE, _SKETCH_OWNERS)


def scan_inline_shard_records(root: str = PACKAGE_DIR) -> list:
    """``(file, line)`` pairs of direct shard-plane record emits outside
    the single builder (serve/shardplane.emit_shard_record)."""
    return _scan_inline(root, _INLINE_SHARD_RE, (_SHARD_OWNER,))


def violations(root: str = PACKAGE_DIR) -> list:
    """Emitted-but-unregistered phases plus inline cost sub-records:
    list of human-readable strings (empty = clean). The tier-1 test
    asserts on this."""
    out = [
        f"{path}:{line}: phase {phase!r} is emitted but not registered "
        "in graphmine_tpu/obs/schema.py"
        for phase, path, line in scan(root)
        if phase not in SCHEMAS
    ]
    out.extend(
        f"{path}:{line}: inline cost=... literal — build cost sub-records "
        "with graphmine_tpu/obs/costmodel.py (CostEstimate.record()), the "
        "single shape owner"
        for path, line in scan_inline_costs(root)
    )
    out.extend(
        f"{path}:{line}: inline mem=... literal — build mem sub-records "
        "with graphmine_tpu/obs/memmodel.py (MemEstimate.record()), the "
        "single shape owner"
        for path, line in scan_inline_mems(root)
    )
    out.extend(
        f"{path}:{line}: inline *_sketch=... literal — build sketch "
        "sub-records with graphmine_tpu/obs/sketch.py "
        "(QuantileSketch.to_state()), the single shape owner"
        for path, line in scan_inline_sketches(root)
    )
    out.extend(
        f"{path}:{line}: direct shard-plane record emit — route "
        "shard_publish/epoch_commit/shard_degraded through "
        "graphmine_tpu/serve/shardplane.py (emit_shard_record), the "
        "single builder"
        for path, line in scan_inline_shard_records(root)
    )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--list", action="store_true",
                    help="print every literal phase emit found")
    args = ap.parse_args(argv)
    found = scan()
    if args.list:
        for phase, path, line in found:
            mark = " " if phase in SCHEMAS else "!"
            print(f"{mark} {phase:<24} {path}:{line}")
    bad = violations()
    if bad:
        print(f"schema_lint: {len(bad)} unregistered phase emit(s):",
              file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return 1
    print(
        f"schema_lint: {len(found)} literal phase emit(s), all registered "
        f"({len(SCHEMAS)} phases in the registry)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
