"""Streaming-ingestion stress harness (VERDICT r2 item 4 "Done" clause).

Generates a synthetic SNAP-style edge list of N rows (optionally weighted),
then ingests it in a CHILD process so the recorded peak RSS belongs to the
ingest alone, and prints one JSON line:

    {"rows": ..., "file_bytes": ..., "seconds": ..., "peak_rss_bytes": ...,
     "edges_bytes": ..., "rss_over_edges": ..., "path": "native-chunked"}

The point being proven: peak host memory is O(edges int32 + chunk +
vocabulary) — the r2 ``np.loadtxt(dtype=str)`` path materialized every row
as Python strings (~180 bytes/row, an ~18 GB wall at 100M rows), while the
r3 chunked native parse stays within a small multiple of the int32 edge
arrays themselves. Usage:

    python tools/ingest_stress.py --rows 100000000 --weighted

Keeps nothing: the generated file is deleted unless --keep.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def generate(path: str, rows: int, vertices: int, weighted: bool,
             seed: int = 0, batch: int = 2_000_000) -> int:
    """Write a power-law-ish edge list; returns file size in bytes."""
    rng = np.random.default_rng(seed)
    with open(path, "wb", buffering=1 << 22) as f:
        f.write(b"# synthetic stress edge list\n")
        done = 0
        while done < rows:
            n = min(batch, rows - done)
            raw = rng.pareto(1.2, size=2 * n)
            ids = np.minimum(
                (raw * vertices / 50).astype(np.int64), vertices - 1
            )
            a, b = ids[:n], ids[n:]
            if weighted:
                w = rng.integers(1, 16, n)
                lines = "\n".join(
                    f"{x} {y} {z / 4.0}"
                    for x, y, z in zip(a.tolist(), b.tolist(), w.tolist())
                )
            else:
                lines = "\n".join(
                    f"{x} {y}" for x, y in zip(a.tolist(), b.tolist())
                )
            f.write(lines.encode())
            f.write(b"\n")
            done += n
    return os.path.getsize(path)


def ingest_child(path: str, weight_col: int | None) -> None:
    """Runs in the measured child: ingest + report RSS on stdout."""
    sys.path.insert(0, _REPO)
    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    # Import baseline (the package pulls jax): recorded separately so the
    # ceiling attributable to INGESTION is readable from the record.
    baseline = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    t0 = time.perf_counter()
    # chunk_bytes is passed EXPLICITLY so the measurement is always the
    # streaming path — small files would otherwise take the bulk path and
    # misattribute a bulk-load RSS number as streaming evidence.
    et = load_edge_list(path, weight_col=weight_col, chunk_bytes=64 << 20)
    dt = time.perf_counter() - t0
    ingest_path = (
        "native-chunked" if native.chunked_parse_available()
        else "numpy-chunked"
    )
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    edges_bytes = et.src.nbytes + et.dst.nbytes + (
        et.weights.nbytes if et.weights is not None else 0
    )
    print(json.dumps({
        "edges": int(et.num_edges),
        "vertices": int(et.num_vertices),
        "seconds": round(dt, 2),
        "peak_rss_bytes": peak,
        "baseline_rss_bytes": baseline,
        "edges_bytes": edges_bytes,
        "ingest_rss_over_edges": round(
            (peak - baseline) / max(edges_bytes, 1), 2
        ),
        "path": ingest_path,
    }))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000_000)
    ap.add_argument("--vertices", type=int, default=10_000_000)
    ap.add_argument("--weighted", action="store_true")
    ap.add_argument("--path", default=None)
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--ingest-only", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--weight-col", type=int, default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.ingest_only:
        ingest_child(args.ingest_only, args.weight_col)
        return 0

    path = args.path or os.path.join(
        tempfile.gettempdir(), f"ingest_stress_{args.rows}.txt"
    )
    try:
        t0 = time.perf_counter()
        size = generate(path, args.rows, args.vertices, args.weighted)
        gen_s = time.perf_counter() - t0
        cmd = [sys.executable, os.path.abspath(__file__),
               "--ingest-only", path]
        if args.weighted:
            cmd += ["--weight-col", "2"]
        p = subprocess.run(cmd, capture_output=True, text=True)
        if p.returncode != 0:
            print(json.dumps({"error": (p.stderr or "")[-500:]}))
            return 1
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        rec.update({
            "rows": args.rows,
            "file_bytes": size,
            "gen_seconds": round(gen_s, 1),
            "weighted": args.weighted,
            "rows_per_sec": round(args.rows / max(rec["seconds"], 1e-3)),
        })
        print(json.dumps(rec))
        return 0
    finally:
        if not args.keep and os.path.exists(path) and args.path is None:
            os.unlink(path)


if __name__ == "__main__":
    sys.exit(main())
