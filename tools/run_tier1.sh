#!/usr/bin/env bash
# Tier-1 verification: the exact command pinned in ROADMAP.md.
#
# Runs the full CPU test suite (excluding @slow) with collection errors
# surfaced instead of aborting the run, and prints the passed-dot count
# the roadmap uses as its no-regression floor. The fault-injection suite
# (-m faults: tests/test_resilience.py + the tripwire/reshard cases in
# tests/test_sharded.py) is part of this default pass.
#
# Usage: tools/run_tier1.sh [--faults-only|--obs-only|--ann-only|--serve-only|--slo-only|--blocking-only|--admission-only|--fleet-only|--wal-only|--trace-only|--perf-only|--quality-only|--mem-only|--sharded2d-only|--tenancy-only|--shardplane-only] [extra pytest args...]
#   --shardplane-only run just the `shardplane`-marked sharded-write-
#                  plane suite (tests/test_shardplane.py: range plan
#                  ownership, deterministic delta splitter bit-parity,
#                  epoch stage/commit/recover incl. torn publish, and
#                  the 3-shard/2-tenant shard-kill chaos acceptance) —
#                  the fast slice when iterating on serve/shardplane.py
#   --tenancy-only run just the `tenancy`-marked multi-tenant serving
#                  suite (tests/test_tenancy.py: namespaced stores,
#                  hostile-id refusal, per-tenant bounds + fair apply,
#                  tenant-scoped WAL replay, per-tenant alerting and
#                  the noisy-neighbor chaos acceptance) — the fast
#                  slice when iterating on tenancy
#   --sharded2d-only run just the `sharded2d`-marked 2D-edge-partition
#                  suite (tests/test_sharded2d.py: neighbor-exchange
#                  bit-parity vs the sort oracle, per-peer boundary
#                  index tables, the crossover/env policy pins,
#                  cost/memmodel exact pins, plan-time pre-degrade, the
#                  serve warm-repair 2D e2e and the exchange bench-tier
#                  smoke) — the fast slice when iterating on the 2D
#                  partition or its exchange plan
#   --mem-only     run just the `mem`-marked memory-plane suite
#                  (tests/test_memmodel.py: the HBM footprint inventory
#                  exact against hand-computed tiny plans, the planner
#                  constant derivation, memory_watermark e2e + the
#                  fault-injected OOM degrade join, /statusz + /profilez
#                  memory surfaces, the obs_report memory section and
#                  the bench_diff memory gate) — the fast slice when
#                  iterating on obs/memmodel.py
#   --quality-only run just the `quality`-marked result-quality suite
#                  (tests/test_quality.py: sketch merge associativity,
#                  PSI drift exactness, canary probe recall + injected
#                  scorer regression, alert firing/resolve/flap, the
#                  /alertz + fleet-merge e2e and the obs_report quality
#                  gate) — the fast slice when iterating on obs/sketch,
#                  obs/quality or obs/alerts
#   --perf-only    run just the `perf`-marked compute-plane performance-
#                  observability suite (tests/test_costmodel.py: the
#                  analytical cost model exact against hand-computed
#                  plans, superstep_timing achieved-vs-model e2e,
#                  bench_diff gate + the trajectory self-check over the
#                  committed BENCH_r01–r05 files, bench.py
#                  --list-missing) — the fast slice when iterating on
#                  obs/costmodel.py or tools/bench_diff.py
#   --faults-only  run just the `faults`-marked recovery suite — the fast
#                  pre-commit loop when iterating on resilience paths
#   --obs-only     run just the `obs`-marked tracing/telemetry suite
#                  (tests/test_obs.py: spans, schema validation, heartbeat,
#                  superstep telemetry, obs_report e2e)
#   --ann-only     run just the `ann`-marked approximate-kNN suite
#                  (tests/test_ann.py + tests/test_lof_policy.py: IVF
#                  contract/recall, the LOF auto-policy crossover, and the
#                  recall/AUROC regression gates) — the fast slice when
#                  iterating on the IVF index or its deployment policy
#   --serve-only   run just the `serve`-marked serving suite
#                  (tests/test_serve.py: snapshot round-trip/rollback,
#                  delta repair equivalence, query engine, live-swap
#                  server) — the fast slice when iterating on serve/
#   --blocking-only run just the `blocking`-marked propagation-blocking
#                  suite (tests/test_blocking.py: blocked-vs-sort bit
#                  parity for LPA/CC/PageRank fused + sharded, crossover
#                  policy, plan_build records, bench-tier smoke) — the
#                  fast slice when iterating on ops/blocking.py
#   --slo-only     run just the `slo`-marked serving-SLO suite
#                  (tests/test_slo.py: histograms + merge associativity,
#                  live /metrics + /statusz under the query hammer,
#                  quantile agreement vs the access_log JSONL, repair
#                  debt, request tracing) — the fast slice when
#                  iterating on the SLO observability layer
#   --admission-only run just the `admission`-marked write-path
#                  overload suite (tests/test_admission.py: the
#                  accept/queue/coalesce/shed policy owner, order-exact
#                  coalescing parity, deadline shedding, LOF-defer rung,
#                  and the burst + slow-repair chaos acceptance test) —
#                  the fast slice when iterating on serve/admission.py
#   --fleet-only   run just the `fleet`-marked replicated-serving suite
#                  (tests/test_fleet.py: circuit breakers, quorum
#                  committed-version routing, writer loss = read-only,
#                  rolling reload, the reload-vs-inflight-delta rebase,
#                  serve_cli client retries, and the 3-replica
#                  kill+slow+roll chaos acceptance test) — the fast
#                  slice when iterating on serve/fleet.py
#   --wal-only     run just the `wal`-marked durable-write-path suite
#                  (tests/test_wal.py: WAL framing/torn-tail/rotation/
#                  compaction, epoch fencing, 202 + kill/restart replay,
#                  duplicate-submit idempotency, log-shipped standby +
#                  lag, fenced promotion, and the writer-SIGKILL chaos
#                  acceptance test) — the fast slice when iterating on
#                  serve/wal.py
set -o pipefail
cd "$(dirname "$0")/.."

MARKER='not slow'
if [ "${1:-}" = "--faults-only" ]; then
    shift
    MARKER='faults and not slow'
elif [ "${1:-}" = "--obs-only" ]; then
    shift
    MARKER='obs and not slow'
elif [ "${1:-}" = "--ann-only" ]; then
    shift
    MARKER='ann and not slow'
elif [ "${1:-}" = "--serve-only" ]; then
    shift
    MARKER='serve and not slow'
elif [ "${1:-}" = "--slo-only" ]; then
    shift
    MARKER='slo and not slow'
elif [ "${1:-}" = "--blocking-only" ]; then
    shift
    MARKER='blocking and not slow'
elif [ "${1:-}" = "--admission-only" ]; then
    shift
    MARKER='admission and not slow'
elif [ "${1:-}" = "--fleet-only" ]; then
    shift
    MARKER='fleet and not slow'
elif [ "${1:-}" = "--wal-only" ]; then
    shift
    MARKER='wal and not slow'
elif [ "${1:-}" = "--trace-only" ]; then
    shift
    MARKER='trace and not slow'
elif [ "${1:-}" = "--perf-only" ]; then
    shift
    MARKER='perf and not slow'
elif [ "${1:-}" = "--quality-only" ]; then
    shift
    MARKER='quality and not slow'
elif [ "${1:-}" = "--mem-only" ]; then
    shift
    MARKER='mem and not slow'
elif [ "${1:-}" = "--sharded2d-only" ]; then
    shift
    MARKER='sharded2d and not slow'
elif [ "${1:-}" = "--tenancy-only" ]; then
    shift
    MARKER='tenancy and not slow'
elif [ "${1:-}" = "--shardplane-only" ]; then
    shift
    MARKER='shardplane and not slow'
fi

LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 "${TIER1_TIMEOUT:-870}" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m "$MARKER" \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
