"""Extended cross-path consistency sweep (manual; heavier than CI's fuzz).

Runs the one-answer invariant — every LPA/CC/PageRank/PPR/kNN execution
path agrees — over many random graph shapes and seeds, unweighted AND
weighted, on the virtual 8-device mesh. CI's ``test_consistency_fuzz``
covers 7 pinned cases; this sweeps hundreds. Run before releases or
after touching any superstep/plan/partition code:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \\
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        PYTHONPATH=. python tools/consistency_sweep.py [num_seeds] [first_seed] [--big]

Chunking into fresh processes is AUTOMATIC since r4 (XLA:CPU's LLVM JIT
arena exhausts after a bounded number of unique-shape compilations per
process — and the 1.10x width ladder's extra bucket classes dropped the
per-process ceiling from ~50 to ~20 small-tier seeds): a parent re-execs
the sweep in ``GRAPHMINE_SWEEP_CHUNK``-seed children (default 12 small /
4 big). ``first_seed`` still works for manual ranges.
``--big`` switches to the big-graph tier: fewer, larger cases (2K-40K
vertices) with injected mega-hubs (degree 2500-6000) so the histogram /
wide bucket classes and large ring rotations are exercised.

Exits nonzero on the first disagreement with a full repro line.
This sweep caught a real shard_map scatter miscompile in round 2
(docs/DESIGN.md) and the PPR convergence-coupling gap fixed by the
pmax-coupled stopping rule.
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np


def _cases(num_seeds: int, first_seed: int):
    """Small-graph tier: many shapes, isolates, self-loops, duplicates."""
    for seed in range(first_seed, first_seed + num_seeds):
        rng = np.random.default_rng(seed)
        v = int(rng.integers(8, 700))
        e = int(rng.integers(1, 12 * v))
        shape = rng.choice(["uniform", "powerlaw", "star", "chain"])
        if shape == "uniform":
            src = rng.integers(0, v, e).astype(np.int32)
            dst = rng.integers(0, v, e).astype(np.int32)
        elif shape == "powerlaw":
            raw = rng.pareto(1.1, size=2 * e)
            ids = np.minimum((raw * v / 15).astype(np.int64), v - 1).astype(np.int32)
            src, dst = ids[:e], ids[e:]
        elif shape == "star":
            hub = int(rng.integers(0, v))
            src = np.full(e, hub, np.int32)
            dst = rng.integers(0, v, e).astype(np.int32)
        else:  # chain + noise
            base = np.arange(min(e, v - 1), dtype=np.int32)
            extra = rng.integers(0, v, max(e - len(base), 0)).astype(np.int32)
            src = np.concatenate([base, extra[: max(e - len(base), 0)]])
            dst = np.concatenate(
                [base + 1,
                 rng.integers(0, v, len(src) - len(base)).astype(np.int32)]
            )
        it = int(rng.integers(1, 6))
        weights = None
        if rng.random() < 0.5:
            # ZERO weights included (r3): weights >= 0 are legal, and the
            # all-zero-hub argmax bug (ADVICE r2) lived exactly in the
            # region the old 1/4..15/4 draw never reached.
            weights = (rng.integers(0, 16, len(src)) / 4.0).astype(np.float32)
        tag = (f"seed={seed} v={v} e={len(src)} shape={shape} iters={it} "
               f"weighted={weights is not None}")
        yield tag, src, dst, v, it, weights, rng


def _big_cases(num_seeds: int, first_seed: int):
    """Mega-hub big-graph tier: histogram/wide bucket classes, big rings."""
    for seed in range(first_seed, first_seed + num_seeds):
        rng = np.random.default_rng(7000 + seed)
        v = int(rng.integers(2000, 40000))
        e = int(rng.integers(v, 8 * v))
        hub = rng.integers(0, v, 3).astype(np.int32)
        hub_e = int(rng.integers(2500, 6000))
        src = np.concatenate(
            [rng.integers(0, v, e), np.repeat(hub, hub_e)]
        ).astype(np.int32)
        dst = np.concatenate(
            [rng.integers(0, v, e), rng.integers(0, v, 3 * hub_e)]
        ).astype(np.int32)
        weights = None
        if seed % 2:
            # zero weights included — mega-hubs with all-zero incoming
            # weight exercise the masked histogram argmax (ADVICE r2)
            weights = (rng.integers(0, 16, len(src)) / 4.0).astype(np.float32)
        tag = f"big seed={seed} v={v} e={len(src)} weighted={weights is not None}"
        yield tag, src, dst, v, 3, weights, rng


def sweep(num_seeds: int = 30, first_seed: int = 0, big: bool = False) -> int:
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.census import census_table
    from graphmine_tpu.ops.degrees import out_degrees, out_weights
    from graphmine_tpu.ops.features import (
        vertex_features,
        vertex_features_host,
    )
    from graphmine_tpu.ops.modularity import modularity
    from graphmine_tpu.ops.knn import knn
    from graphmine_tpu.ops.lof import lof_scores
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.ops.pagerank import pagerank, parallel_personalized_pagerank
    from graphmine_tpu.parallel.knn import can_shard, sharded_knn, sharded_lof
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.ppr import sharded_personalized_pagerank
    from graphmine_tpu.parallel.ring import (
        ring_connected_components,
        ring_label_propagation,
        ring_pagerank,
    )
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_connected_components,
        sharded_label_propagation,
        sharded_pagerank,
    )

    d = min(8, len(jax.devices()))
    mesh = make_mesh(d)
    step = jax.jit(lpa_superstep_bucketed)
    gen = _big_cases(num_seeds, first_seed) if big else _cases(num_seeds, first_seed)
    checked = 0
    for tag, src, dst, v, it, weights, rng in gen:
        g = build_graph(src, dst, num_vertices=v, edge_weights=weights)
        want = np.asarray(label_propagation(g, max_iter=it, plan=None))

        g2, plan = build_graph_and_plan(src, dst, num_vertices=v, edge_weights=weights)
        lbl = jnp.arange(v, dtype=jnp.int32)
        for _ in range(it):
            lbl = step(lbl, g2, plan)
        assert np.array_equal(want, np.asarray(lbl)), f"fused != sort: {tag}"

        sgf = shard_graph_arrays(
            partition_graph(g, mesh=mesh, build_bucket_plan=True), mesh
        )
        assert np.array_equal(
            want, np.asarray(sharded_label_propagation(sgf, mesh, max_iter=it))
        ), f"sharded bucketed != sort: {tag}"
        sg = shard_graph_arrays(partition_graph(g, mesh=mesh), mesh)
        assert np.array_equal(
            want, np.asarray(sharded_label_propagation(sg, mesh, max_iter=it))
        ), f"sharded sort != sort: {tag}"
        assert np.array_equal(
            want, np.asarray(ring_label_propagation(sg, mesh, max_iter=it))
        ), f"ring != sort: {tag}"

        cc = np.asarray(connected_components(g))
        assert np.array_equal(
            cc, np.asarray(sharded_connected_components(sg, mesh))
        ), f"sharded cc: {tag}"
        assert np.array_equal(
            cc, np.asarray(ring_connected_components(sg, mesh))
        ), f"ring cc: {tag}"

        # r3 host twins (scale-out mode's paths): census / modularity /
        # features on a host-resident graph must match the device ops.
        gh = build_graph(src, dst, num_vertices=v, edge_weights=weights,
                         to_device=False)
        for a, b in zip(census_table(want, g), census_table(want, gh)):
            assert np.array_equal(a, b), f"host census: {tag}"
        q0 = float(modularity(jnp.asarray(want), g))
        q1 = float(modularity(want, gh))
        assert abs(q0 - q1) < 2e-4, f"host modularity {q0} vs {q1}: {tag}"
        if not big:
            f0 = np.asarray(vertex_features(g, jnp.asarray(want)))
            f1 = vertex_features_host(gh, want, include_clustering=True)
            assert np.allclose(f0, f1, rtol=2e-4, atol=2e-5), (
                f"host features: {tag}"
            )

        gd = build_graph(src, dst, num_vertices=v, symmetric=False,
                         edge_weights=weights)
        sgd = shard_graph_arrays(partition_graph(gd, mesh=mesh), mesh)
        if weights is None:
            pr_want = np.asarray(pagerank(gd, max_iter=40))
            ow = out_degrees(gd)
        else:
            pr_want = np.asarray(pagerank(gd, max_iter=40, weights=jnp.asarray(weights)))
            ow = out_weights(gd)
        pr_s = np.asarray(sharded_pagerank(sgd, mesh, ow, max_iter=40))
        pr_r = np.asarray(ring_pagerank(sgd, mesh, ow, max_iter=40))
        assert np.allclose(pr_s, pr_want, rtol=3e-4, atol=1e-7), f"sharded pr: {tag}"
        assert np.allclose(pr_r, pr_want, rtol=3e-4, atol=1e-7), f"ring pr: {tag}"

        if not big:
            # source-sharded PPR vs the single-device batched op (the pmax
            # coupling makes both iterate in lockstep — tight tolerance)
            n_src = int(rng.integers(1, 12))
            srcs = rng.integers(0, v, n_src).astype(np.int32)
            ppr_want = np.asarray(parallel_personalized_pagerank(gd, srcs, max_iter=25))
            ppr_got = np.asarray(
                sharded_personalized_pagerank(gd, srcs, mesh, max_iter=25)
            )
            assert np.allclose(
                ppr_got, ppr_want, rtol=3e-4, atol=1e-7
            ), f"sharded ppr: {tag}"

            # ring-sharded kNN/LOF vs single-device (random point clouds)
            n_pts = int(rng.integers(d * 3, 400))
            f_dim = int(rng.integers(2, 12))
            k = int(rng.integers(2, min(16, -(-n_pts // d)) + 1))
            if can_shard(n_pts, d, k):
                pts = rng.normal(size=(n_pts, f_dim)).astype(np.float32)
                # one all-pairs pass at k+1: the first k columns are the
                # k-NN answer (top-k prefixes are stable), the extra
                # column feeds the boundary-tie mask below
                kx = min(k + 1, n_pts - 1)
                kd1, ki1 = knn(pts, k=kx, impl="xla")
                kd1, ki1 = np.asarray(kd1), np.asarray(ki1)
                sd = np.asarray(sharded_knn(pts, mesh, k=k, row_tile=32)[0])
                assert np.allclose(
                    sd, kd1[:, :k], rtol=1e-5, atol=1e-5
                ), f"sharded knn d2: {tag}"
                lw = np.asarray(lof_scores(pts, k=k, impl="xla"))
                lg = np.asarray(sharded_lof(pts, mesh, k=k, row_tile=32))
                # LOF is only defined up to kNN tie-breaking: when a row's
                # k-th and (k+1)-th neighbor distances coincide within the
                # paths' ACTUAL distance discrepancy (usually 0 or a few
                # float32 ulps — seed 5018 found an exact boundary tie in
                # a random cloud), the two paths may legitimately keep
                # different neighbor SETS, and the difference propagates
                # two hops (k-distance -> neighbors' lrd -> LOF). Tiered
                # assert: every row must agree tightly UNLESS it sits in
                # the two-hop neighborhood of a boundary tie — a
                # disagreement anywhere else always fails, so the check
                # cannot go vacuous even though one tie at k=14 blankets
                # 2/3 of a 330-point cloud two hops out (seed 6009).
                close = np.isclose(lg, lw, rtol=5e-3, atol=2e-3)
                if not close.all() and kd1.shape[1] > k:
                    ki = ki1[:, :k]
                    gap = kd1[:, k] - kd1[:, k - 1]
                    obs_row = np.abs(sd - kd1[:, :k]).max(axis=1)
                    # the excuse stays honest only while the tie window is
                    # ulp-scale: if the paths' distances ever drift to the
                    # magnitude the allclose above merely tolerates, a
                    # window built on that drift could blanket every row
                    # and excuse a real bug — fail LOUDLY on drift instead.
                    # Per-ROW scale (ADVICE r4): judging every row against
                    # the cloud's LARGEST k-distance would let one
                    # big-scale row excuse genuine drift on a small one.
                    eps32 = np.finfo(np.float32).eps
                    row_scale = np.maximum(kd1[:, k - 1], 1.0)
                    drift = obs_row > 32 * eps32 * row_scale
                    assert not drift.any(), (
                        f"sharded knn d2 drift {obs_row[drift].max():.3g} "
                        f"on {int(drift.sum())} row(s): {tag}"
                    )
                    # 2*obs_row: a row's k-th and (k+1)-th candidates are
                    # each independently perturbed (and the (k+1)-th
                    # column is not in sd to measure)
                    eps_row = 2 * obs_row + 8 * eps32 * (
                        np.maximum(kd1[:, k - 1], 1e-30)
                    )
                    tie = gap <= eps_row
                    amb = tie | tie[ki].any(1)
                    amb |= amb[ki].any(1)
                    close |= amb
                assert close.all(), f"sharded lof: {tag}"

        checked += 1
        if checked % 10 == 0 or big:
            print(f"{checked}/{num_seeds} ok (last: {tag})", flush=True)
    print(f"consistency sweep: all {checked} cases agree across every path")
    return 0


def _chunk_size(big: bool) -> int:
    """Seeds per child process (env-tunable, clamped >= 1 — a zero or
    negative override must not spawn empty children forever)."""
    return max(
        int(os.environ.get("GRAPHMINE_SWEEP_CHUNK", "4" if big else "12")), 1
    )


def _chunked_main(n: int, first: int, big: bool) -> int:
    """Self-chunking driver: re-exec the sweep in fresh child processes
    every ``chunk`` seeds. XLA:CPU's LLVM JIT arena exhausts after a
    bounded number of unique-shape compilations per process ("Cannot
    allocate memory" from execution_engine.cc) — with the r4 1.10x width
    ladder (~3.5x the populated bucket classes per graph) the ceiling
    dropped from ~50 to ~20 small-tier seeds, so chunking is now
    automatic instead of operator folklore."""
    chunk = _chunk_size(big)
    done = 0
    while done < n:
        take = min(chunk, n - done)
        argv = [sys.executable, os.path.abspath(__file__),
                str(take), str(first + done)] + (["--big"] if big else [])
        rc = subprocess.run(argv).returncode
        if rc != 0:
            return rc
        done += take
    print(f"consistency sweep: all {n} cases agree across every path "
          f"(chunked x{chunk})")
    return 0


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if a != "--big"]
    big = "--big" in sys.argv[1:]
    n = int(args[0]) if args else 30
    first = int(args[1]) if len(args) > 1 else 0
    if os.environ.get("_GRAPHMINE_SWEEP_CHILD") == "1" or n <= _chunk_size(big):
        sys.exit(sweep(n, first, big))
    os.environ["_GRAPHMINE_SWEEP_CHILD"] = "1"  # children run directly
    sys.exit(_chunked_main(n, first, big))
