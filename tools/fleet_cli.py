#!/usr/bin/env python
"""Fleet CLI: run a replicated serving fleet behind one router.

The operator surface of ``graphmine_tpu/serve/fleet.py``
(docs/SERVING.md "Fleet") — the first multi-process subsystem in the
tree::

    # publish a snapshot first (pipeline --snapshot-out, or serve_cli)
    python tools/fleet_cli.py up --store /data/snap --replicas 3 \
        --port 8400 --metrics-out /data/fleet_metrics.jsonl

    python tools/fleet_cli.py status  --url http://127.0.0.1:8400
    python tools/fleet_cli.py roll    --url http://127.0.0.1:8400
    python tools/fleet_cli.py promote --url http://127.0.0.1:8400

``up`` spawns N replica *processes* (``serve_cli.py serve``, each its
own port off ``--replica-base-port``) over ONE shared snapshot store,
waits for each to answer ``/healthz``, and runs the router in the
foreground until interrupted — replica 0 is the designated writer
(single-publisher contract). With ``--standby`` (needs >= 2 replicas)
the writer runs WAL-durable (``serve --wal``) and replica 1 runs as its
log-shipped standby (``--standby-of`` + ``--primary-wal``); writer loss
then auto-promotes the standby behind the store's epoch fence instead
of leaving the fleet read-only (docs/SERVING.md "Replicated writers").
Without ``--standby``, writer loss = read-only fleet, never
split-brain, as before. ``status`` prints the router's ``/fleetz``
(per-replica state/version/breaker, committed version, writer/standby/
epoch, read-only verdict); ``roll`` triggers the zero-downtime rolling
reload (drain → /reload → re-probe → rejoin, one replica at a time,
writer last) after an external publish; ``promote`` forces the
standby-to-writer failover manually (RUNBOOKS §10).

Clients talk to the router exactly like a single server —
``serve_cli.py query/delta --url http://host:PORT`` gets the
consistent-version routing, retries, and 503+Retry-After semantics for
free. Fleet knobs follow the ``GRAPHMINE_FLEET_*`` env convention
(serve/fleet.py ``FleetConfig``).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
import urllib.error
import urllib.request

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/fleet_cli.py` from anywhere
    sys.path.insert(0, _REPO)


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_healthz(host: str, port: int, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if _get_json(f"http://{host}:{port}/healthz", 2.0).get("ok"):
                return True
        except Exception:  # noqa: BLE001 — still starting
            pass
        time.sleep(0.2)
    return False


def cmd_up(args) -> int:
    import signal

    from graphmine_tpu.obs.spans import Tracer
    from graphmine_tpu.pipeline.metrics import MetricsSink
    from graphmine_tpu.serve.fleet import FleetRouter, ReplicaSpec

    # SIGTERM (docker stop, a supervisor, subprocess.terminate) must run
    # the same teardown as Ctrl-C — otherwise the replica child
    # processes leak past the router's death.
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))

    if args.standby and args.replicas < 2:
        print("fleet_cli: --standby needs at least 2 replicas",
              file=sys.stderr)
        return 2

    procs: list = []
    router = None
    serve_cli = f"{_REPO}/tools/serve_cli.py"

    def wal_dir(i: int) -> str:
        return f"{args.store.rstrip('/')}/wal-replica-{i}"

    try:
        for i in range(args.replicas):
            port = args.replica_base_port + i
            cmd = [
                sys.executable, serve_cli, "serve",
                "--store", args.store, "--host", args.host,
                "--port", str(port),
            ]
            if args.standby and i == 0:
                cmd += ["--wal", wal_dir(0)]
            elif args.standby and i == 1:
                cmd += [
                    "--wal", wal_dir(1),
                    "--standby-of",
                    f"http://{args.host}:{args.replica_base_port}",
                    "--primary-wal", wal_dir(0),
                ]
            if args.obs_dir:
                cmd += ["--obs-dir", args.obs_dir]
            elif args.metrics_out:
                cmd += ["--metrics-out", f"{args.metrics_out}.replica{i}"]
            procs.append(subprocess.Popen(cmd))
        for i in range(args.replicas):
            port = args.replica_base_port + i
            if not _wait_healthz(args.host, port, args.startup_timeout_s):
                print(
                    f"fleet_cli: replica {i} on port {port} never answered "
                    f"/healthz within {args.startup_timeout_s:g}s",
                    file=sys.stderr,
                )
                return 2
        sink = None
        if args.obs_dir:
            from graphmine_tpu.pipeline.metrics import shard_sink

            sink = shard_sink(args.obs_dir, "router", max_records=100_000)
        elif args.metrics_out:
            sink = MetricsSink(stream_path=args.metrics_out, tracer=Tracer())
            sink.max_records = 100_000
        specs = [
            ReplicaSpec(f"replica-{i}", args.host, args.replica_base_port + i)
            for i in range(args.replicas)
        ]
        router = FleetRouter(
            specs, writer="replica-0", host=args.host, port=args.port,
            sink=sink,
            standby="replica-1" if args.standby else None,
        )
        host, port = router.start()
        print(
            f"fleet: {args.replicas} replica(s) behind http://{host}:{port} "
            f"(writer replica-0 on port {args.replica_base_port}"
            + (", standby replica-1 log-shipping its WAL"
               if args.standby else "")
            + ")",
            file=sys.stderr,
        )
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return 0


def cmd_status(args) -> int:
    try:
        out = _get_json(f"{args.url.rstrip('/')}/fleetz")
    except (urllib.error.URLError, OSError) as e:
        print(f"fleet_cli: router unreachable at {args.url}: {e}",
              file=sys.stderr)
        return 2
    if getattr(args, "shards", False):
        # sharded-write-plane view (r17): collapse each replica's probed
        # epoch + per-range version vector into a range table — "which
        # epoch is served, which range is behind or read-only" at a
        # glance (RUNBOOKS §17)
        out = {
            "committed_version": out.get("committed_version"),
            "writer": out.get("writer"),
            "read_only": out.get("read_only"),
            "replicas": [
                {
                    "id": r.get("id"),
                    "state": r.get("state"),
                    "writer": r.get("writer"),
                    "writer_shards": r.get("writer_shards"),
                    "epoch": r.get("epoch"),
                    "shard_versions": r.get("shard_versions"),
                    "degraded_shards": r.get("degraded_shards"),
                }
                for r in out.get("replicas", [])
            ],
        }
        print(json.dumps(out, indent=1))
        return 0
    if args.tenant:
        # per-tenant view: collapse each replica's tenant_versions map
        # (the prober's /healthz payload) to the one namespace asked for
        # — the roll/catch-up story for a single tenant at a glance
        out = {
            "tenant": args.tenant,
            "committed_version": out.get("committed_version"),
            "read_only": out.get("read_only"),
            "replicas": [
                {
                    "id": r.get("id"),
                    "state": r.get("state"),
                    "version": (r.get("tenant_versions") or {}).get(
                        args.tenant
                    ),
                    "writer": r.get("writer"),
                }
                for r in out.get("replicas", [])
            ],
        }
    print(json.dumps(out, indent=1))
    return 0


def _post_router(args, path: str) -> int:
    req = urllib.request.Request(
        f"{args.url.rstrip('/')}{path}", data=b"{}", method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=args.timeout_s) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        out = json.loads(e.read())
    except (urllib.error.URLError, OSError) as e:
        print(f"fleet_cli: router unreachable at {args.url}: {e}",
              file=sys.stderr)
        return 2
    print(json.dumps(out, indent=1))
    return 0 if out.get("ok") else 1


def cmd_roll(args) -> int:
    return _post_router(args, "/roll")


def cmd_promote(args) -> int:
    return _post_router(args, "/promote")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("up", help="spawn N replica processes + the router")
    p.add_argument("--store", required=True, help="shared snapshot store")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8400,
                   help="the router's port (clients talk here)")
    p.add_argument("--replica-base-port", type=int, default=8450,
                   help="replica i listens on base+i")
    p.add_argument("--obs-dir", default=None,
                   help="federated metrics plane: router + every replica "
                        "stream their records to per-process shards "
                        "(<role>-<pid>.jsonl) under this directory — "
                        "point tools/trace_stitch.py at it for "
                        "cross-process trace timelines")
    p.add_argument("--metrics-out", default=None,
                   help="router records here; replica i appends to "
                        "PATH.replicaI")
    p.add_argument("--startup-timeout-s", type=float, default=60.0)
    p.add_argument("--standby", action="store_true",
                   help="run replica-0 WAL-durable and replica-1 as its "
                        "log-shipped standby; writer loss auto-promotes "
                        "behind the store's epoch fence instead of going "
                        "read-only")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("status", help="print the router's /fleetz")
    p.add_argument("--url", required=True, help="router base URL")
    p.add_argument("--tenant", default=None,
                   help="collapse the view to one tenant namespace: "
                        "per-replica versions for that tenant only "
                        "(docs/SERVING.md 'Multi-tenant serving')")
    p.add_argument("--shards", action="store_true",
                   help="collapse the view to the sharded write plane: "
                        "per-replica committed epoch + per-range version "
                        "vector + degraded ranges (docs/SERVING.md "
                        "'Sharded write plane')")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("roll", help="trigger a zero-downtime rolling reload")
    p.add_argument("--url", required=True, help="router base URL")
    p.add_argument("--timeout-s", type=float, default=300.0)
    p.set_defaults(fn=cmd_roll)

    p = sub.add_parser(
        "promote",
        help="force the standby-to-writer failover (RUNBOOKS §10: read "
             "the promotion timeline before forcing writes)",
    )
    p.add_argument("--url", required=True, help="router base URL")
    p.add_argument("--timeout-s", type=float, default=300.0)
    p.set_defaults(fn=cmd_promote)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
