"""Hardware-proven checkpoint/resume (VERDICT r4 item 8).

Four rounds tested recovery on CPU meshes only; this tool proves it on
the real accelerator by interrupting an actual pipeline run the way a
preempted TPU job dies — SIGKILL from outside, no atexit, no cleanup —
then resuming from the surviving npz checkpoint:

  1. FRESH    full pipeline run (CLI, checkpointed) — the oracle labels
              and the fresh wall-clock.
  2. KILLED   same run; the parent polls for the first checkpoint file
              and SIGKILLs the process mid-LPA (cadence=1 saves every
              superstep, so the kill lands between supersteps k and 20).
  3. RESUMED  same run with ``--resume``: picks up at iteration k from
              the npz (fingerprint-checked against this exact graph),
              finishes, and must produce labels BYTE-IDENTICAL to the
              fresh run — LPA is deterministic, so resume-then-finish
              and run-straight-through are the same trajectory.

The dataset is the e2e bench tier's 25M-edge string-domain parquet
(``bench.main_e2e``): big enough that supersteps are real device work,
small enough to generate in-tool. The reference has no recovery story at
all (``persist()`` at ``Graphframes.py:82`` is in-memory caching);
SURVEY §5 names checkpoint/resume as the failure-recovery subsystem.

Prints ONE JSON line; exit 0 iff labels match bit-exactly. Run on a live
TPU window (scrubbed-CPU runs prove only the CPU path again):

    python tools/tpu_resume_check.py
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

MAX_ITER = 20  # wider kill window than the parity default of 5


def _make_dataset(tmp: str) -> str:
    import pyarrow as pa
    import pyarrow.parquet as pq

    sys.path.insert(0, _REPO)
    from bench import powerlaw_edges

    v, e = 1 << 18, 25_000_000
    src, dst = powerlaw_edges(v, e, seed=9)
    names = pa.array([f"d{i:07d}.example" for i in range(v)])
    col = lambda ids: pa.DictionaryArray.from_arrays(
        pa.array(ids, pa.int32()), names
    ).cast(pa.string())
    path = os.path.join(tmp, "edges.parquet")
    pq.write_table(pa.table({"_c1": col(src), "_c2": col(dst)}), path)
    return path


def _cli(data: str, ckpt_dir: str, resume: bool = False) -> list[str]:
    argv = [
        sys.executable, "-m", "graphmine_tpu.pipeline",
        "--data-path", data,
        "--batch-rows", "4000000",
        "--max-iter", str(MAX_ITER),
        "--outlier-method", "none",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-every", "1",
    ]
    if resume:
        argv.append("--resume")
    return argv


def _ckpt_artifacts(ckpt_dir: str) -> list[str]:
    """Paths whose existence marks a landed checkpoint: the npz
    (single-device runs) or the sharded manifest (multi-device runs write
    the manifest format since ISSUE 2)."""
    return [
        os.path.join(ckpt_dir, "lpa_labels.npz"),
        os.path.join(ckpt_dir, "lpa_sharded", "manifest.json"),
    ]


def _load_ckpt(ckpt_dir: str):
    """Newest state across both checkpoint formats — the same
    checkpoint.load_newest the driver's --resume uses, so this tool can
    never accept a checkpoint the driver would reject."""
    from graphmine_tpu.pipeline import checkpoint as ckpt

    out = ckpt.load_newest(ckpt_dir)
    if out is None:
        raise FileNotFoundError(f"no checkpoint in {ckpt_dir!r}")
    labels, it = out
    return np.asarray(labels), it


def main() -> int:
    import jax

    device = str(jax.devices()[0])
    tmp = tempfile.mkdtemp(prefix="graphmine_resume_")
    try:
        data = _make_dataset(tmp)
        dirs = {k: os.path.join(tmp, k) for k in ("fresh", "killed")}

        # 1. fresh straight-through run
        t0 = time.perf_counter()
        subprocess.run(
            _cli(data, dirs["fresh"]), check=True, cwd=_REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        fresh_s = time.perf_counter() - t0
        want, it = _load_ckpt(dirs["fresh"])
        assert it == MAX_ITER, it

        # 2. killed run: SIGKILL as soon as the first checkpoint lands
        # (plus one beat so the kill interrupts a LIVE superstep)
        marks = _ckpt_artifacts(dirs["killed"])
        p = subprocess.Popen(
            _cli(data, dirs["killed"]), cwd=_REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = time.time() + 1200
        while not any(os.path.exists(mk) for mk in marks) and time.time() < deadline:
            if p.poll() is not None:
                raise RuntimeError(
                    f"run finished (rc={p.returncode}) before the kill — "
                    "checkpoint never appeared"
                )
            time.sleep(0.02)
        time.sleep(0.5)
        p.send_signal(signal.SIGKILL)
        p.wait()
        _, killed_at = _load_ckpt(dirs["killed"])
        if killed_at >= MAX_ITER:
            raise RuntimeError(
                f"kill landed after the final superstep (iteration "
                f"{killed_at}) — nothing left to resume; rerun"
            )

        # 3. resume the killed run to completion
        t0 = time.perf_counter()
        subprocess.run(
            _cli(data, dirs["killed"], resume=True), check=True, cwd=_REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        resumed_s = time.perf_counter() - t0
        got, it = _load_ckpt(dirs["killed"])
        assert it == MAX_ITER, it

        identical = bool(np.array_equal(got, want))
        print(json.dumps({
            "metric": "checkpoint_resume_labels_identical",
            "value": 1.0 if identical else 0.0,
            "unit": "bool",
            "vs_baseline": 1.0 if identical else 0.0,
            "detail": {
                "num_edges": 25_000_000,
                "max_iter": MAX_ITER,
                "interrupted_after_iteration": killed_at,
                "fresh_wall_seconds": round(fresh_s, 2),
                "resumed_wall_seconds": round(resumed_s, 2),
                "communities": int(len(np.unique(want))),
                "device": device,
            },
        }), flush=True)
        return 0 if identical else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
