"""Cross-backend numerical audit: default (TPU) vs CPU, same inputs.

CI forces 8 virtual CPU devices (tests/conftest.py), so a TPU-only
miscompile passes the suite silently — exactly what happened to the first
betweenness kernel: a ``[M, b]`` segment_sum chained across supersteps
compiled to zeros on the TPU backend while every test stayed green (see
``ops/centrality.py:_brandes_tile`` and docs/DESIGN.md). Run this on a
machine with the real accelerator after touching any lane-batched or
iterated segment-op kernel:

    python tools/tpu_backend_audit.py

Exits nonzero on any mismatch.
"""

import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # `python tools/...` puts tools/ on the path, not the repo
    sys.path.insert(0, _REPO)

REF_PATH = "/tmp/graphmine_cpu_ref.npz"

_COMPUTE = """
import numpy as np
import graphmine_tpu as gm

def compute():
    rng = np.random.default_rng(0)
    v, e = 300, 1500
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = gm.build_graph(src, dst, num_vertices=v)
    gd = gm.build_graph(src, dst, num_vertices=v, symmetric=False)
    # bucketed-min CC (r5): the fused-plan superstep path the cc bench
    # tier headlines — audited against CPU like every other kernel
    from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan

    gp, plan = build_graph_and_plan(src, dst, num_vertices=v)
    w = rng.uniform(0.1, 2.0, e).astype(np.float32)
    labels = gm.label_propagation(g, max_iter=5)
    h, a = gm.hits(gd)
    # kNN/LOF at k=8: impl="auto" resolves to the fused Pallas kernel on
    # TPU and the XLA path on CPU *only for k <= 8* (the r5-measured
    # policy, ops/knn.py), so both rows are real-hardware Pallas-vs-XLA
    # checks — at any larger k they would silently become vacuous
    # XLA-vs-XLA comparisons. (kNN indices are excluded: near-tie
    # orderings may legitimately differ across backends.)
    from graphmine_tpu.ops.knn import knn
    from graphmine_tpu.ops.lof import lof_scores

    pts = rng.normal(size=(512, 8)).astype(np.float32)
    knn_d2, _ = knn(pts, k=8, impl="auto")

    # One shard_map output (VERDICT r4 item 1): the distributed LPA body
    # on a 1-device mesh of whatever backend this process has — on the
    # real TPU this is the first-ever silicon execution class for the
    # shard_map programs, which CPU CI can never de-risk (the r4 Mosaic
    # compile blowup and MXU rounding bugs were both invisible there).
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    mesh = make_mesh(1)
    sg = shard_graph_arrays(
        partition_graph(g, mesh=mesh, build_bucket_plan=True), mesh
    )
    sharded_lpa = sharded_label_propagation(sg, mesh, max_iter=5)

    # IVF-LOF, fused AND mesh-sharded (r6): the deployed large-cloud LOF
    # path (ops/lof.py auto-policy) and its distributed twin. Blob data,
    # not gaussian: the k-means assignment step runs on device, and on a
    # near-tie cloud a backend's last-ulp rounding could flip a border
    # point's cluster — a DIFFERENT candidate set, not a numerics bug.
    # Well-separated blobs keep assignment margins far above float
    # jitter, so these rows compare numerics, not tie-breaks.
    from graphmine_tpu.parallel.knn import sharded_lof

    blob_c = rng.normal(size=(8, 8)).astype(np.float32) * 4
    blob_pts = (
        blob_c[rng.integers(0, 8, 2048)]
        + rng.normal(size=(2048, 8)).astype(np.float32)
    )
    ivf_lof_fused = lof_scores(blob_pts, k=8, impl="ivf")
    ivf_lof_sharded = sharded_lof(blob_pts, mesh, k=8, impl="ivf")
    return {
        "lpa": np.asarray(labels),
        "cc": np.asarray(gm.connected_components(g)),
        "cc_bucketed": np.asarray(
            gm.connected_components(gp, plan=plan)
        ),
        "sp": np.asarray(gm.shortest_paths(
            g, np.arange(16, dtype=np.int32), direction="both",
            landmark_batch=5)),
        "wsp": np.asarray(gm.weighted_shortest_paths(
            g, np.arange(4, dtype=np.int32), w, direction="both")),
        "ppr": np.asarray(gm.parallel_personalized_pagerank(
            gd, np.arange(6, dtype=np.int32))),
        "closeness": np.asarray(gm.closeness_centrality(
            g, vertices=np.arange(12, dtype=np.int32))),
        "bc": np.asarray(gm.betweenness_centrality(
            g, sources=np.arange(20, dtype=np.int32), source_batch=7)),
        "hits_h": np.asarray(h),
        "hits_a": np.asarray(a),
        "pagerank": np.asarray(gm.pagerank(gd, max_iter=50)),
        "knn_d2": np.asarray(knn_d2),
        "lof": np.asarray(lof_scores(pts, k=8)),
        "sharded_lpa": np.asarray(sharded_lpa),
        "ivf_lof_fused": np.asarray(ivf_lof_fused),
        "ivf_lof_sharded": np.asarray(ivf_lof_sharded),
    }
"""


def main() -> int:
    # CPU reference in a subprocess (JAX_PLATFORMS must be set pre-import)
    code = _COMPUTE + f"""
np.savez({REF_PATH!r}, **compute())
print("cpu reference written")
"""
    # Full scrub, not just JAX_PLATFORMS: the axon sitecustomize hook would
    # otherwise route the "CPU reference" child to the TPU too, making the
    # audit vacuously compare TPU against itself.
    import __graft_entry__

    env = __graft_entry__._load_envscrub().virtual_cpu_env(1)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, "-c", code], check=True, env=env)

    ns: dict = {}
    exec(_COMPUTE, ns)  # default backend (the accelerator) in this process
    got = ns["compute"]()
    ref = np.load(REF_PATH)
    bad = []
    for k, dev_val in got.items():
        ok = np.allclose(dev_val, ref[k], rtol=1e-4, atol=1e-5)
        print(f"{k:10s} TPU==CPU: {ok}")
        if not ok:
            diff = np.max(np.abs(dev_val.astype(np.float64) - ref[k].astype(np.float64)))
            print(f"           max abs diff: {diff}")
            bad.append(k)
    if bad:
        print(f"MISMATCH on: {bad}", file=sys.stderr)
        return 1
    print("all backends agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
