#!/usr/bin/env python
"""Serving CLI: run the snapshot query server, query it, ingest deltas.

The operator surface of ``graphmine_tpu/serve/`` (docs/SERVING.md)::

    # publish a snapshot from a pipeline run first:
    python -m graphmine_tpu.pipeline --snapshot-out /data/snap ...

    python tools/serve_cli.py info  --store /data/snap
    python tools/serve_cli.py query --store /data/snap --vertex 12 44 7
    python tools/serve_cli.py query --store /data/snap --community 3 --topk 5
    python tools/serve_cli.py delta --store /data/snap \
        --insert 10,11 --insert 11,12 --delete 3,4
    python tools/serve_cli.py serve --store /data/snap --port 8337 \
        --metrics-out /data/serve_metrics.jsonl --prom-out /data/serve.prom

``serve`` runs until interrupted; ``query``/``delta``/``info`` are
one-shot in-process operations against the store directory (no server
needed). Every subcommand that mutates or resolves emits the serving
records (``query_batch`` / ``delta_apply`` / ``snapshot_publish``) —
point ``tools/obs_report.py`` at ``--metrics-out`` for the joined view.

**HTTP client mode** (r10): ``query`` and ``delta`` take ``--url`` to
talk to a running server or fleet router instead of the store
directory — with client-side resilience: a 503 (admission shed, fleet
unavailable) is retried up to ``--max-retries`` times with
decorrelated-jitter backoff (the r3 retry policy), honoring the
server's ``Retry-After`` hint, and ``--deadline-ms`` bounds the whole
exchange AND propagates as ``X-Deadline-Ms`` so the server/router sheds
work the client has stopped waiting for::

    python tools/serve_cli.py delta --url http://127.0.0.1:8400 \
        --insert 10,11 --deadline-ms 5000 --max-retries 4
    python tools/serve_cli.py query --url http://127.0.0.1:8400 --vertex 12 44

**Durable writes** (r11, docs/SERVING.md "Replicated writers"):
``serve --wal DIR`` makes accepted deltas WAL-durable before the
acknowledgement (replayed on restart); ``serve --wal DIR --standby-of
URL [--primary-wal DIR]`` runs the log-shipped standby. ``delta --url``
always sends ONE ``X-Delta-Id`` idempotency key reused across retries
(a retry after a lost response dedupes server-side, never
double-applies), and ``--ack-wal`` asks for the 202-at-durability
acknowledgement instead of blocking to the publish.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
import urllib.error
import urllib.request

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/serve_cli.py` from anywhere
    sys.path.insert(0, _REPO)


def request_with_retries(
    url: str,
    payload: dict | None = None,
    deadline_ms: int | None = None,
    max_retries: int = 4,
    timeout_s: float = 30.0,
    sleep=time.sleep,
    rng: random.Random | None = None,
    headers: dict | None = None,
) -> dict:
    """One HTTP exchange (POST ``payload``, or GET when ``payload`` is
    None) with bounded client-side resilience.

    Retries 503s (admission sheds, fleet-unavailable) and transport
    failures up to ``max_retries`` extra attempts. The delay before
    attempt ``n`` is the r3 decorrelated-jitter backoff
    (:func:`~graphmine_tpu.pipeline.resilience.backoff_s`, seeded per
    process so a fleet of clients never retries in lockstep), floored by
    the server's ``Retry-After`` hint when one came back — the client
    obeys the server's own estimate of when capacity returns instead of
    hammering through it. ``deadline_ms`` bounds the WHOLE exchange and
    rides every attempt as ``X-Deadline-Ms`` (the r9 deadline semantics
    end-to-end): the server sheds a batch still queued past the budget,
    and the client stops retrying when the budget is gone.

    ``headers`` ride EVERY attempt verbatim — ``cmd_delta`` passes one
    ``X-Delta-Id`` idempotency key generated once per logical request,
    so a retry after a lost acknowledgement (the 202/200 never arrived)
    dedupes server-side in the WAL instead of double-applying
    (docs/SERVING.md "Replicated writers"; pinned by
    tests/test_wal.py).

    Returns ``{"status", "body", "headers", "attempts"}``; transport
    failures with no retries left return ``status: 0`` with the error
    under ``body["error"]``.
    """
    from graphmine_tpu.pipeline.resilience import ResilienceConfig, backoff_s

    policy = ResilienceConfig(backoff_base_s=0.2, backoff_max_s=5.0)
    rng = rng if rng is not None else random.Random(
        f"serve_cli:{os.getpid()}"
    )
    deadline = (
        time.monotonic() + deadline_ms / 1000.0
        if deadline_ms is not None else None
    )
    attempt = 0
    base_headers = dict(headers or {})
    while True:
        attempt += 1
        headers = {"Content-Type": "application/json", **base_headers}
        attempt_timeout = timeout_s
        if deadline is not None:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0 and attempt > 1:
                return last  # noqa: F821 — set on every prior iteration
            remaining_ms = max(1, remaining_ms)
            headers["X-Deadline-Ms"] = str(remaining_ms)
            attempt_timeout = min(timeout_s, remaining_ms / 1000.0)
        req = urllib.request.Request(
            url,
            data=None if payload is None else json.dumps(payload).encode(),
            headers=headers,
        )
        resp_headers: dict = {}
        try:
            with urllib.request.urlopen(req, timeout=attempt_timeout) as r:
                status, raw, resp_headers = r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            status, raw, resp_headers = e.code, e.read(), dict(e.headers)
        except Exception as e:  # noqa: BLE001 — transport weather: retryable
            status, raw = 0, json.dumps({"error": repr(e)}).encode()
        try:
            body = json.loads(raw.decode()) if raw else {}
        except ValueError:
            body = {"error": raw.decode(errors="replace")}
        last = {
            "status": status, "body": body, "headers": resp_headers,
            "attempts": attempt,
        }
        if status not in (0, 503) or attempt > max_retries:
            return last
        delay = backoff_s(policy, attempt, rng)
        retry_after = resp_headers.get("Retry-After", "")
        if retry_after:
            try:
                delay = max(delay, float(retry_after))
            except ValueError:
                pass
        if deadline is not None:
            budget = deadline - time.monotonic()
            if budget <= 0:
                return last
            delay = min(delay, budget)
        sleep(delay)


def _sink(args):
    from graphmine_tpu.obs.spans import Tracer
    from graphmine_tpu.pipeline.metrics import MetricsSink, shard_sink

    obs_dir = getattr(args, "obs_dir", None)
    if obs_dir:
        # the federated metrics plane: this process's records land in
        # its own shard under --obs-dir (trace_stitch joins the dir)
        role = getattr(args, "cmd", None) or "serve"
        if role == "serve" and getattr(args, "standby_of", None):
            role = "standby"
        return shard_sink(obs_dir, role)
    return MetricsSink(
        stream_path=getattr(args, "metrics_out", None), tracer=Tracer()
    )


def _store(args):
    from graphmine_tpu.serve.snapshot import SnapshotStore

    store = SnapshotStore(args.store)
    tenant = getattr(args, "tenant", None)
    if tenant:
        # local mode scopes to the tenant's namespace directly; HTTP
        # mode sends X-Tenant-Id instead (the server does the remap)
        store = store.for_tenant(tenant)
    return store


def cmd_info(args) -> int:
    store = _store(args)
    snap = store.load()
    if snap is None:
        print(f"serve_cli: store at {args.store!r} is empty", file=sys.stderr)
        return 2
    out = {
        **snap.meta,
        "arrays": {k: list(v.shape) for k, v in snap.arrays.items()},
    }
    # Sharded write plane (r17): when the store carries an epochs/
    # directory, report the committed epoch, its per-range version
    # vector and the range table from the durable publish_epoch record
    # — the offline twin of /healthz's epoch + shard_versions.
    shards = _shardplane_info(store, snap)
    if shards is not None:
        out["shardplane"] = shards
    print(json.dumps(out, indent=1, default=str))
    return 0


def _shardplane_info(store, snap):
    """The store's committed-epoch view, read straight off disk via the
    coordinator (no server needed). None when the store has never run
    under writer_shards > 1."""
    import os as _os

    from graphmine_tpu.serve.shardplane import (
        EpochCoordinator,
        ShardPlan,
        SHARDS_DIRNAME,
    )
    from graphmine_tpu.serve.snapshot import EPOCHS_DIRNAME
    from graphmine_tpu.serve.wal import WriteAheadLog

    if not _os.path.isdir(_os.path.join(store.root, EPOCHS_DIRNAME)):
        return None
    coord = EpochCoordinator(
        store, ShardPlan.build(1, int(len(snap["labels"])))
    )
    epoch = coord.committed_epoch()
    rec = coord._read_record(coord._record_path(epoch)) if epoch else None
    out = {
        "committed_epoch": epoch,
        "version_vector": {
            str(k): v for k, v in coord.version_vector(epoch).items()
        },
        "ranges": (rec or {}).get("ranges", []),
        "num_shards": (rec or {}).get("num_shards"),
    }
    # per-shard WAL lag: open each range's log read-only and report its
    # last vs applied seq (the "which range is behind" column)
    wals = {}
    base = _os.path.join(store.root, SHARDS_DIRNAME)
    if _os.path.isdir(base):
        for name in sorted(_os.listdir(base)):
            wal_dir = _os.path.join(base, name, "wal")
            if not _os.path.isdir(wal_dir):
                continue
            try:
                wal = WriteAheadLog(wal_dir, read_only=True)
                s = wal.snapshot()
                wals[name] = {
                    "last_seq": s.get("last_seq"),
                    "applied_seq": s.get("applied_seq"),
                    "pending_entries": s.get("pending_entries"),
                }
                wal.close()
            except (OSError, ValueError):
                wals[name] = {"error": "unreadable"}
    if wals:
        out["shard_wals"] = wals
    return out


def cmd_query(args) -> int:
    from graphmine_tpu.serve.query import QueryEngine
    from graphmine_tpu.serve.server import _jsonable

    if args.url:
        base = args.url.rstrip("/")
        kw = {
            "deadline_ms": args.deadline_ms,
            "max_retries": args.max_retries,
        }
        if args.tenant:
            kw["headers"] = {"X-Tenant-Id": args.tenant}
        merged: dict = {}
        calls = []
        if args.vertex:
            calls.append(
                (f"{base}/query", {"vertices": list(args.vertex)}, None)
            )
        if args.neighbors is not None:
            calls.append((f"{base}/neighbors?v={args.neighbors}", None, None))
        if args.explain is not None:
            # nested under "explain" (the local-mode shape): /explain's
            # body shares keys ("vertex", "neighbors") with the other
            # calls and a flat merge would clobber their answers
            calls.append(
                (f"{base}/explain?vertex={args.explain}", None, "explain")
            )
        if args.community is not None:
            calls.append((
                f"{base}/topk?community={args.community}&k={args.topk}",
                None, None,
            ))
        if not calls:  # bare `query --url`: still resolve something
            calls.append((f"{base}/query", {"vertices": []}, None))
        worst, attempts = 200, 0
        for call_url, payload, nest in calls:
            out = request_with_retries(call_url, payload, **kw)
            attempts += out["attempts"]
            if out["status"] != 200:
                worst = out["status"]
            if nest is not None:
                merged[nest] = out["body"]
            else:
                merged.update(out["body"])
        print(json.dumps({
            "status": worst, "attempts": attempts, **merged,
        }))
        return 0 if worst == 200 else 1

    sink = _sink(args)
    snap = _store(args).load(sink=sink)
    if snap is None:
        print(f"serve_cli: store at {args.store!r} is empty", file=sys.stderr)
        return 2
    eng = QueryEngine(snap)
    out: dict = {"version": eng.version}
    t0 = time.perf_counter()
    if args.vertex:
        batch = eng.query_batch(args.vertex)
        sink.emit(
            "query_batch", endpoint="cli", n=len(args.vertex),
            seconds=round(time.perf_counter() - t0, 6),
        )
        out["rows"] = batch
    if args.neighbors is not None:
        out["neighbors"] = eng.neighbors(args.neighbors)
    if args.explain is not None:
        out["explain"] = eng.explain(args.explain)
    if args.community is not None:
        out["top"] = [
            {"vertex": v, "lof": s}
            for v, s in eng.top_outliers(args.community, args.topk)
        ]
    print(json.dumps(_jsonable(out)))
    if sink.stream_path:
        sink.finalize(sink.stream_path)
    return 0


def cmd_delta(args) -> int:
    def pairs(values):
        # SRC,DST or (weighted snapshots) SRC,DST,WEIGHT
        out = []
        for v in values or ():
            parts = v.split(",")
            if len(parts) == 3:
                out.append((int(parts[0]), int(parts[1]), float(parts[2])))
            else:
                out.append(tuple(int(x) for x in parts))
        return out

    if args.file:
        with open(args.file) as f:
            payload = json.load(f)
    else:
        payload = {
            "insert": [list(p) for p in pairs(args.insert)],
            "delete": [list(p) for p in pairs(args.delete)],
        }
    if args.url:
        # ONE idempotency key per logical request, riding every retry:
        # a resend after a lost acknowledgement dedupes in the server's
        # WAL instead of double-applying (a WAL-less server ignores it).
        delta_id = args.delta_id or f"cli-{os.getpid()}-{os.urandom(6).hex()}"
        headers = {"X-Delta-Id": delta_id}
        if args.tenant:
            # tenant + delta id together ride every retry: the dedupe
            # key is (tenant, delta_id) server-side
            headers["X-Tenant-Id"] = args.tenant
        if args.ack_wal:
            headers["X-Delta-Ack"] = "wal"
        out = request_with_retries(
            f"{args.url.rstrip('/')}/delta", payload,
            deadline_ms=args.deadline_ms,
            max_retries=args.max_retries,
            headers=headers,
        )
        print(json.dumps({
            "status": out["status"], "attempts": out["attempts"],
            "delta_id": delta_id,
            **out["body"],
        }))
        return 0 if out["status"] in (200, 202) else 1
    # in-process path: the ingest machinery (device repair code,
    # compiles) loads only here — --url mode stays HTTP + the host-side
    # retry policy
    from graphmine_tpu.serve.delta import DeltaIngestor, EdgeDelta

    delta = EdgeDelta.from_pairs(
        insert=payload.get("insert", ()), delete=payload.get("delete", ())
    )
    sink = _sink(args)
    ing = DeltaIngestor(_store(args), sink=sink, num_shards=args.num_shards)
    snap = ing.apply(delta)
    last = [r for r in sink.records if r.get("phase") == "delta_apply"][-1]
    print(json.dumps({
        "version": snap.version,
        "snapshot_id": snap.snapshot_id,
        "method": last["method"],
        "inserts": last["inserts"],
        "deletes": last["deletes"],
        "quarantine": last["quarantine"],
        "seconds": last["seconds"],
    }))
    if sink.stream_path:
        sink.finalize(sink.stream_path)
    return 0


def cmd_serve(args) -> int:
    from graphmine_tpu.serve.server import SnapshotServer

    sink = _sink(args)
    # A serving process emits one access_log record per request forever;
    # cap the sink's in-memory copy (the JSONL stream keeps everything
    # on disk) so RSS doesn't grow linearly with traffic.
    sink.max_records = 100_000
    server = SnapshotServer(
        _store(args), host=args.host, port=args.port, sink=sink,
        prom_out=args.prom_out, num_shards=args.num_shards,
        slow_request_s=args.slow_request_s,
        wal=args.wal, standby_of=args.standby_of,
        primary_wal=args.primary_wal,
        profilez_dir=args.profilez_dir,
    )
    host, port = server.start()
    role = (
        f"standby of {args.standby_of}" if args.standby_of
        else ("writer (WAL-durable)" if args.wal else "writer")
    )
    print(
        f"serving snapshot v{server.engine.version} on "
        f"http://{host}:{port} [{role}, epoch {server.writer_epoch}]",
        file=sys.stderr,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if sink.stream_path:
            sink.finalize(sink.stream_path)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p, store_required=True):
        p.add_argument("--store", required=store_required, default=None,
                       help="snapshot store directory")
        p.add_argument("--metrics-out", default=None,
                       help="append serving records to this JSONL")
        p.add_argument("--obs-dir", default=None,
                       help="federated metrics plane: stream this "
                            "process's records to its own shard "
                            "(<role>-<pid>.jsonl) under this directory; "
                            "tools/trace_stitch.py joins a fleet's "
                            "shards into cross-process trace timelines "
                            "(overrides --metrics-out)")

    def client(p):
        p.add_argument("--url", default=None,
                       help="HTTP mode: talk to a running server/fleet "
                            "router at this base URL instead of --store")
        p.add_argument("--deadline-ms", type=int, default=None,
                       help="total budget for the exchange; also sent as "
                            "X-Deadline-Ms so the server sheds work the "
                            "client stopped waiting for")
        p.add_argument("--max-retries", type=int, default=4,
                       help="extra attempts on 503/transport failure "
                            "(decorrelated-jitter backoff, honoring the "
                            "server's Retry-After)")
        p.add_argument("--tenant", default=None,
                       help="tenant namespace: HTTP mode sends it as "
                            "X-Tenant-Id on every attempt; local mode "
                            "scopes --store to tenants/<id>/ "
                            "(docs/SERVING.md 'Multi-tenant serving')")

    p = sub.add_parser("info", help="print the current snapshot manifest")
    common(p)
    p.add_argument("--tenant", default=None,
                   help="read the manifest of this tenant's namespace "
                        "(tenants/<id>/ under --store)")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("query", help="one-shot queries against the store")
    common(p, store_required=False)
    client(p)
    p.add_argument("--vertex", type=int, nargs="*", default=[],
                   help="vertex ids to resolve (batched gather)")
    p.add_argument("--neighbors", type=int, default=None,
                   help="list this vertex's neighbors")
    p.add_argument("--explain", type=int, default=None,
                   help="per-vertex outlier explanation (LOF score + "
                        "rank, community size/decile, neighbor score "
                        "context) — the triage companion to a firing "
                        "canary/drift alert")
    p.add_argument("--community", type=int, default=None,
                   help="top-k outliers of this community")
    p.add_argument("--topk", type=int, default=10)
    p.set_defaults(fn=cmd_query)

    p = sub.add_parser("delta", help="apply one insert/delete batch")
    common(p, store_required=False)
    client(p)
    p.add_argument("--insert", action="append", metavar="SRC,DST[,W]",
                   help="edge to insert (repeatable; the third field is "
                        "the edge weight for weighted snapshots)")
    p.add_argument("--delete", action="append", metavar="SRC,DST",
                   help="edge to delete (repeatable)")
    p.add_argument("--file", default=None,
                   help='JSON file {"insert": [[s,d],...], "delete": [...]}')
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--delta-id", default=None,
                   help="idempotency key (X-Delta-Id) for --url mode; "
                        "default: one generated key reused across retries")
    p.add_argument("--ack-wal", action="store_true",
                   help="--url mode: ask for the WAL-durable 202 "
                        "acknowledgement instead of blocking to publish "
                        "(X-Delta-Ack: wal; needs a server with --wal)")
    p.set_defaults(fn=cmd_delta)

    p = sub.add_parser(
        "serve", help="run the HTTP query server",
        description="Run the HTTP query server. Write-path admission "
        "bounds (docs/SERVING.md 'admission control') come from the "
        "GRAPHMINE_ADMIT_* environment: MAX_PENDING_ROWS, MAX_LAG_S, "
        "MAX_QUEUE_DEPTH, DEFER_FRAC, DEADLINE_S, RETRY_AFTER_S.",
    )
    common(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8337)
    p.add_argument("--prom-out", default=None,
                   help="Prometheus textfile path (updated on each swap); "
                        "the live scrape surface is GET /metrics")
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--slow-request-s", type=float, default=1.0,
                   help="requests slower than this log their body digest "
                        "in the access_log record")
    p.add_argument("--wal", default=None, metavar="DIR",
                   help="write-ahead delta log directory: accepted "
                        "batches fsync here before acknowledgement, "
                        "replay on restart, and GET /wal serves the "
                        "log-shipping feed (docs/SERVING.md 'Replicated "
                        "writers')")
    p.add_argument("--standby-of", default=None, metavar="URL",
                   help="run as the log-shipped standby of the writer at "
                        "URL: refuse client writes, tail its /wal into "
                        "--wal, expose replication lag on /healthz, and "
                        "take over on POST /promote")
    p.add_argument("--primary-wal", default=None, metavar="DIR",
                   help="the primary's WAL directory (shared-storage "
                        "deployments): promotion copies the un-shipped "
                        "tail straight from it, so a writer kill loses "
                        "nothing")
    p.add_argument("--profilez-dir", default=None, metavar="DIR",
                   help="enable the guarded POST /profilez endpoint: "
                        "on-demand XLA profiler captures land under this "
                        "directory, tagged with the requesting trace_id "
                        "(disabled when unset; 501 when jax/profiler is "
                        "unavailable)")
    p.set_defaults(fn=cmd_serve)

    args = ap.parse_args(argv)
    if getattr(args, "url", None) is None and args.store is None:
        ap.error(f"{args.cmd}: one of --store or --url is required")
    if getattr(args, "url", None) is not None and args.metrics_out:
        # the serving records are emitted SERVER-side in HTTP mode
        # (point obs_report at the server/router --metrics-out); saying
        # nothing here would silently drop the observability trail
        print(
            "serve_cli: --metrics-out is ignored with --url (records are "
            "written by the server's own --metrics-out)", file=sys.stderr,
        )
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
