#!/usr/bin/env python
"""Serving CLI: run the snapshot query server, query it, ingest deltas.

The operator surface of ``graphmine_tpu/serve/`` (docs/SERVING.md)::

    # publish a snapshot from a pipeline run first:
    python -m graphmine_tpu.pipeline --snapshot-out /data/snap ...

    python tools/serve_cli.py info  --store /data/snap
    python tools/serve_cli.py query --store /data/snap --vertex 12 44 7
    python tools/serve_cli.py query --store /data/snap --community 3 --topk 5
    python tools/serve_cli.py delta --store /data/snap \
        --insert 10,11 --insert 11,12 --delete 3,4
    python tools/serve_cli.py serve --store /data/snap --port 8337 \
        --metrics-out /data/serve_metrics.jsonl --prom-out /data/serve.prom

``serve`` runs until interrupted; ``query``/``delta``/``info`` are
one-shot in-process operations against the store directory (no server
needed). Every subcommand that mutates or resolves emits the serving
records (``query_batch`` / ``delta_apply`` / ``snapshot_publish``) —
point ``tools/obs_report.py`` at ``--metrics-out`` for the joined view.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # allow `python tools/serve_cli.py` from anywhere
    sys.path.insert(0, _REPO)


def _sink(args):
    from graphmine_tpu.obs.spans import Tracer
    from graphmine_tpu.pipeline.metrics import MetricsSink

    return MetricsSink(
        stream_path=getattr(args, "metrics_out", None), tracer=Tracer()
    )


def _store(args):
    from graphmine_tpu.serve.snapshot import SnapshotStore

    return SnapshotStore(args.store)


def cmd_info(args) -> int:
    snap = _store(args).load()
    if snap is None:
        print(f"serve_cli: store at {args.store!r} is empty", file=sys.stderr)
        return 2
    print(json.dumps({
        **snap.meta,
        "arrays": {k: list(v.shape) for k, v in snap.arrays.items()},
    }, indent=1, default=str))
    return 0


def cmd_query(args) -> int:
    from graphmine_tpu.serve.query import QueryEngine
    from graphmine_tpu.serve.server import _jsonable

    sink = _sink(args)
    snap = _store(args).load(sink=sink)
    if snap is None:
        print(f"serve_cli: store at {args.store!r} is empty", file=sys.stderr)
        return 2
    eng = QueryEngine(snap)
    out: dict = {"version": eng.version}
    t0 = time.perf_counter()
    if args.vertex:
        batch = eng.query_batch(args.vertex)
        sink.emit(
            "query_batch", endpoint="cli", n=len(args.vertex),
            seconds=round(time.perf_counter() - t0, 6),
        )
        out["rows"] = batch
    if args.neighbors is not None:
        out["neighbors"] = eng.neighbors(args.neighbors)
    if args.community is not None:
        out["top"] = [
            {"vertex": v, "lof": s}
            for v, s in eng.top_outliers(args.community, args.topk)
        ]
    print(json.dumps(_jsonable(out)))
    if args.metrics_out:
        sink.finalize(args.metrics_out)
    return 0


def cmd_delta(args) -> int:
    from graphmine_tpu.serve.delta import DeltaIngestor, EdgeDelta

    def pairs(values):
        # SRC,DST or (weighted snapshots) SRC,DST,WEIGHT
        out = []
        for v in values or ():
            parts = v.split(",")
            if len(parts) == 3:
                out.append((int(parts[0]), int(parts[1]), float(parts[2])))
            else:
                out.append(tuple(int(x) for x in parts))
        return out

    if args.file:
        with open(args.file) as f:
            payload = json.load(f)
        delta = EdgeDelta.from_pairs(
            insert=payload.get("insert", ()), delete=payload.get("delete", ())
        )
    else:
        delta = EdgeDelta.from_pairs(
            insert=pairs(args.insert), delete=pairs(args.delete)
        )
    sink = _sink(args)
    ing = DeltaIngestor(_store(args), sink=sink, num_shards=args.num_shards)
    snap = ing.apply(delta)
    last = [r for r in sink.records if r.get("phase") == "delta_apply"][-1]
    print(json.dumps({
        "version": snap.version,
        "snapshot_id": snap.snapshot_id,
        "method": last["method"],
        "inserts": last["inserts"],
        "deletes": last["deletes"],
        "quarantine": last["quarantine"],
        "seconds": last["seconds"],
    }))
    if args.metrics_out:
        sink.finalize(args.metrics_out)
    return 0


def cmd_serve(args) -> int:
    from graphmine_tpu.serve.server import SnapshotServer

    sink = _sink(args)
    # A serving process emits one access_log record per request forever;
    # cap the sink's in-memory copy (the JSONL stream keeps everything
    # on disk) so RSS doesn't grow linearly with traffic.
    sink.max_records = 100_000
    server = SnapshotServer(
        _store(args), host=args.host, port=args.port, sink=sink,
        prom_out=args.prom_out, num_shards=args.num_shards,
        slow_request_s=args.slow_request_s,
    )
    host, port = server.start()
    print(f"serving snapshot v{server.engine.version} on http://{host}:{port}",
          file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if args.metrics_out:
            sink.finalize(args.metrics_out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--store", required=True,
                       help="snapshot store directory")
        p.add_argument("--metrics-out", default=None,
                       help="append serving records to this JSONL")

    p = sub.add_parser("info", help="print the current snapshot manifest")
    common(p)
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("query", help="one-shot queries against the store")
    common(p)
    p.add_argument("--vertex", type=int, nargs="*", default=[],
                   help="vertex ids to resolve (batched gather)")
    p.add_argument("--neighbors", type=int, default=None,
                   help="list this vertex's neighbors")
    p.add_argument("--community", type=int, default=None,
                   help="top-k outliers of this community")
    p.add_argument("--topk", type=int, default=10)
    p.set_defaults(fn=cmd_query)

    p = sub.add_parser("delta", help="apply one insert/delete batch")
    common(p)
    p.add_argument("--insert", action="append", metavar="SRC,DST[,W]",
                   help="edge to insert (repeatable; the third field is "
                        "the edge weight for weighted snapshots)")
    p.add_argument("--delete", action="append", metavar="SRC,DST",
                   help="edge to delete (repeatable)")
    p.add_argument("--file", default=None,
                   help='JSON file {"insert": [[s,d],...], "delete": [...]}')
    p.add_argument("--num-shards", type=int, default=1)
    p.set_defaults(fn=cmd_delta)

    p = sub.add_parser(
        "serve", help="run the HTTP query server",
        description="Run the HTTP query server. Write-path admission "
        "bounds (docs/SERVING.md 'admission control') come from the "
        "GRAPHMINE_ADMIT_* environment: MAX_PENDING_ROWS, MAX_LAG_S, "
        "MAX_QUEUE_DEPTH, DEFER_FRAC, DEADLINE_S, RETRY_AFTER_S.",
    )
    common(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8337)
    p.add_argument("--prom-out", default=None,
                   help="Prometheus textfile path (updated on each swap); "
                        "the live scrape surface is GET /metrics")
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--slow-request-s", type=float, default=1.0,
                   help="requests slower than this log their body digest "
                        "in the access_log record")
    p.set_defaults(fn=cmd_serve)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
