"""Memory planning + scale-out: graphs bigger than one chip, no OOM.

The reference's author fought driver memory with a commented-out "data
slicer" (``Graphframes.py:34-47``). This framework answers with a
measured memory model (``docs/DESIGN.md``) consulted BEFORE allocation:

1. `plan_run(V, E, D)` models per-device HBM for every LPA schedule and
   picks the fastest that fits (single fused kernel → replicated → ring);
2. a config nothing fits fails loudly with the numbers at plan time;
3. the pipeline's scale-out mode keeps an oversized graph host-resident,
   partitions it straight onto the mesh, and runs census/modularity/LOF
   through NumPy-twin + sharded paths.

Run:  python examples/memory_planning.py
(set ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu`` for 8 virtual devices on CPU)
"""

import numpy as np

from graphmine_tpu.pipeline.planner import PlanError, plan_run

GIB = 1 << 30


def show(p):
    print(f"  -> {p.schedule:10s}  {p.bytes_per_device / GIB:7.2f} GiB/device"
          f"   ({p.reason})")


# 1. the planner across scales (16 GiB v5e budget, 8 devices) ------------
print("8 devices, default 16 GiB HBM:")
for v, e, note in [
    (4_613, 18_398, "bundled CommonCrawl sample"),
    (1 << 24, 100_000_000, "north-star config"),
    (65_000_000, 1_800_000_000, "com-friendster class"),
    (300_000_000, 2_500_000_000, "the VERDICT crossover scenario"),
]:
    p = plan_run(v, e, num_devices=8)
    print(f"V={v:>11,} E={e:>13,}  ({note})")
    show(p)

# 2. one device: the fused kernel until the graph outgrows the chip ------
print("\n1 device:")
show(plan_run(1 << 24, 100_000_000, num_devices=1))
try:
    plan_run(300_000_000, 2_500_000_000, num_devices=1)
except PlanError as ex:
    print(f"  -> rejected at plan time:\n     {ex}")

# 3. an explicit schedule is honored but still checked -------------------
try:
    plan_run(300_000_000, 2_500_000_000, num_devices=8,
             requested="replicated")
except PlanError as ex:
    print(f"\nexplicit replicated at 300M vertices:\n  {ex}")

# 4. scale-out mode end to end (shrunken budget so the bundled graph
# counts as "too big for one device"). Needs a multi-device mesh — on a
# CPU host set the XLA_FLAGS/JAX_PLATFORMS from the docstring first.
import os

import jax

if len(jax.devices()) < 2:
    print("\n(scale-out demo skipped: needs >= 2 devices — set "
          "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
          "JAX_PLATFORMS=cpu for a virtual mesh)")
else:
    os.environ["GRAPHMINE_HBM_BYTES"] = "300000"
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    res = run_pipeline(PipelineConfig(
        num_devices=None,  # all visible
        max_iter=5,
        outlier_method="lof",
    ))
    print(f"\nscale-out pipeline: {res.num_communities} communities, "
          f"LOF scored {len(res.lof)} vertices "
          f"(graph stayed host-resident: "
          f"{isinstance(res.graph.src, np.ndarray)})")
