"""Zero-edit migration demo: a pyspark/GraphFrames script on the TPU engine.

Three rungs of the migration ladder:

1. Run an UNMODIFIED pyspark script (e.g. the reference's
   ``CommunityDetection/Graphframes.py``) through the shim CLI::

       python -m graphmine_tpu.compat /path/to/Graphframes.py

2. Keep pyspark call shapes in your own code, swap only the import source
   (this file — ``compat.install()`` makes ``import pyspark`` resolve to
   the shim).

3. Drop to the native API (``graphmine_tpu.Table`` / ``GraphFrame``) for
   the vectorized fast path once the port is settled.

Usage: python examples/compat_migration.py <outlinks_pq_dir>
"""

import sys

from graphmine_tpu import compat

compat.install()

# everything below is ordinary pyspark + graphframes code
import pyspark  # noqa: E402  (resolves to the shim)
from graphframes import GraphFrame  # noqa: E402
from pyspark.sql import SparkSession, functions as F  # noqa: E402


def main(data_dir: str) -> None:
    spark = SparkSession.builder.appName("migration-demo").getOrCreate()

    df = (
        spark.read.parquet(f"{data_dir}/*.snappy.parquet")
        .withColumnRenamed("_c1", "ParentDomain")
        .withColumnRenamed("_c2", "ChildDomain")
        .filter(F.col("ParentDomain").isNotNull()
                & F.col("ChildDomain").isNotNull())
    )
    print(f"{df.count()} edges after the null filter")

    # vertex table from the distinct domains (the reference's RDD idiom,
    # Graphframes.py:53); edges keep duplicates (LPA multiplicity parity)
    domain_rdd = (df.select("ParentDomain", "ChildDomain")
                    .rdd.flatMap(lambda row: row).distinct())
    vertices = domain_rdd.map(lambda d: (d, d)).toDF(["id", "name"])
    edges = df.select(F.col("ParentDomain").alias("src"),
                      F.col("ChildDomain").alias("dst"))

    g = GraphFrame(vertices, edges)
    communities = g.labelPropagation(maxIter=5)
    n = communities.select("label").distinct().count()
    print(f"{n} communities")

    top = (communities.groupBy("label").count()
           .sort(F.desc("count")).limit(5))
    top.show()

    # community sizes -> bottom-decile outlier threshold (the capability
    # the reference specified in its dead code, Graphframes.py:121-137)
    import numpy as np

    sizes = communities.groupBy("label").count()
    counts = np.array([row["count"] for row in sizes.collect()], dtype=np.float64)
    decile = np.quantile(counts, 0.1)
    outliers = sizes.filter(F.col("count") <= decile)
    print(f"{outliers.count()} communities at or below the bottom decile "
          f"(size <= {decile:.0f})")

    communities.write.mode("overwrite").parquet("/tmp/communities_demo.parquet")
    print("wrote /tmp/communities_demo.parquet")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "/root/reference/CommunityDetection/data/outlinks_pq")
