"""Weighted community detection end-to-end (r2 capability tour).

The reference's LPA treats every edge equally (duplicate rows are its only
weighting, ``Graphframes.py:70-81``). This example shows the weighted
extension: per-edge float weights drive the mode (argmax of incoming
weight sums), riding the same fused/sharded/ring fast paths as the
unweighted kernel (docs/DESIGN.md "Weighted LPA on the fast paths").

Run:  python examples/weighted_lpa.py
"""

import os
import tempfile

import numpy as np

import graphmine_tpu as gm

# ── A weighted edge list: two communities joined by a weak bridge ──────────
# Strong intra-community edges (weight 4), one inter-community edge whose
# weight decides whether LPA merges the groups.
edges = [
    ("ada", "bob", 4.0), ("bob", "cat", 4.0), ("cat", "ada", 4.0),
    ("xia", "yen", 4.0), ("yen", "zoe", 4.0), ("zoe", "xia", 4.0),
    ("ada", "xia", 0.5),   # weak bridge
]
with tempfile.TemporaryDirectory() as d:
    path = os.path.join(d, "weighted.txt")
    with open(path, "w") as f:
        f.writelines(f"{s} {t} {w}\n" for s, t, w in edges)

    # 3-column weighted edge list -> EdgeTable with a weights sidecar
    et = gm.load_edge_list(path, weight_col=2)

print("vertices:", et.num_vertices, "edges:", et.num_edges)
print("weights:", et.weights)

# ── Weighted graph + LPA ───────────────────────────────────────────────────
from graphmine_tpu.graph.container import graph_from_edge_table

g = graph_from_edge_table(et)          # carries et.weights as msg_weight
labels = np.asarray(gm.label_propagation(g, max_iter=5))
communities = {}
for v, lab in enumerate(labels):
    communities.setdefault(int(lab), []).append(str(et.names[v]))
print("weighted communities:", sorted(communities.values()))
assert len(communities) == 2, "weak bridge must not merge the triangles"

# The same topology unweighted: the bridge counts as much as any edge.
g_u = gm.build_graph(et.src, et.dst, num_vertices=et.num_vertices)
labels_u = np.asarray(gm.label_propagation(g_u, max_iter=5))
print("unweighted communities:", len(np.unique(labels_u)))

# ── The same flow through the pipeline CLI surface ─────────────────────────
# python -m graphmine_tpu.pipeline --data-path weighted.txt \
#     --data-format edgelist --edge-weight-col 2 --outlier-method none
print("ok")
