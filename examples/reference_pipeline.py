"""The reference pipeline, ported line-for-line to graphmine_tpu.

Every phase of ``CommunityDetection/Graphframes.py`` (the whole reference
project) mapped to its TPU-native equivalent — including the two pieces the
reference only sketched in commented-out code: the data slicer
(``Graphframes.py:34-47``) and the recursive-LPA outlier detector
(``:121-137``). Cited line numbers refer to the reference script.

Run:  python examples/reference_pipeline.py [path/to/outlinks_pq]
"""

import sys

import numpy as np

import graphmine_tpu as gm

DATA = sys.argv[1] if len(sys.argv) > 1 else (
    "/root/reference/CommunityDetection/data/outlinks_pq"
)

# ── Phase 1: Spark bootstrap (Graphframes.py:1-14) ──────────────────────────
# SparkContext("local[*]") + SparkSession + SQLContext  →  nothing: the
# Python process is the engine host; devices come from jax.devices().

# ── Phase 2: ingestion + schema + null filter (:16-32) ──────────────────────
df = gm.Table.read_parquet(DATA)                       # :16
print("row count:", df.count())                        # :18 → 18,399

df = (
    df.withColumnRenamed("_c0", "Parent")              # :26
    .withColumnRenamed("_c1", "ParentDomain")          # :27
    .withColumnRenamed("_c2", "ChildDomain")           # :28
    .withColumnRenamed("_c3", "Child")                 # :29
    .filter("ParentDomain is not null and ChildDomain is not null")  # :30
)
df.show(10)                                            # :32

# (:34-47, commented out in the reference) the data slicer — driver-memory
# workaround the author abandoned. The eager columnar engine doesn't need
# it, but the same ops exist:
#   window = df.with_row_ids().sort("_row_id").limit(2000)
#   rest   = df.with_row_ids().subtract(window)

# ── Phase 3: graph construction (:53-78) ────────────────────────────────────
# .rdd.flatMap(...).distinct() + sha1[:8] NodeHash UDFs  →  one vectorized
# factorize to dense int32 ids (no birthday collisions at scale).
vertices = df.flat_map_distinct("ParentDomain", "ChildDomain")  # :53
print("vertex count:", len(vertices))                  # :54 → 4,613

et = df.to_edge_table("ParentDomain", "ChildDomain", num_rows_raw=18399)  # :70-74
gf = gm.GraphFrame.from_edge_table(et)                 # :78

# ── Phase 4: label propagation (:81-85) ─────────────────────────────────────
labels = gf.labelPropagation(max_iter=5)               # :81
labels = np.asarray(labels)
n_comm = len(np.unique(labels))
print("The number of communities:", n_comm)            # :85 (≈650, tie-break
                                                       #  dependent)

# ── Phase 5: community census (:90-120) ─────────────────────────────────────
# The reference's O(C·V·E) driver-side collect() loops → one segment_sum.
_, sizes, _ = gf.census(labels)
sizes = np.asarray(sizes)
print("community sizes: min", sizes.min(), "median",
      int(np.median(sizes)), "max", sizes.max())       # :120 equivalent

# ── Phase 6: recursive-LPA outliers (:121-137, the dead spec) ───────────────
report = gf.recursive_lpa_outliers(labels)
print("outlier vertices (bottom-decile sub-communities):",
      int(report.outlier_vertices.sum()))

# ── Beyond the reference: the north-star LOF scorer ─────────────────────────
scores = np.asarray(gf.lof_scores(labels=np.asarray(labels), k=15))
top = np.argsort(-scores)[:10]
names = et.names[top]
print("top-10 structural outliers by LOF:")
for name, s in zip(names, scores[top]):
    print(f"  {s:6.2f}  {name}")
