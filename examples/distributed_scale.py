"""Distributed graph mining at scale: mesh, sharded supersteps, checkpoint.

What the reference could never do — `SparkContext("local[*]")`
(``Graphframes.py:12``) pinned it to one machine — expressed as the
mesh-native equivalents this framework treats as first-class:

1. multi-host bootstrap (no-op on one host, pods auto-detect)
2. an ICI (or dcn×ici multi-slice) device mesh
3. vertex-range-sharded label propagation with the degree-bucketed fast
   kernel per shard (one tiled all_gather per superstep)
4. the ring schedule when no device may hold the full label vector
5. sharded manifest checkpoint of distributed label state (per-shard
   sha256, rollback generations) — restorable onto a DIFFERENT device
   count (re-shard on restore, the elastic path after a chip loss)

Runs anywhere: on a laptop/CI set
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``
to get 8 virtual devices (the TPU analog of ``local[*]``); on a real pod
the same code spans every chip jax sees.

Run:  python examples/distributed_scale.py
"""

import numpy as np

import graphmine_tpu as gm
from graphmine_tpu.datasets import rmat
from graphmine_tpu.parallel import (
    initialize_distributed,
    make_mesh,
    ring_label_propagation,
    sharded_connected_components,
    sharded_label_propagation,
)
from graphmine_tpu.parallel.sharded import partition_graph, shard_graph_arrays
from graphmine_tpu.pipeline.checkpoint import load_sharded, save_sharded

# ── 1. bootstrap ─────────────────────────────────────────────────────────
# On a TPU pod each host calls this before touching devices; coordinator
# details come from the environment. Single-process: returns False, same
# code path continues.
multi_host = initialize_distributed()
print(f"multi-host: {multi_host}")

import jax  # after initialize_distributed, so the fleet is visible

print(f"devices: {len(jax.devices())}")

# ── 2. mesh + graph ──────────────────────────────────────────────────────
mesh = make_mesh()                       # all visible devices, 1-D ICI axis
src, dst = rmat(scale=14, edge_factor=12, seed=7)
v = 1 << 14

# Host-side partition: vertex-range shards of the message CSR, plus the
# stacked degree-bucket plan for the fast LPA shard body.
sg = shard_graph_arrays(
    partition_graph(src, dst, num_vertices=v, mesh=mesh, build_bucket_plan=True),
    mesh,
)

# ── 3. sharded supersteps ────────────────────────────────────────────────
labels = sharded_label_propagation(sg, mesh, max_iter=5)
comps = sharded_connected_components(sg, mesh)
print(f"communities: {len(np.unique(np.asarray(labels)))}")
print(f"components:  {len(np.unique(np.asarray(comps)))}")

# Parity guarantee (tested in tests/test_sharded.py): identical labels to
# the single-device kernel, any mesh size, any shard body.
g = gm.build_graph(src, dst, num_vertices=v)
assert np.array_equal(np.asarray(labels), np.asarray(gm.label_propagation(g, max_iter=5)))

# ── 4. ring schedule ─────────────────────────────────────────────────────
# When V outgrows one device's HBM: labels stay sharded, each superstep
# rotates label chunks around the mesh with ppermute (this domain's ring
# attention). Same answer, bounded per-device memory.
ring = ring_label_propagation(sg, mesh, max_iter=5)
assert np.array_equal(np.asarray(ring), np.asarray(labels))

# ── 4b. the rest of the distributed family (r2) ──────────────────────────
# PageRank on both schedules (replicated frontier vs fully-sharded ring),
# personalized PageRank with the SOURCE axis sharded, and the outlier
# path at mesh scale: ring-sharded kNN + distributed LOF.
from graphmine_tpu.parallel import (
    ring_pagerank,
    sharded_lof,
    sharded_pagerank,
    sharded_personalized_pagerank,
)

g_dir = gm.build_graph(src, dst, num_vertices=v, symmetric=False)
sgd = shard_graph_arrays(partition_graph(g_dir, mesh=mesh), mesh)
od = gm.out_degrees(g_dir)
pr = sharded_pagerank(sgd, mesh, od, max_iter=30)
pr_ring = ring_pagerank(sgd, mesh, od, max_iter=30)
assert np.allclose(np.asarray(pr), np.asarray(pr_ring), rtol=2e-4, atol=1e-7)
print(f"pagerank mass: {float(np.asarray(pr).sum()):.4f} (both schedules agree)")

ppr = sharded_personalized_pagerank(g_dir, [0, 7, 42], mesh, max_iter=30)
print(f"ppr columns: {ppr.shape}")

feats = np.asarray(gm.standardize(gm.vertex_features(g, labels)))
lof = np.asarray(sharded_lof(feats, mesh, k=32))
print(f"top LOF score: {lof.max():.2f} (ring-sharded kNN over the mesh)")

# ── 5. checkpoint / resume ───────────────────────────────────────────────
# The sharded manifest format: per-shard files + sha256 manifest, two
# rotated generations with automatic rollback. Restore is shard-count
# AGNOSTIC — a checkpoint taken on this mesh resumes on half the chips
# (the elastic-degradation path after a device loss, docs/RESILIENCE.md).
import tempfile

import jax.numpy as jnp

with tempfile.TemporaryDirectory() as ckdir:
    save_sharded(ckdir, np.asarray(labels), iteration=5,
                 num_shards=mesh.size)
    restored, it = load_sharded(ckdir)
    assert it == 5 and np.array_equal(np.asarray(restored), np.asarray(labels))
    if mesh.size > 1:
        smaller = make_mesh(mesh.size // 2)
        sg_small = shard_graph_arrays(
            partition_graph(g, mesh=smaller), smaller
        )
        resumed = sharded_label_propagation(
            sg_small, smaller, max_iter=1,
            init_labels=jnp.asarray(restored),
        )
        print(f"checkpoint roundtrip ok (resumed on {smaller.size} devices)")
    else:
        print("checkpoint roundtrip ok")

print("distributed example complete")
