// libgraphbuild — native host-side graph builder for graphmine_tpu.
//
// The TPU-native replacement for the host/JVM work the reference pipeline
// delegated to Spark (CommunityDetection/Graphframes.py:53-74: RDD flatMap/
// distinct + per-row sha1 UDFs): streaming edge-list parsing and string
// interning to dense int32 vertex ids, in one pass, no Python per-row cost.
// Exposed to Python via ctypes (graphmine_tpu/io/native.py).
//
// Build: make -C native    (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <new>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct Interner {
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::string> names;
  // Column count of the first data line of a chunked parse session; later
  // lines must match (np.loadtxt's rectangularity contract — the NumPy
  // paths raise "number of columns changed"). Lives here because the
  // interner IS the cross-chunk session state.
  int32_t ncols = -1;

  int32_t intern(std::string_view s) {
    auto it = map.find(std::string(s));
    if (it != map.end()) return it->second;
    int32_t id = static_cast<int32_t>(names.size());
    names.emplace_back(s);
    map.emplace(names.back(), id);
    return id;
  }
};

bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(out->data(), 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

}  // namespace

extern "C" {

// Parses a whitespace-separated edge list ("src dst" per line; lines whose
// first non-space char equals `comment` are skipped). Returns the edge count
// (>= 0) and malloc'd arrays the caller must free via gb_free/gb_free_names,
// -1 on I/O error, -3 when a non-comment data line has fewer than 2
// tokens, or -4 when the column count changes between data lines (ADVICE
// r3 / code-review r4: all ingestion paths reject malformed files the
// same way np.loadtxt does). Endpoint tokens may be arbitrary strings;
// they are interned to dense int32 ids in first-appearance order
// (matching the NumPy fallback in graphmine_tpu/io/factorize.py).
int64_t gb_load_edge_list(const char* path, char comment, int32_t** src_out,
                          int32_t** dst_out, char*** names_out,
                          int64_t* num_vertices) {
  std::string buf;
  if (!read_file(path, &buf)) return -1;

  Interner interner;
  std::vector<int32_t> src, dst;
  const char* p = buf.data();
  const char* end = p + buf.size();
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    // Truncate at the comment char ANYWHERE in the line (np.loadtxt
    // semantics, which every NumPy fallback path inherits): "a b # note"
    // is an edge, "c # note" is a 1-token malformed line, a line whose
    // first char is the comment becomes blank. Parsing must not depend
    // on whether the .so is built.
    const char* cpos =
        static_cast<const char*>(memchr(p, comment, line_end - p));
    const char* data_end = cpos ? cpos : line_end;
    const char* q = p;
    while (q < data_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q < data_end) {
      const char* t0 = q;
      while (q < data_end && *q != ' ' && *q != '\t' && *q != '\r') ++q;
      const char* t0e = q;
      while (q < data_end && (*q == ' ' || *q == '\t')) ++q;
      const char* t1 = q;
      while (q < data_end && *q != ' ' && *q != '\t' && *q != '\r') ++q;
      const char* t1e = q;
      if (t0e > t0 && t1e > t1) {
        // Count the remaining tokens: np.loadtxt rejects files whose
        // data lines change column count ("number of columns changed"),
        // and .so parity demands the same verdict (code-review r4).
        int32_t tok = 2;
        while (q < data_end) {
          while (q < data_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
          const char* s0 = q;
          while (q < data_end && *q != ' ' && *q != '\t' && *q != '\r') ++q;
          if (q > s0) ++tok;
        }
        if (interner.ncols < 0) {
          interner.ncols = tok;
        } else if (tok != interner.ncols) {
          return -4;
        }
        src.push_back(interner.intern({t0, size_t(t0e - t0)}));
        dst.push_back(interner.intern({t1, size_t(t1e - t1)}));
      } else {
        // A non-comment data line with fewer than 2 tokens: hard error
        // (-3), matching the NumPy paths' "needs >= 2 columns" raise —
        // silently dropping edges of a malformed file is the worse bug.
        return -3;
      }
    }
    p = line_end + 1;
  }

  int64_t ne = static_cast<int64_t>(src.size());
  int64_t nv = static_cast<int64_t>(interner.names.size());
  *src_out = static_cast<int32_t*>(malloc(sizeof(int32_t) * (ne ? ne : 1)));
  *dst_out = static_cast<int32_t*>(malloc(sizeof(int32_t) * (ne ? ne : 1)));
  *names_out = static_cast<char**>(malloc(sizeof(char*) * (nv ? nv : 1)));
  if (!*src_out || !*dst_out || !*names_out) return -1;
  if (ne) {
    memcpy(*src_out, src.data(), sizeof(int32_t) * ne);
    memcpy(*dst_out, dst.data(), sizeof(int32_t) * ne);
  }
  for (int64_t i = 0; i < nv; ++i) {
    const std::string& s = interner.names[static_cast<size_t>(i)];
    char* c = static_cast<char*>(malloc(s.size() + 1));
    if (!c) return -1;
    memcpy(c, s.data(), s.size() + 1);
    (*names_out)[i] = c;
  }
  *num_vertices = nv;
  return ne;
}

// ---------------------------------------------------------------------------
// Chunked streaming parse (r3): the whole-file gb_load_edge_list above walls
// out at host RAM for top-rung edge lists (Twitter-2010 text is ~25 GB). The
// chunk API keeps ONE interner alive across calls while the caller feeds
// bounded buffers of complete lines — peak memory is O(chunk + vocabulary +
// edges-so-far int32), the same discipline as the parquet batch_rows path
// (graphmine_tpu/io/edges.py). Weighted columns parse natively here too
// (the old path pushed every weighted load through np.loadtxt(dtype=str)).
// ---------------------------------------------------------------------------

void* gb_interner_new() { return new (std::nothrow) Interner(); }

void gb_interner_free(void* it) { delete static_cast<Interner*>(it); }

int64_t gb_interner_size(void* it) {
  return static_cast<int64_t>(static_cast<Interner*>(it)->names.size());
}

// Snapshot of the interner's names (malloc'd; free via gb_free_names).
// On allocation failure everything already allocated is freed and
// *names_out is nulled — callers never inherit a partial buffer.
int64_t gb_interner_names(void* it, char*** names_out) {
  Interner* interner = static_cast<Interner*>(it);
  int64_t nv = static_cast<int64_t>(interner->names.size());
  *names_out = static_cast<char**>(malloc(sizeof(char*) * (nv ? nv : 1)));
  if (!*names_out) return -1;
  for (int64_t i = 0; i < nv; ++i) {
    const std::string& s = interner->names[static_cast<size_t>(i)];
    char* c = static_cast<char*>(malloc(s.size() + 1));
    if (!c) {
      for (int64_t j = 0; j < i; ++j) free((*names_out)[j]);
      free(*names_out);
      *names_out = nullptr;
      return -1;
    }
    memcpy(c, s.data(), s.size() + 1);
    (*names_out)[i] = c;
  }
  return nv;
}

// Parse a buffer of complete lines ("src dst [cols...]"), interning through
// the shared interner. weight_col: -1 = unweighted, else the 0-based token
// index of a float weight (>= 2; tokens 0-1 are the endpoints). Returns the
// edge count and malloc'd arrays (w_out only when weighted), -1 on
// allocation failure, -2 when a data line lacks the weight token or it does
// not parse as a float, -3 when a non-comment data line has fewer than 2
// tokens, -4 when the column count changes between data lines (all
// matching the NumPy fallback's hard errors; -4 spans chunks via the
// interner's ncols).
int64_t gb_parse_edge_chunk(void* it, const char* buf, int64_t len,
                            char comment, int32_t weight_col,
                            int32_t** src_out, int32_t** dst_out,
                            float** w_out) {
  Interner* interner = static_cast<Interner*>(it);
  std::vector<int32_t> src, dst;
  std::vector<float> w;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    // Truncate at the comment char ANYWHERE in the line (np.loadtxt
    // semantics, matching every NumPy fallback path): "a b # note" is an
    // edge, "c # note" a 1-token malformed line, a leading-comment line
    // blank. Parsing must not depend on whether the .so is built.
    const char* cpos =
        static_cast<const char*>(memchr(p, comment, line_end - p));
    const char* data_end = cpos ? cpos : line_end;
    const char* q = p;
    while (q < data_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q < data_end) {
      // Tokenize; endpoints are tokens 0-1, the weight (if any) token
      // `weight_col`.
      const char* t[2] = {nullptr, nullptr};
      const char* te[2] = {nullptr, nullptr};
      const char* wt = nullptr;
      const char* wte = nullptr;
      int32_t tok = 0;
      while (q < data_end) {
        const char* s0 = q;
        while (q < data_end && *q != ' ' && *q != '\t' && *q != '\r') ++q;
        if (q > s0) {
          if (tok < 2) {
            t[tok] = s0;
            te[tok] = q;
          } else if (tok == weight_col) {
            wt = s0;
            wte = q;
          }
          ++tok;
        }
        while (q < data_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
      }
      if (!te[1]) {
        // Data line with < 2 tokens (te[0] is always set: the guard above
        // saw a non-space data char). -3, the same hard error the NumPy
        // paths raise as "needs >= 2 columns" (ADVICE r3).
        return -3;
      }
      if (interner->ncols < 0) {
        interner->ncols = tok;
      } else if (tok != interner->ncols) {
        // np.loadtxt rectangularity: a file whose data lines change
        // column count is rejected by the NumPy paths — .so parity
        // demands the same verdict (code-review r4).
        return -4;
      }
      if (weight_col >= 0) {
        if (!wt) return -2;
        char tmp[64];
        size_t n = static_cast<size_t>(wte - wt);
        if (n >= sizeof(tmp)) return -2;
        memcpy(tmp, wt, n);
        tmp[n] = '\0';
        char* parse_end = nullptr;
        float val = strtof(tmp, &parse_end);
        if (parse_end != tmp + n) return -2;
        w.push_back(val);
      }
      src.push_back(interner->intern({t[0], size_t(te[0] - t[0])}));
      dst.push_back(interner->intern({t[1], size_t(te[1] - t[1])}));
    }
    p = line_end + 1;
  }

  int64_t ne = static_cast<int64_t>(src.size());
  *src_out = static_cast<int32_t*>(malloc(sizeof(int32_t) * (ne ? ne : 1)));
  *dst_out = static_cast<int32_t*>(malloc(sizeof(int32_t) * (ne ? ne : 1)));
  if (!*src_out || !*dst_out) {
    // no partial buffers survive a failed allocation
    free(*src_out);
    free(*dst_out);
    *src_out = nullptr;
    *dst_out = nullptr;
    return -1;
  }
  if (ne) {
    memcpy(*src_out, src.data(), sizeof(int32_t) * ne);
    memcpy(*dst_out, dst.data(), sizeof(int32_t) * ne);
  }
  if (weight_col >= 0 && w_out) {
    *w_out = static_cast<float*>(malloc(sizeof(float) * (ne ? ne : 1)));
    if (!*w_out) {
      free(*src_out);
      free(*dst_out);
      *src_out = nullptr;
      *dst_out = nullptr;
      return -1;
    }
    if (ne) memcpy(*w_out, w.data(), sizeof(float) * ne);
  }
  return ne;
}

namespace {

// Shared body of the message-CSR builders (graphmine_tpu/graph/container.py
// contract): messages grouped by receiver in stable (input) order; when
// `symmetric`, messages flow both directions (recv = concat(dst, src),
// send = the opposite endpoints). A stable counting sort — O(M + V) vs
// NumPy's O(M log M) argsort, the hot host-side step of every graph build.
// `weights`/`w_sorted` are nullable: when present, both directions of an
// edge carry its weight through the same permutation.
int build_csr_impl(const int32_t* src, const int32_t* dst,
                   const float* weights, int64_t e, int64_t v, int symmetric,
                   int64_t* ptr, int32_t* recv_sorted, int32_t* send_sorted,
                   float* w_sorted) {
  for (int64_t i = 0; i < e; ++i) {
    if (src[i] < 0 || src[i] >= v || dst[i] < 0 || dst[i] >= v) return -1;
  }
  // recv of message i: dst[i] for i < e, then src[i - e] (symmetric only).
  std::vector<int64_t> counts(static_cast<size_t>(v) + 1, 0);
  for (int64_t i = 0; i < e; ++i) ++counts[static_cast<size_t>(dst[i]) + 1];
  if (symmetric) {
    for (int64_t i = 0; i < e; ++i) ++counts[static_cast<size_t>(src[i]) + 1];
  }
  for (int64_t i = 0; i < v; ++i) counts[i + 1] += counts[i];
  memcpy(ptr, counts.data(), sizeof(int64_t) * (static_cast<size_t>(v) + 1));
  std::vector<int64_t> cursor(counts.begin(), counts.end() - 1);
  for (int64_t i = 0; i < e; ++i) {
    int64_t pos = cursor[static_cast<size_t>(dst[i])]++;
    recv_sorted[pos] = dst[i];
    send_sorted[pos] = src[i];
    if (weights) w_sorted[pos] = weights[i];
  }
  if (symmetric) {
    for (int64_t i = 0; i < e; ++i) {
      int64_t pos = cursor[static_cast<size_t>(src[i])]++;
      recv_sorted[pos] = src[i];
      send_sorted[pos] = dst[i];
      if (weights) w_sorted[pos] = weights[i];
    }
  }
  return 0;
}

}  // namespace

// Caller allocates: ptr[v+1] (int64), recv_sorted[m], send_sorted[m]
// (int32) where m = symmetric ? 2*e : e. Returns 0, or -1 when an endpoint
// is out of [0, v) — nothing is written in that case.
int gb_build_message_csr(const int32_t* src, const int32_t* dst, int64_t e,
                         int64_t v, int symmetric, int64_t* ptr,
                         int32_t* recv_sorted, int32_t* send_sorted) {
  return build_csr_impl(src, dst, nullptr, e, v, symmetric, ptr, recv_sorted,
                        send_sorted, nullptr);
}

// Weighted variant of gb_build_message_csr: same layout plus the float32
// weight payload. A separate entry point keeps the ABI compatible with
// older libgraphbuild.so builds.
int gb_build_message_csr_weighted(const int32_t* src, const int32_t* dst,
                                  const float* weights, int64_t e, int64_t v,
                                  int symmetric, int64_t* ptr,
                                  int32_t* recv_sorted, int32_t* send_sorted,
                                  float* w_sorted) {
  return build_csr_impl(src, dst, weights, e, v, symmetric, ptr, recv_sorted,
                        send_sorted, w_sorted);
}

void gb_free(void* p) { free(p); }

void gb_free_names(char** names, int64_t n) {
  if (!names) return;
  for (int64_t i = 0; i < n; ++i) free(names[i]);
  free(names);
}

}  // extern "C"
