"""GraphFrame high-level API tests — the reference user's migration surface."""

import numpy as np
import pytest

from graphmine_tpu.frames import GraphFrame


@pytest.fixture
def gf():
    # triangle 0-1-2 (directed cycle), pendant 3->4, isolated 5
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([1, 2, 0, 4], np.int32)
    names = np.array([f"v{i}" for i in range(6)])
    return GraphFrame((src, dst), vertices={"name": names}, num_vertices=6)


def test_construction_and_repr(gf):
    assert gf.num_vertices == 6 and gf.num_edges == 4
    assert "V=6" in repr(gf)
    assert list(gf.vertices["name"][:2]) == ["v0", "v1"]


def test_from_edge_table_roundtrip():
    from graphmine_tpu.io.edges import EdgeTable

    et = EdgeTable(
        src=np.array([0, 1], np.int32),
        dst=np.array([1, 0], np.int32),
        names=np.array(["a.com", "b.com"]),
    )
    gf = GraphFrame.from_edge_table(et)
    assert gf.num_vertices == 2
    assert list(gf.vertices["name"]) == ["a.com", "b.com"]


def test_degrees(gf):
    assert np.asarray(gf.out_degrees()).tolist() == [1, 1, 1, 1, 0, 0]
    assert np.asarray(gf.in_degrees()).tolist() == [1, 1, 1, 0, 1, 0]
    assert np.asarray(gf.degrees()).tolist() == [2, 2, 2, 1, 1, 0]
    np.testing.assert_array_equal(np.asarray(gf.inDegrees()), np.asarray(gf.in_degrees()))


def test_algorithms_run(gf):
    labels = np.asarray(gf.label_propagation(max_iter=5))
    assert labels.shape == (6,)
    cc = np.asarray(gf.connected_components())
    assert cc.tolist() == [0, 0, 0, 3, 3, 5]
    scc = np.asarray(gf.strongly_connected_components())
    assert scc[0] == scc[1] == scc[2]
    assert len({scc[3], scc[4], scc[5], scc[0]}) == 4
    pr = np.asarray(gf.pagerank(max_iter=50))
    assert pr.shape == (6,) and abs(pr.sum() - 1.0) < 1e-4
    tri, total = gf.triangle_count()
    assert int(total) == 1 and np.asarray(tri)[:3].tolist() == [1, 1, 1]
    sp = np.asarray(gf.shortest_paths([4]))
    assert sp[3, 0] == 1 and sp[4, 0] == 0
    camel = np.asarray(gf.connectedComponents())
    np.testing.assert_array_equal(camel, cc)


def test_bfs_with_predicates(gf):
    paths = gf.bfs(
        from_=lambda v: v["name"] == "v0",
        to=lambda v: v["name"] == "v2",
    )
    assert [p.tolist() for p in paths] == [[0, 1, 2]]
    # id-array form
    paths = gf.bfs(from_=[3], to=[4])
    assert [p.tolist() for p in paths] == [[3, 4]]


def test_find_motif(gf):
    r = gf.find("(a)-[]->(b); (b)-[]->(c); (c)-[]->(a)")
    assert r.num_matches == 3  # rotations of the directed triangle


def test_aggregate_and_pregel(gf):
    import jax.numpy as jnp

    ones = jnp.ones((6,), jnp.int32)
    indeg = gf.aggregate_messages(ones, to_dst=lambda s, d, e: s, reduce="sum")
    np.testing.assert_array_equal(np.asarray(indeg), np.asarray(gf.in_degrees()))
    out = gf.pregel(
        jnp.arange(6, dtype=jnp.int32),
        to_dst=lambda s, d, e: s,
        reduce="max",
        update=lambda st, agg: jnp.maximum(st, agg),
        max_iter=4,
    )
    assert np.asarray(out)[:3].tolist() == [2, 2, 2]


def test_filter_vertices_reindexes_with_orig(gf):
    sub = gf.filter_vertices(lambda v: np.arange(6) < 3)
    assert sub.num_vertices == 3 and sub.num_edges == 3
    assert sub.vertices["orig"].tolist() == [0, 1, 2]
    # filter again: orig still maps to the root frame
    sub2 = sub.filter_vertices([0, 2])
    assert sub2.vertices["orig"].tolist() == [0, 2]
    # 0->1 and 1->2 drop with vertex 1; 2->0 survives, re-indexed to 1->0
    assert sub2.num_edges == 1
    assert (int(sub2.edges["src"][0]), int(sub2.edges["dst"][0])) == (1, 0)


def test_filter_edges_keeps_vertices(gf):
    sub = gf.filter_edges(lambda e: e["src"] != 3)
    assert sub.num_vertices == 6 and sub.num_edges == 3


def test_drop_isolated(gf):
    sub = gf.drop_isolated_vertices()
    assert sub.num_vertices == 5
    assert sub.vertices["orig"].tolist() == [0, 1, 2, 3, 4]
    assert list(sub.vertices["name"]) == ["v0", "v1", "v2", "v3", "v4"]


def test_extras_run(gf):
    labels, q = gf.louvain()
    assert labels.shape == (6,)
    q2 = float(gf.modularity(np.asarray(gf.connected_components())))
    assert -1.0 <= q2 <= 1.0
    cores = np.asarray(gf.core_numbers())
    assert cores.tolist() == [2, 2, 2, 1, 1, 0]
    lof = np.asarray(gf.lof_scores(k=3))
    assert lof.shape == (6,)


def test_edge_attr_columns():
    gf = GraphFrame(
        {"src": [0, 1], "dst": [1, 2], "weight": np.array([0.5, 2.0])},
        num_vertices=3,
    )
    sub = gf.filter_edges(lambda e: e["weight"] > 1.0)
    assert sub.num_edges == 1 and sub.edges["weight"].tolist() == [2.0]


def test_validation_errors():
    with pytest.raises(ValueError):
        GraphFrame({"src": [0, 1]})  # missing dst
    with pytest.raises(ValueError):
        GraphFrame(([0], [1, 2]))  # length mismatch
    with pytest.raises(ValueError):
        GraphFrame(([0], [1]), vertices={"x": np.zeros(5)}, num_vertices=2)


def test_persist_cache_unpersist():
    import numpy as np

    gf = GraphFrame((np.array([0, 1], np.int32), np.array([1, 0], np.int32)))
    assert gf.persist() is gf and gf.cache() is gf
    _ = gf.graph()
    assert gf._graphs
    gf.unpersist()
    assert not gf._graphs


def test_weight_edge_column_flows_through():
    """The GraphFrames 'weight' edge-column convention: communities,
    modularity, and pageRank all see the weights without extra plumbing."""
    import numpy as np

    from graphmine_tpu.frames import GraphFrame

    v = 8
    src, dst, w = [], [], []
    for a in range(v):
        for b in range(a + 1, v):
            src.append(a); dst.append(b)
            w.append(100.0 if (a < 4) == (b < 4) else 1.0)
    gf_w = GraphFrame({"src": np.asarray(src, np.int32),
                       "dst": np.asarray(dst, np.int32),
                       "weight": np.asarray(w, np.float32)})
    gf_u = GraphFrame({"src": np.asarray(src, np.int32),
                       "dst": np.asarray(dst, np.int32)})
    assert gf_w.graph(weighted=True).msg_weight is not None
    assert gf_u.graph(weighted=True).msg_weight is None

    lab_w, q_w = gf_w.louvain()
    lab_w = np.asarray(lab_w)
    assert len(set(lab_w[:4].tolist())) == 1 and lab_w[0] != lab_w[-1]
    _, q_u = gf_u.louvain()
    assert float(q_w) > float(q_u)  # weights reveal the planted split

    pr_w = np.asarray(gf_w.pagerank(max_iter=50))
    pr_u = np.asarray(gf_u.pagerank(max_iter=50))
    assert not np.allclose(pr_w, pr_u)


def test_weight_column_opt_out_and_non_numeric():
    import numpy as np

    from graphmine_tpu.frames import GraphFrame

    src = np.array([0, 1], np.int32)
    dst = np.array([1, 0], np.int32)
    # non-numeric weight column stays inert metadata
    gf = GraphFrame({"src": src, "dst": dst,
                     "weight": np.array(["strong", "weak"])})
    assert gf.edge_weights() is None
    assert gf.graph(weighted=True).msg_weight is None
    np.asarray(gf.connected_components())  # no crash

    # numeric weight honored by weight-aware graph, ignored by default
    gf2 = GraphFrame({"src": src, "dst": dst,
                      "weight": np.array([2.0, 3.0], np.float32)})
    assert gf2.graph(weighted=True).msg_weight is not None
    assert gf2.graph().msg_weight is None  # CC/triangles keep the fast path
    gf2.weight_col = None                  # explicit opt-out
    gf2.unpersist()
    assert gf2.graph(weighted=True).msg_weight is None


def test_frame_lpa_unweighted_by_default_for_graphx_parity():
    import numpy as np

    from graphmine_tpu.frames import GraphFrame

    # weights that would flip the LPA outcome if honored
    src = np.array([0, 1], np.int32)
    dst = np.array([2, 2], np.int32)
    gf = GraphFrame({"src": src, "dst": dst,
                     "weight": np.array([100.0, 1.0], np.float32)})
    default = np.asarray(gf.label_propagation(max_iter=1))
    weighted = np.asarray(gf.label_propagation(max_iter=1, weighted=True))
    assert default[2] == 0   # unweighted tie -> smallest label (GraphX rule)
    assert weighted[2] == 0  # weight 100 also favors label 0
    # reversed weights: only the weighted run changes its answer
    gf2 = GraphFrame({"src": src, "dst": dst,
                      "weight": np.array([1.0, 100.0], np.float32)})
    assert np.asarray(gf2.label_propagation(max_iter=1))[2] == 0
    assert np.asarray(gf2.label_propagation(max_iter=1, weighted=True))[2] == 1


def test_graphframes_positional_construction_string_ids():
    """The reference's literal call shape (Graphframes.py:78):
    GraphFrame(vertices_df, edges_df) with string ids."""
    import numpy as np

    from graphmine_tpu.frames import GraphFrame
    from graphmine_tpu.table import Table

    v = Table(
        id=np.array(["aa", "bb", "cc", "dd"], dtype=object),
        name=np.array(["a.com", "b.com", "c.com", "d.com"], dtype=object),
    )
    e = Table(
        src=np.array(["aa", "bb", "cc"], dtype=object),
        dst=np.array(["bb", "cc", "aa"], dtype=object),
    )
    gf = GraphFrame(v, e)
    assert gf.num_vertices == 4 and gf.num_edges == 3
    # vertex row i == vertex index i; id kept as an attribute
    assert list(gf.vertices["id"]) == ["aa", "bb", "cc", "dd"]
    assert list(gf.edges["src"]) == [0, 1, 2]
    assert list(gf.edges["dst"]) == [1, 2, 0]
    labels = np.asarray(gf.label_propagation(max_iter=5))
    # triangle converges to one community; dd is isolated
    assert len(set(labels[:3])) == 1
    cc = np.asarray(gf.connected_components())
    assert len(np.unique(cc)) == 2


def test_string_edges_without_vertex_table_factorize():
    import numpy as np

    from graphmine_tpu.frames import GraphFrame

    gf = GraphFrame(
        {"src": np.array(["x", "y"], dtype=object),
         "dst": np.array(["y", "z"], dtype=object)}
    )
    assert gf.num_vertices == 3
    assert list(gf.vertices["id"]) == ["x", "y", "z"]  # sorted union
    assert list(gf.edges["src"]) == [0, 1]
    assert list(gf.edges["dst"]) == [1, 2]


def test_graphframes_construction_errors():
    import numpy as np
    import pytest

    from graphmine_tpu.frames import GraphFrame
    from graphmine_tpu.table import Table

    v = Table(id=np.array(["a", "a"], dtype=object))
    e = Table(src=np.array(["a"], dtype=object), dst=np.array(["a"], dtype=object))
    with pytest.raises(ValueError, match="duplicate vertex ids"):
        GraphFrame(v, e)
    v2 = Table(id=np.array(["a", "b"], dtype=object))
    e2 = Table(src=np.array(["a"], dtype=object), dst=np.array(["zz"], dtype=object))
    with pytest.raises(ValueError, match="not found in the vertex"):
        GraphFrame(v2, e2)
