"""Test harness: force an 8-device virtual CPU mesh *before* jax imports.

The TPU analog of the reference's ``SparkContext("local[*]")``
(``Graphframes.py:12``): run the real pjit/shard_map code paths on fake
devices on one host (SURVEY §4, "multi-chip-without-a-cluster").
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_PARQUET = "/root/reference/CommunityDetection/data/outlinks_pq"


@pytest.fixture(scope="session")
def bundled_edges():
    from graphmine_tpu.io.edges import load_parquet_edges

    if not os.path.isdir(REFERENCE_PARQUET):
        pytest.skip("bundled reference parquet not available")
    return load_parquet_edges(REFERENCE_PARQUET)


@pytest.fixture(scope="session")
def bundled_graph(bundled_edges):
    from graphmine_tpu.graph.container import graph_from_edge_table

    return graph_from_edge_table(bundled_edges)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
